"""Flight recorder + end-to-end tracing tests (serve/events.py,
tools/trace_export.py, the serve/metrics.py histograms —
docs/OBSERVABILITY.md).

The load-bearing claims: (1) EVERY structured transition — all nine
request ``Outcome``s, all four training ``StepOutcome``s, every
brownout level move, every replica health move — emits EXACTLY ONE
event through the recorder API, and the health counters can never
disagree with the event stream they summarize; (2) postmortem dumps
validate against the schema and name the faulted entity; (3) the
Perfetto export of a mixed prefill/decode/preemption run validates
and renders per-slot lanes; (4) the tier-labeled latency histograms
golden-parse with correct ``le`` buckets / ``+Inf`` / ``_sum`` /
``_count`` discipline; (5) the recorder is cheap, bounded, and
cleanly disableable."""

import json
import re
from collections import Counter
from types import SimpleNamespace

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.models import gpt as g
from incubator_mxnet_tpu.serve import (EventType, FlightRecorder,
                                       InferenceEngine, Outcome,
                                       Request, Tier, build_fleet,
                                       render_metrics)
from incubator_mxnet_tpu.serve.chaos import NaNWeights, run_chaos
from incubator_mxnet_tpu.serve.events import (DEFAULT_BUCKETS,
                                              terminal_fields,
                                              token_gaps,
                                              validate_event_dict,
                                              validate_postmortem)
from incubator_mxnet_tpu.serve.slo import BrownoutController
from incubator_mxnet_tpu.train.outcomes import StepOutcome, StepRecorder
from tools.trace_export import to_perfetto, validate_trace

VOCAB = 64


@pytest.fixture(scope="module")
def model():
    mx.random.seed(0)
    m = g.gpt_mini(vocab_size=VOCAB, max_length=64)
    m.initialize()
    return m


def _prompt(rng, n):
    return rng.randint(0, VOCAB, size=(n,)).astype(np.int32)


def _drain(eng, reqs, max_steps=3000):
    steps = 0
    while any(r.outcome is None for r in reqs):
        eng.step()
        steps += 1
        assert steps < max_steps, "engine failed to reach quiescence"
    return steps


def _terminals(flight):
    return flight.events(etype=EventType.TERMINAL)


# ------------------------------------------------------------------- #
# recorder core semantics
# ------------------------------------------------------------------- #

def test_recorder_causal_order_ring_bound_and_dump(tmp_path):
    rec = FlightRecorder(capacity=8)
    for i in range(20):
        rec.emit("a", EventType.DECODE_STEP, step=i)
    rec.emit("b", EventType.SUBMIT, request_id=7, tier="STANDARD")
    evs = rec.events()
    # bounded per component: a's ring kept the trailing 8 only
    assert len(rec.events("a")) == 8
    assert [e.data["step"] for e in rec.events("a")] == list(range(12,
                                                                  20))
    # merged view is seq-ordered (total causal order)
    seqs = [e.seq for e in evs]
    assert seqs == sorted(seqs)
    assert rec.emitted == 21
    # serialized events validate, and the dump round-trips
    path = tmp_path / "events.json"
    rec.dump_events(str(path))
    payload = json.loads(path.read_text())
    assert payload["schema_version"] == 1
    for d in payload["events"]:
        validate_event_dict(d)
    with pytest.raises(ValueError):
        validate_event_dict({"seq": 1, "ts": 0.0, "component": "x",
                             "etype": "NOT_A_TYPE"})


def test_recorder_disabled_is_a_noop(model):
    rng = np.random.RandomState(3)
    eng = InferenceEngine(model, num_slots=1, page_size=8, max_len=64,
                          recorder=False)
    reqs = [Request(_prompt(rng, 5), max_new_tokens=2)]
    eng.submit(reqs[0])
    _drain(eng, reqs)
    assert reqs[0].outcome is not None
    assert eng.flight.events() == []
    snap = eng.health_snapshot()
    assert snap["latency_hists"] is None
    assert "_bucket" not in render_metrics(snap)


def test_token_gaps_and_terminal_fields():
    assert token_gaps([1.0, 1.5, 2.5]) == [0.5, 1.0]
    req = SimpleNamespace(outcome=Outcome.EOS, tier=Tier.LATENCY,
                          token_ids=[1, 2, 3], detail="",
                          retry_after_s=None, submit_time=10.0,
                          finish_time=12.0,
                          token_stamps=[10.5, 11.0, 12.0])
    f = terminal_fields(req)
    assert f["outcome"] == "EOS" and f["tier"] == "LATENCY"
    assert f["e2e_s"] == pytest.approx(2.0)
    assert f["ttft_s"] == pytest.approx(0.5)
    assert f["tpot_gaps"] == [0.5, 1.0]


# ------------------------------------------------------------------- #
# event-schema completeness: every Outcome → exactly one TERMINAL
# ------------------------------------------------------------------- #

def test_engine_outcomes_emit_exactly_one_terminal(model):
    """EOS, MAX_TOKENS, SHED, DEADLINE_EXPIRED, FAILED_UNSERVABLE,
    CANCELLED, PREEMPTED all through one engine; the TERMINAL events
    match the per-request outcomes one-to-one and the health counters
    equal the event tally (counters and events can never disagree)."""
    rng = np.random.RandomState(5)
    probe = InferenceEngine(model, num_slots=1, page_size=8,
                            max_len=64, recorder=False)
    p_eos = _prompt(rng, 5)
    pr = Request(p_eos.copy(), max_new_tokens=4)
    probe.submit(pr)
    _drain(probe, [pr])
    first_tok = pr.token_ids[0]          # greedy: reproducible

    eng = InferenceEngine(model, num_slots=1, page_size=8, max_len=64,
                          max_queue=1, max_preemptions=0)
    reqs = {}
    # EOS: stop on the known first token
    reqs["EOS"] = Request(p_eos.copy(), max_new_tokens=4,
                          eos_id=first_tok)
    assert eng.submit(reqs["EOS"])
    _drain(eng, [reqs["EOS"]])
    # MAX_TOKENS
    reqs["MAX_TOKENS"] = Request(_prompt(rng, 5), max_new_tokens=2)
    assert eng.submit(reqs["MAX_TOKENS"])
    _drain(eng, [reqs["MAX_TOKENS"]])
    # FAILED_UNSERVABLE: can never fit (fail-fast at submit)
    reqs["FAILED_UNSERVABLE"] = Request(_prompt(rng, 60),
                                        max_new_tokens=30)
    assert not eng.submit(reqs["FAILED_UNSERVABLE"])
    # SHED: queue bound 1, same tier — the second queued submit sheds
    held = Request(_prompt(rng, 40), max_new_tokens=4)   # blocks slot 0
    filler = Request(_prompt(rng, 5), max_new_tokens=4)
    assert eng.submit(held)
    # occupy the slot so the queue actually builds
    eng.step()
    assert eng.submit(filler)
    reqs["SHED"] = Request(_prompt(rng, 5), max_new_tokens=2)
    assert not eng.submit(reqs["SHED"])
    # CANCELLED: cancel the queued filler
    reqs["CANCELLED"] = filler
    assert eng.cancel(filler)
    # PREEMPTED: max_preemptions=0 — a LATENCY arrival preempts the
    # BATCH holder terminally... the holder is STANDARD; use fresh
    batch = Request(_prompt(rng, 5), max_new_tokens=30,
                    tier=Tier.BATCH)
    # drain the current holder first
    _drain(eng, [held])
    assert eng.submit(batch)
    eng.step()                           # batch takes the slot
    lat = Request(_prompt(rng, 5), max_new_tokens=2, tier=Tier.LATENCY)
    assert eng.submit(lat)
    _drain(eng, [lat])
    reqs["PREEMPTED"] = batch
    assert batch.outcome is Outcome.PREEMPTED
    # DEADLINE_EXPIRED: sub-microsecond deadline, expired in queue
    reqs["DEADLINE_EXPIRED"] = Request(_prompt(rng, 5),
                                       max_new_tokens=2,
                                       deadline_s=1e-7)
    assert eng.submit(reqs["DEADLINE_EXPIRED"])
    import time as _t
    _t.sleep(0.001)
    eng.step()

    for want, r in reqs.items():
        assert r.outcome is not None and r.outcome.value == want, \
            f"{want}: got {r.outcome}"
    terms = _terminals(eng.flight)
    by_rid = Counter(e.request_id for e in terms)
    for want, r in reqs.items():
        assert by_rid[r.request_id] == 1, \
            f"{want}: {by_rid[r.request_id]} TERMINAL events"
        (ev,) = [e for e in terms if e.request_id == r.request_id]
        assert ev.data["outcome"] == want
        assert ev.data["tier"] == r.tier.value
    # counters == event tally, for every outcome ever recorded
    tally = Counter(e.data["outcome"] for e in terms)
    for o, n in eng.health.items():
        assert tally.get(o, 0) == n, f"counter drift on {o}"
    # lifecycle sanity: one SUBMIT per submitted request, decode steps
    # counted 1:1
    submits = Counter(e.request_id
                      for e in eng.flight.events(
                          etype=EventType.SUBMIT))
    assert all(n == 1 for n in submits.values())
    assert len(eng.flight.events(etype=EventType.DECODE_STEP)) == \
        eng.decode_steps
    # exactly one PREEMPT event for the preempted request
    preempts = eng.flight.events(etype=EventType.PREEMPT)
    assert len(preempts) == 1 and \
        preempts[0].request_id == batch.request_id


def test_nonfinite_quarantine_emits_terminal():
    # PRIVATE model: NaNWeights poisons the weights in place via
    # warm_start — the shared module fixture must never see it
    mx.random.seed(2)
    own = g.gpt_mini(vocab_size=VOCAB, max_length=64)
    own.initialize()
    rng = np.random.RandomState(11)
    eng = InferenceEngine(own, num_slots=2, page_size=8, max_len=64)
    reqs = [Request(_prompt(rng, 5), max_new_tokens=8)
            for _ in range(2)]
    run_chaos(eng, reqs, [NaNWeights(at_step=1, seed=0)])
    assert all(r.outcome is Outcome.FAILED_NONFINITE for r in reqs)
    terms = _terminals(eng.flight)
    assert Counter(e.request_id for e in terms) == \
        Counter(r.request_id for r in reqs)
    # the injected fault itself is on the timeline (CHAOS event)
    assert any(e.etype is EventType.CHAOS and
               e.entity == "nan_weights"
               for e in eng.flight.events())


def test_router_failover_events_and_postmortem(model):
    rt = build_fleet(model, 2,
                     engine_kw=dict(num_slots=2, page_size=8,
                                    max_len=64),
                     max_requeues=0)
    rng = np.random.RandomState(7)
    reqs = [Request(_prompt(rng, 5), max_new_tokens=6)
            for _ in range(4)]
    from incubator_mxnet_tpu.serve.chaos import (KillReplica,
                                                 run_fleet_chaos)
    run_fleet_chaos(rt, reqs, [KillReplica(0, at_step=1,
                                           phase="decode")])
    # exactly one client TERMINAL per request
    terms = _terminals(rt.flight)
    assert Counter(e.request_id for e in terms) == \
        Counter(r.request_id for r in reqs)
    failed = [r for r in reqs if r.outcome is Outcome.FAILED_REPLICA]
    assert failed, "the kill produced no FAILED_REPLICA at bound"
    # replica death is one REPLICA_HEALTH transition to DEAD
    deaths = [e for e in rt.flight.events(
        etype=EventType.REPLICA_HEALTH)
        if e.data["to_state"] == "DEAD"]
    assert len(deaths) == 1 and deaths[0].data["replica"] == 0
    # FAILED_REPLICA at the bound dumped a postmortem that validates
    # and names the request + the dead replica
    assert len(rt.flight.postmortems) == len(failed)
    pm = list(rt.flight.postmortems)[-1]
    validate_postmortem(pm)
    assert "request" in pm["entity"]
    ets = [e["etype"] for e in pm["events"]]
    assert "REPLICA_HEALTH" in ets and "CHAOS" in ets
    # replicas adopted fleet lane names
    assert rt.replicas[1].engine._component == "replica1"


# ------------------------------------------------------------------- #
# training / brownout / replica-health / checkpoint / supervisor
# ------------------------------------------------------------------- #

def test_step_outcomes_emit_exactly_one_event_each():
    rec = StepRecorder(max_consecutive_nonfinite=2)
    rec.open_step()
    rec.record(StepOutcome.APPLIED)
    rec.open_step()
    rec.record(StepOutcome.SKIPPED_STALE)
    rec.open_step()
    rec.record(StepOutcome.SKIPPED_NONFINITE)
    rec.open_step()
    out = rec.record(StepOutcome.SKIPPED_NONFINITE)   # escalates
    assert out is StepOutcome.HALTED_POISONED
    evs = rec.flight.events(etype=EventType.TRAIN_STEP)
    assert [e.data["outcome"] for e in evs] == \
        ["APPLIED", "SKIPPED_STALE", "SKIPPED_NONFINITE",
         "HALTED_POISONED"]
    # all four StepOutcome values covered, one event per record()
    assert {e.data["outcome"] for e in evs} == \
        {o.value for o in StepOutcome}
    tally = Counter(e.data["outcome"] for e in evs)
    assert dict(tally) == {k: v for k, v in rec.health.items() if v}
    # the halt dumped a postmortem naming the trainer
    assert len(rec.flight.postmortems) == 1
    pm = rec.flight.postmortems[0]
    validate_postmortem(pm)
    assert pm["reason"] == "HALTED_POISONED"
    assert pm["entity"] == "trainer"


def test_brownout_transitions_emit_one_event_each():
    bo = BrownoutController(enter=(0.5, 0.7, 0.9), exit_margin=0.2,
                            up_steps=1, down_steps=1)
    bo.flight = FlightRecorder(histograms=False)
    snaps = {"num_slots": 4, "queue_depth": 40, "free_pages": 0,
             "active_slots": 4, "estimated_queue_delay_s": None}
    eng = SimpleNamespace(num_pages=11, decode_steps=0,
                          health_snapshot=lambda: dict(snaps))
    for _ in range(3):                   # 0→1→2→3
        bo.update(eng)
        eng.decode_steps += 1
    snaps.update(queue_depth=0, active_slots=0, free_pages=10)
    for _ in range(3):                   # 3→2→1→0
        bo.update(eng)
        eng.decode_steps += 1
    evs = bo.flight.events(etype=EventType.BROWNOUT)
    assert len(evs) == len(bo.timeline) == \
        bo.escalations + bo.deescalations == 6
    for e in evs:                        # one level at a time, logged
        assert abs(e.data["to_level"] - e.data["from_level"]) == 1


def test_replica_health_recovery_emits_transitions(model):
    rt = build_fleet(model, 1,
                     engine_kw=dict(num_slots=1, page_size=8,
                                    max_len=64),
                     breaker_failures=2, probe_recovery=2)
    rep = rt.replicas[0]
    for _ in range(2):
        rt._heartbeat_miss(rep, "unit-driven miss")
    assert rep.state.value == "DEGRADED"
    for _ in range(2):
        rt._step_ok(rep, dt=0.0, compiled=False)
    assert rep.state.value == "SERVING"
    evs = rt.flight.events(etype=EventType.REPLICA_HEALTH)
    assert [(e.data["from_state"], e.data["to_state"])
            for e in evs] == [("SERVING", "DEGRADED"),
                              ("DEGRADED", "SERVING")]


def test_checkpoint_commit_event(tmp_path):
    from incubator_mxnet_tpu.checkpoint import CheckpointManager
    mgr = CheckpointManager(str(tmp_path), keep=2)
    mgr.save(3, {"w": np.arange(4, dtype=np.float32)}, block=True)
    mgr.close()
    evs = mgr.flight.events(etype=EventType.CHECKPOINT_COMMIT)
    assert len(evs) == 1
    assert evs[0].data["step"] == 3
    assert evs[0].entity == str(tmp_path)


def test_supervisor_restart_and_giveup_events(tmp_path):
    import sys
    from incubator_mxnet_tpu.base import MXNetError
    from incubator_mxnet_tpu.train.supervisor import Supervisor
    sup = Supervisor([sys.executable, "-c", "import sys; sys.exit(3)"],
                     max_restarts=1, backoff_s=0.01,
                     postmortem_dir=str(tmp_path))
    with pytest.raises(MXNetError):
        sup.run()
    restarts = sup.flight.events(etype=EventType.SUPERVISOR_RESTART)
    giveups = sup.flight.events(etype=EventType.SUPERVISOR_GIVEUP)
    assert len(restarts) == 1 and restarts[0].data["exit_code"] == 3
    assert len(giveups) == 1
    assert len(sup.flight.postmortems) == 1
    pm = sup.flight.postmortems[0]
    validate_postmortem(pm)
    assert pm.get("path") and json.load(open(pm["path"]))


# ------------------------------------------------------------------- #
# histogram golden-parse (le buckets / +Inf / _sum / _count)
# ------------------------------------------------------------------- #

_HLINE = re.compile(r"^(\w+?)(_bucket|_sum|_count)"
                    r"(\{[^}]*\})?\s([-+0-9.eEIna]+)$")


def _parse_hists(text):
    """{base: {labels-sans-le: {"buckets": [(le, cum)], "sum": x,
    "count": n}}} from the rendered metrics text."""
    out = {}
    for line in text.splitlines():
        m = _HLINE.match(line)
        if not m:
            continue
        base, kind, labels, value = m.groups()
        labels = labels or ""
        le = None
        if kind == "_bucket":
            lm = re.search(r'le="([^"]+)"', labels)
            assert lm, f"bucket without le: {line!r}"
            le = lm.group(1)
            labels = re.sub(r',?le="[^"]+"', "", labels)
        cell = out.setdefault(base, {}).setdefault(
            labels, {"buckets": [], "sum": None, "count": None})
        if kind == "_bucket":
            cell["buckets"].append((le, float(value)))
        elif kind == "_sum":
            cell["sum"] = float(value)
        else:
            cell["count"] = float(value)
    return out


def test_latency_histograms_golden_parse(model):
    rng = np.random.RandomState(9)
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64)
    reqs = [Request(_prompt(rng, 5), max_new_tokens=4,
                    tier=[Tier.LATENCY, Tier.BATCH][i % 2])
            for i in range(6)]
    for r in reqs:
        eng.submit(r)
    _drain(eng, reqs)
    snap = eng.health_snapshot()
    text = render_metrics(snap)
    hists = _parse_hists(text)
    for metric in ("ttft", "tpot", "queue_delay", "e2e_latency"):
        name = f"mxtpu_serve_{metric}_seconds"
        assert name in hists, f"missing histogram {name}"
        assert f"# TYPE {name} histogram" in text
        for labels, cell in hists[name].items():
            assert 'tier="' in labels
            les = [le for le, _ in cell["buckets"]]
            # le set: the full bound family, ascending, closed by +Inf
            assert les[:-1] == [repr(float(b)) for b in
                                DEFAULT_BUCKETS]
            assert les[-1] == "+Inf"
            counts = [c for _, c in cell["buckets"]]
            assert counts == sorted(counts), "buckets not cumulative"
            assert cell["count"] == counts[-1], "+Inf != _count"
            assert cell["sum"] is not None and cell["sum"] >= 0
    # per-token accounting: TPOT observations = tokens - one first
    # token per request (the gaps between consecutive stamps)
    total_gaps = sum(len(r.token_ids) - 1 for r in reqs)
    tpot_cells = hists["mxtpu_serve_tpot_seconds"]
    assert sum(c["count"] for c in tpot_cells.values()) == total_gaps
    # TTFT/e2e: one observation per request, per tier
    ttft = hists["mxtpu_serve_ttft_seconds"]
    assert sum(c["count"] for c in ttft.values()) == len(reqs)
    # histograms come from the SAME stream as the counters: e2e count
    # equals the terminal tally
    assert sum(c["count"] for c in
               hists["mxtpu_serve_e2e_latency_seconds"].values()) == \
        sum(eng.health.values())


def test_router_metrics_include_client_histograms(model):
    rt = build_fleet(model, 2, engine_kw=dict(num_slots=1, page_size=8,
                                              max_len=64))
    rng = np.random.RandomState(15)
    reqs = [Request(_prompt(rng, 5), max_new_tokens=3)
            for _ in range(3)]
    rt.run(reqs)
    text = render_metrics(rt.health_snapshot())
    hists = _parse_hists(text)
    # client-level histograms at the fleet namespace AND per-replica
    # attempt histograms under the replica namespace
    assert "mxtpu_serve_e2e_latency_seconds" in hists
    assert "mxtpu_serve_replica_e2e_latency_seconds" in hists
    for labels in hists["mxtpu_serve_replica_e2e_latency_seconds"]:
        assert 'replica="' in labels
    # the router's DISPATCH events feed the CLIENT queue-delay
    # histogram (one observation per dispatch)
    qd = hists["mxtpu_serve_queue_delay_seconds"]
    assert sum(c["count"] for c in qd.values()) >= len(reqs)


# ------------------------------------------------------------------- #
# Perfetto export
# ------------------------------------------------------------------- #

def test_perfetto_export_mixed_run_slot_lanes(model):
    rng = np.random.RandomState(21)
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64,
                          chunk_pages=1, max_preemptions=4)
    batch = [Request(_prompt(rng, 20), max_new_tokens=6,
                     tier=Tier.BATCH) for _ in range(3)]
    for r in batch:
        eng.submit(r)
    for _ in range(6):
        eng.step()
    lat = [Request(_prompt(rng, 5), max_new_tokens=3,
                   tier=Tier.LATENCY) for _ in range(2)]
    for r in lat:
        eng.submit(r)
    _drain(eng, batch + lat)
    assert eng.preemptions >= 1          # the mix exercises preemption
    trace = to_perfetto(eng.flight.events())
    validate_trace(trace)
    xs = [ev for ev in trace["traceEvents"] if ev["ph"] == "X"]
    slot_lanes = {ev["tid"] for ev in xs
                  if str(ev["tid"]).startswith("slot")}
    assert len(slot_lanes) >= 2, f"per-slot lanes missing: {xs[:3]}"
    cats = {ev.get("cat") for ev in trace["traceEvents"]}
    assert {"request", "prefill", "decode"} <= cats
    # every span is non-negative and timestamps are rebased
    assert all(ev["ts"] >= 0 and ev["dur"] >= 0 for ev in xs)
    # request spans name their outcome
    req_spans = [ev for ev in xs if ev["cat"] == "request"]
    assert any("(MAX_TOKENS)" in ev["name"] or "(EOS)" in ev["name"]
               for ev in req_spans)
    assert any("(preempted)" in ev["name"] for ev in req_spans)
    # json-loadable end to end
    json.loads(json.dumps(trace))


def test_perfetto_export_rejects_malformed():
    with pytest.raises(ValueError):
        validate_trace({"traceEvents": [{"ph": "X", "name": "x",
                                         "pid": 1, "tid": 1,
                                         "ts": 0.0}]})   # no dur
    with pytest.raises(ValueError):
        validate_trace({"not_traceEvents": []})


def test_postmortem_schema_rejects_malformed():
    rec = FlightRecorder(histograms=False)
    rec.emit("x", EventType.SUBMIT, request_id=1, tier="STANDARD")
    pm = rec.postmortem("unit", "entity-x", context={"k": 1})
    validate_postmortem(pm)
    bad = dict(pm)
    bad["events"] = list(reversed([dict(e) for e in pm["events"]] +
                                  [{"seq": 0, "ts": 0.0,
                                    "component": "x",
                                    "etype": "SUBMIT"}]))
    with pytest.raises(ValueError):
        validate_postmortem(bad)
    with pytest.raises(ValueError):
        validate_postmortem({"reason": "r"})
