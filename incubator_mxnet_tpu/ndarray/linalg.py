"""``mx.nd.linalg`` — linear-algebra namespace (parity:
`python/mxnet/ndarray/linalg.py`: ops registered as ``linalg_X`` surfaced
as ``nd.linalg.X``)."""

from __future__ import annotations

import sys as _sys

from ..ops import registry as _registry
from .register import make_op_function

_THIS = _sys.modules[__name__]

for _name in _registry.list_all_names():
    if _name.startswith("linalg_"):
        _short = _name[len("linalg_"):]
        if not hasattr(_THIS, _short):
            setattr(_THIS, _short, make_op_function(_registry.get(_name),
                                                    _short))
