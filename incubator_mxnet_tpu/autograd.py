"""Imperative autograd: tape recording + backward over ``jax.vjp``.

TPU-native re-design of the reference's imperative autograd
(`src/imperative/imperative.cc` ``Imperative::RecordOp/Backward``, AGInfo
nodes attached to NDArrays; Python surface `python/mxnet/autograd.py` —
file-level citations, see SURVEY.md provenance caveat).

Design (SURVEY.md §7.1 stage 2):
  - While ``record()`` is active, every imperative op appends an ``_AGNode``
    holding its *pure* function and input arrays — the tape is a DAG of pure
    closures, not a mutated graph IR.
  - ``backward()`` topo-sorts the reachable tape and runs ``jax.vjp`` per
    node, accumulating cotangents. This trades one extra forward execution
    per node for zero tape-recording overhead on the hot path — the fast
    path for training is ``HybridBlock.hybridize()``, where the whole step
    becomes ONE ``jax.vjp`` of a jitted function (CachedOp analogue).
  - ``grad_req`` semantics ('write'/'add'/'null') follow the reference's
    kWriteTo/kAddTo contract (SURVEY.md §7.2).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from .base import MXNetError

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "mark_variables", "backward", "grad", "Function",
           "set_recording", "set_training", "register_grad_ready_hook",
           "remove_grad_ready_hook"]

_STATE = threading.local()

# ----------------------------------------------------------------------- #
# grad-ready hooks (round 16, docs/TRAINING_PERF.md): backward() flushes
# each marked leaf's gradient as soon as its LAST contributing tape node
# has run — not at the end of the whole backward — and fires these hooks
# at that moment. This is the seam the Trainer's overlapped bucket
# allreduce hangs off: a dtype bucket's collective is issued while the
# rest of the backward is still dispatching, hiding the reduction behind
# remaining compute (the reference's P3 priority propagation, eager
# analogue). Hooks run with recording OFF and must not raise on foreign
# leaves (a hook is global; it sees every backward in the process).
# ----------------------------------------------------------------------- #
_GRAD_READY_HOOKS: Dict[int, object] = {}
_GRAD_HOOK_SEQ = [0]


def register_grad_ready_hook(fn) -> int:
    """Register ``fn(leaf, grad_buffer)`` to fire the moment a marked
    variable's gradient is final inside ``backward()`` (all tape
    contributions accumulated and flushed into the buffer). Returns a
    handle for ``remove_grad_ready_hook``."""
    _GRAD_HOOK_SEQ[0] += 1
    handle = _GRAD_HOOK_SEQ[0]
    _GRAD_READY_HOOKS[handle] = fn
    return handle


def remove_grad_ready_hook(handle) -> None:
    _GRAD_READY_HOOKS.pop(handle, None)


def _st():
    if not hasattr(_STATE, "recording"):
        _STATE.recording = False
        _STATE.training = False
    return _STATE


def is_recording() -> bool:
    return _st().recording


def is_training() -> bool:
    return _st().training


def set_recording(is_record: bool) -> bool:
    st = _st()
    prev, st.recording = st.recording, is_record
    return prev


def set_training(train: bool) -> bool:
    st = _st()
    prev, st.training = st.training, train
    return prev


class _ModeScope:
    def __init__(self, recording: Optional[bool], training: Optional[bool]):
        self._rec = recording
        self._train = training

    def __enter__(self):
        st = _st()
        self._prev = (st.recording, st.training)
        if self._rec is not None:
            st.recording = self._rec
        if self._train is not None:
            st.training = self._train
        return self

    def __exit__(self, *exc):
        st = _st()
        st.recording, st.training = self._prev


def record(train_mode: bool = True) -> _ModeScope:
    """Scope in which executed ops are recorded for differentiation
    (parity: ``mx.autograd.record``)."""
    return _ModeScope(recording=True, training=train_mode)


def pause(train_mode: bool = False) -> _ModeScope:
    """Scope in which ops are NOT recorded (parity: ``mx.autograd.pause``)."""
    return _ModeScope(recording=False, training=train_mode)


def train_mode() -> _ModeScope:
    return _ModeScope(recording=None, training=True)


def predict_mode() -> _ModeScope:
    return _ModeScope(recording=None, training=False)


class _AGNode:
    """One recorded op: a pure fn + its primal inputs + output arrays.

    The analogue of the reference's ``AGInfo``/``nnvm::Node`` pair; the
    "graph" is the web of nodes reachable through ``NDArray._ag_node``.
    """

    __slots__ = ("pure_fn", "primals", "owners", "outputs", "custom_vjp",
                 "name", "tuple_out")

    def __init__(self, pure_fn, primals, owners, outputs, custom_vjp=None,
                 name="", tuple_out=False):
        self.pure_fn = pure_fn      # fn(*primals) -> array | tuple(arrays)
        self.primals = primals      # list[jax.Array]
        self.owners = owners        # list[NDArray | None], aligned w/ primals
        self.outputs = outputs      # list[NDArray]
        self.custom_vjp = custom_vjp  # optional fn(out_cots) -> in_cots
        self.name = name
        self.tuple_out = tuple_out  # pure_fn returns a tuple (even if len 1)


def _record_node(pure_fn, primals, owners, outputs, custom_vjp=None, name="",
                 tuple_out=False):
    node = _AGNode(pure_fn, list(primals), list(owners), list(outputs),
                   custom_vjp, name, tuple_out)
    for idx, o in enumerate(node.outputs):
        o._ag_node = node
        o._ag_idx = idx
    return node


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers to variables (parity:
    ``mx.autograd.mark_variables``)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, g, req in zip(variables, gradients, grad_reqs):
        var._ag_node = None
        var._ag_grad = g
        var._ag_grad_req = req


def _topo(heads) -> List[_AGNode]:
    """Topological order of tape nodes reachable from head arrays."""
    roots = [h._ag_node for h in heads if getattr(h, "_ag_node", None) is not None]
    order: List[_AGNode] = []
    seen: Dict[int, int] = {}  # id(node) -> 0 visiting, 1 done
    stack = [(n, False) for n in roots]
    while stack:
        node, processed = stack.pop()
        nid = id(node)
        if processed:
            seen[nid] = 1
            order.append(node)
            continue
        if nid in seen:
            continue
        seen[nid] = 0
        stack.append((node, True))
        for owner in node.owners:
            child = getattr(owner, "_ag_node", None) if owner is not None else None
            if child is not None and id(child) not in seen:
                stack.append((child, False))
    return order  # already child-before-parent; reverse for backward


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Run backward from ``heads``, accumulating into attached ``.grad``
    buffers (parity: ``MXAutogradBackwardEx``)."""
    from .ndarray.ndarray import NDArray  # local: avoid import cycle

    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, NDArray):
        head_grads = [head_grads]
    if len(heads) != len(head_grads):
        raise MXNetError("heads and head_grads length mismatch")

    # cotangent store: id(NDArray) -> jax.Array
    cots: Dict[int, jax.Array] = {}
    keep: Dict[int, object] = {}  # keep NDArrays alive while we hold their ids
    # leaf accumulation: id(NDArray) -> jax.Array
    leaf_acc: Dict[int, jax.Array] = {}
    leaves: Dict[int, object] = {}

    def _add(store, arr, val):
        key = id(arr)
        if key in store:
            store[key] = store[key] + val
        else:
            store[key] = val

    for h, hg in zip(heads, head_grads):
        g = hg._data if hasattr(hg, "_data") else hg
        if g is None:
            g = jnp.ones(h.shape, h.dtype)
        recorded = getattr(h, "_ag_node", None) is not None
        marked = getattr(h, "_ag_grad", None) is not None
        if recorded:
            _add(cots, h, g)
            keep[id(h)] = h
        if marked:
            _add(leaf_acc, h, g)
            leaves[id(h)] = h
        if not recorded and not marked:
            raise MXNetError(
                "head array is neither recorded nor a marked variable; "
                "did you forget autograd.record() or attach_grad()?")

    order = _topo(heads)
    rev = list(reversed(order))

    # early-finalization schedule: the LAST node (in execution order) that
    # can contribute a cotangent to each marked leaf. Once that node has
    # been processed the leaf's accumulator is final, so it can be flushed
    # into the grad buffer and the grad-ready hooks fired while the rest
    # of the backward is still running (docs/TRAINING_PERF.md overlap).
    last_contrib: Dict[int, int] = {}
    for k, node in enumerate(rev):
        for owner in node.owners:
            if owner is not None and \
                    getattr(owner, "_ag_grad", None) is not None:
                last_contrib[id(owner)] = k
                leaves.setdefault(id(owner), owner)
    flush_at: List[List[object]] = [[] for _ in rev]
    for lid, k in last_contrib.items():
        flush_at[k].append(leaves[lid])

    def _flush_leaf(leaf):
        total = leaf_acc.pop(id(leaf), None)
        if total is None:
            return
        req = getattr(leaf, "_ag_grad_req", "write")
        if req == "null":
            return
        gbuf = leaf._ag_grad
        if req == "add":
            gbuf._data = gbuf._data + total.astype(gbuf.dtype)
        else:  # write
            gbuf._data = total.astype(gbuf.dtype)
        # Trainer's stale-grad contract: a grad buffer backward has
        # refilled is FRESH; Trainer.step marks it stale after applying
        gbuf._fresh = True
        for fn in tuple(_GRAD_READY_HOOKS.values()):
            fn(leaf, gbuf)

    with _ModeScope(recording=False, training=train_mode):
        # marked heads no tape node can reach again (seed-only leaves)
        # are final before any node runs
        for lid in [k for k in leaf_acc if k not in last_contrib]:
            _flush_leaf(leaves[lid])
        for k, node in enumerate(rev):
            out_cots = []
            any_cot = False
            for o in node.outputs:
                c = cots.get(id(o))
                if c is None:
                    c = jnp.zeros(o.shape, o.dtype)
                else:
                    any_cot = True
                out_cots.append(c)
            if any_cot:
                if node.custom_vjp is not None:
                    in_cots = node.custom_vjp(out_cots)
                else:
                    _, vjp_fn = jax.vjp(node.pure_fn, *node.primals)
                    seed = tuple(out_cots) \
                        if node.tuple_out or len(out_cots) > 1 \
                        else out_cots[0]
                    in_cots = vjp_fn(seed)
                for owner, ic in zip(node.owners, in_cots):
                    if owner is None or ic is None:
                        continue
                    if ic.dtype == jax.dtypes.float0:
                        continue  # non-differentiable input (e.g. PRNG key)
                    # an array can be BOTH an intermediate (has a tape
                    # node to propagate through) and a marked variable
                    # (grad() / attach_grad on a non-leaf): feed both
                    child = getattr(owner, "_ag_node", None)
                    if child is not None:
                        _add(cots, owner, ic)
                        keep[id(owner)] = owner
                    if getattr(owner, "_ag_grad", None) is not None:
                        _add(leaf_acc, owner, ic)
                        leaves[id(owner)] = owner
            # flush every leaf whose final contribution this node was —
            # even a node SKIPPED for lack of cotangents finalizes its
            # leaves (nothing later can touch them)
            for leaf in flush_at[k]:
                _flush_leaf(leaf)

        # fallback: anything not finalized by the schedule (defensive —
        # the schedule covers every owner relationship)
        for lid in list(leaf_acc):
            _flush_leaf(leaves[lid])

    if not retain_graph:
        for node in order:
            for o in node.outputs:
                o._ag_node = None
            node.outputs = []
            node.owners = []
            node.primals = []


def _compose_pure(heads, variables):
    """Replay the reachable tape into ONE pure function
    variables -> heads (the reference's CreateGraph path builds the
    backward as a symbolic graph; here the composite + ``jax.vjp`` is
    that graph, and jax's vjp-of-vjp gives every higher order).

    The replay is a SNAPSHOT: pure fns, primal values, and identity keys
    are copied out of the tape, and the NDArray objects are pinned by
    the closure — so a later ``backward(retain_graph=False)`` that
    clears the shared tape nodes cannot corrupt this composite."""
    order = _topo(heads)  # children before parents == forward order
    for node in order:
        if node.pure_fn is None:
            raise MXNetError(
                "create_graph=True is not supported through a custom "
                "autograd.Function (its backward is opaque to replay)")

    pins = list(variables) + list(heads)  # keep ids stable for closure
    replay = []
    produced = set()
    for node in order:
        pins.extend(node.outputs)
        pins.extend(o for o in node.owners if o is not None)
        produced.update(id(o) for o in node.outputs)
        replay.append((
            node.pure_fn, list(node.primals),
            [id(o) if o is not None else None for o in node.owners],
            [id(o) for o in node.outputs]))
    head_ids = [id(h) for h in heads]
    head_vals = [h._data for h in heads]
    seeded_order = [id(v) for v in variables]

    def composite(*var_vals):
        _pins = pins  # mxlint: allow-pinned-name(pin NDArray identities for env keys)
        # leaf variables seed the env; variables that are themselves
        # INTERMEDIATES (grad of a non-leaf) are instead INJECTED at
        # their production site as `replayed + (v - stop_grad(v))`:
        # value unchanged, d/dv is the identity (the ∂/∂v cotangent),
        # and upstream paths THROUGH the variable stay connected — the
        # same both-paths semantics as first-order backward()
        env, inject = {}, {}
        for vid, val in zip(seeded_order, var_vals):
            (inject if vid in produced else env)[vid] = val
        for fn, primals, owner_ids, out_ids in replay:
            prim = [env.get(oid, p) if oid is not None else p
                    for oid, p in zip(owner_ids, primals)]
            outs = fn(*prim)
            outs_t = outs if isinstance(outs, (tuple, list)) else (outs,)
            for oid, val in zip(out_ids, outs_t):
                vv = inject.get(oid)
                if vv is not None:
                    val = val + (vv - jax.lax.stop_gradient(vv))
                env[oid] = val
        return tuple(env.get(hid, hv)
                     for hid, hv in zip(head_ids, head_vals))

    return composite


def _grad_create_graph(heads, variables, head_grads, train_mode):
    """Higher-order path: grads come from ``jax.vjp`` of the replayed
    composite, and the grad computation itself is RECORDED as a tape
    node — so backward()/grad() on the result differentiates again."""
    from .ndarray.ndarray import NDArray

    if len(heads) != len(head_grads):
        raise MXNetError("heads and head_grads length mismatch")
    for h in heads:
        if getattr(h, "_ag_node", None) is None and \
                getattr(h, "_ag_grad", None) is None:
            raise MXNetError(
                "head array is neither recorded nor a marked variable; "
                "did you forget autograd.record() or attach_grad()?")
    # head_grads that are themselves recorded arrays become INPUTS of the
    # recorded grad node (owners include them), so a later backward
    # differentiates through the seed too instead of freezing it
    const_seeds = {}
    seed_inputs = []   # (position, NDArray)
    for i, (h, hg) in enumerate(zip(heads, head_grads)):
        if hg is None:
            const_seeds[i] = jnp.ones(h.shape, h.dtype)
        elif hasattr(hg, "_data"):
            seed_inputs.append((i, hg))
        else:
            const_seeds[i] = hg
    composite = _compose_pure(heads, variables)
    n_vars = len(variables)
    n_heads = len(heads)

    def grad_fn(*vals):
        var_vals, seed_vals = vals[:n_vars], vals[n_vars:]
        seeds = list(range(n_heads))
        it = iter(seed_vals)
        for i in range(n_heads):
            seeds[i] = const_seeds[i] if i in const_seeds else next(it)
        _, vjp_fn = jax.vjp(composite, *var_vals)
        return vjp_fn(tuple(seeds))

    all_vals = tuple(v._data for v in variables) + \
        tuple(hg._data for _, hg in seed_inputs)
    all_owners = list(variables) + [hg for _, hg in seed_inputs]
    with _ModeScope(recording=False, training=train_mode):
        grads = grad_fn(*all_vals)
    outs = [NDArray(g) for g in grads]
    _record_node(grad_fn, list(all_vals), all_owners, outs,
                 name="grad", tuple_out=True)
    return outs


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Return gradients of heads w.r.t. variables without touching their
    ``.grad`` buffers (parity: ``mx.autograd.grad``; ``create_graph=True``
    returns grads that are themselves differentiable)."""
    from .ndarray.ndarray import NDArray

    if isinstance(variables, NDArray):
        variables = [variables]
    if retain_graph is None:
        retain_graph = create_graph
    if create_graph:
        if isinstance(heads, NDArray):
            heads = [heads]
        if head_grads is None:
            head_grads = [None] * len(heads)
        elif isinstance(head_grads, NDArray):
            head_grads = [head_grads]
        return _grad_create_graph(heads, variables, head_grads, train_mode)
    # temporarily mark variables with fresh buffers
    saved = [(getattr(v, "_ag_grad", None), getattr(v, "_ag_grad_req", "write"))
             for v in variables]
    zeros = []
    for v in variables:
        z = v.__class__(jnp.zeros(v.shape, v.dtype))
        zeros.append(z)
        v._ag_grad = z
        v._ag_grad_req = "write"
    try:
        backward(heads, head_grads, retain_graph=retain_graph,
                 train_mode=train_mode)
        return [v._ag_grad for v in variables]
    finally:
        for v, (g, req) in zip(variables, saved):
            v._ag_grad = g
            v._ag_grad_req = req


class Function:
    """User-defined differentiable function (parity:
    ``mx.autograd.Function``, `python/mxnet/autograd.py`).

    Subclass and implement ``forward(self, *inputs)`` and
    ``backward(self, *output_grads)`` over NDArrays. Inside both, autograd
    recording is paused.
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *arrays):
        self._saved = arrays

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray, _wrap

        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)
        if is_recording():
            def custom_vjp(out_cots, _self=self, _n_in=len(inputs)):
                with pause():
                    gs = _self.backward(*[_wrap(c) for c in out_cots])
                if not isinstance(gs, (list, tuple)):
                    gs = [gs]
                if len(gs) != _n_in:
                    raise MXNetError(
                        f"Function.backward returned {len(gs)} grads for "
                        f"{_n_in} inputs")
                return [g._data if g is not None else None for g in gs]

            _record_node(
                pure_fn=None,
                primals=[x._data for x in inputs],
                owners=list(inputs),
                outputs=outs,
                custom_vjp=custom_vjp,
                name=type(self).__name__,
            )
        return outs[0] if single else outs
