"""Optimizers (re-design of `python/mxnet/optimizer/` — SURVEY.md §2.2)."""

from .optimizer import (Optimizer, SGD, NAG, Adam, AdamW, RMSProp, Ftrl,
                        Signum, LAMB, LARS, FTML, Adamax, Nadam, DCASGD,
                        SGLD, AdaGrad, AdaDelta, Updater, create,
                        register, get_updater)
from . import lr_scheduler
from .lr_scheduler import LRScheduler
from . import fused
from .fused import FusedApplier, apply_updates
