"""Symbol attribute scoping (parity: `python/mxnet/attribute.py` —
AttrScope; file-level citation, SURVEY.md caveat).

``with mx.AttrScope(ctx_group="stage1"):`` attaches the given attributes
to every symbol created inside the scope — the reference's mechanism for
`group2ctx` model-parallel placement hints among other graph annotations.
Scopes nest; inner values win on key conflicts."""

from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = ["AttrScope", "current_attrs"]


class AttrScope:
    _current: threading.local = threading.local()

    def __init__(self, **attrs: str):
        for k, v in attrs.items():
            if not isinstance(v, str):
                attrs[k] = str(v)
        self._attrs = attrs
        self._old: Optional[Dict[str, str]] = None

    def get(self, attrs: Optional[Dict[str, str]] = None) -> Dict[str, str]:
        """Merge scope attrs under explicitly-passed ``attrs``."""
        merged = dict(self._attrs)
        if attrs:
            merged.update(attrs)
        return merged

    def __enter__(self) -> "AttrScope":
        prev = getattr(AttrScope._current, "value", None)
        self._old = prev
        merged = dict(prev._attrs) if isinstance(prev, AttrScope) else \
            (dict(prev) if prev else {})
        merged.update(self._attrs)
        self._attrs = merged
        AttrScope._current.value = self
        return self

    def __exit__(self, *exc):
        AttrScope._current.value = self._old
        self._old = None
        return False


def current_attrs() -> Dict[str, str]:
    """Attributes of the innermost active AttrScope ({} outside any)."""
    scope = getattr(AttrScope._current, "value", None)
    return dict(scope._attrs) if isinstance(scope, AttrScope) else {}
