"""Checkpoint helpers (re-design of `python/mxnet/model.py`
save_checkpoint/load_checkpoint; file-level citation — SURVEY.md caveat).

Formats mirror the reference (SURVEY.md §5.4): ``<prefix>-symbol.json``
(graph) + ``<prefix>-NNNN.params`` (name→NDArray dict with ``arg:``/
``aux:`` key prefixes).
"""

from __future__ import annotations

from typing import Dict, Tuple

from .ndarray import NDArray, load as nd_load, save as nd_save
from .symbol.symbol import Symbol, load as sym_load

__all__ = ["save_checkpoint", "load_checkpoint"]


def save_checkpoint(prefix: str, epoch: int, symbol: Symbol,
                    arg_params: Dict[str, NDArray],
                    aux_params: Dict[str, NDArray],
                    format: str = "mxtpu") -> None:
    """Parity: ``mx.model.save_checkpoint`` / `callback.do_checkpoint`.
    ``format="mxnet"`` writes the reference's 1.x ``.params`` binary
    layout, so the resulting ``<prefix>-symbol.json`` +
    ``<prefix>-NNNN.params`` pair opens in reference tooling
    (load_checkpoint auto-detects either layout)."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    payload = {}
    payload.update({f"arg:{k}": v for k, v in (arg_params or {}).items()})
    payload.update({f"aux:{k}": v for k, v in (aux_params or {}).items()})
    nd_save(f"{prefix}-{epoch:04d}.params", payload, format=format)


def load_checkpoint(prefix: str, epoch: int
                    ) -> Tuple[Symbol, Dict[str, NDArray],
                               Dict[str, NDArray]]:
    """Parity: ``mx.model.load_checkpoint`` → (symbol, arg_params,
    aux_params)."""
    symbol = sym_load(f"{prefix}-symbol.json")
    payload = nd_load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for key, val in payload.items():
        kind, _, name = key.partition(":")
        if kind == "arg":
            arg_params[name] = val
        elif kind == "aux":
            aux_params[name] = val
        else:
            arg_params[key] = val
    return symbol, arg_params, aux_params
