"""Test utilities.

Re-design of `python/mxnet/test_utils.py` (file-level citation — SURVEY.md
caveat): ``assert_almost_equal`` with per-dtype tolerances,
``check_numeric_gradient`` (finite differences vs autograd — SURVEY.md §4
idiom 1), ``check_consistency`` (cross-backend equality — idiom 2),
``default_context``, seeded reproducibility helpers (idiom 3).
"""

from __future__ import annotations

import functools
import os
import random as _pyrandom
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from . import autograd
from . import context as _ctx
from . import random as _random
from .base import MXNetError
from .ndarray import NDArray, array as nd_array
from . import ndarray as nd

__all__ = ["assert_almost_equal", "check_numeric_gradient", "check_consistency",
           "default_context", "with_seed", "rand_ndarray", "same",
           "almost_equal", "environment"]

_DTYPE_RTOL = {np.dtype(np.float16): 1e-2, np.dtype(np.float32): 1e-4,
               np.dtype(np.float64): 1e-6}
_DTYPE_ATOL = {np.dtype(np.float16): 1e-2, np.dtype(np.float32): 1e-5,
               np.dtype(np.float64): 1e-7}


def default_context() -> _ctx.Context:
    return _ctx.current_context()


def _to_np(a):
    if isinstance(a, NDArray):
        return a.asnumpy()
    return np.asarray(a)


def same(a, b) -> bool:
    return np.array_equal(_to_np(a), _to_np(b))


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False) -> bool:
    a, b = _to_np(a), _to_np(b)
    rtol = rtol or _DTYPE_RTOL.get(a.dtype, 1e-4)
    atol = atol or _DTYPE_ATOL.get(a.dtype, 1e-5)
    return np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    a_np, b_np = _to_np(a), _to_np(b)
    rtol = rtol if rtol is not None else _DTYPE_RTOL.get(np.dtype(a_np.dtype), 1e-4)
    atol = atol if atol is not None else _DTYPE_ATOL.get(np.dtype(a_np.dtype), 1e-5)
    if not np.allclose(a_np.astype(np.float64), b_np.astype(np.float64),
                       rtol=rtol, atol=atol, equal_nan=equal_nan):
        diff = np.abs(a_np.astype(np.float64) - b_np.astype(np.float64))
        rel = diff / (np.abs(b_np.astype(np.float64)) + atol)
        raise AssertionError(
            f"{names[0]} and {names[1]} differ: max abs {diff.max():.3e}, "
            f"max rel {rel.max():.3e} (rtol={rtol}, atol={atol})\n"
            f"{names[0]}: {a_np.ravel()[:8]}...\n{names[1]}: {b_np.ravel()[:8]}...")


def rand_ndarray(shape, dtype="float32", ctx=None, low=-1.0, high=1.0) -> NDArray:
    data = np.random.uniform(low, high, size=shape).astype(dtype)
    return nd_array(data, ctx=ctx)


def check_numeric_gradient(fn: Callable, inputs: Sequence[NDArray],
                           eps: float = 1e-3, rtol: float = 1e-2,
                           atol: float = 1e-3,
                           grad_nodes: Optional[Sequence[int]] = None):
    """Validate autograd gradients of ``fn`` against central finite
    differences (parity: ``check_numeric_gradient``; SURVEY.md §4 idiom 1).

    ``fn(*inputs) -> NDArray`` must return a scalar-reducible output; we
    reduce with ``sum()`` internally (matching the reference, which uses a
    random projection head — sum is the deterministic variant).
    """
    inputs = [x if isinstance(x, NDArray) else nd_array(x) for x in inputs]
    grad_nodes = list(range(len(inputs))) if grad_nodes is None else list(grad_nodes)

    # analytic gradients (float32 path)
    for i in grad_nodes:
        inputs[i].attach_grad()
    with autograd.record():
        out = fn(*inputs)
        head = out.sum() if out.shape != () else out
    head.backward()
    analytic = [inputs[i].grad.asnumpy().astype(np.float64) for i in grad_nodes]

    # numeric gradients via central differences on float64 host copies
    # (ascontiguousarray: device_get may hand back F-order arrays, and a
    # reshape view would silently copy — perturbations must be in-place)
    host = [np.ascontiguousarray(x.asnumpy(), dtype=np.float64) for x in inputs]

    def eval_sum(arrs) -> float:
        nds = [nd_array(a.astype(inputs[j].asnumpy().dtype))
               for j, a in enumerate(arrs)]
        with autograd.pause():
            o = fn(*nds)
        return float(o.sum().asscalar() if o.shape != () else o.asscalar())

    for gi, i in enumerate(grad_nodes):
        base = host[i]
        num = np.zeros_like(base)
        for idx in np.ndindex(*base.shape):
            orig = base[idx]
            base[idx] = orig + eps
            f_plus = eval_sum(host)
            base[idx] = orig - eps
            f_minus = eval_sum(host)
            base[idx] = orig
            num[idx] = (f_plus - f_minus) / (2 * eps)
        assert_almost_equal(analytic[gi], num, rtol=rtol, atol=atol,
                            names=(f"analytic_grad[{i}]", f"numeric_grad[{i}]"))


def _check_consistency_sym(sym, ctx_list, rtol=None, atol=None):
    """The reference calling form: ``check_consistency(sym, ctx_list)``
    with ctx_list entries like ``{"ctx": mx.cpu(), "data": (2, 3),
    "type_dict": {"data": np.float16}}`` — the fp16-vs-fp32 idiom of
    tests/python/unittest/test_operator.py. One canonical set of
    random inputs/params is generated in float64 and cast per entry;
    outputs AND input gradients must agree within the loosest entry
    dtype's tolerance."""
    from .symbol.executor import Executor

    if not ctx_list:
        raise MXNetError(
            "check_consistency(sym, ctx_list): ctx_list must be a "
            "non-empty list of dicts like {'ctx': mx.cpu(), 'data': "
            "(2, 3), 'type_dict': {'data': np.float16}}")
    rng = np.random.RandomState(0)
    canonical: dict = {}
    runs = []
    worst = np.dtype(np.float64)
    for spec in ctx_list:
        spec = dict(spec)
        ctx = spec.pop("ctx", None)
        type_dict = spec.pop("type_dict", {}) or {}
        grad_req = spec.pop("grad_req", "write")
        ex = Executor.simple_bind(sym, ctx, grad_req=grad_req, **spec)
        for name, arr in ex.arg_dict.items():
            dt = np.dtype(type_dict.get(name, np.float32))
            if name not in canonical:
                canonical[name] = (
                    rng.randint(0, 4, arr.shape).astype(np.int64)
                    if np.issubdtype(dt, np.integer)
                    else rng.uniform(-1.0, 1.0, arr.shape))
            elif canonical[name].shape != tuple(arr.shape):
                raise MXNetError(
                    f"check_consistency: arg {name!r} has shape "
                    f"{tuple(arr.shape)} in one entry but "
                    f"{canonical[name].shape} in another — entries "
                    f"must agree on shapes")
            if np.issubdtype(dt, np.floating) and \
                    worst.itemsize > dt.itemsize:
                worst = dt
            ex.arg_dict[name] = nd_array(canonical[name].astype(dt),
                                         ctx=ctx)
        out = ex.forward(is_train=(grad_req != "null"))
        raw = [o.asnumpy() for o in out]
        outs = [r.astype(np.float64) for r in raw]
        grads = {}
        if grad_req != "null":
            # synthesized unit head gradients (in each output's own
            # dtype) make multi-output symbols comparable (the
            # reference projects with random heads)
            ex.backward([nd_array(np.ones_like(r)) for r in raw])
            grads = {n: g.asnumpy().astype(np.float64)
                     for n, g in ex.grad_dict.items()
                     if g is not None
                     and np.dtype(getattr(g._data, "dtype", np.float32))
                     .kind == "f"}  # int args carry jax float0 tangents
        runs.append((ctx, type_dict, outs, grads))
    trtol = rtol if rtol is not None else _DTYPE_RTOL.get(worst, 1e-4)
    tatol = atol if atol is not None else _DTYPE_ATOL.get(worst, 1e-5)
    ref_ctx, _, ref_outs, ref_grads = runs[0]
    for ctx, _, outs, grads in runs[1:]:
        for r0, r1 in zip(ref_outs, outs):
            assert_almost_equal(r0, r1, rtol=trtol, atol=tatol,
                                names=(f"{ref_ctx}", f"{ctx}"))
        for name in ref_grads:
            if name in grads:
                assert_almost_equal(ref_grads[name], grads[name],
                                    rtol=trtol, atol=tatol,
                                    names=(f"grad({name})@{ref_ctx}",
                                           f"grad({name})@{ctx}"))
    return [r[2] for r in runs]


def check_consistency(fn, inputs_np=None,
                      ctx_list: Optional[Sequence] = None,
                      rtol=None, atol=None):
    """Cross-context/dtype consistency (parity: ``check_consistency`` —
    SURVEY.md §4 idiom 2). Two calling forms:

    - reference form: ``check_consistency(sym, [{"ctx": ..., "data":
      shape, "type_dict": {...}}, ...])`` — inputs synthesized once,
      outputs and gradients compared across entries;
    - function form: ``check_consistency(fn, inputs_np, ctx_list=
      [Context, ...])`` — the same arrays run through ``fn`` per
      context."""
    from .symbol.symbol import Symbol

    if isinstance(fn, Symbol):
        return _check_consistency_sym(fn, inputs_np or ctx_list,
                                      rtol=rtol, atol=atol)
    rtol = 1e-4 if rtol is None else rtol
    atol = 1e-5 if atol is None else atol
    if ctx_list is None:
        ctx_list = [_ctx.cpu(0), _ctx.tpu(0)]
    results = []
    for ctx in ctx_list:
        ins = [nd_array(a, ctx=ctx) for a in inputs_np]
        out = fn(*ins)
        outs = out if isinstance(out, (list, tuple)) else [out]
        results.append([o.asnumpy() for o in outs])
    ref = results[0]
    for ctx, res in zip(ctx_list[1:], results[1:]):
        for r0, r1 in zip(ref, res):
            assert_almost_equal(r0, r1, rtol=rtol, atol=atol,
                                names=(f"{ctx_list[0]}", f"{ctx}"))


def with_seed(seed: Optional[int] = None):
    """Decorator: seed mx/np/python RNGs per test and log the seed on failure
    (parity: tests/python/unittest/common.py @with_seed — SURVEY.md §4
    idiom 3)."""

    def decorator(test_fn):
        @functools.wraps(test_fn)
        def wrapper(*args, **kwargs):
            actual = seed if seed is not None else np.random.randint(0, 2**31)
            np.random.seed(actual)
            _pyrandom.seed(actual)
            _random.seed(actual)
            try:
                return test_fn(*args, **kwargs)
            except Exception:
                print(f"[with_seed] test failed with seed={actual}; "
                      f"reproduce via @with_seed({actual})")
                raise

        return wrapper

    return decorator


class environment:
    """Context manager to scope env vars (parity:
    ``mx.util.environment`` / test_utils.environment)."""

    def __init__(self, *args):
        if len(args) == 2:
            self._env = {args[0]: args[1]}
        else:
            self._env = dict(args[0])

    def __enter__(self):
        self._saved = {k: os.environ.get(k) for k in self._env}
        for k, v in self._env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        return self

    def __exit__(self, *exc):
        for k, v in self._saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
