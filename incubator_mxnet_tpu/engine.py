"""Execution-engine shim.

The reference's L2 dependency engine (`src/engine/threaded_engine.{h,cc}`,
`threaded_engine_perdevice.cc`, `naive_engine.cc`; file-level citations —
SURVEY.md caveat) schedules every op as an async closure with read/write
variable sets. In the TPU-native design that engine is XLA's async dispatch:
jnp calls return futures immediately and ordering comes from data
dependencies inside the compiled program (SURVEY.md §1 "key architectural
invariant" + §7.3).

What remains user-visible — and is provided here — is the engine's *control
surface*:

  - ``NaiveEngine`` debug mode (`MXNET_ENGINE_TYPE=NaiveEngine` in the
    reference, selected in `src/engine/engine.cc`): fully synchronous
    execution to bisect scheduling/async bugs. Here ``set_sync(True)`` (or
    env ``MXTPU_ENGINE_TYPE=NaiveEngine``) makes every imperative op call
    ``jax.block_until_ready`` on its outputs, so exceptions surface at the
    faulting op instead of the next sync point (SURVEY.md §5.2).
  - ``wait_all`` — `Engine::WaitForAll` / `mx.nd.waitall`: drain all pending
    async work on every device.
  - op bulking knobs (`MXNET_EXEC_BULK_EXEC_*`): accepted for API parity;
    XLA fuses within a jitted program, so they are no-ops and say so.
"""

from __future__ import annotations

import threading

from .base import getenv_str

__all__ = ["set_sync", "is_sync", "wait_all", "set_bulk_size", "bulk"]

_state = threading.local()
_DEFAULT_SYNC = getenv_str("MXTPU_ENGINE_TYPE", "").lower() == "naiveengine"


def is_sync() -> bool:
    """True when the debug NaiveEngine (synchronous) mode is active."""
    return getattr(_state, "sync", _DEFAULT_SYNC)


def set_sync(sync: bool = True) -> bool:
    """Toggle synchronous execution (parity: ``MXNET_ENGINE_TYPE=NaiveEngine``,
    `src/engine/naive_engine.cc`). Returns the previous setting."""
    prev = is_sync()
    _state.sync = bool(sync)
    return prev


def _maybe_sync(outputs):
    """Called by the imperative front end after each op when sync mode is on."""
    if is_sync():
        import jax

        for o in outputs:
            jax.block_until_ready(o._data if hasattr(o, "_data") else o)


def wait_all() -> None:
    """Block until all async device work is complete (parity:
    `Engine::WaitForAll` via `MXNDArrayWaitAll`)."""
    import jax
    import jax.numpy as jnp

    from .ndarray.ndarray import _needs_fetch_fence

    for dev in jax.devices():
        probe = jax.device_put(jnp.zeros(()), dev)
        probe.block_until_ready()
        if _needs_fetch_fence():
            # axon tunnel: block_until_ready is a no-op — a device fetch
            # is the only real fence (see NDArray.wait_to_read)
            jax.device_get(probe)


_bulk_size = 0


def set_bulk_size(size: int) -> int:
    """Parity no-op for `MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN` / engine op
    bulking: XLA fuses ops inside a jitted program, so bulking is automatic
    under ``hybridize()``. Returns the previous value."""
    global _bulk_size
    prev, _bulk_size = _bulk_size, int(size)
    return prev


class bulk:
    """Context manager parity for ``mx.engine.bulk(size)``; fusion happens in
    XLA, so this only tracks the requested size."""

    def __init__(self, size: int):
        self._size = size
        self._prev = None

    def __enter__(self):
        self._prev = set_bulk_size(self._size)
        return self

    def __exit__(self, *exc):
        set_bulk_size(self._prev)
