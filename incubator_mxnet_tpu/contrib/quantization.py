"""Post-training int8 quantization (re-design of
`python/mxnet/contrib/quantization.py` + the graph pass in
`src/operator/quantization/quantize_graph_pass.cc` — file-level
citations, SURVEY.md caveat).

Flow (the reference's): run calibration batches through the float net
collecting per-layer activation statistics → choose thresholds
(``naive`` min/max or ``entropy`` KL-optimal) → swap Dense/Conv2D layers
for int8 twins that run MXU int8 matmuls (ops/quantization.py)."""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as _np

from ..base import MXNetError
from ..gluon import nn
from ..gluon.block import HybridBlock
from ..ndarray import NDArray

__all__ = ["quantize_net", "calib_thresholds_entropy", "QuantizedDense",
           "QuantizedConv2D"]


def calib_thresholds_entropy(hist, bin_edges, num_quantized_bins=255):
    """KL-divergence-optimal |threshold| from an activation histogram
    (the reference's LayerHistogramCollector + _get_optimal_threshold;
    TensorRT-style)."""
    hist = hist.astype(_np.float64)
    num_bins = len(hist)
    if num_bins < num_quantized_bins + 2:
        return float(bin_edges[-1])
    best_kl, best_t = _np.inf, bin_edges[-1]
    # candidate thresholds sweep the tail inward
    for i in range(num_quantized_bins, num_bins + 1,
                   max(1, (num_bins - num_quantized_bins) // 64)):
        ref = hist[:i].copy()
        ref[-1] += hist[i:].sum()  # clip outliers into the last bin
        if ref.sum() == 0:
            continue
        # quantize the i bins down to num_quantized_bins
        idx = _np.linspace(0, i, num_quantized_bins + 1).astype(_np.int64)
        q = _np.zeros(i)
        # NOTE: q is deliberately built from the UNCLIPPED slice (the
        # reference/TensorRT algorithm): the outlier mass lives only in
        # ref's last bin, so aggressive clipping shows up as P/Q mismatch
        # — folding it into q too would make the tightest threshold a
        # degenerate KL=0 minimum.
        for j in range(num_quantized_bins):
            lo, hi = idx[j], max(idx[j + 1], idx[j] + 1)
            chunk = hist[lo:hi]
            nz = (chunk > 0).sum()
            if nz:
                q[lo:hi] = _np.where(chunk > 0, chunk.sum() / nz, 0)
        p = ref / ref.sum()
        qs = q.sum()
        if qs == 0:
            continue
        q = q / qs
        mask = p > 0
        kl = float((p[mask] * _np.log(
            _np.maximum(p[mask], 1e-12) / _np.maximum(q[mask], 1e-12)))
            .sum())
        if kl < best_kl:
            # threshold = UPPER edge of the last kept bin (bins [0, i) are
            # kept, so edge index i — len(bin_edges) == num_bins + 1)
            best_kl, best_t = kl, float(bin_edges[i])
    return float(best_t)


def _rebin(hist, edges, new_edges):
    """Redistribute ``hist`` over ``new_edges`` by CDF interpolation so
    histograms accumulated over different activation ranges merge without
    capping the range at the first batch's max."""
    cdf = _np.concatenate([[0.0], _np.cumsum(hist, dtype=_np.float64)])
    new_cdf = _np.interp(new_edges, edges, cdf,
                         left=0.0, right=float(cdf[-1]))
    return _np.diff(new_cdf)


class _Collector:
    """Forward-hook activation statistics collector (parity:
    _LayerOutputCollector / _LayerHistogramCollector)."""

    def __init__(self, mode="naive", num_bins=1024):
        self.mode = mode
        self.num_bins = num_bins
        self.stats: Dict[str, dict] = {}

    def hook(self, name):
        def _h(block, inputs, output):
            arr = inputs[0]
            if not isinstance(arr, NDArray):
                return
            a = _np.asarray(arr.asnumpy())
            st = self.stats.setdefault(name, {"min": _np.inf,
                                              "max": -_np.inf,
                                              "amax": 0.0, "hist": None})
            st["min"] = min(st["min"], float(a.min()))
            st["max"] = max(st["max"], float(a.max()))
            amax = float(_np.abs(a).max())
            st["amax"] = max(st["amax"], amax)
            if self.mode == "entropy":
                rng = (0, max(st["amax"], 1e-8))
                h, edges = _np.histogram(_np.abs(a), bins=self.num_bins,
                                         range=rng)
                if st["hist"] is None:
                    st["hist"], st["edges"] = h.astype(_np.float64), edges
                elif edges[-1] <= st["edges"][-1]:
                    # rebin the new batch into the existing (wider) edges
                    st["hist"] += _rebin(h, edges, st["edges"])
                else:
                    # range grew: rebin the ACCUMULATED hist into the new,
                    # wider edges (fixes first-batch-range capping)
                    st["hist"] = _rebin(st["hist"], st["edges"], edges) + h
                    st["edges"] = edges
        return _h

    def threshold(self, name):
        st = self.stats[name]
        if self.mode == "entropy" and st.get("hist") is not None:
            return calib_thresholds_entropy(st["hist"], st["edges"])
        return st["amax"]


class QuantizedDense(HybridBlock):
    """int8 twin of nn.Dense (reference: quantized_fully_connected)."""

    def __init__(self, float_dense: nn.Dense, input_threshold: float,
                 **kwargs):
        super().__init__(**kwargs)
        w = float_dense.weight.data().asnumpy()
        amax_w = float(_np.abs(w).max()) or 1.0
        self._min_w, self._max_w = -amax_w, amax_w
        sw = amax_w / 127.0
        self._wq = NDArray(_np.clip(_np.round(w / sw), -127, 127)
                           .astype(_np.int8))
        self._bias = float_dense.bias.data() \
            if float_dense.bias is not None else None
        self._thresh = float(input_threshold) or 1.0
        self._flatten = float_dense._flatten
        self._act = float_dense.act

    def hybrid_call(self, x):
        from .. import ndarray as nd
        if self._flatten and len(x.shape) > 2:
            x = nd.flatten(x)
        q, mn, mx_ = nd.quantize_v2(x, min_calib_range=-self._thresh,
                                    max_calib_range=self._thresh)
        out, _, _ = nd.quantized_fully_connected(
            q, self._wq, self._bias, mn, mx_,
            self._min_w, self._max_w)
        if self._act is not None:
            out = self._act(out)
        return out

    def forward(self, *args):
        from ..symbol.symbol import Symbol as _Sym
        if any(isinstance(a, _Sym) for a in args):
            raise MXNetError(
                "quantized layers cannot be traced symbolically; export "
                "the float net, then quantize after loading")
        return self.hybrid_call(*args)



class QuantizedConv2D(HybridBlock):
    """int8 twin of nn.Conv2D (reference: quantized_conv)."""

    def __init__(self, float_conv, input_threshold: float, **kwargs):
        super().__init__(**kwargs)
        w = float_conv.weight.data().asnumpy()
        amax_w = float(_np.abs(w).max()) or 1.0
        self._min_w, self._max_w = -amax_w, amax_w
        sw = amax_w / 127.0
        self._wq = NDArray(_np.clip(_np.round(w / sw), -127, 127)
                           .astype(_np.int8))
        self._bias = float_conv.bias.data() \
            if float_conv.bias is not None else None
        self._kwargs = dict(float_conv._kwargs)
        self._thresh = float(input_threshold) or 1.0
        self._act = float_conv.act

    def hybrid_call(self, x):
        from .. import ndarray as nd
        q, mn, mx_ = nd.quantize_v2(x, min_calib_range=-self._thresh,
                                    max_calib_range=self._thresh)
        out, _, _ = nd.quantized_conv(
            q, self._wq, self._bias, mn, mx_,
            self._min_w, self._max_w,
            stride=self._kwargs["stride"], pad=self._kwargs["pad"],
            dilate=self._kwargs["dilate"],
            num_group=self._kwargs["num_group"])
        if self._act is not None:
            out = self._act(out)
        return out

    def forward(self, *args):
        from ..symbol.symbol import Symbol as _Sym
        if any(isinstance(a, _Sym) for a in args):
            raise MXNetError(
                "quantized layers cannot be traced symbolically; export "
                "the float net, then quantize after loading")
        return self.hybrid_call(*args)



def quantize_net(net: HybridBlock, calib_data=None, calib_mode="naive",
                 quantized_dtype="int8", num_calib_batches=None,
                 exclude_layers: Optional[List[str]] = None):
    """Post-training-quantize a Gluon net IN PLACE and return it
    (parity: contrib.quantization.quantize_net).

    calib_data: iterable of input batches (NDArray or tuple); required.
    calib_mode: 'naive' (min/max) or 'entropy' (KL thresholds).
    """
    if quantized_dtype != "int8":
        raise MXNetError("only int8 quantization is supported")
    if calib_mode not in ("naive", "entropy"):
        raise MXNetError(f"unknown calib_mode {calib_mode!r}")
    if calib_data is None:
        raise MXNetError("quantize_net requires calibration data")
    exclude = set(exclude_layers or [])

    # calibration must observe EAGER arrays (hooks read values), and the
    # layer swap invalidates any compiled graph: drop hybridization and
    # caches on the whole tree first
    was_active = getattr(net, "_active", False)
    net.hybridize(False)

    # 1. attach collectors to every quantizable leaf
    collector = _Collector(mode=calib_mode)
    targets = []

    def find(block, path=""):
        for name, child in block._children.items():
            p = f"{path}.{name}" if path else name
            if isinstance(child, (nn.Dense, nn.Conv2D)):
                if p not in exclude and child.weight._shape_known():
                    targets.append((block, name, p, child))
                    child.register_forward_hook(collector.hook(p))
            else:
                find(child, p)

    # hooks must fire on inputs; our forward hooks get (block, args, out)
    find(net)
    if not targets:
        raise MXNetError("no quantizable layers found (Dense/Conv2D)")

    # 2. run calibration batches
    for i, batch in enumerate(calib_data):
        if num_calib_batches is not None and i >= num_calib_batches:
            break
        xs = batch if isinstance(batch, (tuple, list)) else (batch,)
        net(*xs)

    # 3. swap in quantized twins
    for parent, name, path, child in targets:
        if path not in collector.stats:
            continue
        thresh = collector.threshold(path)
        if isinstance(child, nn.Dense):
            q = QuantizedDense(child, thresh)
        else:
            q = QuantizedConv2D(child, thresh)
        parent._children[name] = q
        setattr(parent, name, q)
    if was_active:
        net.hybridize(True)
    return net
