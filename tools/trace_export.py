"""Perfetto/Chrome trace export of flight-recorder timelines, and the
``obssmoke`` CI gate.

``tools/trace_summary.py`` reads the DEVICE side of a profile (XLA
op lanes, overlap ratios); this tool renders the HOST side — the
semantic spans the flight recorder (serve/events.py) captured: what
every router/engine/slot/trainer lane was doing and when. Load the
output at https://ui.perfetto.dev (or chrome://tracing): one timeline
with

  - a process per component (router, replica<i>/engine, trainer,
    checkpoint, supervisor);
  - per-slot lanes inside an engine: each request's residency
    (ADMIT → TERMINAL/PREEMPT, named by request id and outcome) and
    every prefill chunk as duration spans;
  - a ``steps`` lane of decode/verify steps (width + live occupancy
    in the args);
  - instants for the control plane: SUBMIT, DISPATCH, REQUEUE,
    BROWNOUT, REPLICA_HEALTH, CHECKPOINT_COMMIT, TRAIN_STEP,
    SUPERVISOR_*, CHAOS injections.

Usage:
  python tools/trace_export.py --events events.json --out trace.json
      # events.json = FlightRecorder.dump_events() output
  python tools/trace_export.py --smoke
      # the obssmoke CI stage (ci/run.sh): runs a seeded chaos
      # scenario with the recorder on, asserts the postmortem dump
      # names the injected fault and validates against the schema,
      # and asserts the Perfetto export of a mixed prefill/decode/
      # preemption run validates and shows per-slot lanes.

The export is pure host-side JSON shaping — no jax, no device work.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# request-lifecycle instants that are NOT span endpoints; everything
# else is handled structurally below
_INSTANT_TYPES = ("SUBMIT", "DISPATCH", "REQUEUE", "BROWNOUT",
                  "REPLICA_HEALTH", "CHECKPOINT_COMMIT", "TRAIN_STEP",
                  "SUPERVISOR_RESTART", "SUPERVISOR_GIVEUP", "CHAOS")


def _as_dicts(events):
    out = []
    for e in events:
        out.append(e if isinstance(e, dict) else e.to_dict())
    return out


def to_perfetto(events) -> dict:
    """Convert a flight-recorder event list (``Event`` objects or
    their ``to_dict`` form, any mix of components) into a Chrome
    trace-JSON dict: ``{"traceEvents": [...], "displayTimeUnit":
    "ms"}``. Timestamps are rebased to the earliest event (Perfetto
    wants microseconds from a zero-ish origin, not perf_counter's
    arbitrary epoch)."""
    events = _as_dicts(events)
    if not events:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    origin = min(e["ts"] for e in events)

    def us(ts):
        return (ts - origin) * 1e6

    pids = {}                            # component -> pid
    trace = []

    def pid_of(component):
        if component not in pids:
            pids[component] = len(pids) + 1
            trace.append({"ph": "M", "name": "process_name",
                          "pid": pids[component], "tid": 0,
                          "args": {"name": component}})
        return pids[component]

    # request residency spans: (component, request_id) -> the open
    # ADMIT event; closed by the same request's TERMINAL or PREEMPT
    open_admit = {}
    for e in events:
        comp = e["component"]
        pid = pid_of(comp)
        et = e["etype"]
        data = dict(e.get("data", {}))
        rid = e.get("request_id")
        if et == "ADMIT":
            open_admit[(comp, rid)] = e
            continue
        if et in ("TERMINAL", "PREEMPT"):
            adm = open_admit.pop((comp, rid), None)
            if adm is not None:
                slot = adm.get("data", {}).get("slot", 0)
                name = f"req {rid}"
                if et == "TERMINAL":
                    name += f" ({data.get('outcome', '?')})"
                else:
                    name += " (preempted)"
                trace.append({
                    "ph": "X", "name": name, "cat": "request",
                    "pid": pid, "tid": f"slot{slot}",
                    "ts": us(adm["ts"]),
                    "dur": max(us(e["ts"]) - us(adm["ts"]), 1.0),
                    "args": {**adm.get("data", {}), **data,
                             "request_id": rid}})
            else:
                # terminal without residency (shed/cancel-from-queue):
                # an instant on the events lane
                trace.append({
                    "ph": "i", "s": "t", "name": f"{et} req {rid} "
                    f"{data.get('outcome', '')}".strip(),
                    "cat": "request", "pid": pid, "tid": "events",
                    "ts": us(e["ts"]), "args": data})
            if et == "PREEMPT":          # the instant marks the cause
                trace.append({
                    "ph": "i", "s": "t", "name": f"PREEMPT req {rid}",
                    "cat": "request", "pid": pid, "tid": "events",
                    "ts": us(e["ts"]), "args": data})
            continue
        if et == "PREFILL_CHUNK":
            trace.append({
                "ph": "X", "cat": "prefill",
                "name": f"prefill[{data.get('start', 0)}:+"
                        f"{data.get('n', 0)}]",
                "pid": pid, "tid": f"slot{data.get('slot', 0)}",
                "ts": us(e["ts"]),
                "dur": max(data.get("dur_s", 0.0) * 1e6, 1.0),
                "args": {**data, "request_id": rid}})
            continue
        if et == "DECODE_STEP":
            w = data.get("width", 1)
            trace.append({
                "ph": "X", "cat": "decode",
                "name": "verify" if w > 1 else "decode",
                "pid": pid, "tid": "steps", "ts": us(e["ts"]),
                "dur": max(data.get("dur_s", 0.0) * 1e6, 1.0),
                "args": data})
            continue
        if et in _INSTANT_TYPES:
            name = et
            if rid is not None:
                name += f" req {rid}"
            elif e.get("entity"):
                name += f" {e['entity']}"
            trace.append({"ph": "i", "s": "t", "name": name,
                          "cat": "control", "pid": pid,
                          "tid": "events", "ts": us(e["ts"]),
                          "args": data})
            continue
        trace.append({"ph": "i", "s": "t", "name": et, "cat": "other",
                      "pid": pid, "tid": "events", "ts": us(e["ts"]),
                      "args": data})
    # a still-open residency at export time renders as a span to the
    # last event (the honest "it was live when the recording stopped")
    end = max(e["ts"] for e in events)
    for (comp, rid), adm in open_admit.items():
        trace.append({
            "ph": "X", "name": f"req {rid} (live)", "cat": "request",
            "pid": pid_of(comp),
            "tid": f"slot{adm.get('data', {}).get('slot', 0)}",
            "ts": us(adm["ts"]),
            "dur": max(us(end) - us(adm["ts"]), 1.0),
            "args": {**adm.get("data", {}), "request_id": rid}})
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


def validate_trace(trace: dict) -> None:
    """Raise ValueError unless ``trace`` is a loadable Chrome/Perfetto
    trace-JSON object: a traceEvents list whose entries carry the
    required phase fields with sane types, and the whole thing
    JSON-serializable (the obssmoke "export loads" gate)."""
    if not isinstance(trace, dict) or "traceEvents" not in trace:
        raise ValueError("trace must be a dict with 'traceEvents'")
    if not isinstance(trace["traceEvents"], list):
        raise ValueError("traceEvents must be a list")
    for ev in trace["traceEvents"]:
        for key in ("ph", "name", "pid", "tid"):
            if key not in ev:
                raise ValueError(f"trace event missing {key!r}: {ev}")
        if ev["ph"] not in ("X", "i", "M", "B", "E", "C"):
            raise ValueError(f"unknown phase {ev['ph']!r}")
        if ev["ph"] != "M":
            if not isinstance(ev.get("ts"), (int, float)) or \
                    ev["ts"] < 0:
                raise ValueError(f"bad ts in {ev}")
        if ev["ph"] == "X" and not (isinstance(ev.get("dur"),
                                               (int, float))
                                    and ev["dur"] >= 0):
            raise ValueError(f"X event needs dur >= 0: {ev}")
    try:
        json.dumps(trace)
    except (TypeError, ValueError) as e:
        raise ValueError(f"trace is not JSON-serializable: {e}")


def export_file(events_path: str, out_path: str) -> dict:
    with open(events_path) as f:
        payload = json.load(f)
    events = payload["events"] if isinstance(payload, dict) else payload
    trace = to_perfetto(events)
    validate_trace(trace)
    with open(out_path, "w") as f:
        json.dump(trace, f, indent=1)
        f.write("\n")
    return trace


# --------------------------------------------------------------------- #
# obssmoke (ci/run.sh): the end-to-end observability gate
# --------------------------------------------------------------------- #

def _smoke(tmpdir: str) -> int:
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.models import gpt as g
    from incubator_mxnet_tpu.serve import (InferenceEngine, Request,
                                           Tier, build_fleet)
    from incubator_mxnet_tpu.serve.chaos import (KillReplica,
                                                 run_fleet_chaos)
    from incubator_mxnet_tpu.serve.events import (EventType,
                                                  validate_postmortem)
    errors = []

    mx.random.seed(0)
    model = g.gpt_mini(vocab_size=64, max_length=64)
    model.initialize()
    rng = np.random.RandomState(0)

    def _prompt(n):
        return rng.randint(0, 64, size=(n,)).astype(np.int32)

    # -- 1. seeded replica kill with the recorder on: the postmortem
    #       must name the injected fault, the killed replica, and the
    #       re-queued requests, and validate against the schema ------- #
    print("== obssmoke: seeded replica kill → postmortem")
    rt = build_fleet(model, 2,
                     engine_kw=dict(num_slots=2, page_size=8,
                                    max_len=64),
                     max_requeues=0)
    rt.flight.postmortem_dir = tmpdir
    reqs = [Request(_prompt(5), max_new_tokens=6) for _ in range(4)]
    inj = KillReplica(0, at_step=1, phase="decode")
    run_fleet_chaos(rt, reqs, [inj])
    if not inj.fired:
        errors.append("obssmoke: the kill never fired")
    failed = [r for r in reqs
              if r.outcome is not None and
              r.outcome.value == "FAILED_REPLICA"]
    if not failed:
        errors.append("obssmoke: no FAILED_REPLICA at the requeue "
                      "bound — the postmortem trigger never ran")
    pms = list(rt.flight.postmortems)
    if not pms:
        errors.append("obssmoke: no postmortem dumped")
    for pm in pms:
        try:
            validate_postmortem(pm)
        except ValueError as e:
            errors.append(f"obssmoke: postmortem fails schema: {e}")
    if pms:
        # a bound-hit dumps per request as it lands; the recorder
        # keeps the OLDEST max_postmortems dumps (the first failure is
        # the root cause), so no single dump is guaranteed to name
        # every later casualty — assert over the UNION of the kept
        # dumps' timelines
        all_evs = [e for pm in pms for e in pm["events"]]
        ets = [(e["etype"], e.get("data", {})) for e in all_evs]
        if not any(t == "CHAOS" for t, _ in ets):
            errors.append("obssmoke: postmortems lack the injected "
                          "fault's CHAOS event")
        if not any(t == "REPLICA_HEALTH" and
                   d.get("to_state") == "DEAD" for t, d in ets):
            errors.append("obssmoke: postmortems lack the replica "
                          "death event")
        # max_requeues=0: every killed in-flight request goes straight
        # to FAILED_REPLICA — the TERMINAL/REQUEUE events must name
        # them all somewhere across the kept dumps
        named = {e.get("request_id") for e in all_evs
                 if e["etype"] in ("TERMINAL", "REQUEUE")}
        missing = [r.request_id for r in failed
                   if r.request_id not in named]
        if missing:
            errors.append(f"obssmoke: postmortem timelines do not "
                          f"name re-queued/failed requests {missing}")
        on_disk = pms[0].get("path")
        if not (on_disk and os.path.exists(on_disk)):
            errors.append("obssmoke: postmortem file was not written")
        else:
            with open(on_disk) as f:
                validate_postmortem(json.load(f))

    # -- 2. mixed prefill/decode/preemption run → Perfetto export
    #       validates and shows per-slot lanes ----------------------- #
    print("== obssmoke: mixed prefill/decode/preemption → Perfetto")
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64,
                          chunk_pages=1, max_preemptions=4)
    batch = [Request(_prompt(20), max_new_tokens=8, tier=Tier.BATCH)
             for _ in range(3)]
    for r in batch:
        eng.submit(r)
    for _ in range(6):
        eng.step()
    lat = [Request(_prompt(5), max_new_tokens=4, tier=Tier.LATENCY)
           for _ in range(2)]
    for r in lat:
        eng.submit(r)
    steps = 0
    while any(r.outcome is None for r in batch + lat):
        eng.step()
        steps += 1
        if steps > 2000:
            errors.append("obssmoke: engine failed to drain")
            break
    if eng.preemptions < 1:
        errors.append("obssmoke: the LATENCY arrivals never preempted "
                      "a BATCH slot — the mix is not exercising "
                      "preemption")
    events_path = os.path.join(tmpdir, "events.json")
    trace_path = os.path.join(tmpdir, "trace.json")
    eng.flight.dump_events(events_path)
    try:
        trace = export_file(events_path, trace_path)
    except ValueError as e:
        errors.append(f"obssmoke: Perfetto export invalid: {e}")
        trace = {"traceEvents": []}
    tids = {ev["tid"] for ev in trace["traceEvents"]
            if ev["ph"] == "X"}
    slot_lanes = {t for t in tids if str(t).startswith("slot")}
    if len(slot_lanes) < 2:
        errors.append(f"obssmoke: expected >=2 per-slot lanes in the "
                      f"export, got {sorted(slot_lanes)}")
    cats = {ev.get("cat") for ev in trace["traceEvents"]}
    for want in ("request", "prefill", "decode"):
        if want not in cats:
            errors.append(f"obssmoke: export lacks {want!r} spans")
    evs = eng.flight.events()
    if not any(e.etype is EventType.PREEMPT for e in evs):
        errors.append("obssmoke: no PREEMPT event recorded")

    # -- 2.5 client edge: the HTTP front end's lane must ride the
    #        same timeline — request residency spans on a "frontend"
    #        process carrying the HTTP status and disconnect cause --- #
    print("== obssmoke: HTTP/SSE client edge → frontend lane")
    from incubator_mxnet_tpu.serve import (ServeFrontend,
                                           stream_completion)
    eng_f = InferenceEngine(model, num_slots=2, page_size=8,
                            max_len=64)
    with ServeFrontend(eng_f) as fe:
        ok = stream_completion("127.0.0.1", fe.bound_port,
                               {"prompt": [3, 4, 5],
                                "max_new_tokens": 6})
        cut = stream_completion("127.0.0.1", fe.bound_port,
                                {"prompt": [6, 7, 8],
                                 "max_new_tokens": 48},
                                abort_after_tokens=2)
        tdead = time.perf_counter() + 30
        while len(fe.finished) < 2 and time.perf_counter() < tdead:
            time.sleep(0.02)
    if ok["final"] is None or not cut["aborted"]:
        errors.append("obssmoke: frontend drive did not produce one "
                      "completion + one disconnect")
    ftrace = to_perfetto(eng_f.flight.events())
    try:
        validate_trace(ftrace)
    except ValueError as e:
        errors.append(f"obssmoke: frontend export invalid: {e}")
    fprocs = {ev["args"]["name"] for ev in ftrace["traceEvents"]
              if ev["ph"] == "M" and ev["name"] == "process_name"}
    if "frontend" not in fprocs:
        errors.append(f"obssmoke: export lacks the frontend lane: "
                      f"{sorted(fprocs)}")
    fe_spans = [ev for ev in ftrace["traceEvents"]
                if ev["ph"] == "X" and ev.get("cat") == "request" and
                "http_status" in ev.get("args", {})]
    statuses = {ev["args"]["http_status"] for ev in fe_spans}
    if not {200, 499} <= statuses:
        errors.append(f"obssmoke: frontend request spans lack the "
                      f"200-completion/499-disconnect statuses: "
                      f"{sorted(statuses)}")
    if not any("disconnect" in str(ev["args"].get("cause", ""))
               for ev in fe_spans):
        errors.append("obssmoke: no frontend span carries the "
                      "client-disconnect cause")

    # -- 3. fleet timeline export (router + replica lanes merge) ----- #
    fleet_trace = to_perfetto(rt.flight_events())
    try:
        validate_trace(fleet_trace)
    except ValueError as e:
        errors.append(f"obssmoke: fleet export invalid: {e}")
    procs = {ev["args"]["name"] for ev in fleet_trace["traceEvents"]
             if ev["ph"] == "M" and ev["name"] == "process_name"}
    if "router" not in procs or not any(p.startswith("replica")
                                        for p in procs):
        errors.append(f"obssmoke: fleet export lacks router/replica "
                      f"lanes: {sorted(procs)}")

    for e in errors:
        print(f"FAIL: {e}", file=sys.stderr)
    if not errors:
        print(f"obssmoke ok: postmortem + schema + Perfetto export "
              f"({len(trace['traceEvents'])} engine trace events, "
              f"{len(fleet_trace['traceEvents'])} fleet trace events)")
    return 1 if errors else 0


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--events", help="events JSON "
                    "(FlightRecorder.dump_events output)")
    ap.add_argument("--out", help="trace JSON output path")
    ap.add_argument("--smoke", action="store_true",
                    help="run the obssmoke CI gate")
    args = ap.parse_args()
    if args.smoke:
        import tempfile
        with tempfile.TemporaryDirectory() as td:
            sys.exit(_smoke(td))
    if not args.events or not args.out:
        ap.error("need --events and --out (or --smoke)")
    trace = export_file(args.events, args.out)
    print(f"wrote {args.out} ({len(trace['traceEvents'])} events) — "
          f"load at https://ui.perfetto.dev")


if __name__ == "__main__":
    main()
