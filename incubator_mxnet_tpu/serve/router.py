"""Fleet router: N serving replicas behind one admission surface.

One excellent engine is not a serving tier — "heavy traffic from
millions of users" (ROADMAP item 1) means N ``InferenceEngine``
replicas, and the two things a fleet adds that no single engine can:

  - **Cache-affinity routing.** The 2.7x warm-vs-cold tokens/s win of
    prefix caching (BENCH_SERVE.json) only survives scale-out if a
    request lands where its prefix lives. Admission probes every
    SERVING replica's prefix index (``engine.prefix_probe`` — a
    read-only query, no refcounts, no LRU ticks) and routes to the
    longest match; when nobody has the prefix, it SPILLS to the
    least-estimated-delay replica (the ``health_snapshot`` EWMA/queue
    signals). Affinity is a preference among replicas WITH capacity —
    a full replica is never chosen over an idle one just because it is
    warm (the spill rule).
  - **Structured failover.** A replica death mid-decode must become a
    bounded re-queue, never a lost (or double-finished) request.
    Per-replica health states:

        SERVING   routable; heartbeats healthy
        WARMING   just admitted (``add_replica``) or fresh from a
                  rolling weight swap: routable for SPILL/round-robin
                  only — the affinity probe skips it until it has
                  earned ``warmup_steps`` consecutive healthy steps
                  (compile steps are heartbeat-exempt AND not warmup
                  evidence, exactly the breaker's cold-start rule),
                  then it graduates to SERVING
        DEGRADED  circuit breaker open after ``breaker_failures``
                  consecutive heartbeat misses (a step slower than
                  ``heartbeat_timeout_s``): no new admissions; the
                  replica is only stepped as a HALF-OPEN PROBE on a
                  seeded-jitter exponential backoff schedule;
                  ``probe_recovery`` consecutive healthy probes close
                  the breaker back to SERVING
        DRAINING  leaving the fleet (``remove_replica``) or swapping
                  weights (``upgrade_replica``): no new admissions;
                  queued attempts withdraw back to the router, decode-
                  ready slots migrate to siblings (PR-18 capsules,
                  replay fallback), still-prefilling slots finish in
                  place — the router's own step loop finalises the
                  drain (retire or warm_start) the pass the last slot
                  leaves, so a supervisor death mid-transition can
                  never wedge the fleet
        DEAD      the replica raised out of a step (``ReplicaKilled``
                  or any engine exception — its state can no longer be
                  trusted): terminal, never probed again
        RETIRED   drained out clean by ``remove_replica`` and shut
                  down: terminal, never stepped again. Retired (and
                  dead) replicas stay in ``self.replicas`` as
                  TOMBSTONES — replica index == list position is
                  load-bearing across every in-flight bookkeeping
                  structure, so membership changes never renumber

    On death every in-flight request of that replica is RE-QUEUED with
    its already-emitted tokens preserved: the replay attempt's prompt
    is ``original prompt + emitted tokens`` submitted through NORMAL
    admission on another replica — so the prefix cache absorbs the
    redone work, and because sampling is keyed by absolute sequence
    position under a router-pinned per-request seed, the continuation
    is bit-identical to the tokens the dead replica would have
    produced (greedy trivially; temperature by the per-request RNG
    convention, docs/SERVING.md). Re-queues are BOUNDED:
    ``max_requeues`` per request, after which the request terminates
    ``FAILED_REPLICA`` — a structured give-up with a ``retry_after_s``
    hint, never a silent loss. An in-flight attempt that a replica
    sheds underneath the router (SIGTERM drain / ``shutdown()``) is
    re-queued through the same bounded path.

Fleet-wide backpressure: ``max_queue`` bounds the router's own queue
and ``max_queue_delay_s`` sheds at ROUTER admission when every serving
replica's estimated delay (plus the router backlog riding on top) is
over the limit — the fleet refuses early instead of queuing blindly
into replicas whose own shedding would only bounce the request around.
Every shed/deadline-class terminal the router records carries the same
machine-readable ``retry_after_s`` contract as the engine's
(``Outcome.retryable`` — one backoff surface for clients at both
levels).

Everything here is host-side scheduling over the engines' existing
data-plane contracts: no program compiles, no engine invariant bends —
the fleet chaos harness (serve/chaos.py ``KillReplica`` /
``SlowReplica`` / ``FlappingReplica``, tools/chaos_bench.py
``--fleet``) asserts exactly-one-terminal-outcome, survivor token
parity, per-step page audits on surviving replicas, and the jit-once
compile discipline per replica, under every injected failure.
"""

from __future__ import annotations

import dataclasses
import enum
import time
from collections import deque
from typing import List, Optional

import numpy as np

from ..base import MXNetError
from .engine import InferenceEngine, Request
from .events import EventType, resolve_recorder, terminal_fields
from .outcomes import Outcome
from .slo import Tier, resolve_tier_policies, wants_rebalance
from .transport import PageTransport

__all__ = ["Router", "Replica", "ReplicaState", "ReplicaKilled",
           "build_fleet"]

_ROLES = ("prefill", "decode", "mixed")


class ReplicaState(enum.Enum):
    SERVING = "SERVING"
    WARMING = "WARMING"       # cold admit / post-upgrade: spill-only
    DEGRADED = "DEGRADED"
    DRAINING = "DRAINING"     # leaving or upgrading: no admissions
    DEAD = "DEAD"
    RETIRED = "RETIRED"       # drained out clean: tombstone

    def __str__(self) -> str:
        return self.value


class ReplicaKilled(MXNetError):
    """The process-death fault: a killed replica raises this from every
    subsequent step — the in-process stand-in for 'the replica's host
    stopped answering' (serve/chaos.py ``KillReplica``)."""


class Replica:
    """One engine plus the router's view of its health. The router
    never reads a DEAD replica's engine again — its in-flight requests
    are harvested from the ROUTER'S own bookkeeping (the token stream
    it already received), not from the dead engine's memory."""

    def __init__(self, idx: int, engine: InferenceEngine,
                 role: str = "mixed"):
        if role not in _ROLES:
            raise MXNetError(f"replica role must be one of {_ROLES}, "
                             f"got {role!r}")
        self.idx = idx
        self.engine = engine
        # disaggregated serving role: a 'prefill' replica runs chunked
        # prefill only — the router streams each slot to a decode/
        # mixed sibling the moment prefill publishes its pages; a
        # 'decode' replica takes no fresh admissions while any
        # prefill/mixed sibling can (its slots arrive by migration).
        # 'mixed' (default) does both — a single-role fleet behaves
        # exactly as before this field existed.
        self.role = role
        self.state = ReplicaState.SERVING
        self.killed: Optional[str] = None    # chaos kill reason
        self.delay_s = 0.0                   # chaos per-step stall
        self.consecutive_misses = 0          # heartbeat misses in a row
        self.probe_successes = 0
        self.backoff_s: Optional[float] = None
        self.next_probe_t = 0.0
        self.breaker_opens = 0
        self.probes = 0
        self.steps = 0
        self.death_detail = ""
        self.warm_steps = 0                  # healthy steps while WARMING
        self.drain_reason: Optional[str] = None   # "retire" | "upgrade"
        self.upgrade_src: Optional[dict] = None   # warm_start kwargs

    def kill(self, reason: str = "killed"):
        """Mark the replica process dead: every later ``step`` raises
        ``ReplicaKilled`` (the chaos harness's kill switch)."""
        self.killed = reason

    def _traces(self) -> int:
        e = self.engine
        return (e.decode_trace_count + e.verify_trace_count +
                e.prefill_trace_count + e.copy_trace_count)

    def step(self):
        """One engine scheduler step. Returns ``(advanced, wall_s,
        compiled)``; raises when the replica is dead. ``compiled``
        flags a step that traced a new program — expected-slow, so the
        router exempts it from the heartbeat (a cold replica warming
        its programs is not a sick replica)."""
        if self.killed is not None:
            raise ReplicaKilled(f"replica {self.idx} {self.killed}")
        t0 = time.perf_counter()
        tr0 = self._traces()
        if self.delay_s:
            time.sleep(self.delay_s)         # chaos SlowReplica stall
        n = self.engine.step()
        self.steps += 1
        return n, time.perf_counter() - t0, self._traces() > tr0


@dataclasses.dataclass(eq=False)        # identity semantics: tracked
class _Tracked:                         # entries live in lists and the
                                        # generated __eq__ would compare
                                        # the client's ndarray fields
    """The router's record of one CLIENT request: which replica is
    serving its current attempt, and how many times it has been
    re-queued. The client ``Request`` accumulates the token stream
    across attempts; each attempt is a fresh engine-level ``Request``
    (resume-from-suffix replay)."""

    client: Request
    attempt: Optional[Request] = None
    replica: Optional[int] = None
    requeues: int = 0


class Router:
    """Host-side fleet front: cache-affinity admission + bounded
    replica failover over ``engines`` (see the module docstring).

    ``affinity=False`` degrades routing to pure round-robin over
    serving replicas with capacity — the control arm of
    ``serve_bench --fleet``. ``replica_queue_depth`` caps how many
    requests the router parks in any one replica's own admission queue
    (shallow per-replica queues keep the blast radius of a death
    small and the spill estimate honest); it defaults to the replica's
    slot count."""

    def __init__(self, engines: List[InferenceEngine], *,
                 affinity: bool = True, max_requeues: int = 2,
                 heartbeat_timeout_s: float = 0.75,
                 breaker_failures: int = 3,
                 probe_backoff_s: float = 0.05,
                 probe_backoff_max_s: float = 2.0,
                 probe_recovery: int = 2,
                 replica_queue_depth: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 max_queue_delay_s: Optional[float] = None,
                 stall_steps: int = 2000, seed: int = 0,
                 tier_policies: Optional[dict] = None,
                 roles: Optional[List[str]] = None,
                 rebalance: bool = False,
                 fleet_preempt: bool = False,
                 warmup_steps: int = 2,
                 recorder=None):
        if not engines:
            raise MXNetError("a fleet needs at least one replica")
        if roles is not None and len(roles) != len(engines):
            raise MXNetError(f"roles ({len(roles)}) must match "
                             f"engines ({len(engines)})")
        if roles is not None and engines and \
                all(r == "decode" for r in roles):
            raise MXNetError("a fleet of only 'decode' replicas can "
                             "never prefill — include a 'prefill' or "
                             "'mixed' replica")
        self.replicas = [
            Replica(i, e, role=(roles[i] if roles is not None
                                else "mixed"))
            for i, e in enumerate(engines)]
        # the router's own flight recorder (serve/events.py): CLIENT
        # lifecycle + routing/failover/replica-health events. Each
        # replica keeps its OWN recorder (attempt-level events and
        # histograms must not merge into the client view); a replica
        # still carrying the default lane name is renamed replica<i>
        # so a merged export (``flight_events``) reads as a fleet.
        self.flight = resolve_recorder(recorder)
        self._component = "router"
        for rep in self.replicas:
            if getattr(rep.engine, "_component", None) == "engine":
                rep.engine._component = f"replica{rep.idx}"
        self.affinity = bool(affinity)
        self.max_requeues = int(max_requeues)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.breaker_failures = int(breaker_failures)
        self.probe_backoff_s = float(probe_backoff_s)
        self.probe_backoff_max_s = float(probe_backoff_max_s)
        self.probe_recovery = int(probe_recovery)
        self.replica_queue_depth = replica_queue_depth
        self.warmup_steps = int(warmup_steps)
        self.max_queue = None if max_queue is None else int(max_queue)
        self.max_queue_delay_s = max_queue_delay_s
        self.stall_steps = int(stall_steps)
        self._rng = np.random.RandomState(seed)
        # jitter draws MUST NOT share the seed stream: a breaker event
        # interleaving rand() calls would shift every later request's
        # pinned seed, silently breaking survivor parity between a
        # faulted and a fault-free run of a temperature workload
        self._jitter_rng = np.random.RandomState(
            (seed + 0x9E3779B9) & 0xFFFFFFFF)   # stay in the u32 seed
                                                # domain for any seed
        self._queue: deque = deque()         # _Tracked awaiting dispatch
        self._inflight: List[_Tracked] = []
        self._rr = 0                         # round-robin cursor
        self._stall = 0
        self.steps = 0
        self.health: dict = {o.value: 0 for o in Outcome}
        self.health_by_tier: dict = {
            t.value: {o.value: 0 for o in Outcome} for t in Tier}
        # router-level tier scoping (serve/slo.py): per-tier queue
        # bound / delay limit / default deadline on the ROUTER'S
        # admission surface (each engine still applies its own)
        self._tier_policies = resolve_tier_policies(tier_policies)
        self.requeues = 0
        self.replica_deaths = 0
        self.breaker_opens = 0
        self.probes = 0
        self.recoveries = 0
        self.affinity_routed = 0
        self.tier_affinity_routed = 0    # won on the lower-tier axis
        self.spill_routed = 0
        # page transport (serve/transport.py): live slot migration
        # between replicas — role-split streaming, drain-before-
        # warm_start, brownout rebalancing, fleet-aware preemption.
        # Every failed transfer degrades to the replay fallback
        # (resume-from-suffix re-queue), loudly, WITHOUT charging the
        # request's requeue budget: a failed optimisation is the
        # router's fault, not the request's.
        self._transport = PageTransport()
        self.rebalance = bool(rebalance)
        self.migrations = 0
        self.migrations_failed = 0
        self.migrated_pages = 0
        self.migrated_bytes = 0
        # elastic membership (add_replica / remove_replica /
        # upgrade_replica) tally — serve/metrics.py renders all three
        self.scale_ups = 0
        self.scale_downs = 0
        self.upgrades = 0
        self._fleet_preempt = bool(fleet_preempt)
        if fleet_preempt:
            # fleet-aware preemption: an engine about to preempt a
            # victim offers it to the router first — a successful
            # handoff MOVES the slot to a sibling (zero redone
            # prefill) instead of bouncing it through the queue
            for rep in self.replicas:
                rep.engine.preempt_handoff = \
                    self._make_preempt_handoff(rep.idx)
        self.log: List[str] = []

    # ------------------------------------------------------------- #
    # terminal accounting (the client-facing twin of the engine's)
    # ------------------------------------------------------------- #

    def _fleet_retry_hint(self) -> float:
        """Backoff hint from the healthiest view available: the
        smallest calibrated EWMA service time across live replicas
        (read through ``health_snapshot`` like every other router
        read of engine state)."""
        ewmas = [r.engine.health_snapshot()["ewma_service_s"]
                 for r in self.replicas
                 if r.state not in (ReplicaState.DEAD,
                                    ReplicaState.RETIRED)]
        ewmas = [e for e in ewmas if e]
        return min(ewmas) if ewmas else 0.05

    def _record_terminal(self, request: Request, outcome: Outcome,
                         detail: str = "",
                         retry_after: Optional[float] = None):
        """Exactly-once terminal recording for CLIENT requests — the
        router-level twin of the engine's ``_record_terminal``, same
        double-finish refusal, same retryable-outcomes-carry-a-hint
        contract."""
        if request.outcome is not None:
            raise MXNetError(
                f"request already terminal ({request.outcome}) — "
                f"double-finish is a router bug")
        if retry_after is None and outcome.retryable:
            retry_after = self._fleet_retry_hint()
        request.outcome = outcome
        request.detail = detail
        request.retry_after_s = retry_after
        request.finish_time = time.perf_counter()
        self.health[outcome.value] += 1
        self.health_by_tier[request.tier.value][outcome.value] += 1
        # the client-level TERMINAL event + latency histograms — the
        # engine-level twin lives in InferenceEngine._record_terminal
        # (attempts), this one counts CLIENT terminals exactly once;
        # enabled gate: the O(tokens) derivation is recorder-only work
        if self.flight.enabled:
            self.flight.emit(self._component, EventType.TERMINAL,
                             request_id=request.request_id,
                             **terminal_fields(request))

    # ------------------------------------------------------------- #
    # admission
    # ------------------------------------------------------------- #

    def _alive(self) -> List[Replica]:
        return [r for r in self.replicas
                if r.state not in (ReplicaState.DEAD,
                                   ReplicaState.RETIRED)]

    def _serving(self) -> List[Replica]:
        return [r for r in self.replicas
                if r.state is ReplicaState.SERVING]

    def _routable(self) -> List[Replica]:
        """Replicas that may take NEW admissions: SERVING plus WARMING
        (a warming replica takes spill/round-robin traffic only — the
        affinity probe in ``_route`` is restricted to SERVING, so it
        earns affinity by building its PrefixIndex from spills)."""
        return [r for r in self.replicas
                if r.state in (ReplicaState.SERVING,
                               ReplicaState.WARMING)]

    def _fleet_delay_estimate(self) -> Optional[float]:
        """Estimated admission delay for a NEWLY submitted request:
        the best serving replica's own estimate, plus the router
        backlog's waves riding on top of the fleet's total slots.
        None until any replica has a calibrated EWMA."""
        serving = self._routable()
        if not serving:
            return None
        ests, ewmas, slots = [], [], 0
        for r in serving:
            snap = r.engine.health_snapshot()
            est = snap["estimated_queue_delay_s"]
            if est is None and snap["free_slots"] > 0:
                # an uncalibrated replica with free slots can take the
                # request NOW — it must pull the fleet estimate to 0,
                # not silently drop out (shedding while a replica
                # idles would refuse work the fleet can do)
                est = 0.0
            if est is not None:
                ests.append(est)
            if snap["ewma_service_s"]:
                ewmas.append(snap["ewma_service_s"])
            slots += snap["num_slots"]
        if not ests and not ewmas:
            return None
        base = min(ests) if ests else 0.0
        if self._queue and ewmas:
            base += (len(self._queue) // max(slots, 1)) * min(ewmas)
        return base

    def _shed_one_below(self, tier: Tier) -> bool:
        """Router-queue twin of the engine's drain-lowest-tier-first
        shed: remove the most recently queued _Tracked of the lowest
        tier strictly below ``tier`` and SHED its client. Returns True
        when room was made."""
        victim = None
        for t in self._queue:
            if t.client.tier.order <= tier.order:
                continue
            if victim is None or \
                    t.client.tier.order >= victim.client.tier.order:
                victim = t
        if victim is None:
            return False
        self._queue.remove(victim)
        self._record_terminal(
            victim.client, Outcome.SHED,
            f"displaced from the router queue by a {tier.value} "
            f"submission under overload")
        return True

    def submit(self, request: Request) -> bool:
        """Fleet admission. Returns True when the request was accepted
        for routing; False when it is already terminal — SHED (fleet
        saturated / router queue bound, ``retry_after_s`` set),
        FAILED_UNSERVABLE (no replica could EVER hold it), or
        FAILED_REPLICA (no live replica at all). Tier scoping matches
        the engine's: per-tier default deadline, per-tier queue bound
        and delay limit (falling back to the router globals), and the
        global queue bound drains the lowest queued tier first."""
        request.submit_time = time.perf_counter()
        self.flight.emit(self._component, EventType.SUBMIT,
                         request_id=request.request_id,
                         tier=request.tier.value,
                         queue_depth=len(self._queue))
        pol = self._tier_policies[request.tier]
        if request.deadline_s is None and \
                pol.default_deadline_s is not None:
            request.deadline_s = float(pol.default_deadline_s)
        if request.deadline_s is not None:
            request._deadline_abs = request.submit_time + request.deadline_s
        alive = self._alive()
        if not alive:
            self._record_terminal(
                request, Outcome.FAILED_REPLICA,
                "no live replica in the fleet")
            return False
        total = int(request.prompt_ids.size) + request.max_new_tokens
        if not any(r.engine.can_serve(total) for r in alive):
            self._record_terminal(
                request, Outcome.FAILED_UNSERVABLE,
                f"request needs {total} positions but no replica can "
                f"ever hold it")
            return False
        if request.sampling is not None:
            # same fail-fast the engine applies (one shared validator,
            # serve/sampling.py) — a grammar over the wrong vocab must
            # not bounce through dispatch to die there
            err = request.sampling.validate_for(
                alive[0].engine.model.vocab_size, request.eos_id)
            if err is not None:
                self._record_terminal(request,
                                      Outcome.FAILED_UNSERVABLE, err)
                return False
        # the newcomer's OWN refusals come first (tier bound, delay
        # limit): a request about to be refused anyway must not
        # displace an innocent lower-tier victim on the way out
        if pol.max_queue is not None and \
                sum(1 for t in self._queue
                    if t.client.tier is request.tier) >= pol.max_queue:
            self._record_terminal(
                request, Outcome.SHED,
                f"{request.tier.value} router queue at its tier depth "
                f"limit {pol.max_queue}")
            return False
        delay_limit = pol.max_queue_delay_s \
            if pol.max_queue_delay_s is not None else self.max_queue_delay_s
        if delay_limit is not None:
            est = self._fleet_delay_estimate()
            if est is not None and est > delay_limit:
                self._record_terminal(
                    request, Outcome.SHED,
                    f"fleet-wide estimated delay {est:.3f}s exceeds "
                    f"{delay_limit}s for tier {request.tier.value}",
                    retry_after=est)
                return False
        if self.max_queue is not None and \
                len(self._queue) >= self.max_queue and \
                not self._shed_one_below(request.tier):
            self._record_terminal(
                request, Outcome.SHED,
                f"router queue at depth limit {self.max_queue}")
            return False
        if request.seed is None:
            # pin the sampling stream NOW: a replay attempt on another
            # replica must reproduce the original's draws exactly
            # (position-keyed RNG + same seed == same continuation)
            request.seed = int(self._rng.randint(0, 2 ** 31 - 1))
        self._queue.append(_Tracked(client=request))
        return True

    # ------------------------------------------------------------- #
    # routing
    # ------------------------------------------------------------- #

    def _capacity(self, rep: Replica, snap: dict) -> bool:
        """Will this replica take an admission right now? Respects the
        router's shallow-queue policy AND the engine's own admission
        bounds (``max_queue`` / ``max_queue_delay_s``, read from the
        snapshot) — submitting into a replica that will predictably
        shed would only churn attempt objects and engine SHED
        terminals until capacity frees."""
        eng = rep.engine
        depth = self.replica_queue_depth
        if depth is None:
            depth = eng.num_slots
        if eng.max_queue is not None:
            depth = min(depth, eng.max_queue)
        if not (snap["free_slots"] > 0 or snap["queue_depth"] < depth):
            return False
        if eng.max_queue_delay_s is not None:
            est = snap["estimated_queue_delay_s"]
            if est is not None and est > eng.max_queue_delay_s:
                return False
        return True

    def _attempt_prompt(self, tracked: _Tracked) -> np.ndarray:
        """The replay prompt: original prompt + every token already
        delivered — resume-from-suffix through normal admission."""
        c = tracked.client
        if not c.token_ids:
            return c.prompt_ids
        return np.concatenate([c.prompt_ids,
                               np.asarray(c.token_ids, np.int32)])

    def _can_hold(self, rep: Replica, tracked: _Tracked) -> bool:
        """Per-replica servability — the engine's own bound
        (``can_serve``), so routing can never drift from what a
        replica's admission will accept. In a heterogeneous fleet a
        request must never be spilled onto a replica that would FAIL
        it as unservable while a bigger sibling could serve it."""
        c = tracked.client
        return rep.engine.can_serve(int(c.prompt_ids.size) +
                                    c.max_new_tokens)

    def _route(self, tracked: _Tracked, snaps) -> Optional[Replica]:
        """Pick a replica for this (re)admission: longest prefix match
        among SERVING replicas with capacity (that can hold the
        request at all); least-estimated-delay spill when nobody has
        the prefix (or affinity is off: round-robin). None when no
        serving replica has capacity. ``snaps`` is the dispatch
        pass's one-snapshot-per-replica view (re-snapshotting every
        replica for every queued request would churn dict builds in
        the host hot loop for staleness the live read tolerated
        anyway)."""
        cands = [(r, s) for r, s in snaps
                 if self._can_hold(r, tracked)
                 and self._capacity(r, s)]
        if any(r.role != "decode" for r, _ in cands):
            # role split: a 'decode' replica's slots arrive by page
            # migration, never fresh admission — unless it is the ONLY
            # replica that can take the request (correctness over
            # purity: a request must not starve to honor a role)
            cands = [(r, s) for r, s in cands if r.role != "decode"]
        if not cands:
            return None
        if self.affinity:
            prompt = self._attempt_prompt(tracked)
            # two-axis affinity: HBM-resident prefix length first,
            # then what a replica's lower cache tiers could re-admit
            # by copy (engine.tier_probe — equals prefix_probe when
            # tiers are off, so an untiered fleet routes exactly as
            # before). A replica holding the prefix only in DRAM/disk
            # still beats a cold spill: promotion is a page copy,
            # recompute is a full prefill. WARMING replicas are
            # spill-only: the probe skips them until they graduate
            # (their index is cold anyway — probing it would only add
            # host work to the dispatch hot loop).
            best, best_key = None, (0, 0)
            for r, _ in cands:
                if r.state is not ReplicaState.SERVING:
                    continue
                key = (r.engine.prefix_probe(prompt),
                       r.engine.tier_probe(prompt))
                if key > best_key:
                    best, best_key = r, key
            if best is not None:
                if best_key[0] > 0:
                    self.affinity_routed += 1
                else:
                    self.tier_affinity_routed += 1
                return best
            # spill: least estimated delay, then shortest backlog —
            # occupancy derived from the pass view's free_slots so
            # this pass's own assignments count as load (active_slots
            # is the stale pre-pass reading)
            def load(rs):
                r, s = rs
                est = s["estimated_queue_delay_s"]
                occupied = s["num_slots"] - s["free_slots"]
                return (est if est is not None else 0.0,
                        s["queue_depth"] +
                        occupied / max(1, s["num_slots"]),
                        r.idx)
            rep = min(cands, key=load)[0]
            self.spill_routed += 1
            return rep
        rep = cands[self._rr % len(cands)][0]
        self._rr += 1
        self.spill_routed += 1
        return rep

    def _remint_if_complete(self, tracked: _Tracked) -> bool:
        """Torn-engine-death completion: a replica that died AFTER
        emitting a request's final (or EOS) token but BEFORE recording
        the terminal leaves a preserved stream that already satisfies
        the request. Re-mint the success terminal the dead replica
        owed — the stream is complete, not replayable (a replay would
        feed EOS back through the prompt, or need max_new_tokens=0,
        whose validation raise would escape run()). Returns True when
        a terminal was minted. Checked on EVERY path that would
        replay or give up (dispatch AND the requeue-budget bound —
        FAILED_REPLICA on a complete stream would tell the client to
        retry work it already has)."""
        c = tracked.client
        if c.eos_id >= 0 and int(c.eos_id) in c.token_ids:
            stop = c.token_ids.index(int(c.eos_id)) + 1
            del c.token_ids[stop:]
            del c.token_times[stop:]
            del c.token_stamps[stop:]
            self._record_terminal(
                c, Outcome.EOS,
                "completed across a replica death (EOS preserved, "
                "terminal re-minted by the router)")
            return True
        if c.max_new_tokens - len(c.token_ids) <= 0:
            self._record_terminal(
                c, Outcome.MAX_TOKENS,
                "completed across a replica death (final token "
                "preserved, terminal re-minted by the router)")
            return True
        return False

    def _make_attempt(self, tracked: _Tracked) -> Optional[Request]:
        c = tracked.client
        if self._remint_if_complete(tracked):
            return None
        remaining = c.max_new_tokens - len(c.token_ids)
        deadline = None
        if c._deadline_abs is not None:
            deadline = c._deadline_abs - time.perf_counter()
            if deadline <= 0:
                self._record_terminal(
                    c, Outcome.DEADLINE_EXPIRED,
                    "deadline passed before (re)dispatch")
                return None
        att = Request(self._attempt_prompt(tracked).copy(),
                      max_new_tokens=remaining,
                      temperature=c.temperature, eos_id=c.eos_id,
                      deadline_s=deadline, seed=c.seed, tier=c.tier,
                      # the sampling menu rides every replay attempt;
                      # prompt_len marks where the TRUE prompt ends so
                      # the engine re-derives grammar state and the
                      # stop window from the generated suffix only —
                      # resumed continuations stay bit-identical under
                      # every knob (serve/sampling.py)
                      sampling=c.sampling,
                      prompt_len=(c.prompt_len if c.prompt_len
                                  is not None
                                  else int(c.prompt_ids.size)))
        return att

    def _absorb(self, tracked: _Tracked, att: Request):
        """Fold an attempt's delivered stream into the client request
        (the router already streamed these tokens — they are the part
        of the request no failure may take back)."""
        c = tracked.client
        c.token_ids.extend(att.token_ids)
        c.token_times.extend(att.token_times)
        c.token_stamps.extend(att.token_stamps)
        c.drafted_tokens += att.drafted_tokens
        c.accepted_tokens += att.accepted_tokens

    def _finish_from_attempt(self, tracked: _Tracked, att: Request):
        self._absorb(tracked, att)
        c = tracked.client
        if att.outcome is Outcome.STOP and att._stop_trim:
            # the stop-sequence match reached back into tokens an
            # EARLIER attempt emitted (the engine could only truncate
            # its own stream) — trim the remainder off the client so
            # the matched sequence never appears in the output
            trim = min(att._stop_trim, len(c.token_ids))
            if trim:
                del c.token_ids[-trim:]
                del c.token_times[-trim:]
                del c.token_stamps[-trim:]
        self._record_terminal(c, att.outcome, att.detail,
                              att.retry_after_s)

    def _requeue(self, tracked: _Tracked, detail: str,
                 cause: str = "failover", charge: bool = True):
        """The structured-failover path: bounded, exactly-once-
        terminal. Already-emitted tokens stay on the client; the next
        dispatch replays from the suffix. ``charge=False`` re-queues
        WITHOUT burning the request's requeue budget — the migration
        fallback and drain use it: those re-queues are the router's
        own doing (a failed optimisation / an operator action), and a
        request must not die FAILED_REPLICA for them."""
        if self._remint_if_complete(tracked):
            return                           # nothing left to replay
        if not charge:
            self.requeues += 1
            self.flight.emit(self._component, EventType.REQUEUE,
                             request_id=tracked.client.request_id,
                             cause=cause, requeues=tracked.requeues,
                             detail=detail[:200],
                             tokens_preserved=len(
                                 tracked.client.token_ids))
            self._queue.append(tracked)
            return
        if tracked.requeues >= self.max_requeues:
            self._record_terminal(
                tracked.client, Outcome.FAILED_REPLICA,
                f"gave up after {tracked.requeues} re-queues "
                f"(max_requeues={self.max_requeues}): {detail}")
            # FAILED_REPLICA at the requeue bound is a structured
            # give-up — dump the trailing fleet timeline naming the
            # request (the REQUEUE/REPLICA_HEALTH events name the
            # replicas that failed it) — docs/OBSERVABILITY.md
            self.flight.postmortem(
                "FAILED_REPLICA at requeue bound",
                f"request {tracked.client.request_id}",
                context={"requeues": tracked.requeues,
                         "max_requeues": self.max_requeues,
                         "detail": detail})
            return
        tracked.requeues += 1
        self.requeues += 1
        self.flight.emit(self._component, EventType.REQUEUE,
                         request_id=tracked.client.request_id,
                         cause=cause, requeues=tracked.requeues,
                         detail=detail[:200],
                         tokens_preserved=len(
                             tracked.client.token_ids))
        self.log.append(f"requeue #{tracked.requeues}: {detail} "
                        f"({len(tracked.client.token_ids)} tokens "
                        f"preserved)")
        self._queue.append(tracked)

    def _dispatch(self) -> int:
        """Route queued requests to replicas (FIFO). A queue that
        nothing can take stays queued — unless every replica is DEAD,
        in which case waiting is a lie and the queue drains to
        FAILED_REPLICA."""
        if not self._alive():
            while self._queue:
                t = self._queue.popleft()
                self._record_terminal(
                    t.client, Outcome.FAILED_REPLICA,
                    "every replica is dead")
            return 0
        dispatched = 0
        blocked: deque = deque()
        # tier-priority dispatch: LATENCY routes before STANDARD
        # before BATCH; the sort is stable, so FIFO order within a
        # tier (and every replay's queue position) is preserved
        if any(t.client.tier is not Tier.STANDARD for t in self._queue):
            self._queue = deque(sorted(
                self._queue, key=lambda t: t.client.tier.order))
        # one snapshot per replica per pass; admissions bump the local
        # view so later queue entries see the new depth. The routable
        # set (SERVING + WARMING) is resolved fresh each pass —
        # membership can change between passes (add/remove/upgrade)
        # and a stale candidate list would route into a tombstone.
        snaps = [(r, r.engine.health_snapshot())
                 for r in self._routable()]
        while self._queue:
            t = self._queue.popleft()
            c = t.client
            if c._deadline_abs is not None and \
                    time.perf_counter() > c._deadline_abs:
                self._record_terminal(
                    c, Outcome.DEADLINE_EXPIRED,
                    f"deadline ({c.deadline_s}s) passed in the router "
                    f"queue")
                continue
            rep = self._route(t, snaps)
            if rep is None:
                blocked.append(t)
                if not any(self._capacity(r, s) for r, s in snaps):
                    # fleet-wide out of capacity: nobody behind the
                    # head can route either — stop scanning
                    break
                # the head is blocked PER-REQUEST (only a replica that
                # cannot hold it, or is degraded, has room — the
                # heterogeneous-fleet case): let smaller requests
                # behind it through instead of head-of-line blocking
                # the whole fleet; the head keeps FIFO priority and
                # the stall give-up still watches it
                continue
            att = self._make_attempt(t)
            if att is None:
                continue                     # expired (or completed)
            if not rep.engine.submit(att):
                if att.outcome is Outcome.FAILED_UNSERVABLE:
                    # nothing a retry fixes — propagate
                    self._finish_from_attempt(t, att)
                    continue
                # engine-level shed: the replica's own admission bound
                # is tighter than the router's capacity view. That is
                # BACKPRESSURE, not a replica failure — it must not
                # burn the requeue budget (an instant-retry loop would
                # terminate healthy-fleet overload as FAILED_REPLICA).
                # The request goes back to the queue HEAD and this
                # dispatch pass stops; it waits for capacity like any
                # queued request, bounded by run()'s stall give-up.
                blocked.append(t)
                break
            t.attempt = att
            t.replica = rep.idx
            self._inflight.append(t)
            dispatched += 1
            self.flight.emit(self._component, EventType.DISPATCH,
                             request_id=c.request_id,
                             entity=f"replica{rep.idx}",
                             attempt_id=att.request_id,
                             replica=rep.idx, tier=c.tier.value,
                             queue_delay_s=(
                                 time.perf_counter() - c.submit_time
                                 if c.submit_time is not None
                                 else None))
            for r, s in snaps:               # keep the pass view honest:
                if r is rep:                 # the dispatch consumes a
                    if s["free_slots"] > 0:  # free slot's allowance or
                        s["free_slots"] -= 1 # a queue place — without
                    else:                    # this a replica with one
                        s["queue_depth"] += 1  # free slot would absorb
                    break                    # a whole burst in one pass
        blocked.extend(self._queue)
        self._queue = blocked
        return dispatched

    # ------------------------------------------------------------- #
    # health: heartbeat, breaker, half-open probes, death
    # ------------------------------------------------------------- #

    def _jittered(self, backoff: float) -> float:
        """Seeded jitter (+0..25%) so a fleet of breakers does not
        probe in lockstep — deterministic under the router's seed,
        from a stream SEPARATE from request-seed pinning."""
        return backoff * (1.0 + 0.25 * float(self._jitter_rng.rand()))

    def _heartbeat_miss(self, rep: Replica, detail: str):
        rep.consecutive_misses += 1
        rep.probe_successes = 0
        rep.warm_steps = 0                   # warmup wants HEALTHY runs
        now = time.perf_counter()
        if rep.state in (ReplicaState.SERVING, ReplicaState.WARMING):
            if rep.consecutive_misses >= self.breaker_failures:
                prev = rep.state
                rep.state = ReplicaState.DEGRADED
                rep.backoff_s = self.probe_backoff_s
                rep.next_probe_t = now + self._jittered(rep.backoff_s)
                rep.breaker_opens += 1
                self.breaker_opens += 1
                self.flight.emit(
                    self._component, EventType.REPLICA_HEALTH,
                    entity=f"replica{rep.idx}", replica=rep.idx,
                    from_state=prev.value,
                    to_state=ReplicaState.DEGRADED.value,
                    detail=detail[:200])
                self.log.append(f"replica {rep.idx}: breaker OPEN "
                                f"after {rep.consecutive_misses} "
                                f"misses ({detail})")
        elif rep.state is ReplicaState.DEGRADED:
            # failed half-open probe
            rep.backoff_s = min(rep.backoff_s * 2.0,
                                self.probe_backoff_max_s)
            rep.next_probe_t = now + self._jittered(rep.backoff_s)
            self.log.append(f"replica {rep.idx}: probe failed, backoff "
                            f"-> {rep.backoff_s:.3f}s")
        # DRAINING: a slow step on a replica already leaving the fleet
        # changes nothing — drain already stopped its admissions, and
        # its exit (retire / warm_start) is the fix a breaker would
        # only delay

    def _step_ok(self, rep: Replica, dt: float, compiled: bool):
        if compiled:
            # a step that traced a new program is NEUTRAL: exempt from
            # the heartbeat (compiles are expected-slow — a cold
            # replica warming up is not sick) but also NOT probe
            # evidence (a still-stalled DEGRADED replica must not
            # close its breaker on a slow-but-compiling step)
            return
        if dt > self.heartbeat_timeout_s:
            self._heartbeat_miss(
                rep, f"step took {dt:.3f}s > heartbeat "
                     f"{self.heartbeat_timeout_s}s")
            return
        rep.consecutive_misses = 0
        if rep.state is ReplicaState.WARMING:
            # warmup evidence: a healthy NON-compile step (compile
            # steps returned above — expected-slow is not warm). After
            # ``warmup_steps`` in a row the replica graduates and the
            # affinity probe starts seeing it.
            rep.warm_steps += 1
            if rep.warm_steps >= self.warmup_steps:
                rep.state = ReplicaState.SERVING
                self.flight.emit(
                    self._component, EventType.WARMUP,
                    entity=f"replica{rep.idx}", replica=rep.idx,
                    phase="done", warm_steps=rep.warm_steps)
                self.flight.emit(
                    self._component, EventType.REPLICA_HEALTH,
                    entity=f"replica{rep.idx}", replica=rep.idx,
                    from_state=ReplicaState.WARMING.value,
                    to_state=ReplicaState.SERVING.value,
                    detail="warmup complete")
                self.log.append(f"replica {rep.idx}: warmed up "
                                f"({rep.warm_steps} healthy steps)")
            return
        if rep.state is ReplicaState.DEGRADED:
            rep.probe_successes += 1
            if rep.probe_successes >= self.probe_recovery:
                rep.state = ReplicaState.SERVING
                rep.backoff_s = None
                rep.probe_successes = 0
                self.recoveries += 1
                self.flight.emit(
                    self._component, EventType.REPLICA_HEALTH,
                    entity=f"replica{rep.idx}", replica=rep.idx,
                    from_state=ReplicaState.DEGRADED.value,
                    to_state=ReplicaState.SERVING.value,
                    detail="breaker closed (recovered)")
                self.log.append(f"replica {rep.idx}: breaker CLOSED "
                                f"(recovered)")

    def _on_replica_death(self, rep: Replica, detail: str):
        """A step raised: the replica's state can no longer be
        trusted. Mark it DEAD and re-queue every in-flight request it
        held — from the ROUTER'S bookkeeping (prompt + the tokens
        already streamed), never from the dead engine's memory."""
        prev_state = rep.state
        rep.state = ReplicaState.DEAD
        rep.death_detail = detail
        self.replica_deaths += 1
        self.flight.emit(self._component, EventType.REPLICA_HEALTH,
                         entity=f"replica{rep.idx}", replica=rep.idx,
                         from_state=prev_state.value,
                         to_state=ReplicaState.DEAD.value,
                         detail=detail[:200])
        self.log.append(f"replica {rep.idx}: DEAD ({detail})")
        mine = [t for t in self._inflight if t.replica == rep.idx]
        for t in mine:
            self._inflight.remove(t)
            att, t.attempt, t.replica = t.attempt, None, None
            if att.outcome is not None and \
                    att.outcome not in (Outcome.SHED,
                                        Outcome.PREEMPTED):
                # finished on the replica's last good step, collected
                # here instead of _collect — still exactly one terminal
                self._finish_from_attempt(t, att)
                continue
            self._absorb(t, att)
            self._requeue(t, f"replica {rep.idx} died mid-flight: "
                             f"{detail}")

    # ------------------------------------------------------------- #
    # page transport: live slot migration between replicas
    # ------------------------------------------------------------- #

    def _find_tracked(self, request_id: int) -> Optional[_Tracked]:
        """In-flight lookup by CLIENT request id (the attempt id also
        matches — callers holding an engine-side id still resolve)."""
        for t in self._inflight:
            if t.client.request_id == request_id or \
                    (t.attempt is not None and
                     t.attempt.request_id == request_id):
                return t
        return None

    def migrate(self, request_id: int, dst: int) -> bool:
        """Move ``request_id``'s live slot to replica ``dst`` — pages
        by capsule, zero redone prefill, bit-identical continuation.
        Returns True when the slot now decodes on ``dst``.

        Failure is never partial: an abort BEFORE the source detaches
        (not decode-ready, no capacity probe, source death mid-
        capture) leaves the slot decoding where it was and returns
        False; a failure AFTER (crc mismatch, destination refusal or
        death mid-install) releases the source-side custody and falls
        back to the replay path — the request re-queues from its
        delivered suffix WITHOUT charging its requeue budget, and a
        ``MIGRATE_FAIL`` event records which fallback engaged."""
        tracked = self._find_tracked(request_id)
        if tracked is None or tracked.attempt is None:
            return False
        if not 0 <= dst < len(self.replicas):
            return False                 # membership-safe: a caller
                                         # holding a stale index must
                                         # get the replay fallback's
                                         # refusal, not an IndexError
        src = self.replicas[tracked.replica]
        dst_rep = self.replicas[dst]
        if dst_rep is src or \
                src.state is ReplicaState.DEAD or \
                dst_rep.state in (ReplicaState.DEAD,
                                  ReplicaState.RETIRED,
                                  ReplicaState.DRAINING) or \
                dst_rep.killed is not None:
            return False
        att = tracked.attempt
        if att.outcome is not None:
            return False                 # finished — _collect owns it
        capsule = None
        try:
            capsule = self._transport.capture(src.engine,
                                              att.request_id)
        except Exception as e:           # torn source — death path
            self.flight.emit(self._component, EventType.MIGRATE_FAIL,
                             request_id=tracked.client.request_id,
                             entity=f"replica{src.idx}",
                             src=src.idx, dst=dst, fallback="none",
                             reason=f"{type(e).__name__}: {e}"[:200])
            self.migrations_failed += 1
            return False
        if capsule is None:
            # pre-detach refusal: still prefilling, already gone, or
            # an injected source death aborted the capture — the slot
            # (if any) keeps decoding on the source; nothing to undo
            self.flight.emit(self._component, EventType.MIGRATE_FAIL,
                             request_id=tracked.client.request_id,
                             entity=f"replica{src.idx}",
                             src=src.idx, dst=dst, fallback="none",
                             reason="capture refused/aborted")
            self.migrations_failed += 1
            return False
        # the source slot is detached into custody: from here every
        # path either installs on dst or falls back to replay — and
        # either way releases the custody exactly once
        self.flight.emit(self._component, EventType.MIGRATE_OUT,
                         request_id=tracked.client.request_id,
                         entity=f"replica{src.idx}", src=src.idx,
                         dst=dst, pages=capsule.num_pages,
                         bytes=capsule.nbytes)
        self._inflight.remove(tracked)
        tracked.attempt, tracked.replica = None, None
        self._absorb(tracked, att)
        att2 = self._make_attempt(tracked)
        if att2 is None:
            # completed/expired under the transfer — _make_attempt
            # minted the terminal; the capsule is moot
            src.engine.release_capsule(att.request_id)
            return False
        ok = False
        reason = "install refused"
        try:
            ok = self._transport.install(dst_rep.engine, capsule,
                                         att2)
        except Exception as e:           # torn destination
            ok = False
            reason = f"{type(e).__name__}: {e}"[:200]
        src.engine.release_capsule(att.request_id)
        if not ok:
            if not capsule.verify():
                reason = "capsule crc chain broken"
            self.migrations_failed += 1
            self.flight.emit(self._component, EventType.MIGRATE_FAIL,
                             request_id=tracked.client.request_id,
                             entity=f"replica{dst}", src=src.idx,
                             dst=dst, fallback="replay",
                             reason=reason)
            self.log.append(f"migration {tracked.client.request_id} "
                            f"replica{src.idx}->replica{dst} failed "
                            f"({reason}): replay fallback")
            self._requeue(tracked,
                          f"migration to replica {dst} failed "
                          f"({reason}) — replaying from the suffix",
                          cause="migration-fallback", charge=False)
            return False
        tracked.attempt = att2
        tracked.replica = dst
        self._inflight.append(tracked)
        self.migrations += 1
        self.migrated_pages += capsule.num_pages
        self.migrated_bytes += capsule.nbytes
        self.flight.emit(self._component, EventType.MIGRATE_IN,
                         request_id=tracked.client.request_id,
                         entity=f"replica{dst}", src=src.idx,
                         dst=dst, pages=capsule.num_pages,
                         bytes=capsule.nbytes,
                         attempt_id=att2.request_id)
        return True

    def _migration_dst(self, tracked: _Tracked, exclude: int,
                       decode_pref: bool = True) -> Optional[int]:
        """Pick the destination replica for a migration: routable
        (SERVING, or WARMING — a migrated slot is spill-class work, so
        a warming replica is a legitimate landing zone; DRAINING never
        is: it is on its way OUT), not the source, can hold the
        request, has a free slot — 'decode' and 'mixed' roles only
        when ``decode_pref`` (a migrated slot is decode work; a
        dedicated prefill replica must not collect it back).
        Least-occupied wins, index breaks ties."""
        best, best_key = None, None
        for rep in self._routable():
            if rep.idx == exclude or rep.killed is not None:
                continue
            if decode_pref and rep.role == "prefill":
                continue
            if not self._can_hold(rep, tracked):
                continue
            snap = rep.engine.health_snapshot()
            if snap["free_slots"] <= 0:
                continue
            key = (snap["active_slots"], snap["queue_depth"], rep.idx)
            if best_key is None or key < best_key:
                best, best_key = rep.idx, key
        return best

    def _stream_prefill_roles(self):
        """Role-split streaming: every decode-ready slot on a
        'prefill' replica moves to a decode/mixed sibling NOW — the
        publication moment is the handoff point, so a prefill replica
        never spends a step decoding. A slot that cannot move yet (no
        sibling has a free slot) keeps decoding in place: the role is
        an optimisation, the stream must not stall for it."""
        for rep in self.replicas:
            if rep.role != "prefill" or \
                    rep.state is not ReplicaState.SERVING:
                continue
            for t in [t for t in self._inflight
                      if t.replica == rep.idx]:
                if t.attempt.outcome is not None:
                    continue
                if not rep.engine.decode_ready(t.attempt.request_id):
                    continue
                dst = self._migration_dst(t, exclude=rep.idx)
                if dst is not None:
                    self.migrate(t.client.request_id, dst)

    def _rebalance_brownout(self):
        """Brownout rebalancing: a replica browned out to the
        rebalance level sheds ONE decode-ready slot per fleet pass to
        the least-occupied cool sibling — pages move, tokens don't
        replay, and the hot replica's pressure signal (its own queue +
        occupancy) actually falls instead of bouncing work through
        the router queue."""
        snaps = {r.idx: r.engine.health_snapshot()
                 for r in self._routable()}
        hot = [r for r in self._serving()
               if wants_rebalance(snaps[r.idx]["brownout_level"])]
        for rep in hot:
            for t in [t for t in self._inflight
                      if t.replica == rep.idx]:
                if t.attempt.outcome is not None:
                    continue
                if not rep.engine.decode_ready(t.attempt.request_id):
                    continue
                dst = self._migration_dst(t, exclude=rep.idx)
                if dst is None or \
                        wants_rebalance(
                            snaps[dst]["brownout_level"]):
                    continue             # nowhere cooler to go
                if self.migrate(t.client.request_id, dst):
                    break                # one slot per pass per replica

    def _make_preempt_handoff(self, src_idx: int):
        """The engine->router preemption seam (``fleet_preempt``): the
        engine calls this with its victim's request id BEFORE evicting
        — True means the fleet took the slot (migrated to a sibling,
        or the replay fallback already re-queued it at the router) and
        the engine must not record a PREEMPTED terminal; False means
        the slot is untouched and engine-internal preemption proceeds
        as ever."""
        def handoff(request_id: int) -> bool:
            tracked = self._find_tracked(request_id)
            if tracked is None:
                return False
            dst = self._migration_dst(tracked, exclude=src_idx)
            if dst is None:
                return False
            if self.migrate(tracked.client.request_id, dst):
                return True
            # a post-detach failure already re-queued the request at
            # the router (replay fallback) — the slot is gone from the
            # source either way, so the engine must stand down
            return tracked not in self._inflight
        return handoff

    def drain_replica(self, idx: int) -> dict:
        """One drain pass over replica ``idx`` (the mechanism under
        ``remove_replica`` / ``upgrade_replica``, callable directly
        for a manual drain): queued attempts are withdrawn back to
        the router (they hold no pages), decode-ready slots MIGRATE
        to siblings (zero redone prefill), still-prefilling slots are
        left to finish — call again after ``step()`` until
        ``remaining`` is 0 (the DRAINING states' ``_drain_tick`` does
        exactly that). Zero lost requests, zero charged requeue
        budget. Returns ``{"migrated", "requeued", "remaining"}``."""
        rep = self.replicas[idx]
        migrated = requeued = 0
        for t in [t for t in self._inflight if t.replica == idx]:
            if t.attempt.outcome is not None:
                continue                 # _collect owns it
            if rep.engine.withdraw(t.attempt):
                self._inflight.remove(t)
                att, t.attempt, t.replica = t.attempt, None, None
                self._absorb(t, att)
                self._requeue(t, f"withdrawn in drain of replica "
                                 f"{idx}", cause="drain",
                              charge=False)
                requeued += 1
                continue
            if not rep.engine.decode_ready(t.attempt.request_id):
                continue                 # mid-prefill: next pass
            dst = self._migration_dst(t, exclude=idx)
            if dst is not None and \
                    self.migrate(t.client.request_id, dst):
                migrated += 1
        remaining = sum(1 for t in self._inflight if t.replica == idx)
        return {"migrated": migrated, "requeued": requeued,
                "remaining": remaining}

    # ------------------------------------------------------------- #
    # elastic membership: add / remove / upgrade under live traffic
    # ------------------------------------------------------------- #

    def add_replica(self, engine: InferenceEngine,
                    role: str = "mixed") -> int:
        """Admit a cold engine to the fleet. It enters WARMING —
        spill/round-robin traffic only (the circuit breaker's compile
        exemption covers its cold compiles), graduating to SERVING
        after ``warmup_steps`` consecutive healthy steps, by which
        point its PrefixIndex has started earning affinity the normal
        way. Returns the new replica's index (stable forever — the
        fleet list only ever appends; departures tombstone)."""
        if role not in _ROLES:
            raise MXNetError(f"replica role must be one of {_ROLES}, "
                             f"got {role!r}")
        if role == "decode" and not any(
                r.role != "decode" for r in self._alive()):
            raise MXNetError("cannot add a 'decode' replica to a fleet "
                             "with no live prefill/mixed replica — "
                             "nothing could ever feed it")
        idx = len(self.replicas)
        rep = Replica(idx, engine, role=role)
        rep.state = ReplicaState.WARMING
        if getattr(engine, "_component", None) == "engine":
            engine._component = f"replica{idx}"
        if self._fleet_preempt:
            engine.preempt_handoff = self._make_preempt_handoff(idx)
        self.replicas.append(rep)
        self.scale_ups += 1
        self.flight.emit(self._component, EventType.SCALE_UP,
                         entity=f"replica{idx}", replica=idx,
                         role=role, fleet_size=len(self._alive()))
        self.flight.emit(self._component, EventType.WARMUP,
                         entity=f"replica{idx}", replica=idx,
                         phase="start",
                         warmup_steps=self.warmup_steps)
        self.log.append(f"replica {idx}: joined the fleet "
                        f"(role={role}, WARMING)")
        return idx

    def _check_removable(self, idx: int, verb: str) -> Replica:
        """The shared refusal ladder for remove/upgrade: loud, typed
        errors — a membership mistake must never be a silent no-op."""
        if not 0 <= idx < len(self.replicas):
            raise MXNetError(f"{verb}: no replica {idx} "
                             f"(fleet has {len(self.replicas)})")
        rep = self.replicas[idx]
        if rep.state is ReplicaState.DRAINING:
            raise MXNetError(
                f"{verb}: replica {idx} is already DRAINING "
                f"({rep.drain_reason}) — double membership operation")
        if rep.state in (ReplicaState.DEAD, ReplicaState.RETIRED):
            raise MXNetError(f"{verb}: replica {idx} is "
                             f"{rep.state} — nothing to drain")
        return rep

    def remove_replica(self, idx: int) -> dict:
        """Retire replica ``idx``: stop admissions (DRAINING), migrate
        its decode-ready slots to siblings / withdraw its queued
        attempts back to the router (both via ``drain_replica`` —
        zero lost requests, zero charged requeue budget), and let the
        step loop retire it the pass the last slot leaves. Raises
        loudly on a double remove, a dead/retired target, or when the
        survivors could not serve at all. Returns the first drain
        pass's ``{"migrated","requeued","remaining"}``."""
        rep = self._check_removable(idx, "remove_replica")
        # DRAINING siblings are NOT survivors — they are leaving too,
        # and counting them would let sequential removes drain the
        # whole fleet to zero
        survivors = [r for r in self._alive() if r.idx != idx
                     and r.state is not ReplicaState.DRAINING]
        if not survivors:
            raise MXNetError(f"remove_replica: replica {idx} is the "
                             f"last live replica — a fleet of zero "
                             f"serves nobody")
        if all(r.role == "decode" for r in survivors):
            raise MXNetError(f"remove_replica: removing replica {idx} "
                             f"would leave a decode-only fleet that "
                             f"can never prefill")
        prev = rep.state
        rep.state = ReplicaState.DRAINING
        rep.drain_reason = "retire"
        self.flight.emit(self._component, EventType.SCALE_DOWN,
                         entity=f"replica{idx}", replica=idx,
                         phase="drain",
                         fleet_size=len(self._alive()))
        self.flight.emit(self._component, EventType.REPLICA_HEALTH,
                         entity=f"replica{idx}", replica=idx,
                         from_state=prev.value,
                         to_state=ReplicaState.DRAINING.value,
                         detail="remove_replica: draining to retire")
        self.log.append(f"replica {idx}: DRAINING (retire)")
        return self.drain_replica(idx)

    def upgrade_replica(self, idx: int, params=None, manager=None,
                        step=None) -> dict:
        """Rolling weight swap for one replica: drain it exactly like
        ``remove_replica`` (admissions stop, slots migrate or finish),
        then — on the step-loop pass its last slot leaves — swap
        weights in place via ``engine.warm_start`` (which flushes its
        PrefixIndex and cache tiers: the per-replica stagger of a
        fleet-wide prefix flush) and re-enter through WARMING. The
        weight source is stashed NOW (``params`` or ``manager``/
        ``step``), so the caller — typically the FleetSupervisor — can
        die mid-roll without wedging the swap."""
        if params is None and manager is None:
            raise MXNetError("upgrade_replica needs params= or "
                             "manager= (a weight source to swap in)")
        rep = self._check_removable(idx, "upgrade_replica")
        prev = rep.state
        rep.state = ReplicaState.DRAINING
        rep.drain_reason = "upgrade"
        rep.upgrade_src = ({"params": params} if params is not None
                           else {"manager": manager, "step": step})
        self.flight.emit(self._component, EventType.UPGRADE,
                         entity=f"replica{idx}", replica=idx,
                         phase="drain")
        self.flight.emit(self._component, EventType.REPLICA_HEALTH,
                         entity=f"replica{idx}", replica=idx,
                         from_state=prev.value,
                         to_state=ReplicaState.DRAINING.value,
                         detail="upgrade_replica: draining to swap "
                                "weights")
        self.log.append(f"replica {idx}: DRAINING (upgrade)")
        return self.drain_replica(idx)

    def _drain_tick(self):
        """One drain pass per DRAINING replica per fleet step, plus
        finalisation the pass the replica empties: retire-shutdown or
        warm_start-and-rewarm. Runs from ``step()`` — router-owned, so
        the transition completes no matter what happened to whoever
        started it."""
        for rep in self.replicas:
            if rep.state is not ReplicaState.DRAINING:
                continue
            stats = self.drain_replica(rep.idx)
            if stats["remaining"] > 0:
                continue                     # still-prefilling slots
            if rep.drain_reason == "retire":
                rep.engine.shutdown(
                    f"replica {rep.idx} retired (scale-down)")
                rep.state = ReplicaState.RETIRED
                rep.drain_reason = None
                self.scale_downs += 1
                self.flight.emit(
                    self._component, EventType.SCALE_DOWN,
                    entity=f"replica{rep.idx}", replica=rep.idx,
                    phase="retired", fleet_size=len(self._alive()))
                self.flight.emit(
                    self._component, EventType.REPLICA_HEALTH,
                    entity=f"replica{rep.idx}", replica=rep.idx,
                    from_state=ReplicaState.DRAINING.value,
                    to_state=ReplicaState.RETIRED.value,
                    detail="drained clean, engine shut down")
                self.log.append(f"replica {rep.idx}: RETIRED")
                continue
            # upgrade: swap weights in the emptied engine, re-warm.
            # A warm_start that raises (shape/dtype mismatch, a
            # checkpoint that no longer loads) is a replica the fleet
            # can no longer trust — the death path owns it and the
            # supervisor's dead-replacement machinery takes over.
            src, rep.upgrade_src, rep.drain_reason = \
                rep.upgrade_src, None, None
            try:
                rep.engine.warm_start(**src)
            except Exception as e:
                self.flight.emit(
                    self._component, EventType.UPGRADE,
                    entity=f"replica{rep.idx}", replica=rep.idx,
                    phase="failed",
                    reason=f"{type(e).__name__}: {e}"[:200])
                self._on_replica_death(
                    rep, f"upgrade warm_start failed: "
                         f"{type(e).__name__}: {e}")
                continue
            rep.state = ReplicaState.WARMING
            rep.warm_steps = 0
            self.upgrades += 1
            self.flight.emit(self._component, EventType.UPGRADE,
                             entity=f"replica{rep.idx}",
                             replica=rep.idx, phase="swapped")
            self.flight.emit(self._component,
                             EventType.REPLICA_HEALTH,
                             entity=f"replica{rep.idx}",
                             replica=rep.idx,
                             from_state=ReplicaState.DRAINING.value,
                             to_state=ReplicaState.WARMING.value,
                             detail="weights swapped (warm_start), "
                                    "re-warming")
            self.flight.emit(self._component, EventType.WARMUP,
                             entity=f"replica{rep.idx}",
                             replica=rep.idx, phase="start",
                             warmup_steps=self.warmup_steps)
            self.log.append(f"replica {rep.idx}: upgraded "
                            f"(warm_start), WARMING")

    # ------------------------------------------------------------- #
    # the scheduler
    # ------------------------------------------------------------- #

    def _collect(self):
        """Harvest finished attempts. A SHED attempt (the replica
        drained/shut down underneath us, or shed from its queue) and a
        PREEMPTED attempt (the replica's own preemption budget gave
        the slot away for good) are structured re-queues — both
        retryable capacity signals, both resume from the emitted
        suffix on the next dispatch; everything else propagates to the
        client as-is."""
        for t in [t for t in self._inflight
                  if t.attempt.outcome is not None]:
            self._inflight.remove(t)
            att, t.attempt, t.replica = t.attempt, None, None
            if att.outcome in (Outcome.SHED, Outcome.PREEMPTED):
                self._absorb(t, att)
                self._requeue(t, f"replica {att.outcome} in flight: "
                                 f"{att.detail}")
            else:
                self._finish_from_attempt(t, att)

    def step(self) -> int:
        """One fleet scheduler pass: dispatch, step every steppable
        replica (SERVING always; DEGRADED only when its half-open
        backoff has elapsed — that step IS the probe), handle
        heartbeat/breaker transitions and deaths, collect finished
        attempts. Returns the number of slots that advanced fleet-wide
        (0 = an idle/blocked pass)."""
        self.steps += 1
        self._dispatch()
        advanced = 0
        now = time.perf_counter()
        for rep in self.replicas:
            if rep.state in (ReplicaState.DEAD, ReplicaState.RETIRED):
                continue
            if rep.state is ReplicaState.DEGRADED:
                if now < rep.next_probe_t:
                    continue
                rep.probes += 1
                self.probes += 1
            try:
                n, dt, compiled = rep.step()
            except Exception as e:           # ReplicaKilled or torn
                self._on_replica_death(rep, f"{type(e).__name__}: {e}")
                continue
            advanced += n
            self._step_ok(rep, dt, compiled)
        self._collect()
        if any(r.state is ReplicaState.DRAINING for r in self.replicas):
            # the drain tick lives on the ROUTER'S step loop, not on
            # whoever called remove/upgrade_replica: a supervisor
            # killed mid-transition leaves a DRAINING replica that the
            # next fleet pass still finishes — no wedge by construction
            self._drain_tick()
        if any(r.role == "prefill" for r in self.replicas):
            # role split: hand freshly-published page sets to the
            # decode side the same pass prefill finished them
            self._stream_prefill_roles()
        if self.rebalance:
            self._rebalance_brownout()
        if self._queue:
            self._dispatch()                 # freed slots take work now
        return advanced

    def run(self, requests, arrival_times=None, poll_sleep=1e-3,
            before_step=None, after_step=None):
        """Drive ``requests`` until EVERY one is terminal — the fleet
        twin of ``InferenceEngine.run``, with the same hook surface
        (``before_step(router, i)`` / ``after_step(router, i)``: the
        fleet chaos harness's injection and audit points).

        A non-empty router queue that no live replica can absorb while
        nothing else makes progress gives up on its head, bounded,
        like the engine's own stall handling: FAILED_UNSERVABLE after
        ``stall_steps`` idle passes when the fleet is healthy but
        starved (capacity cause — matching the engine's starved-head
        outcome), FAILED_REPLICA after ``8 * stall_steps`` when
        survivors are wedged DEGRADED past recovery (replica-health
        cause; the larger budget spans several probe-backoff
        cycles)."""
        if arrival_times is None:
            for r in requests:
                self.submit(r)
            pending = []
        else:
            pending = sorted(zip(arrival_times, requests),
                             key=lambda p: p[0])
        t0 = time.perf_counter()
        it = 0
        self._stall = 0
        while pending or self._queue or self._inflight:
            now = time.perf_counter() - t0
            while pending and pending[0][0] <= now:
                self.submit(pending.pop(0)[1])
            if before_step is not None:
                before_step(self, it)
            n = self.step()
            if after_step is not None:
                after_step(self, it)
            it += 1
            if n > 0:
                self._stall = 0
                continue
            if self._queue or self._inflight:
                self._stall += 1
                limit = self._stall_limit()
                if self._stall > limit:
                    self._stall = 0
                    self._fail_starved(limit)
                else:
                    time.sleep(poll_sleep)
            elif pending:
                self._stall = 0
                time.sleep(min(poll_sleep,
                               max(0.0, pending[0][0] - now)))
        return requests

    def _stall_limit(self) -> int:
        """Idle passes before the fleet gives up on its starved head.
        A DEGRADED replica's recovery is pending (half-open probes on
        backoff), so idle passes are expected — give the breaker loop
        several full backoff cycles before concluding it is a wedge,
        but DO keep counting: a permanently-degraded fleet must still
        give up, bounded, not hang forever."""
        degraded = any(r.state is ReplicaState.DEGRADED
                       for r in self._alive())
        return self.stall_steps * (8 if degraded else 1)

    def _fail_starved(self, limit: int):
        """Bounded give-up after ``limit`` idle passes — shared by
        ``run()`` and the HTTP front end's driver (serve/frontend.py),
        one audited outcome path for both."""
        degraded = any(r.state is ReplicaState.DEGRADED
                       for r in self._alive())
        if self._queue:
            head = self._queue.popleft()
            if degraded:
                # replica-health cause: survivors exist
                # but none recovered in time
                self._record_terminal(
                    head.client, Outcome.FAILED_REPLICA,
                    f"no replica recovered within {limit} "
                    f"idle passes (fleet degraded)")
            else:
                # capacity/starvation cause on a healthy
                # fleet — same outcome as the engine's own
                # starved-head give-up (non-retryable:
                # 'retry later' is a lie here)
                self._record_terminal(
                    head.client, Outcome.FAILED_UNSERVABLE,
                    f"router queue head starved for "
                    f"{limit} idle passes (no serving "
                    f"replica could admit it)")
        else:
            # in-flight but frozen: an attempt stuck in a
            # replica's OWN admission queue never advances
            # and (unlike slotted work, which the engine's
            # watchdog evicts) no engine-side give-up
            # covers it — the engine's starved-head path
            # lives in engine.run(), which the router
            # does not use. Withdraw one, bounded, with
            # the same cause split as the queue-head
            # give-up above.
            self._withdraw_starved(degraded, limit)

    def _withdraw_starved(self, degraded: bool, limit: int) -> bool:
        """Pull one attempt out of a live replica's admission queue
        (it holds no pages there) and fail its client — the fleet
        twin of the engine's own starved-queue-head give-up, with the
        SAME cause split as the router-queue give-up: FAILED_REPLICA
        (retryable, hinted) when survivors are wedged DEGRADED,
        FAILED_UNSERVABLE when the fleet is healthy but starved.
        Returns True when one was withdrawn; False means every
        in-flight attempt is slotted (the engines' watchdogs own
        those)."""
        for t in list(self._inflight):
            rep = self.replicas[t.replica]
            if rep.state is ReplicaState.DEAD:
                continue
            if not rep.engine.withdraw(t.attempt):
                continue                     # slotted, not queued
            self._inflight.remove(t)
            att, t.attempt, t.replica = t.attempt, None, None
            self._absorb(t, att)
            if degraded:
                self._record_terminal(
                    t.client, Outcome.FAILED_REPLICA,
                    f"attempt parked in degraded replica {rep.idx}'s "
                    f"admission queue; no recovery within {limit} "
                    f"idle fleet passes")
            else:
                self._record_terminal(
                    t.client, Outcome.FAILED_UNSERVABLE,
                    f"attempt starved in replica {rep.idx}'s "
                    f"admission queue for {limit} idle fleet passes")
            return True
        return False

    def live_tokens(self, request) -> List[int]:
        """The client-visible token stream RIGHT NOW: tokens already
        absorbed onto the client plus the in-flight attempt's
        emissions. Safe to stream before the attempt finishes — the
        partial-tokens-kept contract means a failover/preemption/shed
        can only PRESERVE these (the re-queue absorbs them), never
        take them back; the one exception, a stop-sequence match
        reaching back across an attempt boundary, is bounded by
        max_stop_len - 1 tokens, exactly the holdback the HTTP front
        end applies while stop sequences are armed
        (serve/frontend.py)."""
        for t in self._inflight:
            if t.client is request:
                return list(t.client.token_ids) + \
                    list(t.attempt.token_ids)
        return list(request.token_ids)

    def cancel(self, request, detail: str = "cancelled by client") \
            -> bool:
        """Fleet-level client cancellation: accepts the client
        ``Request`` or its ``request_id``. A QUEUED request terminates
        CANCELLED immediately; an IN-FLIGHT one is cancelled on its
        replica (engine pages reclaimed) and its client terminal is
        recorded here with the partial tokens absorbed. Returns False
        — refused — when the request is already terminal or the
        attempt finished before the cancel could land (the
        double-finish guard's contract: exactly one terminal,
        whichever transition wins)."""
        tracked = None
        for t in self._queue:
            if t.client is request or t.client.request_id == request:
                tracked = t
                break
        if tracked is not None:
            self._queue.remove(tracked)
            self._record_terminal(tracked.client, Outcome.CANCELLED,
                                  detail)
            return True
        for t in self._inflight:
            if t.client is request or t.client.request_id == request:
                tracked = t
                break
        if tracked is None:
            return False
        rep = self.replicas[tracked.replica]
        if rep.state is not ReplicaState.DEAD and rep.killed is None \
                and not rep.engine.cancel(tracked.attempt, detail):
            # the attempt is already terminal on the engine. A REAL
            # finish (EOS, failure, ...) owns the client outcome —
            # _collect will propagate it, the cancel lost the race.
            # But SHED/PREEMPTED would only be RE-QUEUED: the request
            # is still live from the client's view, so the cancel
            # must win — otherwise a disconnected client's request
            # keeps bouncing through the fleet.
            if tracked.attempt.outcome not in (Outcome.SHED,
                                               Outcome.PREEMPTED):
                return False
        # a dead/killed replica cannot execute the cancel RPC — the
        # router's own bookkeeping is authoritative, as on death
        self._inflight.remove(tracked)
        att, tracked.attempt, tracked.replica = \
            tracked.attempt, None, None
        self._absorb(tracked, att)
        self._record_terminal(tracked.client, Outcome.CANCELLED, detail)
        return True

    def shutdown(self, detail: str = "fleet shutdown"):
        """Drain the whole fleet: every live replica's engine drains
        (its in-flight attempts go SHED), and every client request —
        in flight or still queued — terminates SHED with the fleet
        retry hint. Replica health states are left as they were."""
        for rep in self._alive():
            rep.engine.shutdown(detail)
        for t in list(self._inflight):
            self._inflight.remove(t)
            att, t.attempt, t.replica = t.attempt, None, None
            if att is not None and att.outcome is not None and \
                    att.outcome not in (Outcome.SHED,
                                        Outcome.PREEMPTED):
                # finished just before the drain — honor the real
                # outcome, not the shutdown
                self._finish_from_attempt(t, att)
                continue
            if att is not None:
                self._absorb(t, att)
            self._record_terminal(t.client, Outcome.SHED, detail)
        while self._queue:
            self._record_terminal(self._queue.popleft().client,
                                  Outcome.SHED, detail)

    # ------------------------------------------------------------- #
    # observability
    # ------------------------------------------------------------- #

    def flight_events(self):
        """The merged fleet timeline: the router's own events plus
        every replica's — DEAD replicas INCLUDED. The "never read a
        dead engine again" rule protects request/page bookkeeping the
        death left untrustworthy; the flight recorder is the router
        process's own host-side log of what that replica did BEFORE it
        died, which is exactly the evidence a death postmortem exists
        to keep (its lane simply ends at the kill; the router-side
        REPLICA_HEALTH event records the death itself). Ordered by
        timestamp (then seq) — seq is only per-recorder, so the clock
        is the cross-recorder order. This is what
        ``tools/trace_export.py`` turns into one fleet-wide Perfetto
        timeline."""
        evs = list(self.flight.events())
        for rep in self.replicas:
            evs.extend(rep.engine.flight.events())
        evs.sort(key=lambda e: (e.ts, e.component, e.seq))
        return evs

    def health_snapshot(self) -> dict:
        """Consistent fleet-wide snapshot: router outcome tally +
        routing/failover counters + per-replica state (with each LIVE
        replica's own ``health_snapshot``; a DEAD replica reports only
        its state — its engine is gone)."""
        reps = []
        for r in self.replicas:
            entry = {"idx": r.idx, "state": r.state.value,
                     "role": r.role,
                     "breaker_opens": r.breaker_opens,
                     "probes": r.probes, "steps": r.steps,
                     "warm_steps": r.warm_steps,
                     "drain_reason": r.drain_reason}
            if r.state is ReplicaState.DEAD:
                entry["death_detail"] = r.death_detail
            else:
                # RETIRED included: shutdown leaves the engine
                # structurally valid and auditable — its final
                # snapshot is the retirement's evidence
                entry["engine"] = r.engine.health_snapshot()
            reps.append(entry)
        return {
            "outcomes": dict(self.health),
            "outcomes_by_tier": {t: dict(d) for t, d in
                                 self.health_by_tier.items()},
            "queue_depth": len(self._queue),
            "queue_depth_by_tier": {
                t.value: sum(1 for q in self._queue
                             if q.client.tier is t) for t in Tier},
            "inflight": len(self._inflight),
            "requeues": self.requeues,
            "replica_deaths": self.replica_deaths,
            "breaker_opens": self.breaker_opens,
            "probes": self.probes,
            "recoveries": self.recoveries,
            "affinity_routed": self.affinity_routed,
            "tier_affinity_routed": self.tier_affinity_routed,
            "spill_routed": self.spill_routed,
            # page transport: fleet-level migration tally (each
            # replica's snapshot carries its own in/out capsule
            # counters) — serve/metrics.py renders all four
            "migrations": self.migrations,
            "migrations_failed": self.migrations_failed,
            "migrated_pages": self.migrated_pages,
            "migrated_bytes": self.migrated_bytes,
            # elastic membership: live fleet size (tombstones
            # excluded) + the scale/upgrade tally
            "fleet_size": len(self._alive()),
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "upgrades": self.upgrades,
            # CLIENT-level latency histograms (the SLO percentiles a
            # dashboard should alert on — per-replica attempt
            # histograms ride each replica's own engine snapshot)
            "latency_hists": self.flight.hist_snapshot(),
            "replicas": reps,
        }


def build_fleet(model, n_replicas: int, engine_kw: Optional[dict] = None,
                roles: Optional[List[str]] = None,
                **router_kw) -> Router:
    """N homogeneous replicas over ONE model's weights (each engine
    binds the same parameter arrays — host RAM holds one copy) behind
    a Router. ``roles`` (one of 'prefill'|'decode'|'mixed' per
    replica) builds a disaggregated fleet; omitted, every replica is
    'mixed'. The common test/bench constructor."""
    engine_kw = dict(engine_kw or {})
    engines = [InferenceEngine(model, **engine_kw)
               for _ in range(n_replicas)]
    return Router(engines, roles=roles, **router_kw)
