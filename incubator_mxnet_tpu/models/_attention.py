"""Shared packed-qkv flash attention fast path for the transformer
model families (BERT/GPT self-attention cells).

Rationale: the Pallas kernels are (B, H, T, D)-native, but the
projection produces (B, T, 3*H*D). Slicing per-tensor and letting the
sdpa wrapper transpose each of q/k/v (plus the output, plus their AD
mirrors) cost ~19 ms/step of relayout copies at BERT-base B=48 on v5e
(trace_r4). Packing once to (3, B, H, T, D) replaces six-plus
relayouts with one — the same reason the reference keeps an
interleaved QKV buffer for its fused attention GEMMs
(src/operator/contrib/transformer.cc, interleaved_matmul_selfatt_*).

Only used when the TPU kernel will actually consume the bhtd layout
(ops.pallas_attention.tpu_kernel_eligible) — on the jnp fallback the
repack would buy nothing and the sharding constraints between a
transpose and its inverse could stop XLA from cancelling them.
"""

from __future__ import annotations


def packed_flash_self_attention(F, qkv, B, T, H, D, units, causal=False,
                                mask=None, valid_length=None,
                                seq_ax=None):
    """qkv: (B, T, 3, H, D) NDArray (projection output, pre-split).
    Returns the attention output as (B, T, units). ``seq_ax`` keeps an
    active sequence-parallel sharding on the T axis through the packed
    layout (dropping it would force a per-layer all-gather)."""
    from ..parallel.spmd import constrain

    qkv_p = qkv.transpose((2, 0, 3, 1, 4))           # (3, B, H, T, D)
    qkv_p = constrain(qkv_p, None, ("dp", "fsdp"), "tp", seq_ax, None)
    qh = qkv_p._op("slice_axis", axis=0, begin=0,
                   end=1).reshape((B, H, T, D))
    kh = qkv_p._op("slice_axis", axis=0, begin=1,
                   end=2).reshape((B, H, T, D))
    vh = qkv_p._op("slice_axis", axis=0, begin=2,
                   end=3).reshape((B, H, T, D))
    out = F.scaled_dot_product_attention(qh, kh, vh, mask=mask,
                                         causal=causal, flash=True,
                                         valid_length=valid_length,
                                         layout="bhtd")
    out = constrain(out, ("dp", "fsdp"), "tp", seq_ax, None)
    return out.transpose((0, 2, 1, 3)).reshape((B, T, units))


def use_packed_fast_path(D):
    """Gate: engage the packed layout only when the Pallas TPU kernel
    will consume it (self-attention is square, so the causal Tq != Tk
    kernel exclusion can never apply here). MXTPU_FORCE_PACKED=1
    overrides — the CPU test mesh uses it to keep parity coverage of
    the packed wiring. Callers must ALSO ensure the mask is in length
    form (valid_length, or no mask) — a boolean-only mask sends
    use_flash_attention to the jnp fallback where the repack buys
    nothing."""
    import os
    if os.environ.get("MXTPU_FORCE_PACKED") == "1":
        return True
    from ..ops.pallas_attention import tpu_kernel_eligible
    return tpu_kernel_eligible(D, causal=False)
