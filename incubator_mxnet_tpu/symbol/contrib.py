"""``mx.sym.contrib`` — contrib op namespace (symbolic twin of
`python/mxnet/symbol/contrib.py`)."""

from __future__ import annotations

import sys as _sys

from ..ops import registry as _registry

_THIS = _sys.modules[__name__]


def _make(op_name, public):
    from . import _make_symbol_function
    return _make_symbol_function(op_name, public)


for _name in _registry.list_all_names():
    if _name.startswith("_contrib_"):
        _short = _name[len("_contrib_"):]
        if not hasattr(_THIS, _short):
            _spec = _registry.get(_name)
            setattr(_THIS, _short, _make(_spec.name, _short))
