"""Decoder-only causal language model (GPT-style).

The reference era predates decoder-only LMs as a model family, but its
GluonNLP zoo ships language models (`gluonnlp/model/language_model.py` —
AWD-LSTM/StandardRNN; file-level citation, SURVEY.md caveat); this is
the attention-generation replacement for that family and the natural
long-context flagship: causal Pallas flash attention
(ops/pallas_attention.py), per-layer rematerialization, tp/fsdp
parameter shardings, and greedy/temperature decoding as one
``lax.fori_loop`` program (fixed shapes, jitted once).

Sharding follows the BERT layout (qkv/ffn-in column-parallel, output
projections row-parallel, vocab-sharded embedding) so SPMDTrainer runs
it over any dp/fsdp/tp mesh with zero code changes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..base import MXNetError
from ..gluon import nn
from ._attention import packed_flash_self_attention, use_packed_fast_path
from ..gluon.block import HybridBlock
from ..ndarray import NDArray
from .. import initializer as init
from .. import random as _rand

__all__ = ["GPTModel", "gpt_mini", "gpt_small", "lm_loss", "lm_pipeline",
           "greedy_generate", "cached_generate", "init_kv_cache",
           "decode_forward"]


class CausalSelfAttention(HybridBlock):
    """``seq_parallel=True`` routes attention through the sp-axis ring
    (parallel/ring_attention.py) whenever the SPMD step's active mesh has
    an ``sp`` axis of size > 1 — exact long-context attention with the
    sequence sharded across chips; everywhere else it falls back to the
    ordinary (flash-capable) kernel, so the flag is safe to leave on."""

    def __init__(self, units, num_heads, dropout=0.0, dtype="float32",
                 flash=False, seq_parallel=False, **kwargs):
        super().__init__(**kwargs)
        if units % num_heads:
            raise MXNetError(f"units {units} % heads {num_heads} != 0")
        self._units, self._heads, self._flash = units, num_heads, flash
        self._seq_parallel = seq_parallel
        with self.name_scope():
            self.qkv = nn.Dense(3 * units, in_units=units, flatten=False,
                                dtype=dtype,
                                weight_initializer=init.TruncNorm(stdev=0.02))
            self.proj = nn.Dense(units, in_units=units, flatten=False,
                                 dtype=dtype,
                                 weight_initializer=init.TruncNorm(stdev=0.02))
            self.dropout = nn.Dropout(dropout)
        self.qkv.weight._sharding = P("tp", None)
        self.qkv.bias._sharding = P("tp")
        self.proj.weight._sharding = P(None, "tp")

    def hybrid_forward(self, F, x):
        from ..parallel.spmd import constrain
        B, T = x.shape[0], x.shape[1]
        H, D = self._heads, self._units // self._heads
        qkv = self.qkv(x).reshape((B, T, 3, H, D))
        seq_ax = "sp" if self._seq_parallel else None
        mesh = None
        if self._seq_parallel:
            from ..parallel.ring_attention import active_ring_mesh
            mesh = active_ring_mesh(T)
        if mesh is None and self._flash and use_packed_fast_path(D):
            # packed fast path — see models/_attention.py
            out = packed_flash_self_attention(
                F, qkv, B, T, H, D, self._units, causal=True,
                seq_ax=seq_ax)
        else:
            qkv = constrain(qkv, ("dp", "fsdp"), seq_ax, None, "tp", None)
            q = qkv._op("slice_axis", axis=2, begin=0,
                        end=1).reshape((B, T, H, D))
            k = qkv._op("slice_axis", axis=2, begin=1,
                        end=2).reshape((B, T, H, D))
            v = qkv._op("slice_axis", axis=2, begin=2,
                        end=3).reshape((B, T, H, D))
            if mesh is not None:
                from ..parallel.ring_attention import (ring_self_attention,
                                                       ring_flash_attention)
                from ..ops.pallas_attention import _pallas_available
                on_tpu = any(d.platform == "tpu" for d in jax.devices())
                engine = ring_flash_attention if (
                    self._flash and on_tpu and _pallas_available()) \
                    else ring_self_attention
                out = NDArray(engine(
                    q._data, k._data, v._data, mesh=mesh, causal=True,
                    batch_axis=("dp", "fsdp")))
            else:
                out = F.scaled_dot_product_attention(q, k, v, causal=True,
                                                     flash=self._flash)
            out = constrain(out, ("dp", "fsdp"), seq_ax, "tp", None)
            out = out.reshape((B, T, self._units))
        return constrain(self.dropout(self.proj(out)),
                         ("dp", "fsdp"), seq_ax, None)


class GPTBlock(HybridBlock):
    """Pre-norm transformer decoder block (LN → attn → residual,
    LN → MLP → residual)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 layer_norm_eps=1e-5, dtype="float32", flash=False,
                 seq_parallel=False, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ln1 = nn.LayerNorm(epsilon=layer_norm_eps,
                                    in_channels=units)
            self.attn = CausalSelfAttention(units, num_heads, dropout,
                                            dtype=dtype, flash=flash,
                                            seq_parallel=seq_parallel)
            self.ln2 = nn.LayerNorm(epsilon=layer_norm_eps,
                                    in_channels=units)
            self.ffn_in = nn.Dense(hidden_size, in_units=units,
                                   flatten=False, dtype=dtype,
                                   weight_initializer=init.TruncNorm(stdev=0.02))
            self.ffn_out = nn.Dense(units, in_units=hidden_size,
                                    flatten=False, dtype=dtype,
                                    weight_initializer=init.TruncNorm(stdev=0.02))
            self.dropout = nn.Dropout(dropout)
        self._seq_parallel = seq_parallel
        self.ffn_in.weight._sharding = P("tp", None)
        self.ffn_in.bias._sharding = P("tp")
        self.ffn_out.weight._sharding = P(None, "tp")

    def hybrid_forward(self, F, x):
        from ..parallel.spmd import constrain
        seq_ax = "sp" if self._seq_parallel else None
        x = x + self.attn(self.ln1(x))
        x = constrain(x, ("dp", "fsdp"), seq_ax, None)
        h = constrain(self.ffn_in(self.ln2(x)),
                      ("dp", "fsdp"), seq_ax, "tp")
        h = self.dropout(self.ffn_out(F.gelu(h)))
        return constrain(x + h, ("dp", "fsdp"), seq_ax, None)


class GPTModel(HybridBlock):
    """forward(input_ids (B, T)) -> logits (B, T, vocab); weights tied
    with the (vocab-sharded) input embedding."""

    def __init__(self, vocab_size=50257, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=1024,
                 dropout=0.0, layer_norm_eps=1e-5, dtype="float32",
                 flash=False, remat=False, seq_parallel=False, **kwargs):
        super().__init__(**kwargs)
        self.vocab_size = vocab_size
        self.num_layers = num_layers
        self._units = units
        self.hidden_size = hidden_size
        self._dtype = dtype
        self._remat = remat
        self._seq_parallel = seq_parallel
        self.max_length = max_length
        with self.name_scope():
            self.word_embed = nn.Embedding(
                vocab_size, units, sharded=True,
                weight_initializer=init.TruncNorm(stdev=0.02))
            self.position_embed = nn.Embedding(
                max_length, units,
                weight_initializer=init.TruncNorm(stdev=0.02))
            self.embed_dropout = nn.Dropout(dropout)
            for i in range(num_layers):
                blk = GPTBlock(units, hidden_size, num_heads, dropout,
                               layer_norm_eps, dtype=dtype, flash=flash,
                               seq_parallel=seq_parallel)
                self.register_child(blk, f"block{i}")
                setattr(self, f"block{i}", blk)
            self.ln_f = nn.LayerNorm(epsilon=layer_norm_eps,
                                     in_channels=units)

    def hybrid_forward(self, F, input_ids):
        from ..parallel.spmd import constrain
        B, T = input_ids.shape
        pos = F.arange(0, T, dtype="int32").reshape((1, T)) \
            .broadcast_to((B, T))
        x = self.word_embed(input_ids) + self.position_embed(pos)
        x = constrain(x, ("dp", "fsdp"), None, None)
        x = self.embed_dropout(x)
        if self._dtype != "float32":
            x = x.astype(self._dtype)
        from ._remat import remat_call, resolve_policy
        pol = resolve_policy(self._remat)
        for i in range(self.num_layers):
            blk = getattr(self, f"block{i}")
            x = remat_call(blk, x, policy=pol) if self._remat else blk(x)
        # ln_f computes statistics in f32 but returns the input dtype, so
        # the (B, T, vocab) LM-head matmul runs at the compute dtype's MXU
        # rate (an f32 cast here poisoned the biggest matmul in the model);
        # losses do their log-sum-exp reduction with f32 accumulation
        x = self.ln_f(x)
        embed_w = self.word_embed.weight.data()
        logits = F.dot(x, embed_w.astype(x.dtype), transpose_b=True)
        # vocab-sharded logits on tp meshes (see BERTForPretraining)
        from ..parallel.spmd import constrain
        seq_ax = "sp" if self._seq_parallel else None
        logits = constrain(logits, ("dp", "fsdp"), seq_ax, "tp")
        return logits


def lm_loss(model: GPTModel, input_ids, labels, weights=None):
    """Next-token cross entropy, shaped for SPMDTrainer.forward_loss.

    CE as pick − logsumexp with f32 accumulation: the (B, T, vocab)
    log-prob tensor is never materialized and bf16 logits lose no
    reduction precision (same streaming form as BERT's MLM loss)."""
    logits = model(input_ids)
    label_scores = logits.pick(labels, axis=-1)       # (B, T)
    lse = logits._op("logsumexp", axis=-1)
    ll = label_scores.astype("float32") - lse
    if weights is None:
        return -ll.mean()
    denom = weights.sum() + 1e-6
    return -(ll * weights).sum() / denom


def lm_pipeline(model: GPTModel, weighted: bool = False):
    """PipelineSpec for ``lm_loss`` training under the pipelined SPMD
    step (parallel/pipelined.py): stem = embeddings, one pipeline block
    per transformer layer, head = final norm + tied vocab projection +
    the next-token CE as LOCAL partial sums.

    ``weighted`` selects the ``lm_loss(..., weights=...)`` form (batch =
    (input_ids, labels, weights)); default mirrors the plain mean form
    (batch = (input_ids, labels)). The stem/head bodies replicate
    ``GPTModel.hybrid_forward`` + ``lm_loss`` op-for-op so the pipelined
    loss/gradients are bitwise-identical to the GSPMD step."""
    from ..parallel.pipelined import PipelineSpec
    from ..gluon.block import nd as F

    def stem(input_ids, *rest):
        from ..parallel.spmd import constrain
        B, T = input_ids.shape
        pos = F.arange(0, T, dtype="int32").reshape((1, T)) \
            .broadcast_to((B, T))
        x = model.word_embed(input_ids) + model.position_embed(pos)
        x = constrain(x, ("dp", "fsdp"), None, None)
        x = model.embed_dropout(x)
        if model._dtype != "float32":
            x = x.astype(model._dtype)
        return x

    def head(x, input_ids, labels, *rest):
        from ..parallel.spmd import constrain
        x = model.ln_f(x)
        embed_w = model.word_embed.weight.data()
        logits = F.dot(x, embed_w.astype(x.dtype), transpose_b=True)
        logits = constrain(logits, ("dp", "fsdp"), None, "tp")
        label_scores = logits.pick(labels, axis=-1)        # (B, T)
        lse = logits._op("logsumexp", axis=-1)
        ll = label_scores.astype("float32") - lse
        if weighted:
            if not rest:
                raise MXNetError(
                    "lm_pipeline(weighted=True) expects batch = "
                    "(input_ids, labels, weights)")
            w = rest[0]
            return ((ll * w).sum(), w.sum())
        return (ll.sum(), NDArray(jnp.float32(ll._data.size)))

    if weighted:
        def finalize(n, d):
            return -(n / (d + 1e-6))
    else:
        def finalize(n, d):
            return -(n / d)

    blocks = [getattr(model, f"block{i}") for i in range(model.num_layers)]
    return PipelineSpec(
        blocks=blocks, head=head, finalize=finalize, stem=stem,
        stem_modules=[model.word_embed, model.position_embed],
        head_modules=[model.ln_f, model.word_embed],
        name="gpt_lm")


def greedy_generate(model: GPTModel, prompt_ids, max_new_tokens=32,
                    temperature: float = 0.0):
    """Fixed-shape autoregressive decode: ONE lax.fori_loop program over
    a pre-allocated (B, T0 + max_new_tokens) buffer — full-prefix
    recompute per step (no KV cache), the shape-static jit-once design
    (BucketingModule's multi-shape caching is the alternative for many
    prompt lengths)."""
    ids = prompt_ids._data if isinstance(prompt_ids, NDArray) \
        else jnp.asarray(prompt_ids)
    B, T0 = ids.shape
    total = T0 + int(max_new_tokens)
    if total > model.max_length:
        raise MXNetError(f"decode length {total} exceeds max_length "
                         f"{model.max_length}")
    buf = jnp.zeros((B, total), jnp.int32)
    buf = lax.dynamic_update_slice(buf, ids.astype(jnp.int32), (0, 0))
    key = _rand.new_key()

    from ..gluon.block import _hybrid_trace_scope
    from .. import autograd

    def fwd(b):
        with _hybrid_trace_scope(), \
                autograd._ModeScope(recording=False, training=False):
            return model(NDArray(b))._data

    def step(t, carry):
        buf, key = carry
        logits = fwd(buf)                              # (B, total, V)
        idx = jnp.clip(t - 1, 0, total - 1)
        last = lax.dynamic_slice(
            logits, (0, idx, 0), (B, 1, logits.shape[-1]))[:, 0]
        if temperature > 0:
            key, sub = jax.random.split(key)
            nxt = jax.random.categorical(sub, last / temperature, axis=-1)
        else:
            nxt = jnp.argmax(last, axis=-1)
        buf = lax.dynamic_update_slice(
            buf, nxt.astype(jnp.int32)[:, None], (0, idx + 1))
        return buf, key

    buf, _ = lax.fori_loop(T0, total, step, (buf, key))
    return NDArray(buf)


def gpt_mini(vocab_size=512, max_length=128, **kwargs) -> GPTModel:
    """Tiny config for tests/dry-runs."""
    return GPTModel(vocab_size=vocab_size, units=128, hidden_size=512,
                    num_layers=2, num_heads=4, max_length=max_length,
                    **kwargs)


def gpt_small(**kwargs) -> GPTModel:
    return GPTModel(vocab_size=50257, units=768, hidden_size=3072,
                    num_layers=12, num_heads=12, max_length=1024,
                    **kwargs)


# --------------------------------------------------------------------- #
# KV-cached incremental decode (the reference's stateful incremental
# inference path — RNN states / GluonNLP decoder states — re-designed
# for XLA: caches are fixed-shape (B, max_len, H, D) buffers updated
# with dynamic_update_slice, so prefill + every decode step compile to
# static-shape programs and generation is O(T) per new token instead of
# the O(T^2) full-prefix recompute of ``greedy_generate``.)
# --------------------------------------------------------------------- #

def _qkv_heads(attn: CausalSelfAttention, x):
    """Project and split x (B, Tin, units) into per-head q, k, v jnp
    arrays shaped (B, Tin, H, D). Shared by the dense KV-cache decode
    path below and the paged-KV serving engine (serve/engine.py) so the
    projection/split numerics cannot drift between the two caches."""
    B, Tin = x.shape[0], x.shape[1]
    H, D = attn._heads, attn._units // attn._heads
    qkv = attn.qkv(x).reshape((B, Tin, 3, H, D))
    q = qkv._op("slice_axis", axis=2, begin=0, end=1).reshape(
        (B, Tin, H, D))._data
    k = qkv._op("slice_axis", axis=2, begin=1, end=2).reshape(
        (B, Tin, H, D))._data
    v = qkv._op("slice_axis", axis=2, begin=2, end=3).reshape(
        (B, Tin, H, D))._data
    return q, k, v


def _mlp(blk: GPTBlock, x):
    """The decode-path FFN half of a block: ln2 → ffn_in → exact gelu →
    ffn_out (no dropout — inference only). Shared with serve/engine.py."""
    return blk.ffn_out(NDArray(jax.nn.gelu(
        blk.ffn_in(blk.ln2(x))._data, approximate=False)))


def _lm_head(model: GPTModel, x):
    """Final norm + tied vocab projection for the decode paths: cast to
    f32 BEFORE ``ln_f`` (norming bf16 then casting would feed
    bf16-rounded activations into the vocab projection and break token
    parity with the training/greedy path — see ``decode_forward``).
    Shared by the dense KV-cache decode below and every serving-engine
    program (prefill, chunk, K-wide speculative verify) so head
    numerics cannot drift between the caches or between verify
    positions. x: (B, T, units) NDArray → (B, T, vocab) NDArray."""
    x = model.ln_f(x.astype("float32"))
    embed_w = model.word_embed.weight.data()
    return x._op("dot", embed_w, transpose_b=True)


def _attn_decode(attn: CausalSelfAttention, x, k_buf, v_buf, start_pos):
    """Run attention for positions [start_pos, start_pos+Tin) against the
    cache. x: (B, Tin, units); k_buf/v_buf: (B, Tmax, H, D) jnp arrays.
    Returns (out (B, Tin, units), k_buf, v_buf)."""
    B, Tin = x.shape[0], x.shape[1]
    H, D = attn._heads, attn._units // attn._heads
    Tmax = k_buf.shape[1]
    q, k, v = _qkv_heads(attn, x)
    k_buf = lax.dynamic_update_slice(k_buf, k.astype(k_buf.dtype),
                                     (0, start_pos, 0, 0))
    v_buf = lax.dynamic_update_slice(v_buf, v.astype(v_buf.dtype),
                                     (0, start_pos, 0, 0))
    # causal mask against GLOBAL cache positions (static shapes: iota);
    # attention itself reuses the shared sdpa op so masking/softmax
    # numerics stay identical to the training path
    from ..ops.attention import scaled_dot_product_attention as _sdpa
    pos_q = start_pos + lax.broadcasted_iota(jnp.int32, (Tin, Tmax), 0)
    pos_k = lax.broadcasted_iota(jnp.int32, (Tin, Tmax), 1)
    mask = (pos_k <= pos_q)[None, None]            # (1, 1, Tin, Tmax)
    out = _sdpa(q, k_buf.astype(q.dtype), v_buf.astype(q.dtype),
                mask=mask)
    out = NDArray(out.reshape(B, Tin, attn._units))
    return attn.proj(out), k_buf, v_buf


def _block_decode(blk: GPTBlock, x, k_buf, v_buf, start_pos):
    h, k_buf, v_buf = _attn_decode(blk.attn, blk.ln1(x), k_buf, v_buf,
                                   start_pos)
    x = x + h
    return x + _mlp(blk, x), k_buf, v_buf


def init_kv_cache(model: GPTModel, batch_size: int, max_len=None,
                  dtype=None):
    """Fresh (k, v) cache buffers for every layer."""
    H = model.block0.attn._heads
    D = model._units // H
    Tmax = int(max_len or model.max_length)
    dt = jnp.dtype(dtype) if dtype else jnp.dtype(model._dtype)
    mk = lambda: jnp.zeros((batch_size, Tmax, H, D), dt)
    return [(mk(), mk()) for _ in range(model.num_layers)]


def decode_forward(model: GPTModel, ids, caches, start_pos,
                   last_only=False):
    """Forward positions [start_pos, start_pos+Tin) with KV caches.
    ids: (B, Tin) int32; returns (logits, caches) — logits over all Tin
    positions, or only the last one when ``last_only`` (prefill wants
    one next-token row, not a (B, T0, vocab) tensor).

    INFERENCE-ONLY: dropout is never applied on this path, so results
    diverge from ``model(ids)`` under an active training mode — guarded
    below rather than silently wrong."""
    from .. import autograd as _ag
    if _ag.is_training():
        raise MXNetError(
            "decode_forward is inference-only (dropout is skipped); call "
            "it under autograd.predict_mode()")
    B, Tin = ids.shape
    ids_nd = ids if isinstance(ids, NDArray) else NDArray(ids)
    pos = NDArray(start_pos + lax.broadcasted_iota(jnp.int32, (B, Tin), 1))
    x = model.word_embed(ids_nd) + model.position_embed(pos)
    if model._dtype != "float32":
        x = x.astype(model._dtype)
    new_caches = []
    for i in range(model.num_layers):
        blk = getattr(model, f"block{i}")
        k_buf, v_buf = caches[i]
        x, k_buf, v_buf = _block_decode(blk, x, k_buf, v_buf, start_pos)
        new_caches.append((k_buf, v_buf))
    if last_only:
        x = x._op("slice_axis", axis=1, begin=Tin - 1, end=Tin)
    return _lm_head(model, x), new_caches


def cached_generate(model: GPTModel, prompt_ids, max_new_tokens=32,
                    temperature: float = 0.0):
    """KV-cached autoregressive decode: one prefill pass over the prompt,
    then one single-token program per step (both jit-compiled once).
    Same contract/output as ``greedy_generate``."""
    ids = prompt_ids._data if isinstance(prompt_ids, NDArray) \
        else jnp.asarray(prompt_ids)
    B, T0 = ids.shape
    total = T0 + int(max_new_tokens)
    if total > model.max_length:
        raise MXNetError(f"decode length {total} exceeds max_length "
                         f"{model.max_length}")
    from ..gluon.block import _hybrid_trace_scope
    from .. import autograd

    caches = init_kv_cache(model, B, max_len=total)
    key = _rand.new_key()

    with _hybrid_trace_scope(), autograd._ModeScope(recording=False,
                                                    training=False):
        logits, caches = decode_forward(model, NDArray(ids.astype(
            jnp.int32)), caches, 0, last_only=True)
        last = logits._data[:, 0]

        buf = jnp.zeros((B, total), jnp.int32)
        buf = lax.dynamic_update_slice(buf, ids.astype(jnp.int32), (0, 0))

        def step(t, carry):
            buf, last, key, lcaches = carry
            if temperature > 0:
                key, sub = jax.random.split(key)
                nxt = jax.random.categorical(sub, last / temperature,
                                             axis=-1)
            else:
                nxt = jnp.argmax(last, axis=-1)
            buf = lax.dynamic_update_slice(
                buf, nxt.astype(jnp.int32)[:, None], (0, t))
            logits, ncaches = decode_forward(
                model, NDArray(nxt.astype(jnp.int32)[:, None]), lcaches, t)
            return (buf, logits._data[:, 0], key, ncaches)

        buf, _, _, _ = lax.fori_loop(T0, total, step,
                                     (buf, last, key, caches))
    return NDArray(buf)
