"""Prometheus-style text rendering of serving health snapshots.

``render_metrics`` turns ``InferenceEngine.health_snapshot()`` or
``Router.health_snapshot()`` into the Prometheus text exposition
format (``# TYPE``-annotated lines) — the scrape surface an operator's
monitoring stack expects from a serving tier. It is a PURE renderer
over the detached snapshot dicts (never the live-mutated ``health``
state), so a scrape can never observe torn counters; serving it over
HTTP is one handler around one string.

Conventions:

  - counters end in ``_total``; everything instantaneous is a gauge;
  - per-tier outcome counters carry ``{tier=...,outcome=...}`` labels
    (only non-zero series are emitted — the label space is bounded by
    |Tier| x |Outcome| but sparse in practice);
  - a fleet snapshot nests per-replica engine gauges under a
    ``replica="<idx>"`` label plus a ``..._replica_up`` health gauge
    (1 SERVING, 0.5 DEGRADED, 0 DEAD);
  - ``None`` values (e.g. an uncalibrated EWMA) are skipped rather
    than rendered as NaN — absence is the honest representation.

Output is golden-parsed in tests/test_tiers.py: every sample line must
follow a matching ``# TYPE`` declaration and parse back to the
snapshot's numbers.
"""

from __future__ import annotations

from typing import List, Optional

__all__ = ["render_metrics", "render_frontend_metrics"]

_NS = "mxtpu_serve"

# snapshot key -> (metric suffix, prometheus type)
_ENGINE_GAUGES = [
    ("queue_depth", "queue_depth", "gauge"),
    ("active_slots", "active_slots", "gauge"),
    ("free_slots", "free_slots", "gauge"),
    ("num_slots", "num_slots", "gauge"),
    ("free_pages", "free_pages", "gauge"),
    ("ewma_service_s", "ewma_service_seconds", "gauge"),
    ("estimated_queue_delay_s", "estimated_queue_delay_seconds",
     "gauge"),
    ("estimated_queue_delay_priority_s",
     "estimated_queue_delay_priority_seconds", "gauge"),
    ("accept_rate", "accept_rate", "gauge"),
    ("brownout_level", "brownout_level", "gauge"),
    # KV-pool capacity (quantized serving, docs/SERVING.md): the bytes
    # the cache pins (scale metadata included) and how many live pages
    # hold quantized payload — the doubled-working-set dashboard
    ("kv_pool_bytes", "kv_pool_bytes", "gauge"),
    ("kv_quantized_pages", "kv_quantized_pages", "gauge"),
]
_ENGINE_COUNTERS = [
    ("decode_steps", "decode_steps_total"),
    ("drafted_tokens", "drafted_tokens_total"),
    ("accepted_tokens", "accepted_tokens_total"),
    ("prefix_hits", "prefix_hits_total"),
    ("prefix_lookups", "prefix_lookups_total"),
    ("stop_hits", "stop_hits_total"),
    ("constrained_requests", "constrained_requests_total"),
    ("preemptions", "preemptions_total"),
    ("brownout_escalations", "brownout_escalations_total"),
    ("brownout_deescalations", "brownout_deescalations_total"),
    # hierarchical prefix-cache tiers (docs/SERVING.md): demotion /
    # promotion traffic and the integrity-fallback counter — all zero
    # (but present) on an untiered engine
    ("tier_demotions", "kv_tier_demotions_total"),
    ("tier_disk_demotions", "kv_tier_disk_demotions_total"),
    ("tier_promotions", "kv_tier_promotions_total"),
    ("tier_hits", "kv_tier_hits_total"),
    ("tier_hit_tokens", "kv_tier_hit_tokens_total"),
    ("tier_misses", "kv_tier_misses_total"),
    ("tier_crc_fallbacks", "kv_tier_crc_fallbacks_total"),
    ("tier_disk_errors", "kv_tier_disk_errors_total"),
    ("tier_dropped", "kv_tier_dropped_total"),
    # page transport (serve/transport.py): capsule traffic through
    # THIS engine — outbound captures and inbound installs
    ("migrated_out_pages", "kv_migrated_out_pages_total"),
    ("migrated_in_pages", "kv_migrated_in_pages_total"),
    ("migrated_out_bytes", "kv_migrated_out_bytes_total"),
    ("migrated_in_bytes", "kv_migrated_in_bytes_total"),
]
_ROUTER_COUNTERS = [
    ("requeues", "requeues_total"),
    ("replica_deaths", "replica_deaths_total"),
    ("breaker_opens", "breaker_opens_total"),
    ("probes", "probes_total"),
    ("recoveries", "recoveries_total"),
    ("affinity_routed", "affinity_routed_total"),
    ("tier_affinity_routed", "tier_affinity_routed_total"),
    ("spill_routed", "spill_routed_total"),
    # page transport: fleet-level migration tally
    ("migrations", "migrations_total"),
    ("migrations_failed", "migrations_failed_total"),
    ("migrated_pages", "kv_migrated_pages_total"),
    ("migrated_bytes", "kv_migrated_bytes_total"),
    # elastic membership (add/remove/upgrade_replica)
    ("scale_ups", "scale_ups_total"),
    ("scale_downs", "scale_downs_total"),
    ("upgrades", "upgrades_total"),
]

# replica-state gauge: 1.0 fully routable, fractional while joining
# (WARMING: spill-only) or leaving (DRAINING: no admissions), 0.0 gone
_REPLICA_UP = {"SERVING": 1.0, "WARMING": 0.75, "DEGRADED": 0.5,
               "DRAINING": 0.25, "DEAD": 0.0, "RETIRED": 0.0}

# flight-recorder latency metrics (serve/events.py) -> prometheus name
_HIST_METRICS = [
    ("ttft", "ttft_seconds"),
    ("tpot", "tpot_seconds"),
    ("queue_delay", "queue_delay_seconds"),
    ("e2e", "e2e_latency_seconds"),
]


class _Writer:
    """Accumulates samples grouped under one ``# TYPE`` line per
    metric name (the format requires the declaration to precede every
    sample of that name, once). Histogram samples carry the
    Prometheus suffix convention: the ``# TYPE x histogram`` line
    declares ``x``; the samples are ``x_bucket{le=...}`` /
    ``x_sum`` / ``x_count``."""

    def __init__(self):
        self._types: dict = {}           # name -> type
        self._samples: dict = {}         # name -> [(suffix, labels, v)]

    def add(self, name: str, mtype: str, value, labels: str = ""):
        if value is None:
            return
        self._types.setdefault(name, mtype)
        self._samples.setdefault(name, []).append(("", labels,
                                                   float(value)))

    def add_histogram(self, name: str, bounds, counts, hsum, hcount,
                      labels: Optional[dict] = None):
        """One histogram series: ``counts`` is per-bucket (NOT
        cumulative) with the overflow bucket last — rendered as the
        cumulative ``_bucket`` samples the format requires, closed by
        ``le="+Inf"`` == ``_count``."""
        labels = dict(labels or {})
        self._types.setdefault(name, "histogram")
        rows = self._samples.setdefault(name, [])
        cum = 0
        for b, c in zip(bounds, counts):
            cum += c
            rows.append(("_bucket", _labels(**labels, le=repr(float(b))),
                         float(cum)))
        rows.append(("_bucket", _labels(**labels, le="+Inf"),
                     float(hcount)))
        rows.append(("_sum", _labels(**labels), float(hsum)))
        rows.append(("_count", _labels(**labels), float(hcount)))

    def render(self) -> str:
        out: List[str] = []
        for name in self._samples:
            out.append(f"# TYPE {name} {self._types[name]}")
            for suffix, labels, value in self._samples[name]:
                if value == int(value):
                    sval = str(int(value))
                else:
                    sval = repr(value)
                out.append(f"{name}{suffix}{labels} {sval}")
        return "\n".join(out) + "\n"


def _labels(**kv) -> str:
    if not kv:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in kv.items())
    return "{" + inner + "}"


def _emit_hists(w: _Writer, snap: dict, ns: str = _NS,
                extra: Optional[dict] = None):
    """Tier-labeled TTFT/TPOT/queue-delay/e2e histograms from the
    flight recorder's snapshot (``latency_hists``) — derived from the
    SAME event stream as the outcome counters, so the percentiles a
    dashboard computes from these can never disagree with the
    counters next to them (docs/OBSERVABILITY.md)."""
    hists = snap.get("latency_hists")
    if not hists:
        return
    extra = extra or {}
    bounds = hists["bounds"]
    for metric, suffix in _HIST_METRICS:
        for tier, cell in sorted(hists["metrics"].get(metric,
                                                      {}).items()):
            labels = dict(extra)
            if tier:
                labels["tier"] = tier
            w.add_histogram(f"{ns}_{suffix}", bounds, cell["counts"],
                            cell["sum"], cell["count"], labels)


def _emit_outcomes(w: _Writer, snap: dict, ns: str = _NS,
                   extra: Optional[dict] = None):
    extra = extra or {}
    name = f"{ns}_requests_total"
    for outcome, n in snap.get("outcomes", {}).items():
        if n:
            w.add(name, "counter", n,
                  _labels(outcome=outcome, **extra))
    tname = f"{ns}_tier_requests_total"
    for tier, d in snap.get("outcomes_by_tier", {}).items():
        for outcome, n in d.items():
            if n:
                w.add(tname, "counter", n,
                      _labels(tier=tier, outcome=outcome, **extra))
    qname = f"{ns}_tier_queue_depth"
    for tier, n in snap.get("queue_depth_by_tier", {}).items():
        w.add(qname, "gauge", n, _labels(tier=tier, **extra))


def _emit_engine(w: _Writer, snap: dict, ns: str = _NS,
                 extra: Optional[dict] = None):
    extra = extra or {}
    _emit_outcomes(w, snap, ns, extra)
    if "kv_dtype" in snap:
        # info-style gauge: the payload dtype and quant mode ride as
        # labels (strings cannot be sample values), value constant 1
        w.add(f"{ns}_kv_pool_info", "gauge", 1,
              _labels(dtype=snap["kv_dtype"],
                      quant=snap.get("kv_quant", "off"), **extra))
    for key, suffix, mtype in _ENGINE_GAUGES:
        if key in snap:
            w.add(f"{ns}_{suffix}", mtype, snap[key],
                  _labels(**extra))
    # per-tier resident bytes of the hierarchical prefix cache: one
    # gauge, ``tier`` label ("dram"/"disk") — bounded label space
    for tier, nbytes in sorted(snap.get("kv_tier_bytes", {}).items()):
        w.add(f"{ns}_kv_tier_bytes", "gauge", nbytes,
              _labels(tier=tier, **extra))
    for key, suffix in _ENGINE_COUNTERS:
        if key in snap:
            w.add(f"{ns}_{suffix}", "counter", snap[key],
                  _labels(**extra))
    _emit_hists(w, snap, ns, extra)


def render_frontend_metrics(stats: dict) -> str:
    """Prometheus text for the HTTP front end's own counters
    (``ServeFrontend.stats_snapshot()`` — serve/frontend.py): request
    and per-status response totals, disconnect/slow-reader cancels,
    and streamed-token count. Appended to the backend's
    ``render_metrics`` output by the ``/metrics`` handler so one
    scrape covers the client edge and the serving core."""
    w = _Writer()
    w.add(f"{_NS}_http_requests_total", "counter",
          stats.get("http_requests", 0))
    for status, n in sorted(stats.get("http_responses", {}).items()):
        w.add(f"{_NS}_http_responses_total", "counter", n,
              _labels(status=status))
    w.add(f"{_NS}_http_disconnects_total", "counter",
          stats.get("disconnects", 0))
    w.add(f"{_NS}_http_slow_reader_cancels_total", "counter",
          stats.get("slow_reader_cancels", 0))
    w.add(f"{_NS}_sse_tokens_total", "counter",
          stats.get("sse_tokens", 0))
    w.add(f"{_NS}_http_open_streams", "gauge",
          stats.get("open_streams", 0))
    return w.render()


def render_metrics(snapshot: dict) -> str:
    """Render an engine or router ``health_snapshot()`` dict as
    Prometheus text. Router snapshots (detected by their ``replicas``
    entry) emit the fleet-level outcome/routing counters (CLIENT
    requests) plus each live replica's engine metrics under the
    ``{ns}_replica_*`` namespace with a ``replica="<idx>"`` label —
    engine counters count ATTEMPTS (which legitimately exceed client
    requests under requeue), so they must not share a series name
    with the fleet-level counters a dashboard would sum."""
    w = _Writer()
    if "replicas" not in snapshot:
        _emit_engine(w, snapshot)
        return w.render()
    _emit_outcomes(w, snapshot)
    _emit_hists(w, snapshot)             # client-level SLO histograms
    w.add(f"{_NS}_queue_depth", "gauge", snapshot["queue_depth"])
    w.add(f"{_NS}_inflight", "gauge", snapshot["inflight"])
    w.add(f"{_NS}_fleet_size", "gauge",
          snapshot.get("fleet_size", len(snapshot["replicas"])))
    for key, suffix in _ROUTER_COUNTERS:
        w.add(f"{_NS}_{suffix}", "counter", snapshot[key])
    rns = f"{_NS}_replica"
    for rep in snapshot["replicas"]:
        extra = {"replica": rep["idx"]}
        w.add(f"{rns}_up", "gauge",
              _REPLICA_UP.get(rep["state"], 0.0), _labels(**extra))
        if "engine" in rep:
            _emit_engine(w, rep["engine"], rns, extra)
    return w.render()
