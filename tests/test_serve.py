"""Continuous-batching engine tests (serve/).

The load-bearing claims: (1) paged-cache decode emits EXACTLY the
tokens of the dense-cache ``cached_generate`` path, per request, even
when requests share a batch at mixed occupancy; (2) occupancy churn
(prefill-insert, EOS-eviction, slot reuse) never retraces the decode
step; (3) pages are fully reclaimed; (4) per-slot sampling params are
isolated; (5) tp pool sharding through parallel.mesh preserves
tokens."""

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.models import gpt as g
from incubator_mxnet_tpu.serve import InferenceEngine, Request
from incubator_mxnet_tpu.serve.paged_kv import (NULL_PAGE, PageAllocator,
                                                PrefixIndex)


@pytest.fixture(scope="module")
def model():
    mx.random.seed(0)
    m = g.gpt_mini(vocab_size=64, max_length=64)
    m.initialize()
    return m


def _solo_reference(model, prompt, max_new):
    """Per-request oracle: the dense KV-cache decode path."""
    out = g.cached_generate(model, nd.array(prompt[None, :],
                                            dtype="int32"),
                            max_new_tokens=max_new).asnumpy()
    return out[0, prompt.size:]


def test_single_request_matches_cached_generate(model):
    rng = np.random.RandomState(1)
    prompt = rng.randint(0, 64, size=(7,)).astype(np.int32)
    ref = _solo_reference(model, prompt, 12)
    eng = InferenceEngine(model, num_slots=4, page_size=8, max_len=64)
    req = Request(prompt, max_new_tokens=12)
    eng.run([req])
    np.testing.assert_array_equal(np.asarray(req.token_ids, np.int32),
                                  ref)
    assert eng.decode_trace_count == 1


@pytest.mark.slow   # 13-21s (round-10 tier-1 budget repair); ci stage_unit runs it
def test_mixed_occupancy_no_cross_contamination_and_slot_reuse(model):
    """5 ragged requests through 3 slots with staggered arrivals: every
    request's tokens must equal its SOLO dense-cache decode (continuous
    batching is invisible to each request), the decode step compiles
    once across all the insert/evict churn, and every page returns to
    the allocator (slot + page reuse)."""
    rng = np.random.RandomState(2)
    lens = (3, 9, 17, 5, 12)
    news = (10, 6, 14, 8, 12)
    prompts = [rng.randint(0, 64, size=(n,)).astype(np.int32)
               for n in lens]
    refs = [_solo_reference(model, p, k) for p, k in zip(prompts, news)]
    eng = InferenceEngine(model, num_slots=3, page_size=8, max_len=64,
                          num_pages=20)
    reqs = [Request(p, max_new_tokens=k) for p, k in zip(prompts, news)]
    eng.run(reqs, arrival_times=[0.0, 0.0, 0.01, 0.02, 0.03])
    for req, ref in zip(reqs, refs):
        np.testing.assert_array_equal(np.asarray(req.token_ids,
                                                 np.int32), ref)
    assert eng.decode_trace_count == 1, \
        "decode step retraced under occupancy churn"
    # every page is either on the free list or retained by the prefix
    # index (full prompt pages stay cached for reuse) — nothing leaked
    eng.audit_pages()
    assert eng._alloc.free_count == eng.num_pages - 1 - len(eng._prefix)
    assert len(eng._prefix) > 0          # the full prompt pages cached
    assert (eng._page_table == NULL_PAGE).all()
    assert (eng._lengths == 0).all()


def test_eos_eviction_truncates_and_frees(model):
    rng = np.random.RandomState(3)
    prompt = rng.randint(0, 64, size=(6,)).astype(np.int32)
    ref = _solo_reference(model, prompt, 14)
    eos = int(ref[3])
    stop = int(np.argmax(ref == eos))       # first occurrence
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64)
    req = Request(prompt, max_new_tokens=14, eos_id=eos)
    eng.run([req])
    np.testing.assert_array_equal(np.asarray(req.token_ids, np.int32),
                                  ref[:stop + 1])
    assert req.finish_time is not None
    assert eng.active_count == 0
    assert eng._alloc.free_count == eng.num_pages - 1


def test_per_slot_sampling_isolation(model):
    """A greedy request and a temperature>0 request share the decode
    batch; the greedy one's tokens must be bit-identical to its solo
    run — per-slot sampling params must not leak across slots."""
    rng = np.random.RandomState(4)
    p_greedy = rng.randint(0, 64, size=(8,)).astype(np.int32)
    p_hot = rng.randint(0, 64, size=(11,)).astype(np.int32)
    ref = _solo_reference(model, p_greedy, 10)
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64)
    r1 = Request(p_greedy, max_new_tokens=10, temperature=0.0)
    r2 = Request(p_hot, max_new_tokens=10, temperature=1.3)
    eng.run([r1, r2])
    np.testing.assert_array_equal(np.asarray(r1.token_ids, np.int32),
                                  ref)
    assert len(r2.token_ids) == 10
    assert all(0 <= t < 64 for t in r2.token_ids)


@pytest.mark.slow   # 10s (round-11 tier-1 budget repair); admission /
                    # reclaim tier-1 coverage stays via the churn-audit
                    # and unservable tests; ci stage_unit runs it
def test_admission_control_waits_for_pages(model):
    """A pool too small for two concurrent requests serializes them
    (second waits for eviction) instead of corrupting the cache; a pool
    too small for ANY request fails THAT request with the
    FAILED_UNSERVABLE terminal outcome — regression for the old
    behavior where run() raised RuntimeError/MXNetError out of the
    serving loop and took every other in-flight request down with it."""
    from incubator_mxnet_tpu.serve import Outcome
    rng = np.random.RandomState(5)
    prompts = [rng.randint(0, 64, size=(8,)).astype(np.int32)
               for _ in range(2)]
    refs = [_solo_reference(model, p, 8) for p in prompts]
    # each request needs ceil(16/8)=2 pages; 3 non-null pages admit one
    # at a time only
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64,
                          num_pages=4)
    reqs = [Request(p, max_new_tokens=8) for p in prompts]
    eng.run(reqs)
    for req, ref in zip(reqs, refs):
        np.testing.assert_array_equal(np.asarray(req.token_ids,
                                                 np.int32), ref)
    # the old crash path: a request that can NEVER fit the pool, mixed
    # with one that can — the doomed one fails loudly (terminal outcome,
    # detail naming the capacity), the other is served to completion
    tiny = InferenceEngine(model, num_slots=1, page_size=8, max_len=64,
                           num_pages=3)
    doomed = Request(prompts[0], max_new_tokens=16)   # needs 3 > 2 pages
    servable = Request(prompts[1], max_new_tokens=8)  # needs 2 pages
    tiny.run([doomed, servable])
    assert doomed.outcome == Outcome.FAILED_UNSERVABLE
    assert "pages" in doomed.detail
    assert servable.outcome is not None and servable.outcome.ok
    np.testing.assert_array_equal(
        np.asarray(servable.token_ids, np.int32), refs[1])
    assert tiny.unservable == 1
    tiny.audit_pages()


def test_decode_shapes_independent_of_occupancy(model):
    """Drain a batch where every step changes occupancy (different
    max_new per request) — still one decode trace, and prefill traces
    are bounded by the bucket family, not the request count."""
    rng = np.random.RandomState(6)
    reqs = [Request(rng.randint(0, 64, size=(1 + 2 * i,)).astype(
        np.int32), max_new_tokens=3 + i) for i in range(6)]
    eng = InferenceEngine(model, num_slots=4, page_size=8, max_len=64)
    eng.run(reqs)
    assert eng.decode_trace_count == 1
    assert eng.prefill_trace_count <= 3     # pow2 page buckets: 1, 2, 4
    assert all(len(r.token_ids) == 3 + i for i, r in enumerate(reqs))


@pytest.mark.slow   # 13-21s (round-10 tier-1 budget repair); ci stage_unit runs it
def test_tp_sharded_pools_token_parity(model):
    """Pools sharded over the tp mesh axis (H dim) through
    parallel.mesh must reproduce the unsharded tokens exactly — the
    engine is mesh-agnostic data-flow, sharding is placement only."""
    from incubator_mxnet_tpu.parallel.mesh import build_mesh
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual multi-device mesh")
    mesh = build_mesh(axis_sizes={"tp": 2})
    rng = np.random.RandomState(7)
    prompts = [rng.randint(0, 64, size=(n,)).astype(np.int32)
               for n in (5, 13)]
    refs = [_solo_reference(model, p, 9) for p in prompts]
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64,
                          mesh=mesh)
    reqs = [Request(p, max_new_tokens=9) for p in prompts]
    eng.run(reqs)
    for req, ref in zip(reqs, refs):
        np.testing.assert_array_equal(np.asarray(req.token_ids,
                                                 np.int32), ref)


def test_warm_restart_swaps_weights_without_retrace(model, tmp_path):
    """Elastic-checkpointing serve integration: warm_start pushes NEW
    weights into a LIVE engine — tokens must match a fresh engine built
    on those weights (proof the swap took effect) while the decode step
    keeps its single compile (weights are traced inputs, not closure
    constants)."""
    from incubator_mxnet_tpu import checkpoint as ckpt

    mx.random.seed(1234)
    model_b = g.gpt_mini(vocab_size=64, max_length=64)
    model_b.initialize()
    rng = np.random.RandomState(11)
    prompt = rng.randint(0, 64, size=(7,)).astype(np.int32)
    ref_b = _solo_reference(model_b, prompt, 10)

    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64)
    r0 = Request(prompt.copy(), max_new_tokens=10)
    eng.run([r0])
    assert eng.decode_trace_count == 1
    prefills_before = eng.prefill_trace_count

    # ship model_b's weights through a committed checkpoint, then warm
    # restart the live engine from it
    mgr = ckpt.CheckpointManager(str(tmp_path), keep=1)
    eng_b = InferenceEngine(model_b, num_slots=2, page_size=8,
                            max_len=64)
    eng_b.save_checkpoint(mgr, block=True)
    eng.warm_start(manager=mgr)
    r1 = Request(prompt.copy(), max_new_tokens=10)
    eng.run([r1])
    np.testing.assert_array_equal(np.asarray(r1.token_ids, np.int32),
                                  ref_b)
    assert eng.decode_trace_count == 1, "warm restart retraced decode"
    assert eng.prefill_trace_count == prefills_before, \
        "warm restart retraced prefill"
    assert eng.warm_restarts == 1
    mgr.close()


def test_warm_restart_accepts_full_training_capsule_tree(model):
    """Regression: a TRAINING capsule also carries opt/<i>/<j> and
    rng/key entries; warm_start must use only the param/ entries
    instead of letting the extra keys break positional-key detection
    (the advertised train-to-serve path)."""
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64)
    tree = {f"param/{i}": p.data().asnumpy()
            for i, p in enumerate(eng._eng_params)}
    tree["opt/0/0"] = np.zeros((1,), np.float32)
    tree["rng/key"] = np.zeros((2,), np.uint32)
    eng.warm_start(params=tree)
    assert eng.warm_restarts == 1
    assert eng.decode_trace_count == 0   # still nothing traced


def test_warm_restart_rejects_shape_mismatch(model):
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64)
    bad = {str(i): np.zeros((1, 1), np.float32)
           for i in range(len(eng._eng_params))}
    with pytest.raises(MXNetError, match="shape/dtype"):
        eng.warm_start(params=bad)


def test_page_allocator_invariants():
    a = PageAllocator(5)
    assert a.free_count == 4                 # page 0 reserved
    got = {a.alloc() for _ in range(4)}
    assert NULL_PAGE not in got
    with pytest.raises(MXNetError):
        a.alloc()
    a.free(got)
    assert a.free_count == 4
    with pytest.raises(MXNetError):
        a.free([NULL_PAGE])
    with pytest.raises(MXNetError):
        PageAllocator(1)


def test_page_allocator_refcount_hardening():
    """Free-list corruption is refused loudly: freeing the null page,
    double-freeing a page already back on the free list, and dropping a
    refcount below zero all raise instead of silently double-granting
    pages later."""
    a = PageAllocator(6)
    # double free: the second decref finds refcount 0
    p = a.alloc()
    assert a.refcount(p) == 1
    a.free([p])
    assert a.refcount(p) == 0
    with pytest.raises(MXNetError, match="double free"):
        a.free([p])
    assert a.free_count == 5                 # free list not corrupted
    # refcount below zero through the sharing path
    p = a.alloc()
    a.incref(p)
    assert a.refcount(p) == 2
    assert not a.decref(p)                   # still live (a sharer left)
    assert a.decref(p)                       # last ref → free list
    with pytest.raises(MXNetError, match="double free"):
        a.decref(p)
    # the null page is never freeable or shareable
    with pytest.raises(MXNetError, match="null page"):
        a.decref(NULL_PAGE)
    with pytest.raises(MXNetError, match="null page"):
        a.incref(NULL_PAGE)
    # sharing a free page would hand it to two owners
    with pytest.raises(MXNetError, match="incref on free page"):
        a.incref(p)
    # a page freed by its last sharer reappears exactly once
    q = a.alloc()
    a.incref(q)
    a.free([q, q])
    assert sorted(a._free).count(q) == 1


def test_prefix_index_radix_siblings_and_partial():
    """Two prompt families diverging at the SAME depth must both stay
    cached (radix siblings, not last-writer-wins), and a prompt ending
    mid-page matches the boundary page as a partial COPY capped at
    t0 - 1 tokens (the last token's logits must be recomputed)."""
    ps = 4
    a = PageAllocator(16)
    ix = PrefixIndex(ps)
    fam1 = np.arange(8, dtype=np.int32)              # pages [0-3],[4-7]
    fam2 = np.arange(100, 108, dtype=np.int32)       # diverges at page 0
    pg1 = [a.alloc(), a.alloc()]
    pg2 = [a.alloc(), a.alloc()]
    assert ix.insert(fam1, pg1, a) == 2
    assert ix.insert(fam2, pg2, a) == 2              # sibling kept
    # full-page match for a longer prompt of family 1
    shared, partial, cached = ix.match(np.arange(16, dtype=np.int32))
    assert shared == pg1 and partial is None and cached == 8
    # family 2 still matchable (the sibling survived)
    shared, partial, cached = ix.match(
        np.arange(100, 116, dtype=np.int32))
    assert shared == pg2 and cached == 8
    # prompt ending mid-page: boundary page is a partial-copy source
    shared, partial, cached = ix.match(np.arange(7, dtype=np.int32))
    assert shared == [pg1[0]]
    assert partial == (pg1[1], 2) and cached == 6    # capped < t0 = 7
    # a prompt that IS entirely cached still leaves its last token:
    # 8 tokens = 2 full pages, but only page 0 may be shared and the
    # boundary page contributes at most t0 - 1 - ps = 3 tokens
    shared, partial, cached = ix.match(np.arange(8, dtype=np.int32))
    assert shared == [pg1[0]]
    assert partial == (pg1[1], 3) and cached == 7
    # no match at all
    shared, partial, cached = ix.match(
        np.arange(500, 512, dtype=np.int32))
    assert shared == [] and partial is None and cached == 0


def test_prefix_index_reclaim_lru_and_flush():
    """reclaim frees LRU index-only pages (live-slot pages are skipped),
    evicting a parent cascades its unreachable descendants, and flush
    drops everything while slot-held pages survive via the slot refs."""
    ps = 4
    a = PageAllocator(16)
    ix = PrefixIndex(ps)
    fam1 = np.arange(8, dtype=np.int32)
    fam2 = np.arange(100, 108, dtype=np.int32)
    pg1 = [a.alloc(), a.alloc()]
    pg2 = [a.alloc(), a.alloc()]
    ix.insert(fam1, pg1, a)
    # touch family 1 so family 2 becomes the LRU chain
    ix.match(np.arange(12, dtype=np.int32))
    ix.insert(fam2, pg2, a)
    ix.match(np.arange(12, dtype=np.int32))
    # drop the slots' own refs — pages now held only by the index
    a.free(pg1 + pg2)
    free0 = a.free_count
    assert ix.reclaimable(a) == 4
    freed = ix.reclaim(1, a)
    # fam2's root page was LRU; evicting it cascades its child
    assert freed == 2 and a.free_count == free0 + 2
    assert ix.match(np.arange(100, 112, dtype=np.int32))[0] == []
    assert ix.match(np.arange(12, dtype=np.int32))[0] == pg1
    # a page still referenced by a live slot is not reclaimable
    a.incref(pg1[0])
    assert ix.reclaimable(a) == 1            # only the depth-1 page
    ix.flush(a)
    assert len(ix) == 0 and ix.flushes == 1
    assert a.refcount(pg1[0]) == 1           # the slot's ref survived
    a.free([pg1[0]])
    assert a.free_count == a.num_pages - 1


@pytest.mark.slow
def test_prefix_cache_hit_token_parity(model):
    """Requests sharing a persona prefix: later admissions must match
    the cached pages (hit counted, suffix-only prefill) and emit
    EXACTLY their solo tokens — shared pages are read-only, the
    boundary page is copied, so sharing is invisible to every request."""
    rng = np.random.RandomState(21)
    persona = rng.randint(0, 64, size=(20,)).astype(np.int32)
    prompts = [np.concatenate([persona,
                               rng.randint(0, 64, size=(5,)).astype(
                                   np.int32)]) for _ in range(3)]
    refs = [_solo_reference(model, p, 8) for p in prompts]
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64)
    reqs = [Request(p.copy(), max_new_tokens=8) for p in prompts]
    eng.run(reqs)
    for req, ref in zip(reqs, refs):
        np.testing.assert_array_equal(np.asarray(req.token_ids,
                                                 np.int32), ref)
    assert eng.prefix_hits >= 1, "no admission ever hit the cache"
    assert eng.prefix_hit_tokens >= 16      # >= 2 full shared pages
    assert eng.copy_trace_count <= 1        # COW copy compiled once
    assert eng.decode_trace_count == 1
    assert all(v == 1 for v in eng.prefill_trace_counts.values()), \
        f"a prefill bucket retraced: {eng.prefill_trace_counts}"
    eng.audit_pages()


@pytest.mark.slow
def test_shared_pages_cross_slot_isolation(model):
    """Two same-persona requests decode CONCURRENTLY with the persona
    pages mapped into both page tables (one read-only shared mapping):
    each must still emit exactly its solo tokens, and a greedy request
    next to a hot-sampling one stays bit-identical (sharing must not
    leak sampling state either)."""
    rng = np.random.RandomState(22)
    persona = rng.randint(0, 64, size=(16,)).astype(np.int32)
    p1 = np.concatenate([persona, rng.randint(0, 64, size=(4,)).astype(
        np.int32)])
    p2 = np.concatenate([persona, rng.randint(0, 64, size=(7,)).astype(
        np.int32)])
    refs = [_solo_reference(model, p, 10) for p in (p1, p2)]
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64)
    r1 = Request(p1.copy(), max_new_tokens=10)
    r2 = Request(p2.copy(), max_new_tokens=10, temperature=1.1)
    # same _admit pass: r1 cold-prefills + publishes, r2 hits and maps
    # the SAME physical pages while r1 is still live
    eng.run([r1, r2])
    assert eng.prefix_hits == 1
    np.testing.assert_array_equal(np.asarray(r1.token_ids, np.int32),
                                  refs[0])
    assert len(r2.token_ids) == 10
    # greedy parity for the sharer too (own run, fresh engine: both
    # slots greedy, r2 shares r1's persona pages)
    eng2 = InferenceEngine(model, num_slots=2, page_size=8, max_len=64)
    r1b = Request(p1.copy(), max_new_tokens=10)
    r2b = Request(p2.copy(), max_new_tokens=10)
    eng2.run([r1b, r2b])
    assert eng2.prefix_hits == 1
    np.testing.assert_array_equal(np.asarray(r2b.token_ids, np.int32),
                                  refs[1])
    eng2.audit_pages()


@pytest.mark.slow   # 13-21s (round-10 tier-1 budget repair); ci stage_unit runs it
def test_warm_start_flushes_prefix_cache(model):
    """SATELLITE: after a weight swap a previously-cached prefix must
    not be served from stale K/V — the index is flushed (asserted), the
    same prompt re-admitted under new weights emits the NEW model's
    tokens, and the decode step keeps its single compile."""
    mx.random.seed(77)
    model_b = g.gpt_mini(vocab_size=64, max_length=64)
    model_b.initialize()
    rng = np.random.RandomState(23)
    prompt = rng.randint(0, 64, size=(20,)).astype(np.int32)
    ref_a = _solo_reference(model, prompt, 8)
    ref_b = _solo_reference(model_b, prompt, 8)
    # distinguishable models (otherwise staleness would be invisible)
    assert not np.array_equal(ref_a, ref_b)

    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64)
    r0 = Request(prompt.copy(), max_new_tokens=8)
    eng.run([r0])                            # publishes the prefix
    r1 = Request(prompt.copy(), max_new_tokens=8)
    eng.run([r1])                            # served WITH the cache
    assert eng.prefix_hits == 1
    np.testing.assert_array_equal(np.asarray(r1.token_ids, np.int32),
                                  ref_a)

    params_b = {str(i): p.data().asnumpy() for i, p in
                enumerate(model_b.collect_params().values())}
    eng.warm_start(params=params_b)
    assert eng.prefix_flushes == 1
    assert len(eng._prefix) == 0, "warm_start left stale prefix entries"
    hits_before = eng.prefix_hits

    r2 = Request(prompt.copy(), max_new_tokens=8)
    eng.run([r2])
    # stale K/V would reproduce ref_a here; the flush forces a cold
    # prefill under the new weights
    np.testing.assert_array_equal(np.asarray(r2.token_ids, np.int32),
                                  ref_b)
    assert eng.prefix_hits == hits_before    # the re-admission was a miss
    assert eng.decode_trace_count == 1, "weight swap retraced decode"
    eng.audit_pages()


def test_chunk_config_validation(model):
    with pytest.raises(MXNetError, match="power of two"):
        InferenceEngine(model, num_slots=2, page_size=8, max_len=64,
                        chunk_pages=3)
    with pytest.raises(MXNetError, match="token_budget"):
        InferenceEngine(model, num_slots=2, page_size=8, max_len=64,
                        chunk_pages=2, token_budget=8)


@pytest.mark.slow
def test_chunked_prefill_respects_token_budget_and_interleaves(model):
    """A long-prompt arrival under chunked prefill must never process
    more than token_budget prompt tokens per engine step, and decode
    for already-live slots must keep advancing BETWEEN its chunks (the
    TPOT-freeze fix — a monolithic prefill would run to completion
    inside one admission)."""
    rng = np.random.RandomState(24)
    shorts = [Request(rng.randint(0, 64, size=(4,)).astype(np.int32),
                      max_new_tokens=24) for _ in range(2)]
    long_req = Request(rng.randint(0, 64, size=(40,)).astype(np.int32),
                       max_new_tokens=4)
    eng = InferenceEngine(model, num_slots=3, page_size=8, max_len=64,
                          prefix_cache=False, chunk_pages=1)
    for r in shorts:
        eng.submit(r)
    while any(not r.token_ids for r in shorts):
        eng.step()                           # shorts admitted + decoding
    ds0 = eng.decode_steps
    eng.submit(long_req)
    while not long_req.token_ids:
        eng.step()
    # 40 tokens / (1 page * 8) budget = 5 chunks → >= 5 steps, and the
    # shorts decoded through every one of them
    assert eng.decode_steps - ds0 >= 5
    assert min(len(r.token_ids) for r in shorts) >= 5
    assert eng.max_step_prefill_tokens <= eng.token_budget
    while any(eng._slots):
        eng.step()
    ref_long = _solo_reference(model, long_req.prompt_ids, 4)
    np.testing.assert_array_equal(
        np.asarray(long_req.token_ids, np.int32), ref_long)
    for r in shorts:
        np.testing.assert_array_equal(
            np.asarray(r.token_ids, np.int32),
            _solo_reference(model, r.prompt_ids, 24))
    assert eng.decode_trace_count == 1
    eng.audit_pages()


@pytest.mark.slow
@pytest.mark.parametrize("chunk_pages", [1, 2])
def test_chunked_prefill_parity_across_chunk_sizes(model, chunk_pages):
    """SATELLITE: chunked processing must emit bit-identical tokens to
    the monolithic path across chunk sizes {1 page, 2 pages} and
    prompts covering {sub-page, exact-page, odd-tail} lengths, at mixed
    occupancy with staggered arrivals. The oracle is the solo
    dense-cache decode — the same bar the monolithic engine meets, so
    equality here IS first-token parity with PR 2 prefill."""
    rng = np.random.RandomState(25)
    lens = (3, 16, 17, 9, 26)                # odd tails + exact pages
    news = (10, 6, 12, 8, 9)
    prompts = [rng.randint(0, 64, size=(n,)).astype(np.int32)
               for n in lens]
    refs = [_solo_reference(model, p, k) for p, k in zip(prompts, news)]
    eng = InferenceEngine(model, num_slots=3, page_size=8, max_len=64,
                          prefix_cache=False, chunk_pages=chunk_pages)
    reqs = [Request(p, max_new_tokens=k) for p, k in zip(prompts, news)]
    eng.run(reqs, arrival_times=[0.0, 0.0, 0.01, 0.02, 0.03])
    for req, ref in zip(reqs, refs):
        np.testing.assert_array_equal(np.asarray(req.token_ids,
                                                 np.int32), ref)
    assert eng.decode_trace_count == 1
    assert all(k[0] == "chunk" for k in eng.prefill_trace_counts), \
        "chunked engine ran a dense prefill"
    assert all(v == 1 for v in eng.prefill_trace_counts.values()), \
        f"a chunk bucket retraced: {eng.prefill_trace_counts}"
    assert eng.max_step_prefill_tokens <= eng.token_budget
    eng.audit_pages()


@pytest.mark.slow
def test_prefix_churn_accounting_no_leak_no_double_grant(model):
    """SATELLITE: churn admissions/evictions with shared prefixes
    through a POOL SMALL ENOUGH TO FORCE RECLAIM and audit after every
    step: every page is at all times either live-referenced (slots +
    index, refcount exact) or on the free list — no leak, no double
    grant. Token parity holds for every request despite the sharing and
    index evictions."""
    rng = np.random.RandomState(26)
    personas = [rng.randint(0, 64, size=(16,)).astype(np.int32)
                for _ in range(3)]
    prompts = [np.concatenate([personas[i % 3],
                               rng.randint(0, 64, size=(3 + i % 5,))
                               .astype(np.int32)])
               for i in range(9)]
    news = [4 + (i % 3) for i in range(9)]
    refs = [_solo_reference(model, p, k) for p, k in zip(prompts, news)]
    # worst case per request: ceil((23+6)/8)=4 pages; 2 slots → up to 8
    # live pages; 9 usable pages leaves no headroom for the 6 persona
    # pages the index wants to retain → admissions must reclaim
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64,
                          num_pages=10)
    for p, k in zip(prompts, news):
        eng.submit(Request(p.copy(), max_new_tokens=k))
    reqs = [r for r in eng._queue]
    steps = 0
    while eng._queue or eng.active_count:
        eng.step()
        eng.audit_pages()                    # invariant holds mid-churn
        steps += 1
        assert steps < 2000
    for req, ref in zip(reqs, refs):
        np.testing.assert_array_equal(np.asarray(req.token_ids,
                                                 np.int32), ref)
    assert eng.prefix_hits > 0
    assert eng.prefix_reclaimed_pages > 0, \
        "pool never pressured the index — test is not exercising reclaim"
    assert eng.decode_trace_count == 1
    eng.audit_pages()
