"""Host-dispatch benchmark for the optimizer step: eager vs fused vs SPMD.

Measures what the fused whole-tree optimizer step (optimizer/fused.py)
buys on the host side: the eager path dispatches one un-jitted update op
per parameter per step (the overhead MXNet 1.x's op-bulking engine
existed to kill), the fused path dispatches ONE jitted call per
(dtype, stype, hyperparam) group. Parameters are tiny so device compute
is negligible and wall time ≈ host dispatch. CPU-measurable by design —
no TPU needed to validate the host-side win.

Also reports steady-state jit trace counts for the fused path: after
warmup, re-stepping with fixed shapes must not retrace (one trace per
(shape, dtype) signature, ever). ``--smoke`` runs a fast version of that
check and exits non-zero on violation — wired into ci/run.sh as the
tier-1 regression guard for the fused step.

Usage:
  python tools/step_bench.py                 # full bench, banks JSON
  python tools/step_bench.py --smoke         # CI guard (fast, asserts)
  python tools/step_bench.py --json OUT.json
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _build_params(n_params, shape, seed=0):
    import numpy as np
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.gluon.parameter import Parameter
    rng = np.random.RandomState(seed)
    params = []
    for i in range(n_params):
        p = Parameter(f"p{i}", shape=shape)
        p.initialize()
        p.set_data(nd.array(rng.randn(*shape).astype(np.float32)))
        params.append(p)
    return params


def _fill_grads(params, seed):
    import numpy as np
    import jax.numpy as jnp
    rng = np.random.RandomState(seed)
    for p in params:
        g = p.grad()
        g._data = jnp.asarray(rng.randn(*p.shape).astype(np.float32))
        g._fresh = True


def _block(params):
    import jax
    for p in params:
        jax.block_until_ready(p.data()._data)


def _time_steps(trainer, params, steps, warmup=3):
    times = []
    for s in range(warmup + steps):
        _fill_grads(params, seed=100 + s)
        t0 = time.perf_counter()
        trainer.step(1)
        _block(params)
        dt = time.perf_counter() - t0
        if s >= warmup:
            times.append(dt)
    times.sort()
    return times[len(times) // 2]  # median


def bench_trainer(fuse, n_params, shape, steps, optimizer="adam"):
    from incubator_mxnet_tpu import gluon
    params = _build_params(n_params, shape)
    tr = gluon.Trainer(params, optimizer, {"learning_rate": 1e-3},
                       kvstore=None, fuse_step=fuse)
    med = _time_steps(tr, params, steps)
    out = {"per_step_ms": med * 1e3}
    if tr._fused is not None:
        out["trace_count"] = tr._fused.trace_count
        out["group_count"] = len(tr._fused._jits)
        # steady-state guard: more steps with fixed shapes → no retrace
        before = tr._fused.trace_count
        for s in range(3):
            _fill_grads(params, seed=900 + s)
            tr.step(1)
        _block(params)
        out["steady_state_retraces"] = tr._fused.trace_count - before
    return out, tr


def bench_spmd(n_layers, units, steps):
    """SPMD fused fwd+bwd+update step on the default (1-device) mesh —
    the everything-in-one-program upper bound for comparison."""
    import numpy as np
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd, parallel
    from incubator_mxnet_tpu.gluon import nn

    mx.random.seed(0)
    net = nn.Sequential()
    for _ in range(n_layers):
        net.add(nn.Dense(units, in_units=units))
    net.initialize()
    loss_fn = lambda out, y: ((out - y) ** 2).mean()
    tr = parallel.SPMDTrainer(net, loss=loss_fn, optimizer="adam",
                              optimizer_params={"learning_rate": 1e-3})
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(8, units).astype(np.float32))
    y = nd.array(rng.randn(8, units).astype(np.float32))
    times = []
    for s in range(3 + steps):
        t0 = time.perf_counter()
        L = tr.step(x, y)
        jax.block_until_ready(L._data)
        dt = time.perf_counter() - t0
        if s >= 3:
            times.append(dt)
    times.sort()
    return {"per_step_ms": times[len(times) // 2] * 1e3,
            "n_params": 2 * n_layers}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="fast CI guard: assert no steady-state retraces")
    ap.add_argument("--json", default=None,
                    help="bank results here (default BENCH_STEP.json at "
                         "the repo root for a full run; none for --smoke)")
    ap.add_argument("--params", type=int, default=50)
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--optimizer", default="adam")
    args = ap.parse_args()

    if args.smoke:
        args.params, args.steps = 12, 3

    shape = (args.dim, args.dim)
    eager, _ = bench_trainer(False, args.params, shape, args.steps,
                             args.optimizer)
    fused, tr = bench_trainer(True, args.params, shape, args.steps,
                              args.optimizer)
    result = {
        "config": {"n_params": args.params, "shape": list(shape),
                   "optimizer": args.optimizer, "steps": args.steps,
                   "backend": os.environ.get("JAX_PLATFORMS", "cpu")},
        "eager": eager,
        "fused": fused,
        "host_dispatch_speedup": eager["per_step_ms"] / fused["per_step_ms"],
    }
    if not args.smoke:
        result["spmd"] = bench_spmd(args.params // 2, args.dim, args.steps)

    print(json.dumps(result, indent=2))

    ok = True
    if fused.get("steady_state_retraces", 0) != 0:
        print("FAIL: fused step retraced in steady state "
              f"({fused['steady_state_retraces']} retraces across 3 "
              f"fixed-shape steps)", file=sys.stderr)
        ok = False
    if fused.get("trace_count", 0) > fused.get("group_count", 1):
        print("FAIL: fused step compiled more than once per "
              f"(shape, dtype) signature: {fused['trace_count']} traces "
              f"for {fused['group_count']} group(s)", file=sys.stderr)
        ok = False
    if not args.smoke and result["host_dispatch_speedup"] < 5.0:
        print(f"WARN: host dispatch speedup "
              f"{result['host_dispatch_speedup']:.1f}x below the 5x bar",
              file=sys.stderr)

    out = args.json
    if out is None and not args.smoke:
        out = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "BENCH_STEP.json")
    if out:
        with open(out, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
        print(f"banked {out}")

    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
