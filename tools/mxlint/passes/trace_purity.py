"""Pass 1 — retrace / host-leak hazards inside traced code.

Functions reachable from a ``jax.jit`` / ``pjit`` / ``pallas_call``
call site (or decorator) execute under a trace: their Python body runs
once per compilation, their array arguments are abstract tracers. Code
that is harmless on the host is a landmine there:

  - ``float()/int()/bool()/.item()`` on a traced operand either throws
    (ConcretizationTypeError) or — worse, on shape-dependent paths —
    silently bakes a host branch into the trace;
  - ``time.*`` / ``np.random.*`` / ``random.*`` freeze a single draw or
    timestamp into the compiled program forever (the PR-1 LARS
    schedule retrace and the frozen-dropout class of bug);
  - ``np.asarray``/``np.array`` on a traced value forces a host sync
    at trace time and constant-folds the tracer;
  - a closure-captured host scalar that the enclosing scope keeps
    rebinding is a retrace-per-call hazard (cache key churn).

The pass seeds discovery at every jit/pjit/pallas_call site in the
tree (the known entry points — optimizer/fused.py, serve/engine.py,
parallel/spmd.py, ops/ragged_attention.py — plus anything new), walks
the project call graph, and checks every reachable function.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..core import (Finding, Project, SourceUnit, dotted, parent,
                    qualname_of)
from . import _callgraph
from ._callgraph import walk_own

RULE = "trace-host-leak"

_JIT_DOTTED = {"jax.jit", "jax.pjit", "pjit", "jit",
               "pl.pallas_call", "pallas_call", "pallas.pallas_call"}
_PARTIAL_DOTTED = {"functools.partial", "partial"}
_NP_CAST = {"asarray", "array"}


def _is_jit_ref(node: ast.AST, unit: SourceUnit) -> bool:
    d = dotted(node)
    if d is None:
        return False
    if d in ("jit", "pjit", "pallas_call"):
        sym = unit.import_symbols.get(d)
        return sym is not None and sym[0].startswith("jax")
    if d in _JIT_DOTTED:
        return True
    # e.g. jax.experimental.pjit.pjit / pltpu-style aliases
    return d.endswith(".pallas_call") or d.endswith(".pjit") \
        or d == "jax.jit"


def _jit_call_target(call: ast.Call, unit: SourceUnit) \
        -> Optional[ast.AST]:
    """For ``jit(f, ...)`` / ``pallas_call(kernel, ...)`` return the
    expression naming the traced function."""
    if not isinstance(call.func, (ast.Name, ast.Attribute)):
        return None
    if not _is_jit_ref(call.func, unit):
        return None
    return call.args[0] if call.args else None


def _decorator_is_jit(dec: ast.AST, unit: SourceUnit) -> bool:
    if isinstance(dec, (ast.Name, ast.Attribute)):
        return _is_jit_ref(dec, unit)
    if isinstance(dec, ast.Call):
        if isinstance(dec.func, (ast.Name, ast.Attribute)):
            if _is_jit_ref(dec.func, unit):
                return True                      # @jax.jit(...)
            d = dotted(dec.func)
            if d in _PARTIAL_DOTTED and dec.args:   # @partial(jax.jit,…)
                first = dec.args[0]
                return isinstance(first, (ast.Name, ast.Attribute)) \
                    and _is_jit_ref(first, unit)
    return False


def _param_names(func: ast.AST) -> Set[str]:
    a = func.args
    names = [p.arg for p in
             list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return {n for n in names if n not in ("self", "cls")}


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _module_scope_names(unit: SourceUnit) -> Set[str]:
    names: Set[str] = set(unit.import_modules) | set(unit.import_symbols)
    if unit.tree is None:
        return names
    for node in unit.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            names.add(node.name)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                names.update(_names_in(t))
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            names.update(_names_in(node.target))
    return names


class TracePurityPass:
    name = "trace-purity"
    rules = (RULE,)

    def run(self, project: Project) -> Iterable[Finding]:
        cg = _callgraph.CallGraph(project)
        roots: List[ast.AST] = []
        lambda_roots: List[Tuple[ast.Lambda, SourceUnit]] = []
        for unit in project.units:
            if unit.tree is None or unit.path.startswith("tests/"):
                continue
            for node in ast.walk(unit.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    if any(_decorator_is_jit(d, unit)
                           for d in node.decorator_list):
                        roots.append(node)
                elif isinstance(node, ast.Call):
                    tgt = _jit_call_target(node, unit)
                    if tgt is None:
                        continue
                    if isinstance(tgt, ast.Lambda):
                        lambda_roots.append((tgt, unit))
                    elif isinstance(tgt, ast.Name):
                        roots.extend(cg.resolve_name(
                            tgt.id, unit, self._enclosing_func(node)))
                    elif isinstance(tgt, ast.Attribute):
                        roots.extend(self._resolve_attr_target(
                            tgt, unit, cg, node))
        reachable = cg.reachable(roots)
        findings: List[Finding] = []
        for key in reachable:
            info = cg.funcs.get(key)
            if info is None or info.unit.path.startswith("tests/"):
                continue
            findings.extend(self._check_function(info.node, info.unit))
        for lam, unit in lambda_roots:
            findings.extend(self._check_function(lam, unit,
                                                 is_lambda=True))
        return findings

    # ------------------------------------------------------------------ #
    @staticmethod
    def _enclosing_func(node: ast.AST) -> Optional[ast.AST]:
        cur = parent(node)
        while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cur = parent(cur)
        return cur

    def _resolve_attr_target(self, tgt: ast.Attribute, unit: SourceUnit,
                             cg: _callgraph.CallGraph,
                             site: ast.AST) -> List[ast.AST]:
        """``jax.jit(self._decode_step_fn)`` → the method node."""
        fake = ast.Call(func=tgt, args=[], keywords=[])
        for n in ast.walk(fake):
            n._mxparent = getattr(tgt, "_mxparent", None)  # type: ignore
        return cg.resolve_call(fake, unit, self._enclosing_func(site))

    # ------------------------------------------------------------------ #
    def _check_function(self, func: ast.AST, unit: SourceUnit,
                        is_lambda: bool = False) -> List[Finding]:
        out: List[Finding] = []
        params = _param_names(func) if not is_lambda else \
            {a.arg for a in func.args.args}
        symbol = "<lambda>" if is_lambda else qualname_of(func)
        nodes = (ast.walk(func) if is_lambda else walk_own(func))

        def flag(node: ast.AST, msg: str, severity: str = "error"):
            out.append(Finding(RULE, unit.path, node.lineno, msg,
                               symbol=symbol, severity=severity))

        local_assigns = self._local_bindings(func, is_lambda)
        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func) or ""
            # .item(): a device→host force that throws under trace
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args:
                flag(node, "`.item()` inside a traced function — "
                           "device→host force; fails or constant-folds "
                           "under trace")
                continue
            # host casts of traced operands
            if isinstance(node.func, ast.Name) \
                    and node.func.id in ("float", "int", "bool") \
                    and node.args:
                if _names_in(node.args[0]) & params:
                    flag(node, f"host `{node.func.id}()` cast of a "
                               f"traced operand — concretizes the "
                               f"tracer (ConcretizationTypeError or a "
                               f"baked-in constant)")
                continue
            # host clock / host RNG: frozen into the trace
            head = d.split(".")[0] if d else ""
            if head and unit.import_modules.get(head) == "time" \
                    and "." in d:
                flag(node, f"host clock `{d}()` inside a traced "
                           f"function — the timestamp freezes at trace "
                           f"time (and differs per retrace)")
                continue
            if self._is_host_rng(d, head, unit):
                flag(node, f"host RNG `{d}()` inside a traced function "
                           f"— the draw freezes at trace time; use "
                           f"jax.random with a traced key")
                continue
            # numpy materialization of traced values
            if head and unit.import_modules.get(head) == "numpy" \
                    and d.split(".")[-1] in _NP_CAST and node.args:
                if _names_in(node.args[0]) & params:
                    flag(node, f"`{d}()` on a traced operand — forces "
                               f"a host materialization at trace time")
                continue
        # closure-capture hazard: a captured name the enclosing scope
        # keeps rebinding makes the jit cache key (or the baked
        # constant) churn per call — advisory, host-side review needed
        encl = self._enclosing_func(func)
        if encl is not None:
            rebound = self._rebound_in(encl)
            captured = self._free_names(func, params, local_assigns, unit)
            for name, line in sorted(captured.items()):
                if name in rebound:
                    out.append(Finding(
                        RULE, unit.path, line,
                        f"traced closure captures `{name}`, which the "
                        f"enclosing scope rebinds — per-call retrace / "
                        f"stale-constant hazard",
                        symbol=symbol, severity="warn"))
        return out

    @staticmethod
    def _is_host_rng(d: str, head: str, unit: SourceUnit) -> bool:
        if not d or "." not in d:
            return False
        if unit.import_modules.get(head) == "numpy" \
                and d.split(".")[1:2] == ["random"]:
            return True
        return unit.import_modules.get(head) == "random"

    @staticmethod
    def _local_bindings(func: ast.AST, is_lambda: bool) -> Set[str]:
        if is_lambda:
            return set()
        bound: Set[str] = set()
        for node in walk_own(func):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    bound.update(_names_in(t))
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign,
                                   ast.For, ast.withitem)):
                tgt = getattr(node, "target",
                              getattr(node, "optional_vars", None))
                if tgt is not None:
                    bound.update(_names_in(tgt))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                bound.add(node.name)
            elif isinstance(node, ast.comprehension):
                bound.update(_names_in(node.target))
            elif isinstance(node, ast.ExceptHandler) and node.name:
                bound.add(node.name)
        return bound

    def _free_names(self, func: ast.AST, params: Set[str],
                    local: Set[str], unit: SourceUnit) -> Dict[str, int]:
        import builtins as _b
        module_names = _module_scope_names(unit)
        free: Dict[str, int] = {}
        for node in walk_own(func):
            if isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Load):
                n = node.id
                if n in params or n in local or n in module_names \
                        or hasattr(_b, n):
                    continue
                free.setdefault(n, node.lineno)
        return free

    @staticmethod
    def _rebound_in(encl: ast.AST) -> Set[str]:
        """Names the enclosing scope assigns more than once (its OWN
        statements — walk_own already excludes the traced function's
        body and other nested defs)."""
        counts: Dict[str, int] = {}
        for node in walk_own(encl):
            tgt_names: Set[str] = set()
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    tgt_names.update(_names_in(t))
            elif isinstance(node, (ast.AugAssign, ast.For)):
                tgt_names.update(_names_in(node.target))
            for n in tgt_names:
                counts[n] = counts.get(n, 0) + 1
        return {n for n, c in counts.items() if c >= 2}
