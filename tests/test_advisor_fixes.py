"""Regression tests for the round-1 advisor findings (VERDICT round 2,
"What's weak" #4): each test pins the fixed behavior so it cannot
regress silently."""

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, gluon
from incubator_mxnet_tpu.gluon import nn


# --------------------------------------------------------------------- #
# 1. estimator.evaluate with plain (data, label) tuple batches
#    (gluon/contrib/estimator.py ternary-precedence crash)
# --------------------------------------------------------------------- #

def test_estimator_evaluate_tuple_batches():
    from incubator_mxnet_tpu.gluon.contrib.estimator import Estimator
    from incubator_mxnet_tpu import metric as metric_mod

    mx.random.seed(0)
    net = nn.Dense(2, in_units=4)
    net.initialize()
    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                    train_metrics=metric_mod.Accuracy())
    rng = np.random.RandomState(0)
    batches = [(nd.array(rng.randn(3, 4).astype(np.float32)),
                nd.array(rng.randint(0, 2, (3,))))
               for _ in range(2)]
    vals = est.evaluate(iter(batches))
    assert vals and vals[0][0] == "accuracy"
    assert 0.0 <= vals[0][1] <= 1.0


# --------------------------------------------------------------------- #
# 2. KL threshold uses the UPPER bin edge (contrib/quantization.py
#    off-by-one)
# --------------------------------------------------------------------- #

def test_kl_threshold_upper_edge():
    from incubator_mxnet_tpu.contrib.quantization import (
        calib_thresholds_entropy)
    num_bins = 1024
    hist = np.concatenate([np.ones(255), np.zeros(num_bins - 255)])
    bin_edges = np.linspace(0.0, float(num_bins), num_bins + 1)
    t = calib_thresholds_entropy(hist, bin_edges, num_quantized_bins=255)
    # all mass lives in bins [0, 255): the KL-optimal candidate keeps
    # exactly those bins, and the threshold is their UPPER edge (255.0).
    # The off-by-one bug returned bin_edges[254] = 254.0.
    assert t == pytest.approx(255.0)


# --------------------------------------------------------------------- #
# 3. executor.backward after an all-null-grad forward is a no-op
#    (symbol/executor.py raise)
# --------------------------------------------------------------------- #

def test_executor_backward_all_null_noop():
    x = mx.sym.Variable("x")
    y = x * 2.0
    exe = y.bind(args={"x": nd.array([1.0, 2.0])}, grad_req="null")
    exe.forward(is_train=True)
    grads = exe.backward()  # must not raise
    assert not grads or all(g is None for g in grads.values())


# --------------------------------------------------------------------- #
# 4. ROIAlign sample_ratio<=0 is adaptive (ceil(bin_size) samples/bin)
# --------------------------------------------------------------------- #

def _roi_align_np(feat, roi, PH, PW, scale, sample_ratio, s_cap=8):
    """Naive numpy RoIAlign (reference roi_align.cc semantics) for one
    image, one ROI. feat: (C, H, W); roi: [b, x1, y1, x2, y2]."""
    C, H, W = feat.shape
    x1, y1, x2, y2 = (roi[1] * scale, roi[2] * scale,
                      roi[3] * scale, roi[4] * scale)
    rw = max(x2 - x1, 1.0)
    rh = max(y2 - y1, 1.0)
    bin_h, bin_w = rh / PH, rw / PW
    s_h = sample_ratio if sample_ratio > 0 else \
        int(min(max(np.ceil(bin_h), 1), s_cap))
    s_w = sample_ratio if sample_ratio > 0 else \
        int(min(max(np.ceil(bin_w), 1), s_cap))

    def bilin(c, y, x):
        y = min(max(y, 0.0), H - 1.0)
        x = min(max(x, 0.0), W - 1.0)
        y0, x0 = int(np.floor(y)), int(np.floor(x))
        y1_, x1_ = min(y0 + 1, H - 1), min(x0 + 1, W - 1)
        ly, lx = y - y0, x - x0
        return (feat[c, y0, x0] * (1 - ly) * (1 - lx)
                + feat[c, y0, x1_] * (1 - ly) * lx
                + feat[c, y1_, x0] * ly * (1 - lx)
                + feat[c, y1_, x1_] * ly * lx)

    out = np.zeros((C, PH, PW), np.float32)
    for c in range(C):
        for ph in range(PH):
            for pw in range(PW):
                acc = 0.0
                for jy in range(s_h):
                    for jx in range(s_w):
                        yy = y1 + (ph + (jy + 0.5) / s_h) * bin_h
                        xx = x1 + (pw + (jx + 0.5) / s_w) * bin_w
                        acc += bilin(c, yy, xx)
                out[c, ph, pw] = acc / (s_h * s_w)
    return out


def test_roi_align_adaptive_sampling():
    rng = np.random.RandomState(0)
    data = rng.randn(1, 2, 16, 16).astype(np.float32)
    # a large ROI so ceil(bin_h) > 2 — discriminates adaptive from S=1/2
    rois = np.array([[0, 1.0, 1.0, 13.0, 13.0]], np.float32)
    got = nd.contrib.ROIAlign(nd.array(data), nd.array(rois),
                              pooled_size=(2, 2), spatial_scale=1.0,
                              sample_ratio=0).asnumpy()
    want = _roi_align_np(data[0], rois[0], 2, 2, 1.0, 0)
    np.testing.assert_allclose(got[0], want, rtol=1e-4, atol=1e-4)
    # fixed sample_ratio still matches the naive reference too
    got2 = nd.contrib.ROIAlign(nd.array(data), nd.array(rois),
                               pooled_size=(2, 2), spatial_scale=1.0,
                               sample_ratio=2).asnumpy()
    want2 = _roi_align_np(data[0], rois[0], 2, 2, 1.0, 2)
    np.testing.assert_allclose(got2[0], want2, rtol=1e-4, atol=1e-4)


# --------------------------------------------------------------------- #
# 5. sparse retain: device-native, absent rows come back zero
# --------------------------------------------------------------------- #

def test_retain_device_native_semantics():
    from incubator_mxnet_tpu.ndarray import sparse
    rsp = sparse.row_sparse_array(
        (np.arange(6, dtype=np.float32).reshape(3, 2), [0, 2, 4]),
        shape=(6, 2))
    kept = sparse.retain(rsp, [1, 2, 4])
    dense = kept.asnumpy()
    assert dense.shape == (6, 2)
    np.testing.assert_array_equal(dense[1], 0)          # absent row → zero
    np.testing.assert_array_equal(dense[2], [2.0, 3.0])
    np.testing.assert_array_equal(dense[4], [4.0, 5.0])
    np.testing.assert_array_equal(dense[0], 0)          # not requested
    # empty source
    z = sparse.zeros("row_sparse", (4, 2))
    kept0 = sparse.retain(z, [1, 3])
    assert kept0.asnumpy().sum() == 0


# --------------------------------------------------------------------- #
# Round-3 advisor findings (ADVICE.md round 3, all low severity)
# --------------------------------------------------------------------- #

def test_decode_forward_rejects_training_mode():
    """gpt.decode_forward skips dropout, so it must refuse to run while
    training mode is active instead of silently diverging from model()."""
    from incubator_mxnet_tpu.models import gpt as g
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.base import MXNetError

    mx.random.seed(0)
    m = g.gpt_mini(vocab_size=32, max_length=16)
    m.initialize()
    ids = nd.array(np.zeros((1, 4)), dtype="int32")
    caches = g.init_kv_cache(m, 1, max_len=8)
    with autograd.record(train_mode=True):
        with pytest.raises(MXNetError, match="inference-only"):
            g.decode_forward(m, ids, caches, 0)


def test_causal_mask_bottom_right_aligned_for_cached_queries():
    """causal=True with Tq != Tk aligns the triangle bottom-right (the
    KV-cache decode convention): query i attends keys [0, Tk-Tq+i]."""
    from incubator_mxnet_tpu import autograd

    rng = np.random.RandomState(0)
    B, H, D, Tk, Tq = 1, 1, 4, 6, 2
    q = rng.randn(B, Tq, H, D).astype(np.float32)
    k = rng.randn(B, Tk, H, D).astype(np.float32)
    v = rng.randn(B, Tk, H, D).astype(np.float32)
    with autograd.predict_mode():
        out = nd.scaled_dot_product_attention(
            nd.array(q), nd.array(k), nd.array(v), causal=True).asnumpy()
        # reference: full-length causal attention, last Tq rows
        qf = np.concatenate([np.zeros((B, Tk - Tq, H, D), np.float32), q],
                            axis=1)
        full = nd.scaled_dot_product_attention(
            nd.array(qf), nd.array(k), nd.array(v), causal=True).asnumpy()
    np.testing.assert_allclose(out, full[:, Tk - Tq:], rtol=1e-5, atol=1e-5)


def test_histogram_accepts_bin_edges_array():
    x = np.array([0.1, 0.4, 0.5, 0.9, 1.0, 2.5], np.float32)
    edges = np.array([0.0, 0.5, 1.0, 2.0], np.float32)
    h, e = nd.histogram(nd.array(x), bins=nd.array(edges))
    hn, en = np.histogram(x, bins=edges)
    np.testing.assert_array_equal(h.asnumpy(), hn)
    np.testing.assert_allclose(e.asnumpy(), en)


def test_group_adagrad_accepts_keepdims_history():
    """history may be (N,) or the reference's (N, 1); the accumulator
    comes back in the caller's shape and both produce identical steps."""
    rng = np.random.RandomState(0)
    w = rng.randn(4, 3).astype(np.float32)
    g = rng.randn(4, 3).astype(np.float32)
    h_flat = np.abs(rng.randn(4)).astype(np.float32)

    w1, h1 = nd.contrib.group_adagrad_update(
        nd.array(w), nd.array(g), nd.array(h_flat), lr=0.1)
    w2, h2 = nd.contrib.group_adagrad_update(
        nd.array(w), nd.array(g), nd.array(h_flat.reshape(4, 1)), lr=0.1)
    assert h1.shape == (4,) and h2.shape == (4, 1)
    np.testing.assert_allclose(w1.asnumpy(), w2.asnumpy(), rtol=1e-6)
    np.testing.assert_allclose(h1.asnumpy(), h2.asnumpy().ravel(),
                               rtol=1e-6)
    # epsilon OUTSIDE the sqrt (upstream GroupAdaGrad convention)
    exp_h = h_flat + np.mean(np.square(g), axis=1)
    exp_w = w - 0.1 * g / (np.sqrt(exp_h)[:, None] + 1e-5)
    np.testing.assert_allclose(w1.asnumpy(), exp_w, rtol=1e-5)
