#!/usr/bin/env python
"""im2rec: build .rec/.idx RecordIO packs from an image folder or .lst file
(parity: the reference's `tools/im2rec.py`; file-level citation — SURVEY.md
caveat). Output is byte-compatible with the reference format, so existing
.rec datasets work unchanged.

Usage:
    python tools/im2rec.py PREFIX ROOT [--list] [--recursive]
    python tools/im2rec.py PREFIX ROOT            # pack using PREFIX.lst
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402


def make_list(prefix, root, recursive=False, train_ratio=1.0, exts=None):
    exts = exts or [".jpg", ".jpeg", ".png", ".bmp", ".npy"]
    items = []
    label_map = {}
    if recursive:
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = label_map.setdefault(folder, len(label_map))
            for fname in sorted(os.listdir(path)):
                if os.path.splitext(fname)[1].lower() in exts:
                    items.append((os.path.join(folder, fname), label))
    else:
        for fname in sorted(os.listdir(root)):
            if os.path.splitext(fname)[1].lower() in exts:
                items.append((fname, 0))
    with open(prefix + ".lst", "w") as f:
        for i, (rel, label) in enumerate(items):
            f.write(f"{i}\t{label}\t{rel}\n")
    return len(items)


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            yield int(parts[0]), float(parts[1]), parts[-1]


def make_rec(prefix, root, quality=95):
    from incubator_mxnet_tpu.io.recordio import (IndexedRecordIO, IRHeader,
                                                 pack, pack_img)

    rec = IndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    n = 0
    for idx, label, rel in read_list(prefix + ".lst"):
        path = os.path.join(root, rel)
        header = IRHeader(0, label, idx, 0)
        if path.endswith(".npy"):
            img = np.load(path)
            rec.write_idx(idx, pack_img(header, img, quality, ".jpg"))
        else:
            with open(path, "rb") as f:
                rec.write_idx(idx, pack(header, f.read()))
        n += 1
    rec.close()
    return n


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("prefix")
    ap.add_argument("root")
    ap.add_argument("--list", action="store_true",
                    help="generate PREFIX.lst instead of packing")
    ap.add_argument("--recursive", action="store_true",
                    help="class-per-subfolder labels")
    ap.add_argument("--quality", type=int, default=95)
    args = ap.parse_args()
    if args.list:
        n = make_list(args.prefix, args.root, args.recursive)
        print(f"wrote {n} entries to {args.prefix}.lst")
    else:
        if not os.path.exists(args.prefix + ".lst"):
            make_list(args.prefix, args.root, args.recursive)
        n = make_rec(args.prefix, args.root, args.quality)
        print(f"packed {n} records into {args.prefix}.rec")


if __name__ == "__main__":
    main()
