"""Model zoo (parity: GluonCV/GluonNLP model zoos reached from
`python/mxnet/gluon/model_zoo/` — SURVEY.md §2.2; BERT/Transformer come
from the external GluonNLP scripts the baselines cite, BASELINE.md)."""

from . import lenet
from .lenet import LeNet
from . import bert
from .bert import (BERTModel, BERTForPretraining, BERTClassifier,
                   bert_base, bert_large, bert_tiny)

__all__ = ["LeNet", "BERTModel", "BERTForPretraining", "BERTClassifier",
           "bert_base", "bert_large", "bert_tiny"]


def __getattr__(name):
    if name in ("resnet", "transformer", "ssd", "gpt", "faster_rcnn"):
        import importlib
        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
