"""NDArray: the imperative n-dimensional array over ``jax.Array``.

TPU-native re-design of the reference's NDArray
(`include/mxnet/ndarray.h`, `src/ndarray/ndarray.cc`; Python surface
`python/mxnet/ndarray/ndarray.py` — file-level citations, SURVEY.md caveat).

Where the reference pairs each NDArray with an engine variable and pushes
every op into a threaded dependency engine (SURVEY.md §1 invariant), here the
async contract is inherited from XLA: ``jax.Array`` dispatch is asynchronous,
``asnumpy()`` is the sync point (the reference's ``WaitToRead``), and
ordering/races are owned by the compiler+runtime rather than a scheduler.
The dependency engine is therefore *absent by design* (SURVEY.md §7.3).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from ..context import Context, current_context

__all__ = ["NDArray", "_wrap", "_as_jax"]

_DTYPE_ALIASES = {
    "float32": jnp.float32, "float64": jnp.float64, "float16": jnp.float16,
    "bfloat16": jnp.bfloat16, "uint8": jnp.uint8, "int8": jnp.int8,
    "int32": jnp.int32, "int64": jnp.int64, "bool": jnp.bool_,
    "uint32": jnp.uint32, "uint64": jnp.uint64, "int16": jnp.int16,
}


def _to_jnp_dtype(dtype):
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _DTYPE_ALIASES:
            raise MXNetError(f"unknown dtype {dtype!r}")
        return _DTYPE_ALIASES[dtype]
    return dtype


def _as_jax(x, dtype=None):
    """Convert array-like/NDArray/scalar to a jax array."""
    if isinstance(x, NDArray):
        arr = x._data
    elif isinstance(x, jax.Array):
        arr = x
    else:
        arr = jnp.asarray(x, dtype=_to_jnp_dtype(dtype) or (
            jnp.float32 if isinstance(x, (list, tuple, float)) or (
                isinstance(x, _np.ndarray) and x.dtype == _np.float64) else None))
    if dtype is not None:
        arr = arr.astype(_to_jnp_dtype(dtype))
    return arr


_FETCH_FENCE = None  # None = unprobed; bool once probed


def _needs_fetch_fence() -> bool:
    """True on backends where ``block_until_ready`` does not actually
    block (the axon TPU tunnel — verified empirically, bench.py:121 in
    round 3). Probed once per process from the backend platform name."""
    global _FETCH_FENCE
    if _FETCH_FENCE is None:
        try:
            d = jax.devices()[0]
            plat = str(getattr(getattr(d, "client", None), "platform",
                               d.platform))
            _FETCH_FENCE = "axon" in plat.lower()
        except Exception:  # pragma: no cover
            _FETCH_FENCE = False
    return _FETCH_FENCE


def _wrap(data) -> "NDArray":
    return NDArray(data)


class NDArray:
    """An n-dimensional, device-resident, asynchronously-evaluated array.

    Construct via factory functions (``mx.nd.array``, ``mx.nd.zeros`` …);
    the constructor takes a raw ``jax.Array``.
    """

    __slots__ = ("_data", "_ag_node", "_ag_idx", "_ag_grad", "_ag_grad_req",
                 "_fresh", "_ov_member", "__weakref__")

    def __init__(self, data):
        if isinstance(data, NDArray):
            data = data._data
        self._data = data
        self._ag_node = None
        self._ag_idx = 0
        self._ag_grad = None
        self._ag_grad_req = "write"

    # ------------------------------------------------------------------ #
    # basic properties
    # ------------------------------------------------------------------ #
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def size(self) -> int:
        return int(self._data.size)

    @property
    def ndim(self) -> int:
        return self._data.ndim

    @property
    def context(self) -> Context:
        try:
            dev = list(self._data.devices())[0]
        except Exception:
            return current_context()
        if dev.platform == "cpu":
            # In CPU-only processes the host devices double as virtual
            # accelerators (see context.py); report tpu ctx there so
            # device-placement code behaves uniformly.
            if all(d.platform == "cpu" for d in jax.devices()):
                return Context("tpu", dev.id)
            return Context("cpu", dev.id)
        return Context("tpu", dev.id)

    ctx = context

    @property
    def grad(self) -> Optional["NDArray"]:
        return self._ag_grad

    @property
    def stype(self) -> str:
        return "default"

    # ------------------------------------------------------------------ #
    # sync / host transfer
    # ------------------------------------------------------------------ #
    def asnumpy(self) -> _np.ndarray:
        """Copy to host (the sync point — reference ``WaitToRead`` +
        ``MXNDArraySyncCopyToCPU``)."""
        return _np.asarray(jax.device_get(self._data))

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(()).item()

    def item(self):
        return self.asscalar()

    def wait_to_read(self):
        """Block until the value is computed (reference ``WaitToRead``).

        On tunneled remote backends whose ``block_until_ready`` is a
        no-op (observed on the axon transport — see bench.py), a
        one-scalar device fetch provides the real fence: device_get of
        any value derived from this array cannot return before the
        producing computation finishes."""
        jax.block_until_ready(self._data)
        if _needs_fetch_fence():
            jax.device_get(jnp.ravel(self._data)[:1])
        return self

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        if self.size == 0:
            return False
        if self.size == 1:
            return bool(self.asscalar())
        raise MXNetError("ambiguous truth value of multi-element NDArray")

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of 0-d NDArray")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        return f"\n{self.asnumpy()!r}\n<NDArray {('x'.join(map(str, self.shape)) or 'scalar')} @{self.context}>"

    # ------------------------------------------------------------------ #
    # autograd
    # ------------------------------------------------------------------ #
    def attach_grad(self, grad_req: str = "write", stype=None):
        """Allocate a gradient buffer; marks this array as a differentiation
        variable (detaches any recorded history, matching the reference)."""
        self._ag_node = None
        self._ag_grad = NDArray(jnp.zeros(self.shape, self.dtype))
        self._ag_grad_req = grad_req

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    def detach(self) -> "NDArray":
        return NDArray(self._data)

    # ------------------------------------------------------------------ #
    # placement / conversion
    # ------------------------------------------------------------------ #
    def as_in_context(self, context: Context) -> "NDArray":
        if not isinstance(context, Context):
            raise MXNetError("as_in_context expects a Context")
        return NDArray(jax.device_put(self._data, context.jax_device))

    as_in_ctx = as_in_context

    def copyto(self, other: Union[Context, "NDArray"]) -> "NDArray":
        if isinstance(other, Context):
            return self.as_in_context(other)
        other._data = jax.device_put(self._data.astype(other.dtype),
                                     list(other._data.devices())[0])
        return other

    def copy(self) -> "NDArray":
        return NDArray(jnp.copy(self._data))

    def astype(self, dtype, copy=True) -> "NDArray":
        d = _to_jnp_dtype(dtype)
        if not copy and self.dtype == d:
            return self
        from .. import autograd as _ag
        if _ag.is_recording():
            # route through the registered Cast op so the dtype change
            # lands on the tape — a bare jnp astype severs gradient
            # flow through every mixed-precision forward
            return self._op("cast", dtype=d)
        return NDArray(self._data.astype(d))

    def tostype(self, stype: str) -> "NDArray":
        if stype == "default":
            return self
        from . import sparse as _sp
        return _sp.cast_storage(self, stype)

    # ------------------------------------------------------------------ #
    # indexing
    # ------------------------------------------------------------------ #
    @staticmethod
    def _index_leaf(k):
        """Array indexers: float dtypes are POSITIONS and cast to int32
        (the reference's take-convention for ndarray indices); boolean
        masks are rejected with a pointer at nd.boolean_mask (their
        data-dependent output shape cannot trace under jit)."""
        if isinstance(k, NDArray):
            k = k._data
        if hasattr(k, "dtype") and hasattr(k, "ndim"):
            if k.dtype == jnp.bool_:
                raise MXNetError(
                    "boolean-mask indexing has a data-dependent shape; "
                    "use nd.boolean_mask(data, mask) (or nd.where) "
                    "instead")
            if jnp.issubdtype(k.dtype, jnp.floating):
                k = k.astype(jnp.int32)
        return k

    def _index(self, key):
        if isinstance(key, tuple):
            return tuple(self._index_leaf(k) for k in key)
        return self._index_leaf(key)

    def __getitem__(self, key):
        from .register import invoke_by_name
        return invoke_by_name("_slice_index", self, index=self._index(key))

    def __setitem__(self, key, value):
        val = _as_jax(value, dtype=self.dtype) if not isinstance(value, NDArray) \
            else value._data.astype(self.dtype)
        if isinstance(key, slice) and key == slice(None):
            self._data = jnp.broadcast_to(val, self.shape)
        else:
            self._data = self._data.at[self._index(key)].set(val)

    # ------------------------------------------------------------------ #
    # arithmetic — delegates into the op registry so autograd records it
    # ------------------------------------------------------------------ #
    def _binop(self, name, other, reverse=False):
        from .register import invoke_by_name
        if not isinstance(other, NDArray):
            other = NDArray(_as_jax(other, dtype=None).astype(self.dtype)
                            if _np.isscalar(other) or isinstance(other, (int, float))
                            else _as_jax(other))
        a, b = (other, self) if reverse else (self, other)
        return invoke_by_name(name, a, b)

    def __add__(self, o):
        return self._binop("broadcast_add", o)

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop("broadcast_sub", o)

    def __rsub__(self, o):
        return self._binop("broadcast_sub", o, reverse=True)

    def __mul__(self, o):
        return self._binop("broadcast_mul", o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop("broadcast_div", o)

    def __rtruediv__(self, o):
        return self._binop("broadcast_div", o, reverse=True)

    def __mod__(self, o):
        return self._binop("broadcast_mod", o)

    def __rmod__(self, o):
        return self._binop("broadcast_mod", o, reverse=True)

    def __pow__(self, o):
        return self._binop("broadcast_power", o)

    def __rpow__(self, o):
        return self._binop("broadcast_power", o, reverse=True)

    def __neg__(self):
        from .register import invoke_by_name
        return invoke_by_name("negative", self)

    def __abs__(self):
        from .register import invoke_by_name
        return invoke_by_name("abs", self)

    def _inplace_binop(self, name, o):
        """In-place arithmetic under autograd. The recorded op must consume a
        snapshot ALIAS of the pre-mutation value (carrying the old tape
        position / grad buffer) and the tape node's outputs must point back
        at *this* array — otherwise backward either misses the mutated array
        entirely or sees a self-referential node, and gradients are silently
        zero."""
        alias = NDArray(self._data)
        alias._ag_node, alias._ag_idx = self._ag_node, self._ag_idx
        alias._ag_grad, alias._ag_grad_req = self._ag_grad, self._ag_grad_req
        if alias._ag_node is not None:
            # the alias takes over the old output slot so this array is the
            # output of exactly ONE node (cotangents are keyed by identity)
            alias._ag_node.outputs[alias._ag_idx] = alias
        out = alias._binop(name, o)
        self._data = out._data
        self._ag_node, self._ag_idx = out._ag_node, out._ag_idx
        if self._ag_node is not None:
            self._ag_node.outputs[self._ag_idx] = self
        return self

    def __iadd__(self, o):
        return self._inplace_binop("broadcast_add", o)

    def __isub__(self, o):
        return self._inplace_binop("broadcast_sub", o)

    def __imul__(self, o):
        return self._inplace_binop("broadcast_mul", o)

    def __itruediv__(self, o):
        return self._inplace_binop("broadcast_div", o)

    def __eq__(self, o):
        return self._binop("broadcast_equal", o)

    def __ne__(self, o):
        return self._binop("broadcast_not_equal", o)

    def __gt__(self, o):
        return self._binop("broadcast_greater", o)

    def __ge__(self, o):
        return self._binop("broadcast_greater_equal", o)

    def __lt__(self, o):
        return self._binop("broadcast_lesser", o)

    def __le__(self, o):
        return self._binop("broadcast_lesser_equal", o)

    __hash__ = object.__hash__  # identity hash despite __eq__ override

    def __matmul__(self, o):
        from .register import invoke_by_name
        return invoke_by_name("dot", self, o)

    # ------------------------------------------------------------------ #
    # method sugar delegating to ops
    # ------------------------------------------------------------------ #
    def _op(self, name, *args, **kwargs):
        from .register import invoke_by_name
        return invoke_by_name(name, self, *args, **kwargs)

    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = kwargs.pop("shape", shape)
        return self._op("reshape", shape=tuple(shape), **kwargs)

    def reshape_like(self, other):
        return self._op("reshape_like", other)

    def transpose(self, axes=None):
        return self._op("transpose", axes=axes)

    @property
    def T(self):
        return self.transpose()

    def flatten(self):
        return self._op("flatten")

    def expand_dims(self, axis):
        return self._op("expand_dims", axis=axis)

    def squeeze(self, axis=None):
        return self._op("squeeze", axis=axis)

    def swapaxes(self, dim1, dim2):
        return self._op("swapaxes", dim1=dim1, dim2=dim2)

    def flip(self, axis):
        return self._op("flip", axis=axis)

    def tile(self, reps):
        return self._op("tile", reps=reps)

    def repeat(self, repeats, axis=None):
        return self._op("repeat", repeats=repeats, axis=axis)

    def broadcast_to(self, shape):
        return self._op("broadcast_to", shape=tuple(shape))

    def broadcast_like(self, other):
        return self._op("broadcast_like", other)

    def sum(self, axis=None, keepdims=False):
        return self._op("sum", axis=axis, keepdims=keepdims)

    def mean(self, axis=None, keepdims=False):
        return self._op("mean", axis=axis, keepdims=keepdims)

    def prod(self, axis=None, keepdims=False):
        return self._op("prod", axis=axis, keepdims=keepdims)

    def max(self, axis=None, keepdims=False):
        return self._op("max", axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False):
        return self._op("min", axis=axis, keepdims=keepdims)

    def norm(self, ord=2, axis=None, keepdims=False):
        return self._op("norm", ord=ord, axis=axis, keepdims=keepdims)

    def argmax(self, axis=None, keepdims=False):
        return self._op("argmax", axis=axis, keepdims=keepdims)

    def argmin(self, axis=None, keepdims=False):
        return self._op("argmin", axis=axis, keepdims=keepdims)

    def argsort(self, axis=-1, is_ascend=True):
        return self._op("argsort", axis=axis, is_ascend=is_ascend)

    def sort(self, axis=-1, is_ascend=True):
        return self._op("sort", axis=axis, is_ascend=is_ascend)

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        return self._op("topk", axis=axis, k=k, ret_typ=ret_typ,
                        is_ascend=is_ascend)

    def clip(self, a_min, a_max):
        return self._op("clip", a_min=a_min, a_max=a_max)

    def pad(self, mode="constant", pad_width=None, constant_value=0.0):
        return self._op("pad", mode=mode, pad_width=pad_width,
                        constant_value=constant_value)

    def abs(self):
        return self._op("abs")

    def sign(self):
        return self._op("sign")

    def sqrt(self):
        return self._op("sqrt")

    def square(self):
        return self._op("square")

    def exp(self):
        return self._op("exp")

    def log(self):
        return self._op("log")

    def relu(self):
        return self._op("relu")

    def sigmoid(self):
        return self._op("sigmoid")

    def tanh(self):
        return self._op("tanh")

    def softmax(self, axis=-1):
        return self._op("softmax", axis=axis)

    def log_softmax(self, axis=-1):
        return self._op("log_softmax", axis=axis)

    def dot(self, other, transpose_a=False, transpose_b=False):
        return self._op("dot", other, transpose_a=transpose_a,
                        transpose_b=transpose_b)

    def take(self, indices, axis=0, mode="clip"):
        return self._op("take", indices, axis=axis, mode=mode)

    def pick(self, index, axis=-1, keepdims=False):
        return self._op("pick", index, axis=axis, keepdims=keepdims)

    def one_hot(self, depth, on_value=1.0, off_value=0.0):
        return self._op("one_hot", depth=depth, on_value=on_value,
                        off_value=off_value)

    def slice(self, begin, end, step=None):
        return self._op("slice", begin=begin, end=end, step=step)

    def slice_axis(self, axis, begin, end):
        return self._op("slice_axis", axis=axis, begin=begin, end=end)

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return self._op("split", num_outputs=num_outputs, axis=axis,
                        squeeze_axis=squeeze_axis)

    def zeros_like(self):
        return self._op("zeros_like")

    def ones_like(self):
        return self._op("ones_like")
