"""Page-transport tests (serve/transport.py PageCapsule/PageTransport +
serve/engine.py capture/detach/install custody + serve/router.py
migrate/roles/drain/fleet-preempt).

The load-bearing claims: (1) a migrated slot's continuation is
BIT-IDENTICAL to the never-migrated stream — quantized and raw pools,
greedy and seedless temperature (the pinned RNG key travels in the
capsule), and a seeded stream replays replica-independent; (2) every
failure mode degrades to the always-correct replay/in-place fallback
with the page-state contract intact at each step (free XOR live XOR
demoted XOR in-capsule, ``audit_pages``): a capture abort is
PRE-detach (source slot untouched, still decoding), an install abort
rolls the destination back to untouched, a corrupted capsule or a
wire-signature mismatch is refused before any page lands; (3) the
jit-once contract survives transport — the destination's decode and
chunk programs compile once each; (4) the router composes it: migrate
parity + MIGRATE_OUT/IN events + the /metrics counters, role-split
fleets whose prefill replica never decodes, drain with zero lost
requests, fleet-aware preemption that MOVES the victim instead of
requeueing it, and the migrate-vs-cancel race losing to the refusal
ladder."""

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.models import gpt as g
from incubator_mxnet_tpu.serve import (EventType, InferenceEngine,
                                       Outcome, PageTransport, Request,
                                       build_fleet)
from incubator_mxnet_tpu.serve.metrics import render_metrics

VOCAB = 64
PS = 8

ENG_KW = dict(num_slots=2, page_size=PS, max_len=64, chunk_pages=1,
              prefix_cache=True)


@pytest.fixture(scope="module")
def model():
    mx.random.seed(0)
    m = g.gpt_mini(vocab_size=VOCAB, max_length=64)
    m.initialize()
    return m


def _eng(model, **kw):
    return InferenceEngine(model, **dict(ENG_KW, **kw))


def _fleet(model, n=2, **router_kw):
    router_kw.setdefault("seed", 3)
    return build_fleet(model, n, engine_kw=dict(ENG_KW), **router_kw)


def _prompt(seed=5, n=18):
    rng = np.random.RandomState(seed)
    return rng.randint(0, VOCAB, size=(n,)).astype(np.int32)


def _workload(n, seed=42, max_new=8):
    """Greedy (parity-assertable) mixed persona workload."""
    rng = np.random.RandomState(seed)
    persona = rng.randint(0, VOCAB, size=(14,)).astype(np.int32)
    reqs = []
    for i in range(n):
        if i % 2 == 0:
            prompt = np.concatenate(
                [persona,
                 rng.randint(0, VOCAB, size=(3 + i % 4,))
                 .astype(np.int32)])
        else:
            prompt = rng.randint(0, VOCAB,
                                 size=(5 + 3 * (i % 3),)).astype(np.int32)
        reqs.append(Request(prompt, max_new_tokens=max_new))
    return reqs


def _step_until(eng, pred, guard=400):
    for _ in range(guard):
        if pred():
            return True
        eng.step()
    return pred()


def _run_to_tokens(eng, req, k):
    assert eng.submit(req)
    assert _step_until(eng, lambda: len(req.token_ids) >= k), \
        f"source never reached {k} tokens"


def _finish(eng, req):
    assert _step_until(eng, lambda: req.outcome is not None), \
        "request never reached a terminal"


def _reference(model, req_kw, **eng_kw):
    eng = _eng(model, **eng_kw)
    req = Request(**req_kw)
    eng.run([req], poll_sleep=1e-4)
    assert req.outcome is not None and req.outcome.ok
    return list(req.token_ids)


# --------------------------------------------------------------------- #
# capture/install parity — the headline correctness claim
# --------------------------------------------------------------------- #

@pytest.mark.parametrize("kv_quant,temperature", [
    (None, 0.0),
    # the temperature arms double the work (the seedless oracle needs
    # a second reference run) — tier-1 keeps the greedy pair, the
    # full stage_unit runs all four
    pytest.param(None, 0.8, marks=pytest.mark.slow),
    ("int8", 0.0),
    pytest.param("int8", 0.8, marks=pytest.mark.slow),
], ids=["f32-greedy", "f32-temp", "int8-greedy", "int8-temp"])
def test_capture_install_parity(model, kv_quant, temperature):
    """Migrate a slot mid-stream between two engines: the combined
    stream (source tokens + destination continuation) must equal the
    never-migrated oracle. The temperature arms are SEEDLESS — the
    parity there is carried entirely by the pinned key travelling in
    the capsule (identically-constructed engines replay the same key
    stream, asserted as a precondition)."""
    kw = {} if kv_quant is None else {"kv_quant": kv_quant}
    req_kw = dict(prompt_ids=_prompt(), max_new_tokens=8,
                  temperature=temperature)
    want = _reference(model, req_kw, **kw)
    if temperature > 0.0:
        # precondition for the seedless oracle: engine key streams are
        # construction-deterministic
        assert _reference(model, req_kw, **kw) == want

    src = _eng(model, **kw)
    dst = _eng(model, **kw)
    req = Request(**req_kw)
    _run_to_tokens(src, req, 3)
    head = list(req.token_ids)

    tr = PageTransport()
    cap = tr.capture(src, req.request_id)
    assert cap is not None and tr.captures == 1
    src.audit_pages()                    # pages in in-capsule custody
    att = cap.make_resume_request()
    assert att is not None
    assert tr.install(dst, cap, att) and tr.installs == 1
    assert src.release_capsule(req.request_id) == cap.num_pages
    src.audit_pages()
    dst.audit_pages()

    _finish(dst, att)
    assert att.outcome.ok
    assert head + list(att.token_ids) == want

    # jit-once survives transport on BOTH ends
    for eng in (src, dst):
        assert eng.decode_trace_count <= 1
        assert all(v == 1 for v in eng.prefill_trace_counts.values())


@pytest.mark.slow   # ~10 s: three engines + two full reference runs
def test_seeded_temperature_replica_independent(model):
    """The cross-replica seed gap: a SEEDED stream is a function of
    (seed, position) alone, so it replays identically on any replica —
    a fresh engine run and a mid-stream migration must both reproduce
    it exactly."""
    req_kw = dict(prompt_ids=_prompt(11), max_new_tokens=8,
                  temperature=0.8, seed=1234)
    want = _reference(model, req_kw)
    # a different engine (different construction history: its internal
    # key stream has advanced) still replays the seeded stream
    other = _eng(model)
    warm = Request(_prompt(12), max_new_tokens=2)
    other.run([warm], poll_sleep=1e-4)
    again = Request(**req_kw)
    other.run([again], poll_sleep=1e-4)
    assert list(again.token_ids) == want

    src, dst = _eng(model), _eng(model)
    req = Request(**req_kw)
    _run_to_tokens(src, req, 3)
    tr = PageTransport()
    cap = tr.capture(src, req.request_id)
    assert cap is not None
    att = cap.make_resume_request()
    assert tr.install(dst, cap, att)
    src.release_capsule(req.request_id)
    _finish(dst, att)
    assert list(req.token_ids) + list(att.token_ids) == want


# --------------------------------------------------------------------- #
# failure modes — every one degrades, loudly, with clean audits
# --------------------------------------------------------------------- #

def test_capture_abort_pre_detach_leaves_slot_decoding(model):
    """An abort ANYWHERE during capture lands before the detach, so
    the source slot is untouched — it keeps decoding in place and the
    stream still matches the oracle (the fallback owes nothing)."""
    req_kw = dict(prompt_ids=_prompt(21), max_new_tokens=8)
    want = _reference(model, req_kw)
    src = _eng(model)
    req = Request(**req_kw)
    _run_to_tokens(src, req, 3)
    tr = PageTransport()
    tr._capture_abort = lambda: True
    assert tr.capture(src, req.request_id) is None
    assert tr.capture_failures == 1
    src.audit_pages()
    _finish(src, req)
    assert req.outcome.ok and list(req.token_ids) == want


def test_install_abort_rolls_destination_back(model):
    """A mid-install abort (destination dying) frees every allocated
    page and refuses — the destination ends exactly as it began, and
    the source's custody release is still the caller's to run."""
    src, dst = _eng(model), _eng(model)
    req = Request(_prompt(22), max_new_tokens=8)
    _run_to_tokens(src, req, 3)
    tr = PageTransport()
    cap = tr.capture(src, req.request_id)
    assert cap is not None
    free0 = dst._alloc.free_count
    tr._install_abort = lambda: True
    att = cap.make_resume_request()
    assert tr.install(dst, cap, att) is False
    assert tr.install_failures == 1
    assert dst._alloc.free_count == free0
    dst.audit_pages()
    assert src.release_capsule(req.request_id) == cap.num_pages
    src.audit_pages()


def test_corrupt_capsule_refused(model):
    """Wire bit rot: one flipped payload byte breaks the crc chain —
    ``verify`` fails, ``install`` refuses before any page lands, and
    ``payloads`` raises rather than expose unvouched bytes."""
    src, dst = _eng(model), _eng(model)
    req = Request(_prompt(23), max_new_tokens=8)
    _run_to_tokens(src, req, 3)
    tr = PageTransport()
    cap = tr.capture(src, req.request_id)
    assert cap is not None and cap.verify()
    cap.corrupt(page_idx=0, byte=5)
    assert not cap.verify()
    att = cap.make_resume_request()
    free0 = dst._alloc.free_count
    assert tr.install(dst, cap, att) is False
    assert tr.install_failures == 1
    assert dst._alloc.free_count == free0
    with pytest.raises(MXNetError, match="crc chain"):
        cap.payloads()
    src.release_capsule(req.request_id)
    src.audit_pages()


def test_wire_sig_mismatch_refused(model):
    """A capsule captured off a quantized pool must not install into a
    raw pool (the payload encodings differ) — refused by wire
    signature before the crc is even walked."""
    src = _eng(model, kv_quant="int8")
    dst = _eng(model)
    req = Request(_prompt(24), max_new_tokens=8)
    _run_to_tokens(src, req, 3)
    tr = PageTransport()
    cap = tr.capture(src, req.request_id)
    assert cap is not None
    att = cap.make_resume_request()
    assert tr.install(dst, cap, att) is False
    assert tr.install_failures == 1
    src.release_capsule(req.request_id)
    src.audit_pages()
    dst.audit_pages()


def test_custody_accounting(model):
    """Between detach and release the pages live in the FOURTH state
    (in-capsule custody): not free, not a slot's, still refcounted —
    ``audit_pages`` accepts them, the free count is unchanged until
    release, and a double release is a no-op returning 0."""
    src = _eng(model)
    req = Request(_prompt(25), max_new_tokens=8)
    _run_to_tokens(src, req, 3)
    free_live = src._alloc.free_count
    tr = PageTransport()
    cap = tr.capture(src, req.request_id)
    assert cap is not None
    assert src._alloc.free_count == free_live   # custody, not freed
    src.audit_pages()
    assert src.migrated_out_pages == cap.num_pages
    assert src.migrated_out_bytes == cap.nbytes
    assert src.release_capsule(req.request_id) == cap.num_pages
    assert src._alloc.free_count > free_live
    assert src.release_capsule(req.request_id) == 0
    src.audit_pages()


def test_capture_refuses_unknown_request(model):
    src = _eng(model)
    tr = PageTransport()
    assert tr.capture(src, 10 ** 9) is None
    assert tr.capture_failures == 1
    src.audit_pages()


# --------------------------------------------------------------------- #
# the router composition
# --------------------------------------------------------------------- #

def _fleet_audit(rt):
    from incubator_mxnet_tpu.serve.router import ReplicaState
    for rep in rt.replicas:
        if rep.state is not ReplicaState.DEAD and rep.killed is None:
            rep.engine.audit_pages()


def test_router_migrate_parity_events_metrics(model):
    """``Router.migrate`` mid-run: token streams stay identical to an
    unmigrated fleet, MIGRATE_OUT/MIGRATE_IN land in the merged
    timeline, and every transport counter reaches /metrics under its
    documented name."""
    base = _fleet(model)
    reqs_b = _workload(6)
    base.run(reqs_b)
    rt = _fleet(model)
    reqs = _workload(6)
    moved = {}

    def before(router, i):
        if moved or i < 3:
            return
        for t in list(router._inflight):
            if t.attempt is None or t.attempt.outcome is not None:
                continue
            rep = router.replicas[t.replica]
            if not rep.engine.decode_ready(t.attempt.request_id):
                continue
            # probe the destination first: a refused migrate counts as
            # a failed one (it IS one — the fallback ran), and this
            # test asserts the clean-path counters
            snap = router.replicas[1 - t.replica].engine.health_snapshot()
            if snap["free_slots"] <= 0 or snap["free_pages"] < 6:
                continue
            if router.migrate(t.client.request_id, 1 - t.replica):
                moved["cid"] = t.client.request_id
                return

    rt.run(reqs, before_step=before)
    assert moved, "no slot ever became migratable"
    assert all(r.outcome is not None and r.outcome.ok for r in reqs)
    assert [list(r.token_ids) for r in reqs] == \
        [list(r.token_ids) for r in reqs_b]
    assert rt.migrations >= 1 and rt.migrations_failed == 0
    assert rt.migrated_pages >= 1 and rt.migrated_bytes > 0
    _fleet_audit(rt)

    ev = rt.flight_events()
    outs = [e for e in ev if e.etype is EventType.MIGRATE_OUT]
    ins = [e for e in ev if e.etype is EventType.MIGRATE_IN]
    assert moved["cid"] in [e.request_id for e in outs]
    assert moved["cid"] in [e.request_id for e in ins]

    text = render_metrics(rt.health_snapshot())
    for name in ("migrations_total", "migrations_failed_total",
                 "kv_migrated_pages_total", "kv_migrated_bytes_total"):
        assert name in text, f"{name} missing from fleet /metrics"
    etext = "".join(render_metrics(rep.engine.health_snapshot())
                    for rep in rt.replicas)
    for name in ("kv_migrated_out_pages_total",
                 "kv_migrated_in_pages_total",
                 "kv_migrated_out_bytes_total",
                 "kv_migrated_in_bytes_total"):
        assert name in etext, f"{name} missing from engine /metrics"


@pytest.mark.slow   # ~10 s: two fleets; the split contract is also
def test_role_split_fleet(model):    # drilled every CI run by migratesmoke
    """roles=['prefill','decode']: every stream prefills on the
    prefill replica and hands off at publication — the prefill replica
    never spends a decode step, the decode replica admits nothing
    fresh, and the streams equal a mixed fleet's (the split is
    invisible in the tokens)."""
    mixed = _fleet(model)
    reqs_m = _workload(4, seed=17)
    mixed.run(reqs_m)

    rt = build_fleet(model, 2, engine_kw=dict(ENG_KW, num_slots=4),
                     roles=["prefill", "decode"], seed=3)
    reqs = _workload(4, seed=17)
    rt.run(reqs)
    assert all(r.outcome is not None and r.outcome.ok for r in reqs)
    assert [list(r.token_ids) for r in reqs] == \
        [list(r.token_ids) for r in reqs_m]
    assert rt.migrations >= len(reqs)
    # publication is one decode step in (the step that emits the first
    # token makes the slot decode-ready), so the prefill replica is
    # allowed at most that boundary step per stream — the decode
    # replica must carry everything else
    assert rt.replicas[0].engine.decode_steps <= len(reqs), \
        "the prefill replica kept decoding past publication"
    assert rt.replicas[1].engine.decode_steps > \
        rt.replicas[0].engine.decode_steps
    _fleet_audit(rt)


@pytest.mark.slow   # ~11 s: two fleets; drain zero-lost/zero-redone
def test_drain_replica_zero_lost(model):   # is migratesmoke's headline gate
    """Drain a replica mid-run: decode-ready slots migrate, queued
    attempts bounce back to the router, nothing is lost, and the
    streams match an undrained fleet."""
    base = _fleet(model)
    reqs_b = _workload(6, seed=29, max_new=10)
    base.run(reqs_b)
    rt = _fleet(model)
    reqs = _workload(6, seed=29, max_new=10)
    drained = {"migrated": 0, "requeued": 0}

    def before(router, i):
        if drained.get("done") or i < 4:
            return
        r = router.drain_replica(0)
        drained["migrated"] += r["migrated"]
        drained["requeued"] += r["requeued"]
        if r["remaining"] == 0:
            drained["done"] = True

    rt.run(reqs, before_step=before)
    assert drained.get("done"), "the drain never completed"
    assert drained["migrated"] >= 1, \
        "the drained replica held no decode-ready work"
    assert all(r.outcome is not None and r.outcome.ok for r in reqs)
    assert [list(r.token_ids) for r in reqs] == \
        [list(r.token_ids) for r in reqs_b]
    _fleet_audit(rt)


@pytest.mark.slow   # ~8 s: affinity warmup + solo reference run
def test_fleet_preempt_handoff(model):
    """Fleet-aware preemption: a LATENCY admission preempting a BATCH
    victim offers it to the router FIRST — the victim MOVES to the
    sibling (pages migrate, zero requeue, zero redone prefill) and its
    stream still matches an uninterfered solo run."""
    rt = build_fleet(model, 2,
                     engine_kw=dict(ENG_KW, num_slots=1),
                     fleet_preempt=True, seed=3)
    rng = np.random.RandomState(31)
    head = rng.randint(0, VOCAB, size=(14,)).astype(np.int32)

    def _with_tail(seed):
        trng = np.random.RandomState(seed)
        return np.concatenate(
            [head, trng.randint(0, VOCAB, size=(4,)).astype(np.int32)])

    warm = Request(_with_tail(1), max_new_tokens=2)
    rt.run([warm], poll_sleep=1e-4)
    assert warm.outcome.ok
    src = next(i for i, rep in enumerate(rt.replicas)
               if rep.engine.prefix_probe(_with_tail(2)) > 0)

    batch_kw = dict(prompt_ids=_with_tail(2), max_new_tokens=10,
                    tier="BATCH")
    want = _reference(model, batch_kw, num_slots=1)
    batch = Request(**batch_kw)
    assert rt.submit(batch)
    for _ in range(400):
        rt.step()
        t = rt._find_tracked(batch.request_id)
        if t is not None and t.attempt is not None \
                and t.replica == src and len(batch.token_ids) + \
                len(t.attempt.token_ids) >= 2 \
                and rt.replicas[src].engine.decode_ready(
                    t.attempt.request_id):
            break
    else:
        pytest.fail("BATCH victim never reached decode on the "
                    "affinity replica")

    lat = Request(_with_tail(3), max_new_tokens=2, tier="LATENCY")
    assert rt.submit(lat)
    for _ in range(600):
        if batch.outcome is not None and lat.outcome is not None:
            break
        rt.step()
    assert batch.outcome is not None and batch.outcome.ok
    assert lat.outcome is not None and lat.outcome.ok
    assert rt.migrations == 1, "the victim did not move to the sibling"
    assert rt.requeues == 0, "the handoff bounced through the queue"
    assert list(batch.token_ids) == want
    handed = [e for e in rt.flight_events()
              if e.etype is EventType.PREEMPT and e.data.get("handoff")]
    assert handed, "no handoff-flagged PREEMPT event"
    _fleet_audit(rt)


def test_migrate_cancel_race(model):
    """The refusal ladder must lose the migrate-vs-cancel race in both
    orders: cancel-then-migrate refuses with NO migration events;
    migrate-then-cancel leaves exactly one CANCELLED terminal on the
    destination."""
    rt = _fleet(model)

    def _decode_ready(req):
        t = rt._find_tracked(req.request_id)
        return (t is not None and t.attempt is not None
                and t.replica is not None
                and rt.replicas[t.replica].engine.decode_ready(
                    t.attempt.request_id))

    def _mig_events():
        return sum(1 for e in rt.flight_events()
                   if e.etype in (EventType.MIGRATE_OUT,
                                  EventType.MIGRATE_IN,
                                  EventType.MIGRATE_FAIL))

    # cancel first: migrate must refuse silently
    r1 = Request(_prompt(41), max_new_tokens=12)
    assert rt.submit(r1)
    assert _step_until(rt, lambda: _decode_ready(r1))
    t = rt._find_tracked(r1.request_id)
    dst = 1 - t.replica
    ev0 = _mig_events()
    assert rt.cancel(r1)
    assert rt.migrate(r1.request_id, dst) is False
    assert _mig_events() == ev0, "a refused migrate emitted events"
    assert r1.outcome == Outcome.CANCELLED

    # migrate first: the cancel lands on the destination, exactly once
    r2 = Request(_prompt(42), max_new_tokens=12)
    assert rt.submit(r2)
    assert _step_until(rt, lambda: _decode_ready(r2))
    t = rt._find_tracked(r2.request_id)
    assert rt.migrate(r2.request_id, 1 - t.replica)
    assert rt.cancel(r2)
    assert r2.outcome == Outcome.CANCELLED
    assert _step_until(rt, lambda: not rt._inflight and not rt._queue)
    assert rt.migrations == 1
    _fleet_audit(rt)
