"""Module — the legacy symbolic trainer API.

Re-design of `python/mxnet/module/base_module.py` + `module.py` +
`executor_manager.py` (file-level citations — SURVEY.md caveat; call stack
§3.3). The reference binds a Symbol per context into a
`DataParallelExecutorGroup`; here one bound :class:`~..symbol.Executor`
compiles the whole graph to XLA, and data parallelism is the SPMD mesh
path (``parallel.SPMDTrainer``) rather than per-context executor groups.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import initializer as _init_mod
from .. import metric as _metric_mod
from .. import optimizer as _opt_mod
from ..base import MXNetError
from ..context import current_context
from ..io import DataDesc
from ..ndarray import NDArray, zeros as nd_zeros
from ..ndarray.ndarray import _as_jax
from ..symbol.executor import Executor
from ..symbol.symbol import Symbol

__all__ = ["BaseModule", "Module"]


def _norm_shapes(shapes) -> List[Tuple[str, tuple]]:
    out = []
    for s in shapes or []:
        if isinstance(s, DataDesc):
            out.append((s.name, tuple(s.shape)))
        else:
            name, shape = s[0], s[1]
            out.append((name, tuple(shape)))
    return out


class BaseModule:
    """Shared high-level train/eval loop (parity: `BaseModule`)."""

    def __init__(self, logger=None):
        self.logger = logger or logging.getLogger("incubator_mxnet_tpu")
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False

    # subclass interface: bind, init_params, forward, backward, update,
    # get_outputs, update_metric, get_params/set_params

    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None, reset=True,
              epoch=0, batch_end_callback=None):
        if not isinstance(eval_metric, _metric_mod.EvalMetric):
            eval_metric = _metric_mod.create(eval_metric)
        if reset:
            eval_data.reset()
        eval_metric.reset()
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch >= num_batch:
                break
            self.forward(batch, is_train=False)
            self.update_metric(eval_metric, batch.label)
            if batch_end_callback is not None:
                for cb in _as_list(batch_end_callback):
                    cb(_BatchEndParam(epoch, nbatch, eval_metric))
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, reset=True):
        """Concatenated outputs over the iterator (parity:
        ``BaseModule.predict``)."""
        import jax.numpy as jnp

        if reset:
            eval_data.reset()
        chunks: List[List[NDArray]] = []
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch >= num_batch:
                break
            self.forward(batch, is_train=False)
            outs = self.get_outputs()
            pad = getattr(batch, "pad", 0) or 0
            if pad:
                outs = [NDArray(o._data[:o.shape[0] - pad]) for o in outs]
            chunks.append(outs)
        if not chunks:
            return []
        cat = [NDArray(jnp.concatenate([c[i]._data for c in chunks]))
               for i in range(len(chunks[0]))]
        return cat if len(cat) > 1 else cat[0]

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=None, initializer=None,
            arg_params=None, aux_params=None, allow_missing=False,
            force_init=False, begin_epoch=0, num_epoch=None,
            validation_metric=None):
        """The canonical epoch loop (parity: ``Module.fit`` — SURVEY.md
        §3.3)."""
        if num_epoch is None:
            raise MXNetError("fit: num_epoch is required")
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label, for_training=True)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if not isinstance(eval_metric, _metric_mod.EvalMetric):
            eval_metric = _metric_mod.create(eval_metric)
        validation_metric = validation_metric or eval_metric

        for epoch in range(begin_epoch, num_epoch):
            eval_metric.reset()
            train_data.reset()
            for nbatch, batch in enumerate(train_data):
                self.forward_backward(batch)
                self.update()
                self.update_metric(eval_metric, batch.label)
                if batch_end_callback is not None:
                    for cb in _as_list(batch_end_callback):
                        cb(_BatchEndParam(epoch, nbatch, eval_metric))
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            if epoch_end_callback is not None:
                arg_p, aux_p = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric, epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f",
                                     epoch, name, val)


class _BatchEndParam:
    def __init__(self, epoch, nbatch, eval_metric):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = None


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


class Module(BaseModule):
    """Single-symbol module (parity: ``mx.mod.Module``)."""

    def __init__(self, symbol: Symbol, data_names=("data",),
                 label_names=("softmax_label",), context=None, logger=None,
                 **_ignored):
        super().__init__(logger)
        self._symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._context = context or current_context()
        arg_names = symbol.list_arguments()
        self._param_names = [n for n in arg_names
                             if n not in self._data_names
                             and n not in self._label_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._exec = None
        self._data_shapes = []
        self._label_shapes = []
        self._grad_req = "write"
        self._inputs_need_grad = False
        self._optimizer = None
        self._opt_states: Dict[str, object] = {}

    @property
    def symbol(self) -> Symbol:
        return self._symbol

    @property
    def data_names(self):
        return list(self._data_names)

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        return [DataDesc(n, s) for n, s in self._data_shapes]

    @property
    def label_shapes(self):
        return [DataDesc(n, s) for n, s in self._label_shapes]

    @property
    def output_shapes(self):
        shapes = dict(self._data_shapes + self._label_shapes)
        shapes.update({n: tuple(self._exec.arg_dict[n].shape)
                       for n in self._param_names})
        _, out_shapes, _ = self._symbol.infer_shape(**shapes)
        return list(zip(self.output_names, out_shapes))

    # -- bind --------------------------------------------------------- #
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, grad_req="write",
             shared_module=None):
        if self.binded and not force_rebind:
            return
        self._data_shapes = _norm_shapes(data_shapes)
        self._label_shapes = _norm_shapes(label_shapes)
        self._grad_req = grad_req if for_training else "null"
        self._inputs_need_grad = inputs_need_grad
        self._for_training = for_training

        known = dict(self._data_shapes + self._label_shapes)
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**known)
        if arg_shapes is None:
            raise MXNetError(
                "bind: cannot infer parameter shapes from data/label shapes;"
                f" arguments: {self._symbol.list_arguments()}")
        arg_names = self._symbol.list_arguments()
        self._arg_shape = dict(zip(arg_names, arg_shapes))
        self._aux_shape = dict(zip(self._aux_names, aux_shapes))

        if shared_module is not None and shared_module._exec is not None:
            # bucketing: share parameter arrays with the master module
            args = {n: shared_module._exec.arg_dict[n]
                    for n in self._param_names}
            aux = dict(shared_module._exec.aux_dict)
            self._opt_states = shared_module._opt_states
            self._optimizer = shared_module._optimizer
            self.params_initialized = shared_module.params_initialized
            self.optimizer_initialized = shared_module.optimizer_initialized
        else:
            args = {n: nd_zeros(self._arg_shape[n])
                    for n in self._param_names}
            aux = {n: nd_zeros(self._aux_shape[n]) for n in self._aux_names}
        for n, s in self._data_shapes + self._label_shapes:
            args[n] = nd_zeros(s)

        req = {}
        for n in arg_names:
            if n in self._param_names:
                req[n] = self._grad_req
            elif n in self._data_names and inputs_need_grad and for_training:
                req[n] = "write"
            else:
                req[n] = "null"
        self._exec = self._symbol.bind(self._context, args=args,
                                       grad_req=req, aux_states=aux)
        self.binded = True

    # -- params ------------------------------------------------------- #
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        if self.params_initialized and not force_init:
            return
        if not self.binded:
            raise MXNetError("init_params: call bind first")
        initializer = initializer or _init_mod.Uniform(0.01)
        # Module.load stashes checkpoint params here; they are applied on
        # the first init_params call (reference: load → fit(arg_params=...))
        if arg_params is None:
            arg_params = getattr(self, "_loaded_args", None)
            self._loaded_args = None
        if aux_params is None:
            aux_params = getattr(self, "_loaded_aux", None)
            self._loaded_aux = None
        for name in self._param_names:
            dst = self._exec.arg_dict[name]
            if arg_params and name in arg_params:
                Executor._set_in_place(dst, arg_params[name],
                                       "parameter", name)
            else:
                if arg_params and not allow_missing:
                    raise MXNetError(
                        f"init_params: parameter {name!r} missing from "
                        f"arg_params and allow_missing=False")
                initializer(name, dst)
        for name in self._aux_names:
            dst = self._exec.aux_dict[name]
            if aux_params and name in aux_params:
                Executor._set_in_place(dst, aux_params[name],
                                       "aux state", name)
            else:
                if aux_params and not allow_missing:
                    raise MXNetError(
                        f"init_params: aux state {name!r} missing from "
                        f"aux_params and allow_missing=False")
                arr = nd_zeros(self._aux_shape[name])
                if name.endswith(("moving_var", "running_var")):
                    arr = arr + 1.0
                dst._data = arr._data
        self.params_initialized = True

    def get_params(self):
        arg = {n: self._exec.arg_dict[n] for n in self._param_names}
        aux = dict(self._exec.aux_dict)
        return arg, aux

    def set_params(self, arg_params, aux_params=None, allow_missing=False,
                   force_init=True, allow_extra=False):
        if not self.binded:
            raise MXNetError("set_params: call bind first")
        self._exec.copy_params_from(arg_params or {}, aux_params or {},
                                    allow_extra_params=allow_extra)
        self.params_initialized = True

    # -- optimizer ---------------------------------------------------- #
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, _opt_mod.Optimizer):
            self._optimizer = optimizer
        else:
            self._optimizer = _opt_mod.create(
                optimizer, **(optimizer_params or {}))
        self._optimizer.param_idx2name = {
            i: n for i, n in enumerate(self._param_names)}
        self._opt_states = {
            n: self._optimizer.create_state(
                i, self._exec.arg_dict[n])
            for i, n in enumerate(self._param_names)}
        preload = getattr(self, "_preload_opt_states", None)
        if preload is not None:
            import jax.tree_util as jtu

            from .. import checkpoint as _ckpt

            with open(preload, "rb") as f:
                payload = f.read()
            if _ckpt.is_capsule_bytes(payload):
                arrays, meta = _ckpt.load_capsule_bytes(payload)
                for n, count in (meta.get("opt_leaf_counts")
                                 or {}).items():
                    if n in self._opt_states:
                        self._opt_states[n] = _ckpt.fill_state(
                            self._opt_states[n], arrays, f"opt/{n}",
                            expect=int(count))
                self._optimizer.num_update = int(
                    meta.get("num_update", 0))
                self._optimizer._index_update_count = {
                    int(k): int(v) for k, v in
                    (meta.get("index_update_count") or {}).items()}
            else:                        # legacy pickle .states payload
                import pickle

                saved = pickle.loads(payload)
                for n, s in saved.items():
                    if n in self._opt_states:
                        self._opt_states[n] = jtu.tree_map(
                            lambda a: NDArray(_as_jax(a))
                            if not isinstance(a, NDArray) else a, s)
            self._preload_opt_states = None
        self.optimizer_initialized = True

    # -- execution ---------------------------------------------------- #
    def forward(self, data_batch, is_train=None):
        if not self.binded:
            raise MXNetError("forward: call bind first")
        if is_train is None:
            is_train = getattr(self, "_for_training", True)
        def _feed(arr):
            # sparse batch data (LibSVMIter CSR, row_sparse) densifies
            # at the graph boundary: the symbolic executor's ops are
            # dense-XLA programs (the reference dispatches per-op
            # sparse kernels instead; SURVEY §7.3 substitution)
            if hasattr(arr, "tostype") and getattr(arr, "stype",
                                                   "default") != "default":
                arr = arr.tostype("default")
            return arr if isinstance(arr, NDArray) \
                else NDArray(_as_jax(arr))

        feeds = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feeds[name] = _feed(arr)
        if data_batch.label is not None:
            for name, arr in zip(self._label_names, data_batch.label):
                feeds[name] = _feed(arr)
        self._exec.forward(is_train=is_train, **feeds)

    def backward(self, out_grads=None):
        # the executor seeds ones itself inside the fused fwd+bwd program
        self._exec.backward(out_grads)

    def update(self):
        if not self.optimizer_initialized:
            raise MXNetError("update: call init_optimizer first")
        for i, name in enumerate(self._param_names):
            grad = self._exec.grad_dict.get(name)
            if grad is None:
                continue
            weight = self._exec.arg_dict[name]
            self._optimizer.update(i, weight, grad, self._opt_states[name])

    def get_outputs(self, merge_multi_context=True):
        return list(self._exec.outputs)

    def get_input_grads(self, merge_multi_context=True):
        if not self._inputs_need_grad:
            raise MXNetError("bind with inputs_need_grad=True first")
        return [self._exec.grad_dict[n] for n in self._data_names]

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self.get_outputs())

    # -- checkpointing (parity: python/mxnet/model.py helpers) --------- #
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        from ..model import save_checkpoint

        arg, aux = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg, aux)
        if save_optimizer_states:
            # routed through the checkpoint subsystem's capsule blob
            # (crc32-checked; magic-dispatched on load so legacy pickle
            # .states files keep working — SURVEY.md §5.4)
            from .. import checkpoint as _ckpt

            tree, leaf_counts = {}, {}
            for n, s in self._opt_states.items():
                leaves, _ = _ckpt.flatten_state(s)
                leaf_counts[n] = len(leaves)
                for j, leaf in enumerate(leaves):
                    tree[f"opt/{n}/{j}"] = leaf
            meta = {"kind": "module-states",
                    "opt_leaf_counts": leaf_counts,
                    "num_update": int(self._optimizer.num_update),
                    # per-param update counts MUST travel too:
                    # Adam/LAMB bias correction restarts at t=1
                    # without them while momenta hold late-step values
                    "index_update_count": {
                        str(k): int(v) for k, v in
                        self._optimizer._index_update_count.items()}}
            _ckpt.save_capsule_file(f"{prefix}-{epoch:04d}.states",
                                    tree, meta)

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        from ..model import load_checkpoint

        sym, arg, aux = load_checkpoint(prefix, epoch)
        mod = Module(sym, **kwargs)
        # applied by the first init_params() after bind (see init_params)
        mod._loaded_args = arg
        mod._loaded_aux = aux
        if load_optimizer_states:
            mod._preload_opt_states = f"{prefix}-{epoch:04d}.states"
        return mod
