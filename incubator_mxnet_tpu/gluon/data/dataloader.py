"""DataLoader: parallel sample loading + async device transfer.

Re-design of `python/mxnet/gluon/data/dataloader.py` (file-level citation —
SURVEY.md caveat, pipeline stack §3.5). The reference forks worker
processes that build batches in shared-memory NDArrays; here workers
(processes or threads) produce host numpy batches and a prefetch thread
overlaps ``jax.device_put`` with consumption — the double-buffering the
reference got from PrefetcherIter. XLA's async dispatch hides the
host→device copy behind the previous step's compute.
"""

from __future__ import annotations

import multiprocessing
import os
import queue
import threading
from typing import Callable, Optional

import numpy as np

from ...base import MXNetError
from ...ndarray import NDArray
from ...ndarray.ndarray import _as_jax
from .dataset import Dataset
from .sampler import BatchSampler, RandomSampler, SequentialSampler, Sampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(samples):
    """Stack samples into a batch (parity: gluon default_batchify_fn)."""
    elem = samples[0]
    if isinstance(elem, NDArray):
        import jax.numpy as jnp
        return NDArray(jnp.stack([s._data for s in samples]))
    if isinstance(elem, (tuple, list)):
        return tuple(default_batchify_fn(list(s)) for s in zip(*samples))
    arr = np.asarray(samples)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return arr


def _as_device_batch(batch, device=None):
    """numpy → NDArray (device transfer point)."""
    if isinstance(batch, (tuple, list)):
        return tuple(_as_device_batch(b, device) for b in batch)
    if isinstance(batch, NDArray):
        return batch
    return NDArray(_as_jax(batch))


_worker_dataset = None


def _worker_init(dataset):
    global _worker_dataset
    _worker_dataset = dataset


def _worker_fn(indices, batchify_fn):
    return batchify_fn([_worker_dataset[i] for i in indices])


class DataLoader:
    """Iterate a Dataset in batches.

    Parameters mirror the reference: batch_size, shuffle, sampler,
    last_batch, batch_sampler, batchify_fn, num_workers, prefetch.

    ``num_workers > 0`` with the default process pool starts workers via
    ``forkserver`` (never ``fork`` — forking the JAX-threaded parent can
    deadlock a worker in a copied lock). Like every spawn-family start
    method this re-imports ``__main__`` in the worker, so scripts that
    build a worker DataLoader must use the standard
    ``if __name__ == "__main__":`` idiom. Datasets/batchify_fns must be
    picklable; set ``MXTPU_WORKER_CONTEXT=fork`` to opt back into fork,
    or ``thread_pool=True`` for a ThreadPool with none of these
    constraints.
    """

    def __init__(self, dataset: Dataset, batch_size=None, shuffle=False,
                 sampler: Optional[Sampler] = None, last_batch=None,
                 batch_sampler: Optional[BatchSampler] = None,
                 batchify_fn: Optional[Callable] = None, num_workers=0,
                 pin_memory=False, prefetch: Optional[int] = None,
                 thread_pool: bool = False, timeout=120):
        self._dataset = dataset
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError(
                    "batch_size is required when batch_sampler is not given")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle must be False with custom sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise MXNetError(
                "batch_size/shuffle/sampler/last_batch incompatible with "
                "batch_sampler")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers or 2)
        self._thread_pool = thread_pool
        self._pool = None
        if self._num_workers > 0:
            if thread_pool:
                from multiprocessing.pool import ThreadPool
                self._pool = ThreadPool(self._num_workers,
                                        initializer=_worker_init,
                                        initargs=(dataset,))
            else:
                # Never fork the JAX-threaded parent: os.fork() from a
                # multithreaded process can deadlock a worker in a copied
                # lock (the reference needed explicit fork handlers for
                # the same class of bug — src/initialize.cc, file-level
                # citation). forkserver execs a fresh server process and
                # forks workers from THAT, so no JAX thread is ever
                # copied; spawn is the fallback, fork an explicit opt-in
                # via MXTPU_WORKER_CONTEXT for non-picklable datasets.
                name = os.environ.get("MXTPU_WORKER_CONTEXT")
                if name is not None:
                    try:  # explicit opt-in must not be silently dropped
                        ctx = multiprocessing.get_context(name)
                    except ValueError:
                        raise MXNetError(
                            f"MXTPU_WORKER_CONTEXT={name!r} is not a "
                            f"start method on this platform (want fork/"
                            f"forkserver/spawn)")
                else:
                    try:
                        ctx = multiprocessing.get_context("forkserver")
                    except ValueError:  # platform without forkserver
                        ctx = multiprocessing.get_context("spawn")
                self._pool = ctx.Pool(self._num_workers,
                                      initializer=_worker_init,
                                      initargs=(dataset,))

    def __len__(self):
        return len(self._batch_sampler)

    def __iter__(self):
        if self._pool is not None:
            yield from self._iter_workers()
        else:
            yield from self._iter_prefetch()

    def _iter_prefetch(self):
        """Single-process path with a device-transfer prefetch thread."""
        q: "queue.Queue" = queue.Queue(maxsize=max(self._prefetch, 1))
        stop = object()

        def producer():
            try:
                for indices in self._batch_sampler:
                    batch = self._batchify_fn(
                        [self._dataset[i] for i in indices])
                    q.put(_as_device_batch(batch))
            except Exception as e:  # surface in consumer
                q.put(e)
            q.put(stop)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get(timeout=self._timeout)
            if item is stop:
                break
            if isinstance(item, Exception):
                raise item
            yield item

    def _iter_workers(self):
        """Worker-pool path with a rolling async window (the reference's
        prefetching worker pool)."""
        results = []
        it = iter(self._batch_sampler)

        def submit():
            try:
                indices = next(it)
            except StopIteration:
                return False
            results.append(self._pool.apply_async(
                _worker_fn, (indices, self._batchify_fn)))
            return True

        # always keep at least one request in flight, else prefetch=0 would
        # never enter the drain loop and the epoch would yield nothing
        for _ in range(max(self._prefetch, 1)):
            if not submit():
                break
        while results:
            batch = results.pop(0).get(self._timeout)
            submit()
            yield _as_device_batch(batch)

    def __del__(self):
        if self._pool is not None:
            self._pool.terminate()
