"""Standalone attention-kernel micro-benchmark (TPU).

Times the Pallas attention paths WITHOUT the surrounding model: dense
single-tile kernels (default head-grouping and hpp=1) vs the streaming
FlashAttention-2 kernels vs the jnp blockwise fallback, fwd-only and
fwd+bwd, across sequence lengths. Seconds per data point after the
first compile — the cheap way to spend a short tunnel window
characterizing kernels (the full bench rungs cost minutes each).

Env knobs are flipped BETWEEN calls inside this one process; that is
sound because every knob (dense threshold, hpp, blocks) is resolved in
the non-jitted wrappers and threaded as a static jit arg, so each
setting retraces instead of hitting a stale cache entry.

Prints ONE JSON line: {"kernel_bench": [{...per config...}]}.
"""

import json
import os
import sys
import time


def _bench_one(T, reps=20):
    import numpy as np
    import jax
    import jax.numpy as jnp
    from incubator_mxnet_tpu.ops.pallas_attention import (
        flash_attention_bhtd)

    # interpret mode off-TPU lets the harness self-check on CPU
    interp = not any(d.platform != "cpu" for d in jax.devices())
    B, H, D = 8, 12, 64
    kq = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(kq, i),
                                 (B, H, T, D), jnp.bfloat16)
               for i in range(3))
    vl = jnp.full((B,), T, jnp.int32)
    g = jax.random.normal(jax.random.fold_in(kq, 9), (B, H, T, D),
                          jnp.bfloat16)

    # fresh jit-wrapped callables per _bench_one call: a new function
    # object forces a retrace, so the env knobs read by the non-jitted
    # inner wrappers are honored for THIS config (and eager per-op
    # dispatch through the tunnel never pollutes the timing)
    @jax.jit
    def _fwd_j(q_, k_, v_):
        return flash_attention_bhtd(q_, k_, v_, vl, False, None, interp)

    def _loss(q_, k_, v_):
        o = flash_attention_bhtd(q_, k_, v_, vl, False, None, interp)
        return jnp.sum(o.astype(jnp.float32) * g.astype(jnp.float32))

    _bwd_j = jax.jit(jax.grad(_loss, argnums=(0, 1, 2)))

    def fwd():
        return _fwd_j(q, k, v)

    def fwdbwd():
        return _bwd_j(q, k, v)

    out = {}
    for name, fn in (("fwd", fwd), ("fwdbwd", fwdbwd)):
        r = fn()
        np.asarray(jax.tree_util.tree_leaves(r)[0])   # compile + fence
        t0 = time.perf_counter()
        for _ in range(reps):
            r = fn()
        np.asarray(jax.tree_util.tree_leaves(r)[0])   # fence (axon:
        # block_until_ready is a no-op; the fetch is the sync point)
        dt = (time.perf_counter() - t0) / reps
        flops = (4 if name == "fwd" else 14) * B * H * T * T * D
        out[name] = {"ms": round(dt * 1e3, 3),
                     "mxu_pct": round(100 * flops / dt / 197e12, 1)}
    return out


def main():
    import jax
    if not any(d.platform != "cpu" for d in jax.devices()):
        print(json.dumps({"error": "no TPU visible"}))
        return 1

    results = []
    # (label, env overrides) — resolved per call in the non-jit wrappers
    configs = [
        ("dense-grouped-T512", 512, {}),
        ("dense-hpp1-T512", 512, {"MXTPU_FLASH_FWD_HPP": "1",
                                  "MXTPU_FLASH_BWD_HPP": "1"}),
        ("streaming-T512", 512, {"MXTPU_FLASH_DENSE_T": "0"}),
        ("jnpfallback-T512", 512, {"MXTPU_FLASH_FORCE_FALLBACK": "1"}),
        ("dense-grouped-T1024", 1024, {"MXTPU_FLASH_DENSE_T": "1024"}),
        ("streaming-T1024", 1024, {"MXTPU_FLASH_DENSE_T": "0"}),
        ("streaming-T2048", 2048, {"MXTPU_FLASH_DENSE_T": "0"}),
    ]
    saved = {}
    for label, T, env in configs:
        for k_, v_ in env.items():
            saved.setdefault(k_, os.environ.get(k_))
            os.environ[k_] = v_
        try:
            r = _bench_one(T)
            results.append({"config": label, "T": T, **r})
        except Exception as e:          # a failing variant must not
            results.append({"config": label, "T": T,   # kill the rest
                            "error": f"{type(e).__name__}: {e}"[:300]})
        finally:
            for k_ in env:
                if saved.get(k_) is None:
                    os.environ.pop(k_, None)
                else:
                    os.environ[k_] = saved[k_]
        # cumulative line after EVERY config: a timeout mid-run still
        # leaves the last complete JSON for the ladder to bank
        print(json.dumps({"kernel_bench": results}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
