"""Pass 4 — hidden device→host syncs in the hot-loop modules.

The serving/training hot loops are built around ONE designed host sync
per step (the decode readback); every additional forcing op —
``.item()``, ``jax.device_get``, ``np.asarray``/``np.array`` over a
jax value, an implicit ``bool()`` in a host branch — serializes the
host against the device pipeline and silently costs a dispatch bubble
on every step. The chaos harnesses cannot see these (they are
correctness-neutral); only a static pass can.

Scope: the hot functions of serve/engine.py (step/run and the
admission/prefill/draft path), serve/router.py dispatch, and the fused
optimizer apply — plus everything they call in the same module. Device
values are tracked by a small forward taint: results of calling
jit-compiled attributes (``self.X`` where ``X`` was assigned
``jax.jit(…)``), jit-dict lookups, ``jax.*``/``jnp.*`` calls, and
same-module functions that return such values. EVERY finding here
requires a waiver naming why the sync is off the critical path — that
is the point: the designed syncs become documented contracts.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..core import Finding, Project, SourceUnit, dotted, qualname_of
from ._callgraph import walk_own

RULE = "host-sync"

# module path -> seed hot functions (method names); the pass closes
# over same-module self/local calls from these.
HOT_SEEDS: Dict[str, Set[str]] = {
    "incubator_mxnet_tpu/serve/engine.py": {
        "step", "run", "_advance_prefill", "_run_chunk",
        "_dense_prefill", "_finish_prefill", "_propose_drafts",
        "_ensure_tail_pages", "_admit", "_try_admit", "_finish_token",
        "_evict", "_quarantine", "_expire_slots", "_expire_queue",
        "_preempt",
    },
    "incubator_mxnet_tpu/serve/router.py": {
        "_dispatch", "step", "run", "_route", "_collect",
    },
    "incubator_mxnet_tpu/optimizer/fused.py": {
        "apply", "_apply_group", "grad_all_finite", "accumulate",
    },
    # round 16: the overlapped allreduce runs INSIDE backward — a hidden
    # sync in a grad-ready hook stalls the remaining backward dispatch,
    # which is exactly the overlap the feature exists to create
    "incubator_mxnet_tpu/gluon/trainer.py": {
        "_on_grad_ready", "_issue_bucket", "_pushpull_chunk",
        "_overlap_flush", "_allreduce_grads", "_bucketed_pushpull",
        "_int8_pushpull", "accumulate_grads",
    },
}

_FORCING_CASTS = {"float", "int", "bool"}
_NP_CAST = {"asarray", "array"}


def _head(d: Optional[str]) -> str:
    return d.split(".")[0] if d else ""


class _ModuleModel:
    """Per-module facts: jit-valued attributes/dicts and the
    returns-device fixpoint over its functions."""

    def __init__(self, unit: SourceUnit):
        self.unit = unit
        self.jit_attrs: Set[str] = set()
        self.jit_dict_attrs: Set[str] = set()
        # name -> EVERY def of that name (router.py has Replica.step
        # AND Router.step — last-wins would silently drop one hot
        # path's coverage; the pass errs toward analyzing all of them)
        self.functions: Dict[str, List[ast.AST]] = {}
        self.returns_device: Set[str] = set()     # function/method names
        if unit.tree is None:
            return
        for node in ast.walk(unit.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.setdefault(node.name, []).append(node)
        self._collect_jit_attrs()
        self._fixpoint()

    def _is_jit_call(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        d = dotted(node.func) or ""
        return d in ("jax.jit", "jax.pjit") or d.endswith(".pallas_call")

    def _collect_jit_attrs(self) -> None:
        jit_locals: Set[Tuple[int, str]] = set()  # (scope id, name)
        for node in ast.walk(self.unit.tree):
            if not isinstance(node, ast.Assign):
                continue
            is_jit = self._is_jit_call(node.value)
            for t in node.targets:
                if isinstance(t, ast.Attribute) and is_jit:
                    self.jit_attrs.add(t.attr)        # self.X = jax.jit
                elif isinstance(t, ast.Name) and is_jit:
                    jit_locals.add(t.id)
                elif isinstance(t, ast.Subscript):
                    base = t.value
                    if isinstance(base, ast.Attribute):
                        v = node.value
                        if is_jit or (isinstance(v, ast.Name)
                                      and v.id in jit_locals):
                            self.jit_dict_attrs.add(base.attr)

    def _fixpoint(self) -> None:
        changed = True
        while changed:
            changed = False
            for name, fns in self.functions.items():
                if name in self.returns_device:
                    continue
                for fn in fns:
                    taint = _TaintWalker(self, fn)
                    taint.walk()
                    if taint.returns_tainted:
                        self.returns_device.add(name)
                        changed = True
                        break


class _TaintWalker:
    """One forward pass over a function body tracking which local names
    hold device values."""

    def __init__(self, model: _ModuleModel, func: ast.AST):
        self.model = model
        self.func = func
        self.tainted: Set[str] = set()
        self.returns_tainted = False
        self.sinks: List[Tuple[ast.AST, str]] = []

    # -- expression taint ---------------------------------------------- #
    def is_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Call):
            return self.call_returns_device(node) or any(
                self.is_tainted(a) for a in node.args)
        if isinstance(node, (ast.Attribute, ast.Subscript, ast.Starred)):
            return self.is_tainted(node.value)
        if isinstance(node, ast.BinOp):
            return self.is_tainted(node.left) or \
                self.is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` are host identity checks —
            # they never touch the device value
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in node.ops):
                return False
            return self.is_tainted(node.left) or any(
                self.is_tainted(c) for c in node.comparators)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_tainted(e) for e in node.elts)
        if isinstance(node, ast.IfExp):
            return self.is_tainted(node.body) or \
                self.is_tainted(node.orelse)
        return False

    def call_returns_device(self, call: ast.Call) -> bool:
        func = call.func
        d = dotted(func) or ""
        h = _head(d)
        mods = self.model.unit.import_modules
        # jax.* / jnp.* values are device values
        if h and mods.get(h, "").startswith("jax") and "." in d:
            return True
        if isinstance(func, ast.Attribute):
            base = func.value
            # self.<jit attr>(…)
            if isinstance(base, ast.Name) and base.id == "self":
                if func.attr in self.model.jit_attrs:
                    return True
                if func.attr in self.model.returns_device:
                    return True
                return False
            # self.<jit dict attr>[k](…) handled via Name assignment;
            # direct form self._jits[sig](…):
            if isinstance(base, ast.Subscript) and \
                    isinstance(base.value, ast.Attribute) and \
                    base.value.attr in self.model.jit_dict_attrs:
                return True
            # <jit dict attr>.get(sig)(…) — rare, covered by locals
            return False
        if isinstance(func, ast.Name):
            if func.id in self.tainted:     # fn = self._jits[sig]; fn()
                return True
            if func.id in self.model.returns_device:
                return True
        if isinstance(func, ast.Subscript):
            base = func.value
            if isinstance(base, ast.Attribute) and \
                    base.attr in self.model.jit_dict_attrs:
                return True
        return False

    def _jit_lookup(self, value: ast.AST) -> bool:
        """name = self._jits[sig] / self._jits.get(sig) / jax.jit(f)."""
        if isinstance(value, ast.Subscript):
            base = value.value
            return isinstance(base, ast.Attribute) and \
                base.attr in self.model.jit_dict_attrs
        if isinstance(value, ast.Call):
            f = value.func
            if isinstance(f, ast.Attribute) and f.attr == "get" and \
                    isinstance(f.value, ast.Attribute) and \
                    f.value.attr in self.model.jit_dict_attrs:
                return True
            d = dotted(f) or ""
            if d in ("jax.jit", "jax.pjit") or d.endswith(".pallas_call"):
                return True
        return False

    # -- statement walk ------------------------------------------------ #
    def _is_forcing_cast(self, node: ast.AST) -> bool:
        """float()/int()/bool()/np.asarray()/np.array() RESULTS are host
        values — the sync already happened at the cast (which is where
        the sink fires); downstream uses are free."""
        if not isinstance(node, ast.Call):
            return False
        f = node.func
        if isinstance(f, ast.Name) and f.id in _FORCING_CASTS:
            return True
        d = dotted(f) or ""
        return bool(d) and \
            self.model.unit.import_modules.get(_head(d)) == "numpy" \
            and d.split(".")[-1] in _NP_CAST

    def walk(self, collect_sinks: bool = False) -> None:
        """One forward pass in statement order: each statement's own
        expressions are checked for sinks against the taint state AT
        THAT POINT, then its bindings are applied (so
        ``emitted = np.asarray(emitted)`` flags the sync AND untaints
        the rebound name for everything after)."""
        self._call_sinks: List[Tuple[ast.AST, str]] = []
        self._branch_sinks: List[Tuple[ast.AST, str]] = []
        for stmt in self._ordered_stmts(self.func):
            for expr in self._own_exprs(stmt):
                if collect_sinks:
                    self._scan_expr_sinks(expr)
            if isinstance(stmt, ast.Assign):
                src_tainted = (self.is_tainted(stmt.value) or
                               self._jit_lookup(stmt.value)) and \
                    not self._is_forcing_cast(stmt.value)
                for t in stmt.targets:
                    for name_node in ast.walk(t):
                        if isinstance(name_node, ast.Name):
                            if src_tainted:
                                self.tainted.add(name_node.id)
                            else:
                                self.tainted.discard(name_node.id)
            elif isinstance(stmt, ast.AugAssign):
                if isinstance(stmt.target, ast.Name) and \
                        self.is_tainted(stmt.value):
                    self.tainted.add(stmt.target.id)
            elif isinstance(stmt, ast.Return) and stmt.value is not None:
                if self.is_tainted(stmt.value):
                    self.returns_tainted = True
            elif isinstance(stmt, (ast.If, ast.While)) and collect_sinks:
                if self.is_tainted(stmt.test):
                    self._branch_sinks.append(
                        (stmt, "implicit `bool()` on a device value in "
                               "a host branch — hidden device→host "
                               "sync"))

    @staticmethod
    def _ordered_stmts(func: ast.AST):
        stmts = [n for n in walk_own(func) if isinstance(n, ast.stmt)]
        stmts.sort(key=lambda n: (n.lineno, n.col_offset))
        return stmts

    @staticmethod
    def _own_exprs(stmt: ast.stmt):
        """The expression children of a statement (not nested stmts)."""
        if isinstance(stmt, ast.Assign):
            return [stmt.value]
        if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            return [stmt.value] if stmt.value is not None else []
        if isinstance(stmt, (ast.Return, ast.Expr)):
            return [stmt.value] if stmt.value is not None else []
        if isinstance(stmt, (ast.If, ast.While)):
            return [stmt.test]
        if isinstance(stmt, ast.For):
            return [stmt.iter]
        if isinstance(stmt, ast.With):
            return [i.context_expr for i in stmt.items]
        if isinstance(stmt, ast.Assert):
            return [stmt.test]
        if isinstance(stmt, ast.Raise):
            return [e for e in (stmt.exc, stmt.cause) if e is not None]
        return []

    def _scan_expr_sinks(self, expr: ast.AST) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                msg = self._sink_message(node)
                if msg:
                    self._call_sinks.append((node, msg))

    def find_sinks(self) -> List[Tuple[ast.AST, str]]:
        self.tainted = set()
        self.walk(collect_sinks=True)
        # keep only the INNERMOST sink of a nested chain like
        # int(np.asarray(tok)) — the inner call is the actual sync
        ids = {id(n) for n, _ in self._call_sinks}
        out = list(self._branch_sinks)
        for node, msg in self._call_sinks:
            nested = any(id(sub) in ids for sub in ast.walk(node)
                         if sub is not node)
            if not nested:
                out.append((node, msg))
        return out

    def _sink_message(self, call: ast.Call) -> Optional[str]:
        func = call.func
        d = dotted(func) or ""
        if isinstance(func, ast.Attribute) and func.attr == "item" \
                and not call.args:
            # taint-guarded like the other sinks: `.item()` on a host
            # numpy scalar is not a sync and must not demand a waiver
            # asserting a falsehood (trace-purity separately flags
            # .item() inside traced code regardless of taint)
            if self.is_tainted(func.value):
                return ("`.item()` — device→host sync; the host "
                        "stalls on the device pipeline")
            return None
        if d == "jax.device_get":
            return "`jax.device_get` — explicit device→host sync"
        mods = self.model.unit.import_modules
        h = _head(d)
        tail = d.split(".")[-1] if d else ""
        if h and mods.get(h) == "numpy" and tail in _NP_CAST:
            if call.args and self.is_tainted(call.args[0]):
                return (f"`{d}()` over a device value — forces a "
                        f"device→host sync")
            return None
        if isinstance(func, ast.Name) and func.id in _FORCING_CASTS:
            if call.args and self.is_tainted(call.args[0]):
                return (f"host `{func.id}()` of a device value — "
                        f"forces a device→host sync")
        return None


class HostSyncPass:
    name = "host-sync"
    rules = (RULE,)

    def __init__(self, hot_seeds: Optional[Dict[str, Set[str]]] = None):
        self.hot_seeds = HOT_SEEDS if hot_seeds is None else hot_seeds

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for path, seeds in self.hot_seeds.items():
            unit = project.by_path.get(path)
            if unit is None or unit.tree is None:
                continue
            model = _ModuleModel(unit)
            hot = self._close_over_calls(model, seeds)
            for name in sorted(hot):
                for fn in model.functions.get(name, ()):
                    taint = _TaintWalker(model, fn)
                    for node, msg in taint.find_sinks():
                        out.append(Finding(
                            RULE, unit.path, node.lineno,
                            f"{msg} (hot path: "
                            f"{path.rsplit('/', 1)[-1]}:{name}) — "
                            f"requires a waiver naming why this is "
                            f"off the critical path",
                            symbol=qualname_of(node)))
        return out

    @staticmethod
    def _close_over_calls(model: _ModuleModel,
                          seeds: Set[str]) -> Set[str]:
        hot = set(n for n in seeds if n in model.functions)
        work = list(hot)
        while work:
            name = work.pop()
            for fn in model.functions.get(name, ()):
                for node in walk_own(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = None
                    f = node.func
                    if isinstance(f, ast.Attribute) and \
                            isinstance(f.value, ast.Name) and \
                            f.value.id == "self":
                        callee = f.attr
                    elif isinstance(f, ast.Name):
                        callee = f.id
                    if callee and callee in model.functions \
                            and callee not in hot:
                        hot.add(callee)
                        work.append(callee)
        return hot
