"""Utility helpers (re-design of `python/mxnet/util.py`; file-level citation
— SURVEY.md caveat): the ``environment()`` context manager for scoped env-var
overrides (reference: `mx.util.environment` / `test_utils.environment`,
SURVEY.md §5.6) plus numpy-semantics toggles used by ``mx.npx``."""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager
from typing import Optional, Union

__all__ = ["environment", "getenv", "setenv", "set_np", "reset_np",
           "is_np_array", "is_np_shape", "set_np_shape", "use_np",
           "np_array", "np_shape"]


@contextmanager
def environment(*args):
    """Scoped environment-variable override.

    ``environment(name, value)`` or ``environment({name: value, ...})``;
    value ``None`` unsets. Parity: ``mx.util.environment`` — the reference
    uses this to flip `MXNET_*` engine/memory knobs per test (SURVEY.md
    §5.6 tier 2; our namespace is ``MXTPU_*``).
    """
    if len(args) == 1 and isinstance(args[0], dict):
        overrides = args[0]
    elif len(args) == 2:
        overrides = {args[0]: args[1]}
    else:
        raise ValueError("environment() takes (name, value) or a dict")
    saved = {k: os.environ.get(k) for k in overrides}
    try:
        for k, v in overrides.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = str(v)
        yield
    finally:
        for k, old in saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old


def getenv(name: str) -> Optional[str]:
    """Parity: ``mx.util.getenv`` (backed by `MXGetEnv` in the reference)."""
    return os.environ.get(name)


def setenv(name: str, value: Optional[str]) -> None:
    """Parity: ``mx.util.setenv``."""
    if value is None:
        os.environ.pop(name, None)
    else:
        os.environ[name] = value


# --- numpy-semantics switches (reference: mx.util.set_np / npx.set_np) -----

_np_state = threading.local()


def is_np_array() -> bool:
    """True when ``mx.np`` array semantics are active (parity:
    `python/mxnet/util.py` is_np_array)."""
    return getattr(_np_state, "array", False)


def is_np_shape() -> bool:
    """True when numpy shape semantics (0-dim/0-size arrays) are active."""
    return getattr(_np_state, "shape", False)


def set_np_shape(active: bool) -> bool:
    prev = is_np_shape()
    _np_state.shape = bool(active)
    return prev


def set_np(shape: bool = True, array: bool = True) -> None:
    """Activate numpy semantics (parity: ``mx.npx.set_np``). The TPU build's
    arrays are jnp-backed so numpy semantics are natively available; the
    flag only affects front-end behaviours (e.g. Gluon blocks returning
    ``mx.np`` arrays)."""
    if array and not shape:
        raise ValueError("array semantics require shape semantics")
    _np_state.array = bool(array)
    _np_state.shape = bool(shape)


def reset_np() -> None:
    """Parity: ``mx.npx.reset_np``."""
    set_np(shape=False, array=False)


@contextmanager
def np_array(active: bool = True):
    prev = is_np_array()
    _np_state.array = bool(active)
    try:
        yield
    finally:
        _np_state.array = prev


@contextmanager
def np_shape(active: bool = True):
    prev = set_np_shape(active)
    try:
        yield
    finally:
        set_np_shape(prev)


def use_np(func):
    """Decorator parity for ``mx.util.use_np``: run ``func`` under numpy
    array+shape semantics."""
    import functools

    @functools.wraps(func)
    def _wrapped(*args, **kwargs):
        with np_shape(True), np_array(True):
            return func(*args, **kwargs)

    return _wrapped
