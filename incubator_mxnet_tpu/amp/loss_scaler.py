"""Dynamic loss scaler (re-design of `python/mxnet/amp/loss_scaler.py`;
file-level citation — SURVEY.md caveat).

Used for float16 AMP; bfloat16 (the TPU default) has fp32's exponent range
and normally runs with ``loss_scale=1`` — the scaler still functions so the
fp16 contract is fully supported.
"""

from __future__ import annotations

import numpy as np

__all__ = ["LossScaler"]


class LossScaler:
    """Dynamic loss scaling: multiply the loss by ``loss_scale`` before
    backward; after backward, check gradients for inf/nan — on overflow skip
    the update and halve the scale, otherwise grow the scale 2× every
    ``scale_window`` clean steps (the reference's exact policy)."""

    def __init__(self, init_scale: float = 2. ** 16, scale_factor: float = 2.,
                 scale_window: int = 2000, tolerance: float = 0.):
        self.loss_scale = float(init_scale)
        self._scale_factor = float(scale_factor)
        self._scale_window = int(scale_window)
        self._unskipped = 0

    def has_overflow(self, params) -> bool:
        """True if any parameter gradient contains inf/nan. Checked on-device
        with one small fetch (reference: `multi_all_finite` op)."""
        import jax.numpy as jnp

        total = None
        for p in params:
            g = p.grad() if callable(getattr(p, "grad", None)) else p
            data = getattr(g, "_data", g)
            bad = jnp.logical_not(jnp.isfinite(data)).sum()
            total = bad if total is None else total + bad
        if total is None:
            return False
        return bool(np.asarray(total) > 0)

    def update_scale(self, overflow: bool) -> None:
        if overflow:
            self.loss_scale = max(1., self.loss_scale / self._scale_factor)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0

    # -- checkpoint capsule ride-along (docs/CHECKPOINTING.md) --------- #
    def state_dict(self) -> dict:
        """Scale + clean-step streak — rides in the capsule meta so a
        resumed run re-enters the EXACT scaler trajectory (bit-exact
        resume contract; without it a restart would re-warm the scale
        and diverge the loss sequence)."""
        return {"loss_scale": float(self.loss_scale),
                "scale_factor": float(self._scale_factor),
                "scale_window": int(self._scale_window),
                "unskipped": int(self._unskipped)}

    def load_state_dict(self, state: dict) -> None:
        self.loss_scale = float(state["loss_scale"])
        self._scale_factor = float(
            state.get("scale_factor", self._scale_factor))
        self._scale_window = int(
            state.get("scale_window", self._scale_window))
        self._unskipped = int(state.get("unskipped", 0))
