"""Host-dispatch + training-throughput benchmark for the optimizer step.

Two modes:

DISPATCH (default): measures what the fused whole-tree optimizer step
(optimizer/fused.py) buys on the host side: the eager path dispatches
one un-jitted update op per parameter per step (the overhead MXNet
1.x's op-bulking engine existed to kill), the fused path dispatches ONE
jitted call per (dtype, stype, hyperparam) group. Parameters are tiny
so device compute is negligible and wall time ≈ host dispatch.
``--smoke`` asserts the steady-state no-retrace contract — wired into
ci/run.sh (stepbench) as the tier-1 regression guard for the fused step.

MFU (``--mfu``, round 16 — docs/TRAINING_PERF.md): trains a small GPT
through the REAL trainers and banks tokens/s next to an honest MFU
number computed from the same run (analytic fwd+bwd FLOPs per
utils/flops.py over measured wall time vs per-device peak), across the
round-16 levers: overlapped bucket-ready allreduce {off,on} ×
gradient accumulation {1,4,8} on the eager Trainer (paired alternating
windows, the ckpt_bench jitter methodology), and accumulation {1,4,8}
on SPMDTrainer over dp and fsdp meshes, plus a per-device-lane overlap
ratio from a profiler capture (tools/trace_summary.overlap_stats).
``--mfu --smoke`` is the ci/run.sh mfubench gate: an accumulation-count
change that RETRACES the step, a non-finite microbatch that does NOT
veto the whole accumulated apply, a guarded accumulated trajectory that
diverges from the unguarded one on a clean stream, or a
non-deterministic overlap issue schedule all fail the stage.

Usage:
  python tools/step_bench.py                 # dispatch bench, banks JSON
  python tools/step_bench.py --smoke         # CI guard (fast, asserts)
  python tools/step_bench.py --mfu           # training bench, BENCH_MFU.json
  python tools/step_bench.py --mfu --smoke   # mfubench CI gates
  python tools/step_bench.py --json OUT.json
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--mfu" in sys.argv:
    # the SPMD arms need a multi-device mesh; must land before jax import
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()


def _build_params(n_params, shape, seed=0):
    import numpy as np
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.gluon.parameter import Parameter
    rng = np.random.RandomState(seed)
    params = []
    for i in range(n_params):
        p = Parameter(f"p{i}", shape=shape)
        p.initialize()
        p.set_data(nd.array(rng.randn(*shape).astype(np.float32)))
        params.append(p)
    return params


def _fill_grads(params, seed):
    import numpy as np
    import jax.numpy as jnp
    rng = np.random.RandomState(seed)
    for p in params:
        g = p.grad()
        g._data = jnp.asarray(rng.randn(*p.shape).astype(np.float32))
        g._fresh = True


def _block(params):
    import jax
    for p in params:
        jax.block_until_ready(p.data()._data)


def _time_steps(trainer, params, steps, warmup=3):
    times = []
    for s in range(warmup + steps):
        _fill_grads(params, seed=100 + s)
        t0 = time.perf_counter()
        trainer.step(1)
        _block(params)
        dt = time.perf_counter() - t0
        if s >= warmup:
            times.append(dt)
    times.sort()
    return times[len(times) // 2]  # median


def bench_trainer(fuse, n_params, shape, steps, optimizer="adam"):
    from incubator_mxnet_tpu import gluon
    params = _build_params(n_params, shape)
    tr = gluon.Trainer(params, optimizer, {"learning_rate": 1e-3},
                       kvstore=None, fuse_step=fuse)
    med = _time_steps(tr, params, steps)
    out = {"per_step_ms": med * 1e3}
    if tr._fused is not None:
        out["trace_count"] = tr._fused.trace_count
        out["group_count"] = len(tr._fused._jits)
        # steady-state guard: more steps with fixed shapes → no retrace
        before = tr._fused.trace_count
        for s in range(3):
            _fill_grads(params, seed=900 + s)
            tr.step(1)
        _block(params)
        out["steady_state_retraces"] = tr._fused.trace_count - before
    return out, tr


def bench_spmd(n_layers, units, steps):
    """SPMD fused fwd+bwd+update step on the default (1-device) mesh —
    the everything-in-one-program upper bound for comparison."""
    import numpy as np
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd, parallel
    from incubator_mxnet_tpu.gluon import nn

    mx.random.seed(0)
    net = nn.Sequential()
    for _ in range(n_layers):
        net.add(nn.Dense(units, in_units=units))
    net.initialize()
    loss_fn = lambda out, y: ((out - y) ** 2).mean()
    tr = parallel.SPMDTrainer(net, loss=loss_fn, optimizer="adam",
                              optimizer_params={"learning_rate": 1e-3})
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(8, units).astype(np.float32))
    y = nd.array(rng.randn(8, units).astype(np.float32))
    times = []
    for s in range(3 + steps):
        t0 = time.perf_counter()
        L = tr.step(x, y)
        jax.block_until_ready(L._data)
        dt = time.perf_counter() - t0
        if s >= 3:
            times.append(dt)
    times.sort()
    return {"per_step_ms": times[len(times) // 2] * 1e3,
            "n_params": 2 * n_layers}


# ----------------------------------------------------------------------- #
# --mfu: training throughput with honest MFU accounting (round 16)
# ----------------------------------------------------------------------- #

def _tiny_gpt(seed=0, vocab=256, units=64, hidden=256, layers=2,
              heads=4, max_len=64):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.models.gpt import GPTModel
    mx.random.seed(seed)
    model = GPTModel(vocab_size=vocab, units=units, hidden_size=hidden,
                     num_layers=layers, num_heads=heads,
                     max_length=max_len, dropout=0.0)
    model.initialize()
    return model


def _token_micros(B, T, vocab, k, seed=0):
    """k deterministic (inputs, labels) microbatches of the synthetic
    next-token stream (the serve_bench int8-allreduce workload)."""
    import numpy as np
    from incubator_mxnet_tpu import nd
    rng = np.random.RandomState(seed)
    micros = []
    for m in range(k):
        base = rng.randint(0, vocab, (B, 1))
        ids = (base + np.arange(T + 1)[None, :]) % vocab
        micros.append((nd.array(ids[:, :-1], dtype="int32"),
                       nd.array(ids[:, 1:], dtype="int32")))
    return micros


def _block_params(params):
    import jax
    for p in params:
        jax.block_until_ready(p.data()._data)


def _eager_opt_steps(model, tr, micros, n_steps):
    """Run ``n_steps`` optimizer steps of len(micros) microbatches each
    through the eager Trainer; returns wall seconds."""
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu.models.gpt import lm_loss
    k = len(micros)
    params = list(model.collect_params().values())
    t0 = time.perf_counter()
    for _ in range(n_steps):
        if k == 1:
            with autograd.record():
                loss = lm_loss(model, *micros[0])
            tr.backward(loss)
            tr.step(1)
        else:
            for m in range(k):
                with autograd.record():
                    loss = lm_loss(model, *micros[m])
                tr.backward(loss)
                tr.accumulate_grads()
            tr.step(k)
    _block_params(params)
    return time.perf_counter() - t0


def bench_eager_overlap(accum_counts, steps, B, T, vocab, errors,
                        smoke):
    """Overlap {off,on} × accumulation arms on the eager Trainer with
    the int8-allreduce bucketed pushpull engaged (the seam whose
    dispatch overlap can hide). STRICT per-step alternation with ABBA
    ordering (arm order flips every step) and medians of per-step
    times — the round-10 guard-overhead methodology: this box's speed
    swings mid-session, and paired windows disagreed on the SIGN of
    effects this small (PERF_NOTES rounds 10/16). Each arm owns its
    model+trainer so state never crosses arms."""
    from incubator_mxnet_tpu.gluon import Trainer
    from incubator_mxnet_tpu.utils.flops import (gpt_train_flops, mfu,
                                                 peak_flops_per_device)

    peak = peak_flops_per_device()
    out = {}
    for k in accum_counts:
        arms = {}
        for overlap in (False, True):
            model = _tiny_gpt(seed=5)
            tr = Trainer(
                model.collect_params(), "adam",
                {"learning_rate": 1e-3}, kvstore="device",
                int8_allreduce=True, overlap_allreduce=overlap)
            if k > 1:
                # declare the rounds upfront: overlap defers to apply
                # time (each gradient byte still crosses once per
                # accumulated step — banked as the parity it is)
                tr.set_grad_accumulation(True)
            arms[overlap] = (model, tr)
        micros = _token_micros(B, T, vocab, k, seed=3)
        # warmup: compiles + overlap plan build (plan lands at step 1,
        # hooks issue from step 2 on)
        for model, tr in arms.values():
            _eager_opt_steps(model, tr, micros, 2)
        times = {False: [], True: []}
        for s in range(steps):
            order = (False, True) if s % 2 == 0 else (True, False)
            for overlap in order:
                model, tr = arms[overlap]
                times[overlap].append(
                    _eager_opt_steps(model, tr, micros, 1))
        med = {ov: sorted(ts)[len(ts) // 2]
               for ov, ts in times.items()}
        ratio = med[False] / med[True]
        tokens_per_step = B * T * k
        flops_per_step = gpt_train_flops(arms[False][0], B, T) * k
        arm_out = {}
        for overlap in (False, True):
            # arm_kind is the machine-checkable contract (round 19):
            # "overlap" arms MUST issue buckets during backward (gated
            # below); "parity" arms exist to bound the overhead — at
            # accum>1 both eager arms run the identical deferred path
            kind = "overlap" if (overlap and k == 1) else "parity"
            arm_out["overlap_on" if overlap else "overlap_off"] = {
                "arm_kind": kind,
                "per_step_ms": med[overlap] * 1e3,
                "tokens_per_s": tokens_per_step / med[overlap],
                **mfu(flops_per_step, med[overlap], 1, peak),
            }
        sched = arms[True][1].grad_issue_schedule
        arm_out["overlap_speedup_median_ratio"] = ratio
        arm_out["buckets_issued_overlapped"] = len(sched)
        arm_out["methodology"] = ("strict per-step ABBA alternation, "
                                  "median per-step times; at accum>1 "
                                  "both arms run the identical "
                                  "deferred-overlap path (parity arm)")
        out[f"accum_{k}"] = arm_out
        if k == 1 and not sched:
            errors.append("mfu/eager: overlap arm never issued a "
                          "bucket during backward")
        floor = 0.80 if smoke else 0.90
        if ratio < floor:
            errors.append(
                f"mfu/eager accum_{k}: overlap-on tokens/s "
                f"{ratio:.2f}x of overlap-off — under the {floor}x "
                f"no-worse floor")
    return out


def bench_spmd_accum(accum_counts, steps, B, T, vocab, errors,
                     trace_dir=None):
    """Accumulation arms on SPMDTrainer over dp2 and fsdp2 meshes: ONE
    once-compiled microbatch program per trainer across every
    accumulation count (the no-retrace gate), tokens/s + MFU per arm;
    optionally captures a profiler trace of the dp2 k=max arm for the
    per-device-lane overlap ratio (trace_summary.overlap_stats)."""
    import jax
    import numpy as np
    from incubator_mxnet_tpu import parallel
    from incubator_mxnet_tpu.models.gpt import lm_loss
    from incubator_mxnet_tpu.parallel import mesh as pmesh
    from incubator_mxnet_tpu.utils.flops import (gpt_train_flops, mfu,
                                                 peak_flops_per_device)

    peak = peak_flops_per_device()
    out = {}
    for tag, axes, sharding in (
            ("dp2", {"dp": 2}, "replicated"),
            ("fsdp2", {"dp": 1, "fsdp": 2}, "fsdp")):
        model = _tiny_gpt(seed=7)
        mesh = pmesh.build_mesh(devices=jax.devices()[:2],
                                axis_sizes=axes)
        tr = parallel.SPMDTrainer(
            model, optimizer="adam",
            optimizer_params={"learning_rate": 1e-3},
            forward_loss=lm_loss, mesh=mesh, sharding=sharding)
        arm_out = {}
        for k in accum_counts:
            micros = _token_micros(B, T, vocab, k, seed=3)
            tr.step_microbatches(micros)         # warm (compile once)
            t0 = time.perf_counter()
            for _ in range(steps):
                L = tr.step_microbatches(micros)
            jax.block_until_ready(L._data)
            dt = (time.perf_counter() - t0) / steps
            flops_per_step = gpt_train_flops(model, B, T) * k
            arm_out[f"accum_{k}"] = {
                # the GSPMD step leaves collective placement to the
                # compiler: it is the parity reference the pipelined
                # arms are measured (and bitwise-checked) against
                "arm_kind": "parity",
                "per_step_ms": dt * 1e3,
                "tokens_per_s": B * T * k / dt,
                **mfu(flops_per_step, dt, 2, peak),
            }
        arm_out["accum_step_trace_count"] = tr.accum_step_trace_count
        if tr.accum_step_trace_count != 1:
            errors.append(
                f"mfu/spmd {tag}: microbatch program compiled "
                f"{tr.accum_step_trace_count}x across accumulation "
                f"counts {list(accum_counts)} — an accumulation-count "
                f"change retraced the step")
        if tag == "dp2" and trace_dir is not None:
            try:
                micros = _token_micros(B, T, vocab, max(accum_counts),
                                       seed=3)
                with jax.profiler.trace(trace_dir):
                    for _ in range(3):
                        L = tr.step_microbatches(micros)
                    jax.block_until_ready(L._data)
                from trace_summary import overlap_stats
                st = overlap_stats(trace_dir)
                arm_out["overlap_trace"] = {
                    "overlap_ratio": st["overlap_ratio"],
                    "collective_ms": st["collective_us"] / 1e3,
                    "exposed_ms": st["exposed_us"] / 1e3,
                    "n_device_lanes": st["n_device_lanes"],
                }
            except Exception as e:                # profiler optional
                arm_out["overlap_trace"] = {"error": str(e)[:200]}
        out[tag] = arm_out
    return out


def bench_pipelined(accum_counts, steps, B, T, vocab, errors,
                    trace_dir=None):
    """In-program overlapped (pipelined) arms over dp2 and fsdp2
    (round 19): each mesh pairs a pipelined trainer with a baseline
    GSPMD trainer built from an identically-seeded model, and three
    gates append to ``errors``:

      parity     3 single-batch steps on the identical token stream —
                 losses AND final params bitwise-equal to the baseline
                 on dp2 (the pipelined step reorders the same math, it
                 does not approximate it; any reduction reorder breaks
                 this gate). Under fsdp the gate is allclose(1e-5,
                 1e-6): GSPMD's per-dot cost model may pick a
                 different contraction strategy for SHARDED params
                 (partial-contraction + AR + slice vs all-to-all +
                 full contraction) depending on the dot shapes, and
                 the manually-segmented pipelined program can draw the
                 other choice — an ulp-level program-structure
                 artifact, not a math difference (tests/
                 test_pipelined_step.py pins strict bitwise fsdp2
                 parity at its T=16 regime where the choices agree)
      no-retrace ONE compiled microbatch program across every
                 accumulation count (pipelined_accum_step_trace_count)
      structure  StableHLO of the compiled step: the grad-collective
                 shape sequence matches plan_grad_buckets order and
                 backward dots sit strictly between the first and last
                 grad collective — the overlap is *structural*, so the
                 gate holds on CPU where wall-clock overlap cannot

    Banks tokens/s + MFU per accumulation count (arm_kind "overlap")
    plus buckets_issued from the trace-time ledger; on a full run the
    dp2 arm also captures a profiler trace for the per-device-lane
    overlap_ratio."""
    import jax
    import numpy as np
    from incubator_mxnet_tpu import nd, parallel
    from incubator_mxnet_tpu.models.gpt import lm_loss, lm_pipeline
    from incubator_mxnet_tpu.parallel import mesh as pmesh
    from incubator_mxnet_tpu.utils.flops import (gpt_train_flops, mfu,
                                                 peak_flops_per_device)

    peak = peak_flops_per_device()
    out = {}
    for tag, axes, sharding in (
            ("dp2", {"dp": 2}, "replicated"),
            ("fsdp2", {"dp": 1, "fsdp": 2}, "fsdp")):
        mesh = pmesh.build_mesh(devices=jax.devices()[:2],
                                axis_sizes=axes)
        model_b = _tiny_gpt(seed=9)
        model_p = _tiny_gpt(seed=9)
        tr_b = parallel.SPMDTrainer(
            model_b, forward_loss=lm_loss, optimizer="adam",
            optimizer_params={"learning_rate": 1e-3},
            mesh=mesh, sharding=sharding)
        tr_p = parallel.SPMDTrainer(
            model_p, pipeline=lm_pipeline(model_p), optimizer="adam",
            optimizer_params={"learning_rate": 1e-3},
            mesh=mesh, sharding=sharding)

        # -- parity gate over 3 paired steps (see docstring: bitwise
        #    on dp2, allclose under fsdp)
        if sharding == "fsdp":
            check = lambda a, b: np.allclose(a, b, rtol=1e-5,
                                             atol=1e-6)
            parity_check = "allclose(rtol=1e-5, atol=1e-6)"
        else:
            check = np.array_equal
            parity_check = "bitwise"
        rng = np.random.RandomState(17)
        for s in range(3):
            ids = nd.array(rng.randint(0, vocab, (B, T))
                           .astype(np.int32))
            lbl = nd.array(rng.randint(0, vocab, (B, T))
                           .astype(np.int32))
            lb = tr_b.step(ids, lbl).asnumpy()
            lp = tr_p.step(ids, lbl).asnumpy()
            if not check(lb, lp):
                errors.append(
                    f"mfu/pipelined {tag}: step {s} loss diverged from "
                    f"the GSPMD baseline ({lb!r} vs {lp!r}) — the "
                    f"pipelined step must stay {parity_check}")
                break
        else:
            pb = [p.data().asnumpy() for _, p in
                  model_b.collect_params().items()]
            pp = [p.data().asnumpy() for _, p in
                  model_p.collect_params().items()]
            bad = sum(0 if check(a, b) else 1
                      for a, b in zip(pb, pp))
            if bad:
                errors.append(
                    f"mfu/pipelined {tag}: {bad} parameter(s) diverged "
                    f"beyond {parity_check} from the GSPMD baseline "
                    f"after 3 parity-gated steps")

        # -- structure gate (single-batch program just traced above)
        try:
            rep = tr_p.pipelined_structure()
            if not rep.get("order_matches_plan"):
                errors.append(
                    f"mfu/pipelined {tag}: compiled grad-collective "
                    f"order does not match plan_grad_buckets order")
            if not rep.get("interleaved"):
                errors.append(
                    f"mfu/pipelined {tag}: no backward dot between the "
                    f"first and last grad collective — the step "
                    f"compiled to the serial (unoverlapped) shape")
        except Exception as e:
            errors.append(f"mfu/pipelined {tag}: structure report "
                          f"failed: {e}")
            rep = {}

        # -- no-retrace gate + throughput arms (microbatch program)
        arm_out = {}
        for k in accum_counts:
            micros = _token_micros(B, T, vocab, k, seed=3)
            tr_p.step_microbatches(micros)       # warm (compile once)
            t0 = time.perf_counter()
            for _ in range(steps):
                L = tr_p.step_microbatches(micros)
            jax.block_until_ready(L._data)
            dt = (time.perf_counter() - t0) / steps
            flops_per_step = gpt_train_flops(model_p, B, T) * k
            arm_out[f"accum_{k}"] = {
                "arm_kind": "overlap",
                "per_step_ms": dt * 1e3,
                "tokens_per_s": B * T * k / dt,
                **mfu(flops_per_step, dt, 2, peak),
            }
        traces = tr_p.pipelined_accum_step_trace_count
        arm_out["pipelined_accum_step_trace_count"] = traces
        if traces != 1:
            errors.append(
                f"mfu/pipelined {tag}: microbatch program compiled "
                f"{traces}x across accumulation counts "
                f"{list(accum_counts)} — an accumulation-count change "
                f"retraced the pipelined step")
        arm_out["buckets_issued"] = len(tr_p.pipelined_bucket_order
                                        or [])
        arm_out["parity_check"] = parity_check
        arm_out["structure"] = {
            k: rep.get(k) for k in ("collective_op", "n_buckets",
                                    "order_matches_plan", "interleaved",
                                    "n_backward_dots_between")
            if k in rep}
        if tag == "dp2" and trace_dir is not None:
            try:
                micros = _token_micros(B, T, vocab, max(accum_counts),
                                       seed=3)
                with jax.profiler.trace(trace_dir):
                    for _ in range(3):
                        L = tr_p.step_microbatches(micros)
                    jax.block_until_ready(L._data)
                from trace_summary import overlap_stats
                st = overlap_stats(trace_dir)
                arm_out["overlap_trace"] = {
                    "overlap_ratio": st["overlap_ratio"],
                    "collective_ms": st["collective_us"] / 1e3,
                    "exposed_ms": st["exposed_us"] / 1e3,
                    "n_device_lanes": st["n_device_lanes"],
                }
            except Exception as e:                # profiler optional
                arm_out["overlap_trace"] = {"error": str(e)[:200]}
        out[tag] = arm_out
    return out


def bench_pipelined_int8_convergence(errors, smoke):
    """Convergence delta of the traced int8 grad all-reduce on the
    pipelined dp2 path — serve_bench.bench_int8_allreduce's
    methodology (same model config, stream, and 5% gate) so the two
    banks stay comparable: gpt_mini on a fixed deterministic batch,
    f32 arm vs int8 arm, divergence = max per-step |Δloss| normalised
    by the f32 arm's loss drop.  PR-11 banked 1.37% on this stream;
    the gate is 5%.  Also banks the on/off WALL-TIME delta via the
    round-10 strict per-step ABBA alternation (no gate: on a CPU rung
    the quantize/dequant ops are pure added work while the psum is
    free — EQuARX's win needs a bandwidth-bound ICI mesh)."""
    import jax
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd, parallel
    from incubator_mxnet_tpu.models.gpt import gpt_mini, lm_pipeline
    from incubator_mxnet_tpu.parallel import mesh as pmesh

    steps = 25 if smoke else 120
    B, T = 8, 32
    mesh = pmesh.build_mesh(devices=jax.devices()[:2],
                            axis_sizes={"dp": 2})
    trainers = {}
    for arm, int8 in (("f32", False), ("int8", True)):
        mx.random.seed(0)
        m = gpt_mini(vocab_size=512, max_length=96, dropout=0.0)
        m.initialize()
        trainers[arm] = parallel.SPMDTrainer(
            m, pipeline=lm_pipeline(m), optimizer="adam",
            optimizer_params={"learning_rate": 1e-3},
            mesh=mesh, sharding="replicated", int8_allreduce=int8)
    rng = np.random.RandomState(0)
    ids = nd.array(rng.randint(0, 512, (B, T)).astype(np.int32))
    lbl = nd.array(rng.randint(0, 512, (B, T)).astype(np.int32))
    lf, lq = [], []
    for _ in range(steps):
        lf.append(float(trainers["f32"].step(ids, lbl).asnumpy()))
        lq.append(float(trainers["int8"].step(ids, lbl).asnumpy()))
    ledger = trainers["int8"].pipelined_issue_ledger or []
    quantized_ran = any(e.get("op") == "int8_psum" for e in ledger)
    if not quantized_ran:
        errors.append("mfu/int8: the int8 arm never issued a quantized "
                      "all-reduce (ledger has no int8_psum entries)")
    span = max(lf[0] - min(lf), 1e-9)
    div = max(abs(a - b) for a, b in zip(lf, lq)) / span
    alt_steps = 10 if smoke else 20
    times = {"f32": [], "int8": []}
    for s in range(alt_steps):
        order = ("f32", "int8") if s % 2 == 0 else ("int8", "f32")
        for arm in order:
            t0 = time.perf_counter()
            L = trainers[arm].step(ids, lbl)
            jax.block_until_ready(L._data)
            times[arm].append(time.perf_counter() - t0)
    med = {a: sorted(t)[len(t) // 2] for a, t in times.items()}
    if lq[0] - min(lq) <= 0:
        errors.append("mfu/int8: the int8 arm failed to learn (loss "
                      "never improved on the fixed batch)")
    if div > 0.05:
        errors.append(
            f"mfu/int8: int8 all-reduce diverged {div:.1%} from the "
            f"f32 pipelined arm (gate 5%; PR-11 banked 1.37% on this "
            f"stream)")
    return {
        "arm_kind": "overlap",
        "steps": steps,
        "f32_loss_first_min": [lf[0], min(lf)],
        "int8_loss_first_min": [lq[0], min(lq)],
        "divergence_vs_f32": div,
        "gate": 0.05,
        "pr11_reference": 0.0137,
        "quantized_collective_ran": quantized_ran,
        "on_off_delta": {
            "f32_per_step_ms": med["f32"] * 1e3,
            "int8_per_step_ms": med["int8"] * 1e3,
            "int8_over_f32_ratio": med["int8"] / med["f32"],
            "methodology": ("strict per-step ABBA alternation, median "
                            "per-step times (round-10); ungated — the "
                            "CPU rung pays the quantize/dequant work "
                            "and gets psum bandwidth for free, so the "
                            "sign only inverts on a real ICI mesh"),
        },
        "methodology": ("serve_bench.bench_int8_allreduce stream: "
                        "gpt_mini(vocab 512) on one fixed batch, adam "
                        "lr 1e-3, max per-step |loss delta| / f32 loss "
                        "drop; both arms run the pipelined dp2 step, "
                        "only the bucket collective differs"),
    }


def mfu_invariant_gates(B, T, vocab, errors):
    """The mfubench correctness gates (cheap, always run): combined
    verdict per accumulated round, guarded==unguarded bit-identity on
    clean streams, deterministic overlap issue schedule."""
    import jax
    import numpy as np
    from incubator_mxnet_tpu import nd, parallel
    from incubator_mxnet_tpu.gluon import Trainer
    from incubator_mxnet_tpu.models.gpt import lm_loss
    from incubator_mxnet_tpu.parallel import mesh as pmesh
    from incubator_mxnet_tpu.train import StepOutcome

    def flagged_loss(m, inputs, labels, flag):
        # the poison channel: flag==1 is the identity, a NaN flag
        # poisons this microbatch's loss (and so every gradient) as
        # PURE TRACED DATA — no retrace across clean/poisoned rounds
        return lm_loss(m, inputs, labels) * flag.mean()

    def spmd_trainer(guard=True, seed=11, loss_fn=None):
        model = _tiny_gpt(seed=seed, vocab=vocab)
        mesh = pmesh.build_mesh(devices=jax.devices()[:2],
                                axis_sizes={"dp": 2})
        tr = parallel.SPMDTrainer(
            model, optimizer="adam",
            optimizer_params={"learning_rate": 1e-3},
            forward_loss=loss_fn or lm_loss, mesh=mesh, guard=guard)
        return model, tr

    def with_flag(micros, nan_at=None):
        out = []
        for m, (i, l) in enumerate(micros):
            f = np.ones((B,), np.float32)
            if m == nan_at:
                f[0] = np.nan
            out.append((i, l, nd.array(f)))
        return out

    # 1. combined verdict: a NaN in microbatch 2 of 4 vetoes the WHOLE
    #    accumulated apply bit-identically, as exactly one outcome
    model, tr = spmd_trainer(loss_fn=flagged_loss)
    micros = _token_micros(B, T, vocab, 4, seed=3)
    tr.step_microbatches(with_flag(micros))
    before = [p.data().asnumpy().copy() for p in tr._params]
    h_before = sum(tr.health.values())
    tr.step_microbatches(with_flag(micros, nan_at=1))
    if tr.last_outcome is not StepOutcome.SKIPPED_NONFINITE:
        errors.append("mfu/gates: non-finite microbatch 2/4 did not "
                      "record SKIPPED_NONFINITE for the round")
    if sum(tr.health.values()) != h_before + 1:
        errors.append("mfu/gates: accumulated round did not record "
                      "exactly one outcome")
    for b, a in zip(before, [p.data().asnumpy() for p in tr._params]):
        if not np.array_equal(b, a):
            errors.append("mfu/gates: vetoed accumulated round mutated "
                          "parameters")
            break
    tr.step_microbatches(with_flag(micros))
    if tr.last_outcome is not StepOutcome.APPLIED:
        errors.append("mfu/gates: clean round after a veto failed to "
                      "apply")
    if tr.accum_step_trace_count != 1:
        errors.append("mfu/gates: poisoned/clean transition retraced "
                      "the microbatch program")

    # 2. guarded accumulated trajectory bit-identical to unguarded on a
    #    clean stream
    finals = []
    for guard in (True, False):
        model_g, tr_g = spmd_trainer(guard=guard, seed=13)
        micros = _token_micros(B, T, vocab, 4, seed=5)
        for _ in range(3):
            tr_g.step_microbatches(micros)
        finals.append([p.data().asnumpy() for p in tr_g._params])
    for a, b in zip(*finals):
        if not np.array_equal(a, b):
            errors.append("mfu/gates: guarded accumulated trajectory "
                          "diverged from unguarded on a clean stream")
            break

    # 3. overlap issue schedule: stable across backwards and equal to
    #    the deterministic plan order
    model = _tiny_gpt(seed=5)
    tr = Trainer(model.collect_params(), "adam",
                 {"learning_rate": 1e-3}, kvstore="device",
                 int8_allreduce=True, overlap_allreduce=True)
    micros = _token_micros(B, T, vocab, 1, seed=3)
    scheds = []
    for _ in range(3):
        _eager_opt_steps(model, tr, micros, 1)
        scheds.append(list(tr.grad_issue_schedule))
    if scheds[1] != scheds[2] or not scheds[1]:
        errors.append("mfu/gates: overlapped bucket issue order not "
                      "deterministic across runs")
    if tr._overlap_sched not in (None, False) and \
            scheds[2] != tr._overlap_sched.order:
        errors.append("mfu/gates: issue order diverged from the "
                      "deterministic plan order")


def run_mfu(args):
    # split the tiny model into several buckets so bucket-READY issue
    # has something to overlap (one bucket degenerates to the serial
    # path: its last member gradient is the end of backward) — and so
    # the determinism gate asserts a real multi-bucket schedule
    saved_limit = os.environ.get("MXTPU_GRAD_BUCKET_BYTES")
    os.environ["MXTPU_GRAD_BUCKET_BYTES"] = str(64 * 1024)
    try:
        _run_mfu(args)
    finally:
        if saved_limit is None:
            os.environ.pop("MXTPU_GRAD_BUCKET_BYTES", None)
        else:
            os.environ["MXTPU_GRAD_BUCKET_BYTES"] = saved_limit


def _run_mfu(args):
    errors = []
    B, T, vocab = (4, 32, 256) if args.smoke else (8, 32, 256)
    accum_counts = (1, 4) if args.smoke else (1, 4, 8)
    eager_steps = 4 if args.smoke else 20
    spmd_steps = 2 if args.smoke else 6

    model_meta = _tiny_gpt(seed=5)
    from incubator_mxnet_tpu.utils.flops import (count_params,
                                                 gpt_train_flops,
                                                 peak_flops_per_device)
    peak = peak_flops_per_device()
    result = {
        "config": {
            "model": "gpt(tiny)",
            "vocab": vocab, "units": model_meta._units,
            "layers": model_meta.num_layers,
            "hidden": model_meta.hidden_size,
            "microbatch": B, "seq_len": T,
            "n_params": count_params(model_meta),
            "model_flops_per_microbatch":
                gpt_train_flops(model_meta, B, T),
            "peak_flops_per_device": peak["flops"],
            "peak_source": peak["source"],
            "device_kind": peak["device_kind"],
            "accum_counts": list(accum_counts),
            "backend": os.environ.get("JAX_PLATFORMS", "cpu"),
            "smoke": bool(args.smoke),
            "methodology": "strict per-step ABBA alternation between "
                           "overlap arms, median per-step times (the "
                           "round-10 small-effect methodology); MFU = "
                           "analytic fwd+bwd FLOPs (utils/flops.py) / "
                           "wall time / per-device peak",
        },
    }
    del model_meta

    mfu_invariant_gates(B, T, vocab, errors)
    result["eager_overlap_int8"] = bench_eager_overlap(
        accum_counts, eager_steps, B, T, vocab, errors, args.smoke)
    import tempfile
    trace_dir = None if args.smoke else tempfile.mkdtemp(
        prefix="mxtpu_mfu_trace_")
    result["spmd"] = bench_spmd_accum(accum_counts, spmd_steps, B, T,
                                      vocab, errors,
                                      trace_dir=trace_dir)
    # pipelined gates always run k in {1,4,8} — the no-retrace claim
    # is about the accumulation-count FAMILY, so smoke must cover it
    pipe_trace = None if args.smoke else tempfile.mkdtemp(
        prefix="mxtpu_pipe_trace_")
    result["pipelined"] = bench_pipelined(
        (1, 4, 8), spmd_steps, B, T, vocab, errors,
        trace_dir=pipe_trace)
    result["pipelined_int8_convergence"] = \
        bench_pipelined_int8_convergence(errors, args.smoke)

    # field-presence gate: every arm banks an MFU number; every
    # overlap-kind arm banks a nonzero bucket count (an "overlap" arm
    # that issued 0 buckets measured the serial path under a flattering
    # label)
    for section in ("eager_overlap_int8", "spmd", "pipelined"):
        for arm_key, arm in result[section].items():
            if not isinstance(arm, dict):
                continue
            for sub_key, sub in arm.items():
                if isinstance(sub, dict) and "per_step_ms" in sub and \
                        "mfu" not in sub:
                    errors.append(f"mfu: arm {section}.{arm_key}."
                                  f"{sub_key} lacks an mfu field")
            kinds = {sub.get("arm_kind") for sub in arm.values()
                     if isinstance(sub, dict)}
            if "overlap" in kinds:
                issued = arm.get("buckets_issued",
                                 arm.get("buckets_issued_overlapped"))
                if not issued:
                    errors.append(
                        f"mfu: overlap arm {section}.{arm_key} "
                        f"reports {issued!r} buckets issued — the "
                        f"overlapped path never ran")

    print(json.dumps(result, indent=2))
    out = args.json
    if out is None and not args.smoke:
        out = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "BENCH_MFU.json")
    if out:
        with open(out, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
        print(f"banked {out}")
    for e in errors:
        print(f"FAIL: {e}", file=sys.stderr)
    sys.exit(1 if errors else 0)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="fast CI guard: assert no steady-state retraces")
    ap.add_argument("--mfu", action="store_true",
                    help="training-throughput mode: overlap/accumulation "
                         "arms with MFU accounting (BENCH_MFU.json)")
    ap.add_argument("--json", default=None,
                    help="bank results here (default BENCH_STEP.json / "
                         "BENCH_MFU.json at the repo root for a full "
                         "run; none for --smoke)")
    ap.add_argument("--params", type=int, default=50)
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--optimizer", default="adam")
    args = ap.parse_args()

    if args.mfu:
        run_mfu(args)
        return

    if args.smoke:
        args.params, args.steps = 12, 3

    shape = (args.dim, args.dim)
    eager, _ = bench_trainer(False, args.params, shape, args.steps,
                             args.optimizer)
    fused, tr = bench_trainer(True, args.params, shape, args.steps,
                              args.optimizer)
    result = {
        "config": {"n_params": args.params, "shape": list(shape),
                   "optimizer": args.optimizer, "steps": args.steps,
                   "backend": os.environ.get("JAX_PLATFORMS", "cpu")},
        "eager": eager,
        "fused": fused,
        "host_dispatch_speedup": eager["per_step_ms"] / fused["per_step_ms"],
    }
    if not args.smoke:
        result["spmd"] = bench_spmd(args.params // 2, args.dim, args.steps)

    print(json.dumps(result, indent=2))

    ok = True
    if fused.get("steady_state_retraces", 0) != 0:
        print("FAIL: fused step retraced in steady state "
              f"({fused['steady_state_retraces']} retraces across 3 "
              f"fixed-shape steps)", file=sys.stderr)
        ok = False
    if fused.get("trace_count", 0) > fused.get("group_count", 1):
        print("FAIL: fused step compiled more than once per "
              f"(shape, dtype) signature: {fused['trace_count']} traces "
              f"for {fused['group_count']} group(s)", file=sys.stderr)
        ok = False
    if not args.smoke and result["host_dispatch_speedup"] < 5.0:
        print(f"WARN: host dispatch speedup "
              f"{result['host_dispatch_speedup']:.1f}x below the 5x bar",
              file=sys.stderr)

    out = args.json
    if out is None and not args.smoke:
        out = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "BENCH_STEP.json")
    if out:
        with open(out, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
        print(f"banked {out}")

    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
