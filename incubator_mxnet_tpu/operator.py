"""Custom operators in Python (``mx.operator``).

Parity target: `python/mxnet/operator.py` (CustomOp/CustomOpProp +
``mx.nd.Custom``) backed by `src/operator/custom/custom.cc`, which runs
user Python callbacks on a dedicated worker thread (file-level citations
— SURVEY.md caveat).

TPU-native design: the user's numpy forward/backward run on HOST via
``jax.pure_callback`` wrapped in a ``jax.custom_vjp`` — so a Custom op is
a first-class traced primitive: it composes with jit/vjp like any other
op, while the callback boundary isolates the arbitrary Python from XLA.
(The reference's dedicated-thread design solved GIL-vs-engine deadlocks;
here the callback mechanism owns that problem.)"""

from __future__ import annotations

from typing import Dict, List, Type

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError
from .ndarray import NDArray

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered",
           "custom"]

_CUSTOM_REGISTRY: Dict[str, Type["CustomOpProp"]] = {}


class CustomOp:
    """User op: override ``forward``/``backward``; use ``assign`` to
    honor the write/add/null request (parity: mx.operator.CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise MXNetError(
            f"{type(self).__name__}.backward not implemented")

    @staticmethod
    def assign(dst, req, src):
        if req == "null":
            return
        src = np.asarray(src, dtype=dst.dtype)
        if req == "add":
            dst += src
        else:  # write / inplace
            dst[...] = src


class CustomOpProp:
    """Shape/type inference + operator factory
    (parity: mx.operator.CustomOpProp)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self) -> List[str]:
        return ["data"]

    def list_outputs(self) -> List[str]:
        return ["output"]

    def list_auxiliary_states(self) -> List[str]:
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def create_operator(self, ctx, in_shapes, in_dtypes) -> CustomOp:
        raise NotImplementedError


def register(reg_name: str):
    """Class decorator registering a CustomOpProp under ``op_type``
    (parity: mx.operator.register)."""

    def _deco(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register expects a CustomOpProp subclass")
        _CUSTOM_REGISTRY[reg_name] = prop_cls
        return prop_cls

    return _deco


def get_all_registered() -> List[str]:
    return sorted(_CUSTOM_REGISTRY)


def custom(*inputs, op_type: str, **kwargs):
    """Invoke a registered custom op (parity: ``mx.nd.Custom``).

    Differentiable: backward dispatches to the user's
    ``CustomOp.backward`` through the same callback mechanism."""
    if op_type not in _CUSTOM_REGISTRY:
        raise MXNetError(
            f"custom op {op_type!r} not registered; known: "
            f"{get_all_registered()}")
    prop = _CUSTOM_REGISTRY[op_type](**kwargs)
    arrs = [x._data if isinstance(x, NDArray) else jnp.asarray(x)
            for x in inputs]
    in_shapes = [tuple(a.shape) for a in arrs]
    in_types = [np.dtype(str(a.dtype)) for a in arrs]
    shapes = prop.infer_shape(list(map(list, in_shapes)))
    out_shapes = [tuple(s) for s in shapes[1]]
    types = prop.infer_type(list(in_types))
    out_types = list(types[1])
    op = prop.create_operator(None, in_shapes, in_types)
    n_in = len(arrs)

    out_structs = tuple(jax.ShapeDtypeStruct(s, t)
                        for s, t in zip(out_shapes, out_types))
    in_structs = tuple(jax.ShapeDtypeStruct(s, t)
                       for s, t in zip(in_shapes, in_types))

    def _forward_np(*xs):
        in_data = [np.asarray(x) for x in xs]
        out_data = [np.zeros(s, t) for s, t in zip(out_shapes, out_types)]
        op.forward(True, ["write"] * len(out_data), in_data, out_data, [])
        return tuple(out_data)

    def _backward_np(*xs):
        in_data = [np.asarray(x) for x in xs[:n_in]]
        cots = [np.asarray(x) for x in xs[n_in:]]
        out_data = list(_forward_np(*in_data))
        in_grad = [np.zeros(s, t) for s, t in zip(in_shapes, in_types)]
        op.backward(["write"] * n_in, cots, in_data, out_data, in_grad, [])
        return tuple(in_grad)

    @jax.custom_vjp
    def _call(*xs):
        return jax.pure_callback(_forward_np, out_structs, *xs,
                                 vmap_method="sequential")

    def _fwd(*xs):
        return _call(*xs), xs

    def _bwd(res, cots):
        grads = jax.pure_callback(_backward_np, in_structs, *res, *cots,
                                  vmap_method="sequential")
        return tuple(grads)

    _call.defvjp(_fwd, _bwd)

    from . import autograd

    # run through the standard imperative path so autograd records it
    outs_raw = _call(*arrs)
    outs = [NDArray(o) for o in outs_raw]
    if autograd.is_recording():
        owners = [x if isinstance(x, NDArray) else None for x in inputs]
        autograd._record_node(lambda *xs: _call(*xs), arrs, owners, outs,
                              name=f"Custom[{op_type}]", tuple_out=True)
    return outs if len(outs) > 1 else outs[0]
