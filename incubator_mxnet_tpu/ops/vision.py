"""Spatial-transform / resampling / patch operators.

Parity targets (file-level citations, SURVEY.md caveat — upstream paths):
  - UpSampling           src/operator/nn/upsampling.cc
  - BilinearSampler      src/operator/bilinear_sampler.cc
  - GridGenerator        src/operator/grid_generator.cc
  - SpatialTransformer   src/operator/spatial_transformer.cc
  - im2col / col2im      src/operator/nn/im2col.h
  - fft / ifft           src/operator/contrib/fft.cc (cuFFT there)

TPU-first design: every op is ONE pure jnp/lax computation with static
shapes — gathers with per-tap validity weights instead of the reference's
hand-written CUDA samplers, ``lax.conv_general_dilated_patches`` for
im2col (XLA lowers it onto the same window machinery as convolution),
and ``col2im`` as the exact adjoint of ``im2col`` via ``jax.vjp`` (the
reference maintains a separate handwritten scatter kernel; the adjoint
identity is the whole spec). Gradients of every op come from jax.vjp of
the same function (registry contract, ops/registry.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import register
from .nn import _tup


# --------------------------------------------------------------------- #
# sampling helpers
# --------------------------------------------------------------------- #

def _bilinear_weights_1d(scale):
    """The reference's bilinear deconvolution filter of size
    2*scale - scale % 2 (upsampling.cc init)."""
    k = 2 * scale - scale % 2
    center = (2 * scale - 1 - scale % 2) / (2.0 * scale)
    idx = jnp.arange(k, dtype=jnp.float32)
    return 1.0 - jnp.abs(idx / scale - center)


def _grid_sample_zero_pad(feat, ys, xs):
    """Bilinear sample one image. feat: (C, H, W); ys/xs: (Ho, Wo) in
    PIXEL coords. Out-of-boundary taps contribute zero (the reference
    BilinearSampler contract)."""
    C, H, W = feat.shape
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    ly = ys - y0
    lx = xs - x0

    def tap(yi, xi, w):
        valid = (yi >= 0) & (yi <= H - 1) & (xi >= 0) & (xi <= W - 1)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        vals = feat[:, yc, xc]                      # (C, Ho, Wo)
        return vals * (w * valid)[None]

    return (tap(y0, x0, (1 - ly) * (1 - lx))
            + tap(y0, x0 + 1, (1 - ly) * lx)
            + tap(y0 + 1, x0, ly * (1 - lx))
            + tap(y0 + 1, x0 + 1, ly * lx))


# --------------------------------------------------------------------- #
# UpSampling
# --------------------------------------------------------------------- #

@register("UpSampling", aliases=("up_sampling",))
def upsampling(*data, scale=2, sample_type="nearest", num_filter=0,
               multi_input_mode="concat", num_args=None, workspace=None):
    """Spatial upsampling by an integer ``scale``.

    ``nearest``: pixel repetition (any number of inputs; all upsampled to
    the FIRST input's scaled size, then channel-concatenated — the
    reference's multi-input contract). ``bilinear``: the reference's
    fixed bilinear deconvolution (kernel 2s - s%2, stride s, pad
    ceil((s-1)/2)) applied per channel; a trailing weight argument, when
    supplied (reference signature), is used as the deconvolution filter.
    """
    if not data:
        raise MXNetError("UpSampling needs at least one input")
    scale = int(scale)
    if sample_type == "nearest":
        target = (data[0].shape[2] * scale, data[0].shape[3] * scale)
        outs = []
        for x in data:
            s_h = target[0] // x.shape[2]
            s_w = target[1] // x.shape[3]
            outs.append(jnp.repeat(jnp.repeat(x, s_h, axis=2), s_w, axis=3))
        return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=1)
    if sample_type != "bilinear":
        raise MXNetError(f"unknown sample_type {sample_type!r}")

    x = data[0]
    B, C, H, W = x.shape
    k = 2 * scale - scale % 2
    pad = -(-(scale - 1) // 2)  # ceil((scale-1)/2), the reference's pad
    if len(data) > 1:
        # reference weight layout (C, 1, k, k) → IOHW per-group (1, C, k, k)
        weight = jnp.transpose(data[1], (1, 0, 2, 3))
    else:
        w1 = _bilinear_weights_1d(scale)
        weight = jnp.broadcast_to((w1[:, None] * w1[None, :])[None, None],
                                  (1, C, k, k)).astype(x.dtype)
    dn = lax.conv_dimension_numbers(x.shape, weight.shape,
                                    ("NCHW", "IOHW", "NCHW"))
    k_eff = k
    padding = [(k_eff - 1 - pad, k_eff - 1 - pad)] * 2
    out = lax.conv_general_dilated(
        x, jnp.flip(weight, axis=(2, 3)),
        window_strides=(1, 1),
        padding=padding,
        lhs_dilation=(scale, scale),
        dimension_numbers=dn,
        feature_group_count=C,
    )
    # reference output size is exactly scale * input
    return out[:, :, :H * scale, :W * scale]


# --------------------------------------------------------------------- #
# BilinearSampler / GridGenerator / SpatialTransformer
# --------------------------------------------------------------------- #

@register("BilinearSampler", aliases=("bilinear_sampler",))
def bilinear_sampler(data, grid, cudnn_off=None):
    """Sample ``data`` at ``grid`` locations. data: (B, C, H, W); grid:
    (B, 2, Ho, Wo), channel 0 = x, channel 1 = y, normalized to [-1, 1]
    over the input extent. Out-of-range locations read zero."""
    B, C, H, W = data.shape
    xs = (grid[:, 0] + 1.0) * (W - 1) / 2.0          # (B, Ho, Wo)
    ys = (grid[:, 1] + 1.0) * (H - 1) / 2.0
    return jax.vmap(_grid_sample_zero_pad)(data, ys, xs)


@register("GridGenerator", aliases=("grid_generator",))
def grid_generator(data, transform_type="affine", target_shape=None):
    """Generate a sampling grid for BilinearSampler.

    ``affine``: data (B, 6) row-major 2x3 matrices over the normalized
    target grid. ``warp``: data (B, 2, H, W) pixel-offset flow field.
    Output (B, 2, Ho, Wo) normalized to [-1, 1]."""
    if transform_type == "affine":
        if target_shape is None:
            raise MXNetError("affine GridGenerator needs target_shape")
        Ho, Wo = int(target_shape[0]), int(target_shape[1])
        theta = data.reshape(-1, 2, 3)
        xt = jnp.linspace(-1.0, 1.0, Wo)
        yt = jnp.linspace(-1.0, 1.0, Ho)
        yy, xx = jnp.meshgrid(yt, xt, indexing="ij")   # (Ho, Wo)
        base = jnp.stack([xx.ravel(), yy.ravel(),
                          jnp.ones(Ho * Wo)])          # (3, Ho*Wo)
        out = jnp.einsum("bij,jk->bik", theta, base.astype(data.dtype))
        return out.reshape(-1, 2, Ho, Wo)
    if transform_type == "warp":
        B, two, H, W = data.shape
        jj = jnp.arange(W, dtype=data.dtype)
        ii = jnp.arange(H, dtype=data.dtype)
        x = (data[:, 0] + jj[None, None, :]) * (2.0 / max(W - 1, 1)) - 1.0
        y = (data[:, 1] + ii[None, :, None]) * (2.0 / max(H - 1, 1)) - 1.0
        return jnp.stack([x, y], axis=1)
    raise MXNetError(f"unknown transform_type {transform_type!r}")


@register("SpatialTransformer", aliases=("spatial_transformer",))
def spatial_transformer(data, loc, target_shape=None,
                        transform_type="affine", sampler_type="bilinear",
                        cudnn_off=None):
    """Affine spatial transformer network head: GridGenerator(loc) then
    BilinearSampler over ``data``."""
    if transform_type != "affine" or sampler_type != "bilinear":
        raise MXNetError("SpatialTransformer supports affine + bilinear")
    if target_shape is None:
        target_shape = data.shape[2:]
    grid = grid_generator(loc, transform_type="affine",
                          target_shape=target_shape)
    return bilinear_sampler(data, grid)


# --------------------------------------------------------------------- #
# im2col / col2im
# --------------------------------------------------------------------- #

@register("im2col")
def im2col(data, kernel=None, stride=None, dilate=None, pad=None):
    """Sliding-window patch extraction. data: (B, C, H, W) → output
    (B, C*kh*kw, oh*ow) (reference layout)."""
    kernel = _tup(kernel, 2)
    nsp = len(kernel)
    stride = tuple(s or 1 for s in (_tup(stride, nsp) or (1,) * nsp))
    dilate = tuple(d or 1 for d in (_tup(dilate, nsp) or (1,) * nsp))
    pad = _tup(pad, nsp) or (0,) * nsp
    patches = lax.conv_general_dilated_patches(
        data, filter_shape=kernel, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate)
    B = patches.shape[0]
    return patches.reshape(B, patches.shape[1], -1)


@register("col2im")
def col2im(data, output_size=None, kernel=None, stride=None, dilate=None,
           pad=None):
    """Adjoint of im2col: scatter-add patches back into the image.
    data: (B, C*kh*kw, L) → (B, C, *output_size)."""
    kernel = _tup(kernel, 2)
    nsp = len(kernel)
    if output_size is None:
        raise MXNetError("col2im needs output_size")
    hw = tuple(int(s) for s in _tup(output_size, nsp))
    C = data.shape[1]
    for k in kernel:
        C //= k
    img_shape = (data.shape[0], C) + hw

    def fwd(img):
        return im2col(img, kernel=kernel, stride=stride, dilate=dilate,
                      pad=pad)

    zeros = jnp.zeros(img_shape, data.dtype)
    _, vjp = jax.vjp(fwd, zeros)
    return vjp(data)[0]


# --------------------------------------------------------------------- #
# fft / ifft (contrib)
# --------------------------------------------------------------------- #

@register("fft", aliases=("_contrib_fft",))
def fft(data, compute_size=128):
    """FFT along the last axis (reference: contrib/fft.cc, cuFFT).
    Real input (..., d) → interleaved real/imag output (..., 2d)."""
    c = jnp.fft.fft(data.astype(jnp.float32), axis=-1)
    out = jnp.stack([c.real, c.imag], axis=-1)
    return out.reshape(data.shape[:-1] + (2 * data.shape[-1],)) \
        .astype(data.dtype)


@register("ifft", aliases=("_contrib_ifft",))
def ifft(data, compute_size=128):
    """Inverse FFT along the last axis. Interleaved input (..., 2d) →
    real output (..., d). Reference contract: NO 1/d normalization —
    ``ifft(fft(x)) == d * x`` (contrib/fft.cc)."""
    d = data.shape[-1] // 2
    inter = data.reshape(data.shape[:-1] + (d, 2)).astype(jnp.float32)
    c = lax.complex(inter[..., 0], inter[..., 1])
    out = jnp.fft.ifft(c, axis=-1).real * d
    return out.astype(data.dtype)
