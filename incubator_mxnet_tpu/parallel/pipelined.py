"""In-program overlapped gradient collectives (ROADMAP item 5).

PR 13 measured the failure this module removes: inside the one-program
GSPMD step XLA schedules every gradient all-reduce AFTER the whole
backward (`overlap_ratio: 0.0` in BENCH_MFU.json) — the collectives are
a serial tail, not an overlapped stream. The reference framework solved
the same problem host-side with P3 priority scheduling of kvstore
push/pull during backward (SURVEY.md §2.3); the TPU-native analogue is
to make the overlap a property of the *compiled program*: the step runs
under `shard_map`, backward is decomposed per layer block with chained
`jax.vjp` pullbacks, and each gradient bucket's collective is issued as
an explicit in-program `lax.psum` (or a ppermute ring) *between* block
pullbacks, so the collective for block i+1's gradients is in flight on
ICI while block i's backward computes.

Correctness contract (asserted in tests/test_pipelined_step.py):

- **Bitwise parity.** The pipelined step reproduces the GSPMD step's
  loss/param/optimizer-state trajectories bit-for-bit on clean streams
  over the 2-device dp and fsdp meshes. The parity recipe mirrors what
  GSPMD's partitioner emits: the loss is computed as LOCAL partial sums
  (`PipelineSpec.head` returns un-normalized per-shard sums and counts),
  the partials tree is psummed over the batch axes, and a pure
  `finalize` reproduces the baseline's scalar loss expression on the
  globals — division by a power-of-two shard count is exact, and a
  2-device all-reduce is a single commutative add, so every op matches
  the partitioned baseline's local computation exactly.
- **Deterministic issue order.** Buckets come from
  `collectives.plan_grad_buckets` (the audited packing) and are issued
  strictly in plan order through `collectives.BucketSchedule` at trace
  time — a collective is a cross-replica rendezvous, and a reordered
  issue is the silent deadlock PR 13 fenced host-side. The per-trace
  ledger (`SPMDTrainer.pipelined_issue_ledger`) records what was issued;
  `structure_report` re-derives the order from the lowered StableHLO so
  the *compiled* order, not just the traced one, is asserted.
- **Guard/scaler/accum compose unchanged.** The PR-8 all-finite guard
  reads the post-collective (for int8: dequantized) gradients, combines
  the per-shard verdicts with a `pmin`, and the skip-step stays a
  where-select; loss scaling rides the backward seed; accumulation
  folds into the same donated f32 carry as the GSPMD accum step.

Sharding support: dp and fsdp batch axes (tp/sp/pp/ep must be size 1 on
this path — tensor-parallel models keep the GSPMD step). fsdp params are
all-gathered to full values at the top of the body (ZeRO), gradients are
psummed at full size and sliced back to the local shard — at 2 devices
this is bitwise the partitioner's gather/reduce-scatter pair.

Known limits (documented in docs/TRAINING_PERF.md): parameter-mutating
forwards (BatchNorm running stats) raise loudly; dropout>0 runs but
draws per-shard masks (no bitwise parity with the GSPMD step's global
mask); norm-based optimizers (LAMB/LARS) are rejected under fsdp because
the update would see shard-local norms.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec

from .. import autograd, random as _random
from ..base import MXNetError, getenv_int
from ..ndarray import NDArray
from .collectives import (BucketSchedule, int8_bucket_allreduce,
                          plan_grad_buckets, ring_allreduce_flat)

__all__ = ["PipelineSpec", "build_pipelined_step",
           "build_pipelined_accum_step", "structure_report",
           "ring_allreduce_flat"]


def _bucket_limit_bytes():
    return getenv_int("MXTPU_GRAD_BUCKET_BYTES", 0) or \
        getenv_int("MXTPU_GRAD_BUCKET_MB", 32) * (1 << 20)


# --------------------------------------------------------------------- #
# pipeline structure declaration
# --------------------------------------------------------------------- #
class PipelineSpec:
    """Declares a model's layer stack as stem → blocks → head for the
    pipelined backward.

    Parameters
    ----------
    blocks : sequence — the pipeline blocks, in forward order. Each
        entry is either a HybridBlock (called as ``blk(x, *ctx)``) or a
        ``(modules, fn)`` pair where ``modules`` is the list of blocks
        owning the entry's parameters and ``fn(x_nd, *ctx_nds)`` runs
        it. Blocks must have pairwise-disjoint parameters.
    head : callable ``head(x_nd, *batch_nds) -> tuple of scalar
        NDArrays`` — the LOCAL PARTIAL SUMS of the loss (un-normalized
        per-shard sums/counts). Runs with head (and tied) params bound.
    finalize : callable over the PSUMMED partials (jnp scalars) →
        scalar loss. Must be parameter-free pure arithmetic and must
        reproduce the baseline loss expression exactly (bitwise parity
        hinges on it): e.g. for a mean, return ``n / d`` where ``head``
        emitted ``(sum(x), float(x.size))``.
    stem_modules / head_modules : blocks owning the stem/head params.
    stem : callable ``stem(*batch_nds) -> x0 NDArray`` (default: the
        first batch element as-is, e.g. when embeddings sit in block 0).
    context : optional ``context(*batch_nds) -> tuple of NDArrays`` —
        parameter-independent constants handed to every block (e.g. the
        BERT attention mask). No gradient flows through the context.
    name : diagnostic label.

    Parameters appearing in both ``stem_modules`` and ``head_modules``
    (tied embeddings) are owned by the stem; the head receives them as
    an explicit differentiation argument and the two cotangent
    contributions are summed — same 2-term sum autodiff produces for
    the GSPMD step, so parity holds.
    """

    def __init__(self, blocks, head, finalize, stem_modules=(),
                 head_modules=(), stem=None, context=None, name=""):
        self.block_entries = []
        for b in blocks:
            if isinstance(b, tuple):
                mods, fn = b
                self.block_entries.append((list(mods), fn))
            else:
                self.block_entries.append(
                    ([b], (lambda x, *ctx, _b=b: _b(x, *ctx))))
        self.head = head
        self.finalize = finalize
        self.stem_modules = list(stem_modules)
        self.head_modules = list(head_modules)
        self.stem = stem
        self.context = context
        self.name = name or "pipeline"

    # -- parameter-to-segment mapping ---------------------------------- #
    def segment_params(self, params, train_idx):
        """Partition the trainable parameter indices over the segments.

        Returns ``(stem_own, block_own, head_own, tied)`` — lists of
        indices into ``params``; ``tied`` are head-visible params owned
        by the stem. Raises on overlap between blocks or uncovered
        trainables."""
        train_set = set(train_idx)
        # identity on the Parameter object, not its data NDArray
        by_id = {id(params[i]): i for i in range(len(params))}

        def collect(modules):
            seen, out = set(), []
            for m in modules:
                # bare Parameters (e.g. a tied-decoder bias hung directly
                # off the model) are accepted alongside blocks
                ps = m.collect_params().values() \
                    if hasattr(m, "collect_params") else [m]
                for p in ps:
                    i = by_id.get(id(p))
                    if i is None or i not in train_set or i in seen:
                        continue
                    seen.add(i)
                    out.append(i)
            return sorted(out)

        stem_own = collect(self.stem_modules)
        head_raw = collect(self.head_modules)
        block_own, claimed = [], set(stem_own)
        for bi, (mods, _) in enumerate(self.block_entries):
            own = [i for i in collect(mods) if i not in claimed]
            dup = [i for i in collect(mods)
                   if i in claimed and i not in stem_own]
            if dup:
                raise MXNetError(
                    f"pipeline block {bi} shares trainable params "
                    f"{[params[i].name for i in dup]} with an earlier "
                    f"block — pipelined blocks must be disjoint")
            shared_stem = [i for i in collect(mods) if i in stem_own]
            if shared_stem:
                raise MXNetError(
                    f"pipeline block {bi} shares params "
                    f"{[params[i].name for i in shared_stem]} with the "
                    f"stem — tie params only between stem and head")
            block_own.append(own)
            claimed.update(own)
        tied = [i for i in head_raw if i in claimed]
        bad_tie = [i for i in tied if i not in stem_own]
        if bad_tie:
            raise MXNetError(
                f"head params {[params[i].name for i in bad_tie]} are "
                f"owned by a pipeline block — ties are only supported "
                f"between stem and head (the embedding/decoder pattern)")
        head_own = [i for i in head_raw if i not in claimed]
        claimed.update(head_own)
        missing = [params[i].name for i in train_idx if i not in claimed]
        if missing:
            raise MXNetError(
                f"pipeline spec does not cover trainable params "
                f"{missing}; add their blocks to stem_modules / blocks "
                f"/ head_modules")
        return stem_own, block_own, head_own, tied


# --------------------------------------------------------------------- #
# fsdp gather / slice against a param's PartitionSpec
# --------------------------------------------------------------------- #
def _spec_entries(spec, ndim):
    entries = list(spec) if spec is not None else []
    entries += [None] * (ndim - len(entries))
    return [tuple(e) if isinstance(e, (tuple, list)) else
            ((e,) if e is not None else ()) for e in entries]


def _gather_full(val, spec, mesh_shape):
    """All-gather a sharded param to its full value (ZeRO gather).
    Gathers minor (last-listed) axes first so the tile order matches
    the NamedSharding layout."""
    for d, axes in enumerate(_spec_entries(spec, val.ndim)):
        for ax in reversed(axes):
            if mesh_shape.get(ax, 1) > 1:
                val = lax.all_gather(val, ax, axis=d, tiled=True)
    return val


def _slice_local(val, spec, mesh_shape):
    """Slice a full (reduced) gradient back to the local shard."""
    for d, axes in enumerate(_spec_entries(spec, val.ndim)):
        live = [ax for ax in axes if mesh_shape.get(ax, 1) > 1]
        if not live:
            continue
        size = 1
        for ax in live:
            size *= mesh_shape[ax]
        idx = jnp.int32(0)
        for ax in live:  # major-first fold, matching the tile order
            idx = idx * mesh_shape[ax] + lax.axis_index(ax)
        local = val.shape[d] // size
        val = lax.dynamic_slice_in_dim(val, idx * local, local, axis=d)
    return val


def _is_sharded(spec, mesh_shape):
    return any(mesh_shape.get(ax, 1) > 1
               for axes in _spec_entries(spec, 64) for ax in axes)


# --------------------------------------------------------------------- #
# bucket collectives
# --------------------------------------------------------------------- #
def _reduce_bucket(vals, raxes, mode, int8, mesh_shape):
    """One bucket's in-program collective (the traced primitives live in
    collectives.py). Returns the reduced member list plus the ledger
    entry describing what was emitted."""
    if not raxes:
        return list(vals), {"op": "none"}
    if int8 and all(jnp.issubdtype(v.dtype, jnp.floating) for v in vals):
        out = int8_bucket_allreduce(vals, raxes)
        return out, {"op": "int8_psum",
                     "shapes": [tuple(v.shape) for v in vals]}
    if mode == "ring":
        if len(raxes) != 1:
            raise MXNetError(
                "grad_collective='ring' needs exactly one batch axis "
                f"with size > 1, got {raxes}")
        ax = raxes[0]
        flat = jnp.concatenate(
            [v.astype(jnp.float32).reshape(-1) for v in vals]) \
            if len(vals) > 1 else vals[0].astype(jnp.float32).reshape(-1)
        red = ring_allreduce_flat(flat, ax, mesh_shape[ax])
        out, off = [], 0
        for v in vals:
            out.append(red[off:off + v.size].reshape(v.shape)
                       .astype(v.dtype))
            off += v.size
        return out, {"op": "ring",
                     "shapes": [tuple(v.shape) for v in vals]}
    summed = lax.psum(tuple(vals), raxes)
    return list(summed), {"op": "psum",
                          "shapes": [tuple(v.shape) for v in vals]}


# --------------------------------------------------------------------- #
# the pipelined forward/backward core (runs inside shard_map)
# --------------------------------------------------------------------- #
def _pipelined_grads(trainer, spec, train_full, frozen_vals, key, batch,
                     scale, raxes, train_specs_by_idx, remat_plan):
    """Per-shard forward + per-segment backward with bucket collectives
    issued between pullbacks. ``train_full`` maps param index → FULL
    (gathered) value. Returns (loss_val, local grads by train_idx order,
    ledger)."""
    params = trainer._params
    train_idx = trainer._train_idx
    train_set = set(train_idx)
    mesh_shape = dict(trainer.mesh.shape)
    from ..gluon.block import _hybrid_trace_scope
    from ..models._remat import resolve_policy

    stem_own, block_own, head_own, tied = spec.segment_params(
        params, train_idx)

    member_info = [(i, int(params[i]._data._data.size),
                    int(params[i]._data._data.dtype.itemsize),
                    str(params[i]._data._data.dtype)) for i in train_idx]
    plan = plan_grad_buckets(member_info, _bucket_limit_bytes())
    sched = BucketSchedule(plan)

    int8 = bool(trainer._int8_allreduce)
    mode = trainer._grad_collective
    full_grads, local_grads, ledger = {}, {}, []

    tied_head_grads = {}

    def _issue(buckets):
        for b in buckets:
            vals = [full_grads[i] for i in b.indices]
            # tied params carry a second (head/decoder) cotangent: it
            # rides the same bucket collective as an extra operand and
            # is summed AFTER the reduction — GSPMD reduces the two
            # transpose partials independently before adding them, so
            # reducing their pre-added sum would break bitwise parity
            extra_idx = [i for i in b.indices if i in tied_head_grads]
            extras = [tied_head_grads[i] for i in extra_idx]
            red, entry = _reduce_bucket(vals + extras, raxes, mode, int8,
                                        mesh_shape)
            entry["key"] = b.key
            entry["indices"] = list(b.indices) + extra_idx
            ledger.append(entry)
            by_tied = dict(zip(extra_idx, red[len(vals):]))
            for i, g in zip(b.indices, red[:len(vals)]):
                if i in by_tied:
                    g = g + by_tied[i]
                sp = train_specs_by_idx[i]
                local_grads[i] = _slice_local(g, sp, mesh_shape) \
                    if _is_sharded(sp, mesh_shape) else g

    def _bind(idx_list, vals):
        for i, v in zip(idx_list, vals):
            params[i]._data = NDArray(v)

    saved = [p._data for p in params]
    frozen_idx = [i for i in range(len(params)) if i not in train_set]
    try:
        _bind(frozen_idx, frozen_vals)
        _bind(train_idx, [train_full[i] for i in train_idx])
        with _hybrid_trace_scope(), _random.key_provider(key), \
                autograd._ModeScope(recording=False, training=True):
            batch_nds = [NDArray(b) for b in batch]
            ctx = tuple(spec.context(*batch_nds)) if spec.context \
                else ()
            ctx_vals = tuple(c._data for c in ctx)

            def stem_fn(vals):
                _bind(stem_own, vals)
                x0 = spec.stem(*batch_nds) if spec.stem else batch_nds[0]
                return x0._data

            x, pull_stem = jax.vjp(
                stem_fn, tuple(train_full[i] for i in stem_own))

            pulls = []
            for bi, (mods, fn) in enumerate(spec.block_entries):
                own = block_own[bi]

                entry = remat_plan[bi] if remat_plan else False
                if entry:
                    # remat'd blocks take their RNG base key as an
                    # explicit input (the remat_call contract): provider
                    # state mutated inside the checkpoint trace would
                    # leak inner tracers, and an input key replays
                    # identically in the recompute pass
                    def block_fn_k(vals, xv, bkey, _own=own, _fn=fn):
                        _bind(_own, vals)
                        with _random.key_provider(bkey):
                            return _fn(NDArray(xv),
                                       *[NDArray(c) for c in ctx_vals]
                                       )._data

                    ck = jax.checkpoint(block_fn_k,
                                        policy=resolve_policy(entry))
                    x, pull3 = jax.vjp(
                        ck, tuple(train_full[i] for i in own), x,
                        _random.new_key())
                    pull = (lambda g, _p=pull3: _p(g)[:2])
                else:
                    def block_fn(vals, xv, _own=own, _fn=fn):
                        _bind(_own, vals)
                        return _fn(NDArray(xv),
                                   *[NDArray(c) for c in ctx_vals])._data

                    x, pull = jax.vjp(
                        block_fn, tuple(train_full[i] for i in own), x)
                pulls.append(pull)

            def head_fn(vals, tvals, xv):
                _bind(head_own, vals)
                _bind(tied, tvals)
                parts = spec.head(NDArray(xv), *batch_nds)
                return tuple(p._data if isinstance(p, NDArray) else p
                             for p in parts)

            partials, pull_head = jax.vjp(
                head_fn, tuple(train_full[i] for i in head_own),
                tuple(train_full[i] for i in tied), x)
            for p in partials:
                if getattr(p, "ndim", 0) != 0:
                    raise MXNetError(
                        f"PipelineSpec.head must return scalar local "
                        f"partial sums; got shape {p.shape}")
            # frozen params must come back untouched: the pipelined
            # body returns them as-is, so a mutating forward (BN
            # running stats) would silently drop its update — fail loud
            for i in frozen_idx:
                if params[i]._data._data is not (
                        frozen_vals[frozen_idx.index(i)]):
                    raise MXNetError(
                        f"pipelined step does not support parameter-"
                        f"mutating forwards (param {params[i].name} was "
                        f"reassigned, e.g. BatchNorm running stats); "
                        f"use the GSPMD step for this model")
    finally:
        for p, s in zip(params, saved):
            p._data = s

    # --- loss: psum the local partials, finalize on the globals ------- #
    g_partials = lax.psum(partials, raxes) if raxes else partials

    def fin(*gs):
        L = spec.finalize(*gs)
        L = L._data if isinstance(L, NDArray) else L
        return L * scale  # loss scaling rides the backward seed

    loss_scaled, pull_fin = jax.vjp(fin, *g_partials)
    seeds = pull_fin(jnp.float32(1.0))
    loss_val = loss_scaled / scale

    # --- backward, deepest segment first, collectives interleaved ----- #
    g_head, g_tied_head, g_x = pull_head(seeds)
    for j, i in enumerate(tied):
        tied_head_grads[i] = g_tied_head[j]
    for i, g in zip(head_own, g_head):
        full_grads[i] = g
        _issue(sched.mark_ready(i))
    for bi in range(len(spec.block_entries) - 1, -1, -1):
        g_bvals, g_x = pulls[bi](g_x)
        for i, g in zip(block_own[bi], g_bvals):
            full_grads[i] = g
            _issue(sched.mark_ready(i))
    (g_stem,) = pull_stem(g_x)
    for i, g in zip(stem_own, g_stem):
        full_grads[i] = g
        _issue(sched.mark_ready(i))
    _issue(sched.drain())
    if len(sched.issued) != len(plan):  # pragma: no cover - invariant
        raise MXNetError("pipelined bucket schedule did not drain")

    grads = tuple(local_grads[i] for i in train_idx)
    return loss_val, grads, ledger


# --------------------------------------------------------------------- #
# step builders (mirror spmd._build_step / _build_accum_step)
# --------------------------------------------------------------------- #
def _pipeline_prereqs(trainer):
    mesh = trainer.mesh
    for ax in ("tp", "sp", "pp", "ep"):
        if mesh.shape.get(ax, 1) > 1:
            raise MXNetError(
                f"pipelined step supports dp/fsdp meshes only; axis "
                f"{ax!r} has size {mesh.shape[ax]} — use the GSPMD "
                f"step for tensor/sequence/pipeline-parallel models")
    from ..optimizer.fused import norm_based
    if trainer.sharding_mode == "fsdp" and norm_based(trainer._optimizer):
        raise MXNetError(
            f"pipelined fsdp step cannot run norm-based optimizer "
            f"{type(trainer._optimizer).__name__}: the fused update "
            f"would see shard-local norms")
    raxes = tuple(a for a in ("fsdp", "dp") if mesh.shape[a] > 1)
    return raxes


def _specs(trainer, n_batch):
    repl, batch_sh, train_sh, frozen_sh, state_sh = \
        trainer._step_shardings()
    return {
        "repl": repl, "batch_sh": batch_sh,
        "train": tuple(s.spec for s in train_sh),
        "frozen": tuple(s.spec for s in frozen_sh),
        "state": tuple(s.spec for s in state_sh),
        "batch": PartitionSpec(("fsdp", "dp")),
        "train_sh": train_sh, "frozen_sh": frozen_sh,
        "state_sh": tuple(state_sh),
        "n_batch": n_batch,
    }


def build_pipelined_step(trainer, n_batch):
    """The pipelined analogue of ``SPMDTrainer._build_step`` — same call
    signature, same outputs, same donation — so the host-side ``step``
    path runs unchanged."""
    raxes = _pipeline_prereqs(trainer)
    spec = trainer._pipeline
    params = trainer._params
    train_idx = trainer._train_idx
    optimizer = trainer._optimizer
    guard = trainer.guard
    mesh = trainer.mesh
    base_rescale = float(optimizer.rescale_grad)
    sp = _specs(trainer, n_batch)
    mesh_shape = dict(mesh.shape)
    train_specs_by_idx = {i: s for i, s in zip(train_idx, sp["train"])}
    remat_plan = trainer._remat_plan

    def pstep(train_vals, frozen_vals, opt_leaves, opt_tree, t, lr,
              scale, key, *batch):
        if not trainer._pipe_lowering:  # python body = trace time only
            trainer.step_trace_count += 1
            trainer.pipelined_step_trace_count += 1

        def body(train_vals, frozen_vals, opt_leaves, t, lr, scale,
                 key, *batch):
            full = {}
            for i, v in zip(train_idx, train_vals):
                s = train_specs_by_idx[i]
                full[i] = _gather_full(v, s, mesh_shape) \
                    if _is_sharded(s, mesh_shape) else v
            loss_val, grads, ledger = _pipelined_grads(
                trainer, spec, full, frozen_vals, key, batch, scale,
                raxes, train_specs_by_idx, remat_plan)
            if not trainer._pipe_lowering:
                trainer.pipelined_issue_ledger = ledger
                trainer.pipelined_bucket_order = [e["key"]
                                                 for e in ledger]
            opt_state = jtu.tree_unflatten(opt_tree, opt_leaves)
            from ..optimizer.fused import all_finite, apply_updates
            new_train, new_states = apply_updates(
                optimizer, train_idx, train_vals, grads, opt_state, t,
                lr, rescale_grad=jnp.float32(base_rescale) / scale)
            new_train = tuple(new_train)
            new_leaves = tuple(jtu.tree_leaves(tuple(new_states)))
            if guard:
                # guard verdict on the POST-collective grads (for int8:
                # the dequantized values), per-shard then pmin-combined
                # so fsdp shards agree — the PR-8 where-select skip
                ok_flag = all_finite(grads)
                if raxes:
                    ok_flag = lax.pmin(ok_flag, raxes)
                apply_p = ok_flag > 0
                new_train = tuple(jnp.where(apply_p, nw, w)
                                  for nw, w in zip(new_train,
                                                   train_vals))
                new_leaves = tuple(jnp.where(apply_p, nl, ol)
                                   for nl, ol in zip(new_leaves,
                                                     opt_leaves))
            else:
                ok_flag = jnp.float32(1.0)
            return (new_train, tuple(frozen_vals), new_leaves,
                    loss_val, ok_flag)

        mapped = shard_map(
            body, mesh=mesh,
            in_specs=(sp["train"], sp["frozen"], sp["state"],
                      PartitionSpec(), PartitionSpec(), PartitionSpec(),
                      PartitionSpec()) + (sp["batch"],) * n_batch,
            out_specs=(sp["train"], sp["frozen"], sp["state"],
                       PartitionSpec(), PartitionSpec()),
            check_rep=False)
        return mapped(train_vals, frozen_vals, opt_leaves, t, lr,
                      scale, key, *batch)

    donate = (0, 2) if trainer.donate else ()
    repl = sp["repl"]
    return jax.jit(
        pstep, static_argnums=(3,),
        in_shardings=(sp["train_sh"], sp["frozen_sh"], sp["state_sh"],
                      repl, repl, repl, repl)
        + (sp["batch_sh"],) * n_batch,
        out_shardings=(sp["train_sh"], sp["frozen_sh"], sp["state_sh"],
                       repl, repl),
        donate_argnums=donate)


def build_pipelined_accum_step(trainer, n_batch):
    """Pipelined analogue of ``_build_accum_step`` — the same donated
    f32 accumulator carry, combined verdict and is_last-gated apply, so
    ``step_microbatches`` host code runs unchanged and k stays pure
    host data (one trace for k ∈ {1,4,8,...})."""
    raxes = _pipeline_prereqs(trainer)
    spec = trainer._pipeline
    train_idx = trainer._train_idx
    optimizer = trainer._optimizer
    guard = trainer.guard
    mesh = trainer.mesh
    base_rescale = float(optimizer.rescale_grad)
    sp = _specs(trainer, n_batch)
    mesh_shape = dict(mesh.shape)
    train_specs_by_idx = {i: s for i, s in zip(train_idx, sp["train"])}
    remat_plan = trainer._remat_plan

    def pastep(train_vals, frozen_vals, opt_leaves, opt_tree, acc_vals,
               acc_ok, acc_loss, t, lr, scale, inv_k, is_last, key,
               *batch):
        if not trainer._pipe_lowering:
            trainer.accum_step_trace_count += 1
            trainer.pipelined_accum_step_trace_count += 1

        def body(train_vals, frozen_vals, opt_leaves, acc_vals, acc_ok,
                 acc_loss, t, lr, scale, inv_k, is_last, key, *batch):
            full = {}
            for i, v in zip(train_idx, train_vals):
                s = train_specs_by_idx[i]
                full[i] = _gather_full(v, s, mesh_shape) \
                    if _is_sharded(s, mesh_shape) else v
            loss_val, grads, ledger = _pipelined_grads(
                trainer, spec, full, frozen_vals, key, batch, scale,
                raxes, train_specs_by_idx, remat_plan)
            if not trainer._pipe_lowering:
                trainer.pipelined_issue_ledger = ledger
                trainer.pipelined_bucket_order = [e["key"]
                                                 for e in ledger]
            new_acc = tuple(a + g.astype(jnp.float32)
                            for a, g in zip(acc_vals, grads))
            from ..optimizer.fused import all_finite, apply_updates
            if guard:
                ok_here = all_finite(grads)
                if raxes:
                    ok_here = lax.pmin(ok_here, raxes)
                ok_round = acc_ok * ok_here
            else:
                ok_round = jnp.float32(1.0)
            loss_round = acc_loss + loss_val
            opt_state = jtu.tree_unflatten(opt_tree, opt_leaves)
            apply_grads = tuple(a * inv_k for a in new_acc)
            new_train, new_states = apply_updates(
                optimizer, train_idx, train_vals, apply_grads,
                opt_state, t, lr,
                rescale_grad=jnp.float32(base_rescale) / scale)
            new_leaves = tuple(jtu.tree_leaves(tuple(new_states)))
            last_p = is_last > 0
            apply_p = jnp.logical_and(last_p, ok_round > 0)
            new_train = tuple(jnp.where(apply_p, nw, w)
                              for nw, w in zip(new_train, train_vals))
            new_leaves = tuple(jnp.where(apply_p, nl, ol)
                               for nl, ol in zip(new_leaves,
                                                 opt_leaves))
            acc_out = tuple(jnp.where(last_p, jnp.zeros_like(na), na)
                            for na in new_acc)
            acc_ok_out = jnp.where(last_p, jnp.float32(1.0), ok_round)
            acc_loss_out = jnp.where(last_p, jnp.float32(0.0),
                                     loss_round)
            return (new_train, tuple(frozen_vals), new_leaves, acc_out,
                    acc_ok_out, acc_loss_out, loss_round * inv_k,
                    ok_round)

        P = PartitionSpec
        mapped = shard_map(
            body, mesh=mesh,
            in_specs=(sp["train"], sp["frozen"], sp["state"],
                      sp["train"], P(), P(), P(), P(), P(), P(), P(),
                      P()) + (sp["batch"],) * n_batch,
            out_specs=(sp["train"], sp["frozen"], sp["state"],
                       sp["train"], P(), P(), P(), P()),
            check_rep=False)
        return mapped(train_vals, frozen_vals, opt_leaves, acc_vals,
                      acc_ok, acc_loss, t, lr, scale, inv_k, is_last,
                      key, *batch)

    donate = (0, 2, 4) if trainer.donate else ()
    repl = sp["repl"]
    return jax.jit(
        pastep, static_argnums=(3,),
        in_shardings=(sp["train_sh"], sp["frozen_sh"], sp["state_sh"],
                      sp["train_sh"], repl, repl, repl, repl, repl,
                      repl, repl, repl) + (sp["batch_sh"],) * n_batch,
        out_shardings=(sp["train_sh"], sp["frozen_sh"], sp["state_sh"],
                       sp["train_sh"], repl, repl, repl, repl),
        donate_argnums=donate)


# --------------------------------------------------------------------- #
# structural overlap assertion (CPU-runnable, lowered-text based)
# --------------------------------------------------------------------- #
def _collect_ops(text, op_names):
    """Walk StableHLO text and return ``[(line_no, op, result_shapes)]``
    in program order. Region-holding ops (all_reduce) print their type
    signature on the closing line; scan forward to the first ``->``."""
    import re
    shape_re = re.compile(r"tensor<([^>]*)>")
    lines = text.splitlines()
    out = []
    for n, line in enumerate(lines):
        hit = next((op for op in op_names
                    if "stablehlo." + op in line), None)
        if hit is None:
            continue
        if "->" not in line and (") ->" not in line):
            sig = ""
            for m in range(n, min(n + 200, len(lines))):
                if "->" in lines[m]:
                    sig = lines[m].split("->", 1)[1]
                    break
        else:
            sig = line.split("->", 1)[1] if "->" in line else line
        shapes = []
        for s in shape_re.findall(sig):
            dims = [d for d in s.split("x")[:-1]]
            try:
                shapes.append(tuple(int(d) for d in dims))
            except ValueError:
                shapes.append(tuple(dims))
        out.append((n, hit, shapes))
    return out


def structure_report(text, ledger):
    """Assertable structure facts from a pipelined step's lowered
    StableHLO against the trace-time issue ledger.

    Returns a dict with:
      - ``n_grad_collective_groups`` vs ``n_buckets`` — every bucket's
        collective made it into the program, as a distinct group;
      - ``order_matches_plan`` — the program-order shapes of the grad
        collectives equal the ledger's bucket-member shapes in plan
        order (the deterministic-rendezvous contract, now asserted on
        the *compiled* program);
      - ``interleaved`` — at least one backward ``dot_general`` sits
        strictly between the first and last grad collective, i.e. the
        collectives are interleaved with backward, not clustered after
        it (the PR-13 `overlap_ratio: 0.0` failure shape).
    Scalar all-reduces (loss partials, guard pmin, int8 amax pmax) are
    excluded by the rank filter."""
    ring = any(e.get("op") == "ring" for e in ledger)
    coll_op = "collective_permute" if ring else "all_reduce"
    ops = _collect_ops(text, [coll_op, "dot_general"])
    colls = [(n, shapes) for n, op, shapes in ops
             if op == coll_op and any(len(s) > 0 for s in shapes)]
    dots = [n for n, op, _ in ops if op == "dot_general"]

    # group consecutive collective ops (one bucket's members emit one
    # variadic op or several adjacent ops, no dot_general in between)
    groups = []
    for n, shapes in colls:
        if groups and not any(groups[-1][-1][0] < d < n for d in dots):
            groups[-1].append((n, shapes))
        else:
            groups.append([(n, shapes)])

    expected = [[tuple(s) for s in e.get("shapes", [])]
                for e in ledger if e.get("op") != "none"]
    # adjacent buckets issued from the same pullback print as one
    # textual group, so the order contract is on the FLAT program-order
    # shape sequence (bucket boundaries are the plan's, not the text's)
    exp_flat = [s for b in expected for s in b]
    got_flat = [s for g in groups for _, shapes in g for s in shapes]
    if ring:
        order_ok = len(got_flat) >= len(expected) > 0
    else:
        order_ok = got_flat == exp_flat
    interleaved = False
    if groups:
        first_end = groups[0][-1][0]
        last_start = groups[-1][0][0]
        interleaved = any(first_end < d < last_start for d in dots)
    return {
        "collective_op": coll_op,
        "n_buckets": len(expected),
        "n_grad_collective_groups": len(groups),
        "order_matches_plan": bool(order_ok),
        "interleaved": bool(interleaved),
        "n_backward_dots_between": sum(
            1 for d in dots
            if groups and groups[0][-1][0] < d < groups[-1][0][0]),
    }
