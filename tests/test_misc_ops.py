"""Tests for the round-3 misc operator batch (numpy oracle +
check_numeric_gradient idiom, reference test_operator.py strategy)."""

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd
from incubator_mxnet_tpu.test_utils import check_numeric_gradient


def test_khatri_rao():
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    b = np.arange(9, dtype=np.float32).reshape(3, 3)
    got = nd.khatri_rao(nd.array(a), nd.array(b)).asnumpy()
    want = np.vstack([np.kron(a[:, j], b[:, j]) for j in range(3)]).T
    np.testing.assert_allclose(got, want)


def test_cumsum_cumprod_digamma():
    x = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    np.testing.assert_allclose(nd.cumsum(nd.array(x), axis=1).asnumpy(),
                               np.cumsum(x, 1))
    np.testing.assert_allclose(nd.cumprod(nd.array(x), axis=0).asnumpy(),
                               np.cumprod(x, 0))
    # digamma vs known values: psi(1) = -euler_gamma, psi(2) = 1 - gamma
    d = nd.digamma(nd.array([1.0, 2.0])).asnumpy()
    np.testing.assert_allclose(d[0], -0.5772157, rtol=1e-4)
    np.testing.assert_allclose(d[1], 1 - 0.5772157, rtol=1e-4)


def test_ravel_unravel_roundtrip():
    shape = (3, 4, 5)
    flat = np.array([0, 17, 59, 23], np.int32)
    coords = nd.unravel_index(nd.array(flat), shape=shape).asnumpy()
    want = np.stack(np.unravel_index(flat, shape))
    np.testing.assert_array_equal(coords, want)
    back = nd.ravel_multi_index(nd.array(coords), shape=shape).asnumpy()
    np.testing.assert_array_equal(back, flat)


def test_choose_fill_element_0index():
    lhs = np.arange(12, dtype=np.float32).reshape(3, 4)
    rhs = np.array([1, 3, 0], np.float32)
    got = nd.choose_element_0index(nd.array(lhs), nd.array(rhs)).asnumpy()
    np.testing.assert_allclose(got, [1.0, 7.0, 8.0])
    mhs = np.array([-1.0, -2.0, -3.0], np.float32)
    filled = nd.fill_element_0index(nd.array(lhs), nd.array(mhs),
                                    nd.array(rhs)).asnumpy()
    assert filled[0, 1] == -1 and filled[1, 3] == -2 and filled[2, 0] == -3
    assert filled[0, 0] == 0.0


def test_moments():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 4).astype(np.float32)
    mean, var = nd.moments(nd.array(x), axes=(0, 2))
    np.testing.assert_allclose(mean.asnumpy(), x.mean(axis=(0, 2)),
                               rtol=1e-5)
    np.testing.assert_allclose(var.asnumpy(), x.var(axis=(0, 2)),
                               rtol=1e-4, atol=1e-5)


def test_correlation_matches_naive():
    rng = np.random.RandomState(1)
    B, C, H, W = 1, 2, 6, 6
    d1 = rng.randn(B, C, H, W).astype(np.float32)
    d2 = rng.randn(B, C, H, W).astype(np.float32)
    got = nd.Correlation(nd.array(d1), nd.array(d2), kernel_size=1,
                         max_displacement=1, stride1=1, stride2=1,
                         pad_size=0).asnumpy()
    disps = [-1, 0, 1]
    centers = range(1, H - 1)
    want = np.zeros((B, 9, H - 2, W - 2), np.float32)
    for di, dy in enumerate(disps):
        for dj, dx in enumerate(disps):
            for yi, y in enumerate(centers):
                for xi, x in enumerate(centers):
                    want[:, di * 3 + dj, yi, xi] = (
                        d1[:, :, y, x] * d2[:, :, y + dy, x + dx]
                    ).mean(axis=1)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_crop():
    x = np.arange(32, dtype=np.float32).reshape(1, 2, 4, 4)
    ref = np.zeros((1, 2, 2, 2), np.float32)
    out = nd.Crop(nd.array(x), nd.array(ref), center_crop=True).asnumpy()
    np.testing.assert_allclose(out, x[:, :, 1:3, 1:3])
    out2 = nd.Crop(nd.array(x), h_w=(2, 3), offset=(1, 0)).asnumpy()
    np.testing.assert_allclose(out2, x[:, :, 1:3, 0:3])


def test_output_heads_gradients():
    rng = np.random.RandomState(2)
    d = nd.array(rng.randn(4, 3).astype(np.float32))
    lab = nd.array(np.array([0, 2, 1, 0], np.float32))
    # logistic: forward sigmoid, grad (p - l)/B
    x = nd.array(rng.randn(4, 1).astype(np.float32))
    lab2 = nd.array((rng.rand(4, 1) > 0.5).astype(np.float32))
    x.attach_grad()
    with autograd.record():
        out = nd.LogisticRegressionOutput(x, lab2)
    out.backward()
    p = 1 / (1 + np.exp(-x.asnumpy()))
    # reference scaling: grad_scale / num_output (=1 here), NOT /batch
    np.testing.assert_allclose(x.grad.asnumpy(),
                               (p - lab2.asnumpy()), rtol=1e-5)
    # SVM: no violation → zero grad
    big = nd.array(np.array([[10.0, -10.0], [-10.0, 10.0]], np.float32))
    labs = nd.array(np.array([0, 1], np.float32))
    big.attach_grad()
    with autograd.record():
        o = nd.SVMOutput(big, labs, margin=1.0)
    o.backward()
    np.testing.assert_allclose(big.grad.asnumpy(), 0.0)
    # MAE: sign gradient
    m = nd.array(np.array([[2.0], [-3.0]], np.float32))
    lm = nd.array(np.zeros((2, 1), np.float32))
    m.attach_grad()
    with autograd.record():
        om = nd.MAERegressionOutput(m, lm)
    om.backward()
    np.testing.assert_allclose(m.grad.asnumpy(), [[1.0], [-1.0]])


def test_amp_multicast_and_all_finite():
    a = nd.array(np.ones((2, 2), np.float32)).astype("bfloat16")
    b = nd.array(np.ones((2, 2), np.float32))
    outs = nd.amp_multicast(a, b, num_outputs=2)
    assert str(outs[0].dtype) == "float32" and str(outs[1].dtype) == \
        "float32"
    narrow = nd.amp_multicast(a, b, num_outputs=2, cast_narrow=True)
    assert str(narrow[0].dtype) == "bfloat16"
    ok = nd.all_finite(b).asnumpy()
    assert ok == 1.0
    bad = nd.array(np.array([np.inf, 1.0], np.float32))
    assert nd.all_finite(bad).asnumpy() == 0.0
    assert nd.multi_all_finite(b, bad, num_arrays=2).asnumpy() == 0.0


def test_misc_gradients():
    rng = np.random.RandomState(3)
    x = rng.randn(3, 4).astype(np.float32)
    check_numeric_gradient(lambda d: nd.cumsum(d, axis=1), [nd.array(x)])
    check_numeric_gradient(
        lambda d: nd.khatri_rao(d, nd.array(np.ones((2, 4), np.float32))),
        [nd.array(x)])


def test_new_optimizer_ops_and_ftml_class():
    """Round-3 optimizer op batch: mp/multi variants + FTML end to end."""
    from incubator_mxnet_tpu import autograd, gluon

    w = nd.array(np.ones(4, np.float32))
    g = nd.array(np.full(4, 0.5, np.float32))
    w32 = nd.array(np.ones(4, np.float32))
    out_b, out_32 = nd.mp_sgd_update(w.astype("bfloat16"),
                                     g.astype("bfloat16"), w32, lr=0.1)
    assert str(out_b.dtype) == "bfloat16"
    np.testing.assert_allclose(out_32.asnumpy(), 0.95)
    nw, nh = nd.adagrad_update(w, g, nd.zeros((4,)), lr=0.1)
    np.testing.assert_allclose(nh.asnumpy(), 0.25)
    ws = [nd.array(np.ones(3, np.float32)),
          nd.array(np.ones(2, np.float32))]
    gs = [nd.array(np.ones(3, np.float32)),
          nd.array(np.ones(2, np.float32))]
    outs = nd.multi_sgd_update(ws, gs, lrs=[0.1, 0.2], wds=[0.0, 0.0])
    np.testing.assert_allclose(outs[0].asnumpy(), 0.9)
    np.testing.assert_allclose(outs[1].asnumpy(), 0.8)

    # FTML trains
    mx.random.seed(0)
    net = gluon.nn.Dense(3, in_units=4)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "ftml",
                       {"learning_rate": 0.02}, kvstore=None)
    rng = np.random.RandomState(0)
    X = rng.randn(16, 4).astype(np.float32)
    y = rng.randint(0, 3, (16,))
    lf = gluon.loss.SoftmaxCrossEntropyLoss()
    losses = []
    for _ in range(15):
        with autograd.record():
            L = lf(net(nd.array(X)), nd.array(y)).mean()
        L.backward()
        tr.step(1)
        losses.append(float(L.asnumpy()))
    assert losses[-1] < losses[0]


def test_multi_sum_sq_multi_lars_and_lars_optimizer():
    from incubator_mxnet_tpu import gluon
    rng = np.random.RandomState(1)
    w = rng.randn(4, 5).astype(np.float32)
    g = rng.randn(4, 5).astype(np.float32)
    sums = nd.multi_sum_sq([nd.array(w), nd.array(g)]).asnumpy()
    np.testing.assert_allclose(sums, [np.sum(w * w), np.sum(g * g)],
                               rtol=1e-5)

    # multi_lars trust-ratio oracle
    lrs = np.array([0.1], np.float32)
    wds = np.array([1e-4], np.float32)
    out = nd.multi_lars(nd.array(lrs), nd.array(sums[0:1]),
                        nd.array(sums[1:2]), nd.array(wds),
                        eta=0.001, eps=1e-8).asnumpy()
    wn, gn = np.sqrt(sums[0]), np.sqrt(sums[1])
    want = lrs * (0.001 * wn / (gn + wds * wn + 1e-8))
    np.testing.assert_allclose(out, want, rtol=1e-5)

    # top-level cast_storage parity alias
    rs = nd.cast_storage(nd.array(np.eye(3, dtype=np.float32)),
                         "row_sparse")
    assert rs.stype == "row_sparse"

    # LARS optimizer trains a small net
    mx.random.seed(0)
    net = gluon.nn.Dense(3, in_units=4)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "lars",
                       {"learning_rate": 0.05, "momentum": 0.9},
                       kvstore=None)
    X = rng.randn(16, 4).astype(np.float32)
    y = rng.randint(0, 3, (16,))
    lf = gluon.loss.SoftmaxCrossEntropyLoss()
    losses = []
    for _ in range(15):
        with autograd.record():
            L = lf(net(nd.array(X)), nd.array(y)).mean()
        L.backward()
        tr.step(1)
        losses.append(float(L.asnumpy()))
    assert losses[-1] < losses[0]


def test_interleaved_matmul_attention_parity():
    """interleaved_matmul_* vs an explicit-einsum numpy oracle."""
    rng = np.random.RandomState(2)
    S, B, H, D = 6, 2, 2, 4
    qkv = rng.randn(S, B, H * 3 * D).astype(np.float32)
    att_qk = nd.contrib.interleaved_matmul_selfatt_qk(
        nd.array(qkv), heads=H).asnumpy()
    x = qkv.reshape(S, B, H, 3, D)
    q = x[..., 0, :].transpose(1, 2, 0, 3).reshape(B * H, S, D)
    k = x[..., 1, :].transpose(1, 2, 0, 3).reshape(B * H, S, D)
    v = x[..., 2, :].transpose(1, 2, 0, 3).reshape(B * H, S, D)
    want = np.einsum("bqd,bkd->bqk", q / np.sqrt(D), k)
    np.testing.assert_allclose(att_qk, want, rtol=1e-5, atol=1e-5)

    att = np.exp(att_qk) / np.exp(att_qk).sum(-1, keepdims=True)
    out = nd.contrib.interleaved_matmul_selfatt_valatt(
        nd.array(qkv), nd.array(att), heads=H).asnumpy()
    want_o = np.einsum("bqk,bkd->bqd", att, v)
    want_o = want_o.reshape(B, H, S, D).transpose(2, 0, 1, 3).reshape(
        S, B, H * D)
    np.testing.assert_allclose(out, want_o, rtol=1e-5, atol=1e-5)

    # encdec pair
    Sq, Sk = 5, 7
    qs = rng.randn(Sq, B, H * D).astype(np.float32)
    kv = rng.randn(Sk, B, H * 2 * D).astype(np.float32)
    qk = nd.contrib.interleaved_matmul_encdec_qk(
        nd.array(qs), nd.array(kv), heads=H).asnumpy()
    qm = qs.reshape(Sq, B, H, D).transpose(1, 2, 0, 3).reshape(B * H, Sq, D)
    kvx = kv.reshape(Sk, B, H, 2, D)
    km = kvx[..., 0, :].transpose(1, 2, 0, 3).reshape(B * H, Sk, D)
    vm = kvx[..., 1, :].transpose(1, 2, 0, 3).reshape(B * H, Sk, D)
    np.testing.assert_allclose(
        qk, np.einsum("bqd,bkd->bqk", qm / np.sqrt(D), km),
        rtol=1e-5, atol=1e-5)
    att2 = np.exp(qk) / np.exp(qk).sum(-1, keepdims=True)
    out2 = nd.contrib.interleaved_matmul_encdec_valatt(
        nd.array(kv), nd.array(att2), heads=H).asnumpy()
    want2 = np.einsum("bqk,bkd->bqd", att2, vm).reshape(
        B, H, Sq, D).transpose(2, 0, 1, 3).reshape(Sq, B, H * D)
    np.testing.assert_allclose(out2, want2, rtol=1e-5, atol=1e-5)

    # div_sqrt_dim
    np.testing.assert_allclose(
        nd.contrib.div_sqrt_dim(nd.array(qs)).asnumpy(), qs / np.sqrt(D * H),
        rtol=1e-6)


def test_box_encode_decode_roundtrip():
    rng = np.random.RandomState(3)
    B, N = 2, 5
    anchors = np.sort(rng.rand(B, N, 4).astype(np.float32), axis=-1)
    deltas = (rng.randn(B, N, 4) * 0.1).astype(np.float32)
    dec = nd.contrib.box_decode(nd.array(deltas), nd.array(anchors)).asnumpy()
    # encode the decoded boxes back against the same anchors: identity
    samples = np.ones((B, N), np.float32)
    matches = np.tile(np.arange(N), (B, 1)).astype(np.float32)
    enc, mask = nd.contrib.box_encode(
        nd.array(samples), nd.array(matches), nd.array(anchors),
        nd.array(dec))
    np.testing.assert_allclose(enc.asnumpy(), deltas, rtol=1e-3, atol=1e-4)
    assert mask.asnumpy().min() == 1.0


def test_bipartite_matching():
    score = np.array([[[0.9, 0.1], [0.8, 0.7], [0.2, 0.6]]], np.float32)
    row, col = nd.contrib.bipartite_matching(nd.array(score), threshold=0.0)
    # greedy: (0,0)=0.9 first, then (1,1)=0.7; row2 unmatched
    np.testing.assert_array_equal(row.asnumpy()[0], [0, 1, -1])
    np.testing.assert_array_equal(col.asnumpy()[0], [0, 1])


def test_gradientmultiplier_and_group_adagrad():
    x = nd.array(np.array([1.0, 2.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = (nd.contrib.gradientmultiplier(x, scalar=-0.5) * 3.0).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [-1.5, -1.5])

    w = np.ones((3, 4), np.float32)
    g = np.full((3, 4), 2.0, np.float32)
    h = np.zeros((3,), np.float32)
    nw, nh = nd.contrib.group_adagrad_update(
        nd.array(w), nd.array(g), nd.array(h), lr=0.1)
    np.testing.assert_allclose(nh.asnumpy(), np.full(3, 4.0))
    np.testing.assert_allclose(
        nw.asnumpy(), w - 0.1 * g / (np.sqrt(4.0) + 1e-5), rtol=1e-6)


def test_adaptive_avg_pooling_general_size():
    rng = np.random.RandomState(4)
    x = rng.rand(1, 2, 5, 7).astype(np.float32)
    out = nd.contrib.AdaptiveAvgPooling2D(nd.array(x),
                                          output_size=(2, 3)).asnumpy()
    # exact per-bin oracle
    want = np.zeros((1, 2, 2, 3), np.float32)
    for i in range(2):
        for j in range(3):
            hs, he = (i * 5) // 2, ((i + 1) * 5 + 1) // 2
            ws, we = (j * 7) // 3, ((j + 1) * 7 + 2) // 3
            want[:, :, i, j] = x[:, :, hs:he, ws:we].mean(axis=(2, 3))
    np.testing.assert_allclose(out, want, rtol=1e-5)


def test_bipartite_matching_ascend_threshold():
    # ascending (distance) mode: matches with score > threshold rejected
    score = np.array([[[0.9]]], np.float32)
    row, _ = nd.contrib.bipartite_matching(nd.array(score), is_ascend=True,
                                           threshold=0.5)
    np.testing.assert_array_equal(row.asnumpy()[0], [-1])
    row2, _ = nd.contrib.bipartite_matching(nd.array(score), is_ascend=True,
                                            threshold=0.95)
    np.testing.assert_array_equal(row2.asnumpy()[0], [0])


def test_logsumexp_value_and_gradient():
    rng = np.random.RandomState(5)
    x = (rng.randn(4, 7) * 5).astype(np.float32)
    lse = nd.logsumexp(nd.array(x), axis=-1).asnumpy()
    m = x.max(-1, keepdims=True)
    want = np.log(np.exp(x - m).sum(-1)) + m[:, 0]
    np.testing.assert_allclose(lse, want, rtol=1e-5)

    # d lse / d x = softmax(x)
    xn = nd.array(x)
    xn.attach_grad()
    with autograd.record():
        out = nd.logsumexp(xn, axis=-1).sum()
    out.backward()
    sm = np.exp(x - m) / np.exp(x - m).sum(-1, keepdims=True)
    np.testing.assert_allclose(xn.grad.asnumpy(), sm, rtol=1e-4, atol=1e-5)

    # bf16 input: f32 accumulation keeps the value accurate
    xb = nd.array(x).astype("bfloat16")
    lse_b = nd.logsumexp(xb, axis=-1).asnumpy()
    np.testing.assert_allclose(lse_b, want, rtol=2e-2)


def test_sldwin_attention_ops():
    rng = np.random.RandomState(0)
    B, L, H, D, w = 2, 8, 2, 4, 2
    q = rng.randn(B, L, H * D).astype(np.float32)
    k = rng.randn(B, L, H * D).astype(np.float32)
    v = rng.randn(B, L, H * D).astype(np.float32)
    s = nd.contrib.sldwin_atten_score(nd.array(q), nd.array(k), 1,
                                      num_heads=H, w=w, symmetric=True)
    qh = q.reshape(B, L, H, D).transpose(0, 2, 1, 3)
    kh = k.reshape(B, L, H, D).transpose(0, 2, 1, 3)
    full = np.einsum("bhqd,bhkd->bhqk", qh, kh).reshape(B * H, L, L)
    band = np.abs(np.arange(L)[:, None] - np.arange(L)[None, :]) <= w
    np.testing.assert_allclose(s.asnumpy(), full * band, rtol=1e-5)

    # asymmetric (causal-window) band keeps only j <= i
    s_asym = nd.contrib.sldwin_atten_score(nd.array(q), nd.array(k), 1,
                                           num_heads=H, w=w,
                                           symmetric=False).asnumpy()
    band_a = ((np.arange(L)[None, :] - np.arange(L)[:, None]) <= 0) & \
        ((np.arange(L)[None, :] - np.arange(L)[:, None]) >= -w)
    np.testing.assert_allclose(s_asym, full * band_a, rtol=1e-5)

    m = nd.contrib.sldwin_atten_mask_like(
        s, 1, nd.array([L, 5]), num_heads=H, w=w, symmetric=True).asnumpy()
    assert m[2][:, 5:].sum() == 0 and m[0].sum() == band.sum()

    ctx = nd.contrib.sldwin_atten_context(s, nd.array(v), 1, num_heads=H,
                                          w=w, symmetric=True)
    vh = v.reshape(B, L, H, D).transpose(0, 2, 1, 3)
    want = np.einsum("bhqk,bhkd->bhqd",
                     (full * band).reshape(B, H, L, L), vh)
    want = want.transpose(0, 2, 1, 3).reshape(B, L, H * D)
    np.testing.assert_allclose(ctx.asnumpy(), want, rtol=1e-4, atol=1e-5)

    # dilation=2: only even offsets within the window survive
    s_d = nd.contrib.sldwin_atten_score(nd.array(q), nd.array(k), 2,
                                        num_heads=H, w=1,
                                        symmetric=True).asnumpy()
    dmat = np.arange(L)[None, :] - np.arange(L)[:, None]
    band_d = (np.abs(dmat) <= 2) & (dmat % 2 == 0)
    np.testing.assert_allclose(s_d, full * band_d, rtol=1e-5)


def test_sldwin_backward_with_tensor_dilation():
    """dilation as an NDArray (the reference contract) must survive the
    autograd re-trace — regression for the int(tracer) crash."""
    rng = np.random.RandomState(1)
    B, L, H, D, w = 1, 6, 1, 3, 1
    q = nd.array(rng.randn(B, L, H * D).astype(np.float32))
    k = nd.array(rng.randn(B, L, H * D).astype(np.float32))
    v = nd.array(rng.randn(B, L, H * D).astype(np.float32))
    dil = nd.array(np.array([1], np.int32))
    q.attach_grad()
    with autograd.record():
        s = nd.contrib.sldwin_atten_score(q, k, dil, num_heads=H, w=w)
        ctx = nd.contrib.sldwin_atten_context(s, v, dil, num_heads=H, w=w)
        out = ctx.sum()
    out.backward()
    assert float(np.abs(q.grad.asnumpy()).sum()) > 0


def test_random_distribution_additions():
    mx.random.seed(0)
    a = nd.random.laplace(0.0, 2.0, shape=(20000,)).asnumpy()
    assert abs(np.median(a)) < 0.1 and 2.5 < a.std() < 3.2  # std=sqrt(2)*b
    assert nd.random.randn(3, 4).shape == (3, 4)
    nb = nd.random.negative_binomial(k=5, p=0.5, shape=(20000,)).asnumpy()
    assert 4.6 < nb.mean() < 5.4          # mean = k(1-p)/p
    g = nd.random.generalized_negative_binomial(
        mu=3.0, alpha=0.2, shape=(20000,)).asnumpy()
    assert 2.7 < g.mean() < 3.3 and 4.0 < g.var() < 5.8  # var=mu+a*mu^2


def test_histogram_and_float_tests():
    x = np.array([0.1, 0.4, 0.6, 0.9, 0.2], np.float32)
    h, e = nd.histogram(nd.array(x), bins=2, range=(0.0, 1.0))
    hn, en = np.histogram(x, bins=2, range=(0, 1))
    np.testing.assert_array_equal(h.asnumpy(), hn)
    np.testing.assert_allclose(e.asnumpy(), en)
    # auto-range path
    h2, _ = nd.histogram(nd.array(x), bins=4)
    assert int(h2.asnumpy().sum()) == 5
    y = nd.array(np.array([1.0, np.nan, np.inf], np.float32))
    np.testing.assert_array_equal(nd.contrib.isnan(y).asnumpy(), [0, 1, 0])
    np.testing.assert_array_equal(nd.contrib.isinf(y).asnumpy(), [0, 0, 1])
    np.testing.assert_array_equal(nd.contrib.isfinite(y).asnumpy(),
                                  [1, 0, 0])


def test_histogram_inverted_range_and_mask_dtype():
    with pytest.raises(mx.MXNetError):
        nd.histogram(nd.array(np.ones(3, np.float32)), bins=2,
                     range=(2.0, 0.0))
    y = nd.array(np.array([1.0, np.nan], np.float32))
    m = nd.contrib.isnan(y)
    assert str(m.dtype) in ("float32", "<dtype: 'float32'>"), m.dtype
    np.testing.assert_allclose((1.0 - m).asnumpy(), [1.0, 0.0])


def test_tril_triu_meshgrid():
    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    np.testing.assert_array_equal(nd.tril(nd.array(a)).asnumpy(),
                                  np.tril(a))
    np.testing.assert_array_equal(nd.triu(nd.array(a), k=-1).asnumpy(),
                                  np.triu(a, k=-1))
    xs, ys = nd.meshgrid(nd.array([1.0, 2.0]), nd.array([3.0, 4.0, 5.0]))
    ex, ey = np.meshgrid([1.0, 2.0], [3.0, 4.0, 5.0])
    np.testing.assert_array_equal(xs.asnumpy(), ex)
    np.testing.assert_array_equal(ys.asnumpy(), ey)


def test_quantize_v1_explicit_range_and_gesvd():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 5).astype(np.float32)
    # reference default: uint8 AFFINE over [min, max]
    q, lo, hi = nd.contrib.quantize(nd.array(x), nd.array([-3.0]),
                                    nd.array([3.0]))
    assert q.dtype == "uint8"
    scale = 6.0 / 255
    zero = np.round(3.0 / scale)
    np.testing.assert_allclose((q.asnumpy().astype(np.float32) - zero)
                               * scale, x, atol=scale)
    # int8 symmetric form
    q8, _, _ = nd.contrib.quantize(nd.array(x), nd.array([-3.0]),
                                   nd.array([3.0]), out_type="int8")
    assert q8.dtype == "int8"
    np.testing.assert_allclose(q8.asnumpy() * (3.0 / 127), x,
                               atol=3.0 / 127)

    A = rng.randn(3, 5).astype(np.float32)
    U, L, V = nd.linalg_gesvd(nd.array(A))
    rec = (U.asnumpy() * L.asnumpy()[None, :]) @ V.asnumpy()
    np.testing.assert_allclose(rec, A, rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_sample_family_per_element_params():
    """sample_* ops draw one batch of `shape` per LEADING element of the
    parameter arrays (reference multisample_op.cc convention)."""
    mx.random.seed(0)
    al = nd.array(np.array([1.0, 20.0], np.float32))
    be = nd.array(np.array([2.0, 0.5], np.float32))
    g = nd.sample_gamma(al, be, shape=(8000,)).asnumpy()
    assert g.shape == (2, 8000)
    assert 1.6 < g[0].mean() < 2.4          # mean = alpha*beta = 2
    assert 9.0 < g[1].mean() < 11.0         # 20*0.5 = 10

    lam = nd.array(np.array([0.5, 4.0], np.float32))
    e = nd.sample_exponential(lam, shape=(8000,)).asnumpy()
    assert 1.8 < e[0].mean() < 2.2 and 0.22 < e[1].mean() < 0.28

    p = nd.sample_poisson(lam, shape=(8000,)).asnumpy()
    assert 0.4 < p[0].mean() < 0.6 and 3.8 < p[1].mean() < 4.2

    k = nd.array(np.array([5.0], np.float32))
    pr = nd.array(np.array([0.5], np.float32))
    num = nd.sample_negative_binomial(k, pr, shape=(8000,)).asnumpy()
    assert 4.5 < num.mean() < 5.5           # mean = k(1-p)/p = 5

    mu = nd.array(np.array([3.0], np.float32))
    alpha = nd.array(np.array([0.2], np.float32))
    gn = nd.sample_generalized_negative_binomial(
        mu, alpha, shape=(8000,)).asnumpy()
    assert 2.7 < gn.mean() < 3.3 and 3.9 < gn.var() < 6.0


def test_preloaded_multi_sgd_family():
    """lrs/wds as device arrays must match the attr-based multi_* ops."""
    rng = np.random.RandomState(0)
    ws = [nd.array(rng.randn(3, 2).astype(np.float32)) for _ in range(2)]
    gs = [nd.array(rng.randn(3, 2).astype(np.float32)) for _ in range(2)]
    lrs, wds = [0.1, 0.02], [0.01, 0.0]
    want = nd.multi_sgd_update(ws, gs, lrs=lrs, wds=wds)
    got = nd.preloaded_multi_sgd_update(
        ws, gs, nd.array(np.array(lrs, np.float32)),
        nd.array(np.array(wds, np.float32)))
    for w_, g_ in zip(want, got):
        np.testing.assert_allclose(g_.asnumpy(), w_.asnumpy(), rtol=1e-6)

    ms = [nd.zeros((3, 2)) for _ in range(2)]
    got_mom = nd.preloaded_multi_sgd_mom_update(
        ws, gs, ms, nd.array(np.array(lrs, np.float32)),
        nd.array(np.array(wds, np.float32)), momentum=0.9)
    assert len(got_mom) == 4                # (w, mom) per tensor
    w32 = [nd.array(w.asnumpy().astype(np.float32)) for w in ws]
    got_mp = nd.preloaded_multi_mp_sgd_update(
        ws, gs, w32, nd.array(np.array(lrs, np.float32)),
        nd.array(np.array(wds, np.float32)))
    np.testing.assert_allclose(got_mp[0].asnumpy(), want[0].asnumpy(),
                               rtol=1e-6)
