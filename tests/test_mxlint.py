"""mxlint (tools/mxlint): the AST invariant analyzer.

Per pass: at least one TRUE-POSITIVE fixture (a distilled version of a
bug class this repo actually shipped — the PR-9 double-finish race,
retrace storms, page leaks, hidden host syncs, stat-counter races) and
one CLEAN fixture the pass must stay silent on. Plus waiver and
baseline round-trips, and the lintcore CI contract: the real tree is
clean, and injecting any single fixture bug (one per pass) makes the
gate exit non-zero.

Everything here is pure-AST host work — no jax arrays are built, so
the whole module stays well inside the tier-1 budget.
"""

import json
import os
import textwrap

import pytest

from tools.mxlint import analyze_project, build_project
from tools.mxlint.cli import main as mxlint_main
from tools.mxlint.core import load_baseline, save_baseline
from tools.mxlint.passes import default_passes
from tools.mxlint.passes.host_sync import HostSyncPass

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------------------------- #
# harness
# --------------------------------------------------------------------- #

def _tree(tmp_path, files):
    """Materialize {relpath: source} under tmp_path; returns root."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(tmp_path)


def _findings(tmp_path, files, rule=None, passes=None, baseline=None):
    root = _tree(tmp_path, files)
    project = build_project(sorted(files), root)
    out = analyze_project(project, passes or default_passes(),
                          baseline or {})
    if rule is not None:
        out = [f for f in out if f.rule == rule]
    return out


def _active(findings):
    return [f for f in findings
            if f.status == "active" and f.severity == "error"]


# --------------------------------------------------------------------- #
# pass 1: trace-host-leak
# --------------------------------------------------------------------- #

BAD_TRACED = {
    "incubator_mxnet_tpu/ops/badtrace.py": """
        import time
        import numpy as np
        import jax


        def traced(x, y):
            t = time.time()
            f = float(x)
            r = np.random.rand()
            m = np.asarray(y)
            return x * t + f + r + m.sum()


        fast = jax.jit(traced)
    """,
}

CLEAN_TRACED = {
    "incubator_mxnet_tpu/ops/goodtrace.py": """
        import time
        import numpy as np
        import jax
        import jax.numpy as jnp


        def traced(x, key):
            noise = jax.random.normal(key, x.shape)
            return jnp.tanh(x) + noise


        fast = jax.jit(traced)


        def host_helper(v):
            # NOT reachable from any jit site: host casts are fine here
            return float(v) + time.time() + np.random.rand()
    """,
}


def test_trace_pass_flags_host_leaks(tmp_path):
    active = _active(_findings(tmp_path, BAD_TRACED,
                               rule="trace-host-leak"))
    msgs = "\n".join(f.message for f in active)
    assert len(active) >= 4
    assert "host clock" in msgs
    assert "float()" in msgs
    assert "host RNG" in msgs
    assert "np.asarray" in msgs


def test_trace_pass_clean_fixture(tmp_path):
    assert _active(_findings(tmp_path, CLEAN_TRACED,
                             rule="trace-host-leak")) == []


def test_trace_pass_follows_call_graph(tmp_path):
    files = {
        "incubator_mxnet_tpu/ops/chained.py": """
            import jax


            def helper(v):
                return int(v) + 1


            def traced(x):
                return helper(x)


            fast = jax.jit(traced)
        """,
    }
    active = _active(_findings(tmp_path, files, rule="trace-host-leak"))
    assert len(active) == 1 and active[0].symbol == "helper"


def test_trace_pass_decorated_and_method_roots(tmp_path):
    files = {
        "incubator_mxnet_tpu/ops/decorated.py": """
            import functools
            import time
            import jax


            @functools.partial(jax.jit, static_argnames=("k",))
            def decorated(x, k):
                return x * time.monotonic()


            class Engine:
                def __init__(self):
                    self._step = jax.jit(self._step_fn)

                def _step_fn(self, x):
                    return bool(x)
        """,
    }
    active = _active(_findings(tmp_path, files, rule="trace-host-leak"))
    symbols = {f.symbol for f in active}
    assert "decorated" in symbols
    assert "Engine._step_fn" in symbols


# --------------------------------------------------------------------- #
# pass 2: terminal-outcome (the PR-9 double-finish race, distilled)
# --------------------------------------------------------------------- #

BAD_OUTCOME = {
    "incubator_mxnet_tpu/serve/badoutcome.py": """
        class Scheduler:
            def _record_terminal(self, request, outcome):
                request.outcome = outcome
                self.health[outcome.value] += 1

            def evict_expired(self, request, outcome):
                # the double-finish race: a second writer that does not
                # go through the recorder
                request.outcome = outcome

            def fixup_counts(self, outcome):
                self.health[outcome.value] += 1
    """,
}

CLEAN_OUTCOME = {
    "incubator_mxnet_tpu/serve/goodoutcome.py": """
        class Scheduler:
            def __init__(self):
                self.health = {}

            def _record_terminal(self, request, outcome):
                request.outcome = outcome
                self.health[outcome.value] += 1

            def reset_for_requeue(self, request):
                request.outcome = None      # reset, not a terminal

            def evict(self, request, outcome):
                self._record_terminal(request, outcome)
    """,
}


def test_outcome_pass_flags_second_writer(tmp_path):
    active = _active(_findings(tmp_path, BAD_OUTCOME,
                               rule="terminal-outcome"))
    assert {f.symbol for f in active} == \
        {"Scheduler.evict_expired", "Scheduler.fixup_counts"}


def test_outcome_pass_clean_fixture(tmp_path):
    assert _active(_findings(tmp_path, CLEAN_OUTCOME,
                             rule="terminal-outcome")) == []


def test_outcome_pass_scoped_to_serve_and_train(tmp_path):
    files = {"incubator_mxnet_tpu/gluon/other.py": """
        class T:
            def set(self, r, o):
                r.outcome = o
    """}
    assert _active(_findings(tmp_path, files,
                             rule="terminal-outcome")) == []


BAD_EVENT_BUFFER = {
    "incubator_mxnet_tpu/serve/badevents.py": """
        class Engine:
            def sneak_event(self, ev):
                # bypasses FlightRecorder.emit: no seq, no histogram
                # ingestion, no capacity bound — the round-17 event
                # discipline violation, distilled
                self.flight._rings["engine"].append(ev)

            def peek(self):
                return list(self.flight._rings.values())
    """,
}

CLEAN_EVENT_BUFFER = {
    "incubator_mxnet_tpu/serve/goodevents.py": """
        from collections import deque


        class FlightRecorder:
            def __init__(self):
                self._rings = {}

            def emit(self, component, ev):
                ring = self._rings.setdefault(component, deque())
                ring.append(ev)

            def events(self):
                return [e for r in self._rings.values() for e in r]


        class Engine:
            def record(self, ev):
                self.flight.emit("engine", ev)   # the one API
    """,
}


def test_outcome_pass_flags_event_buffer_bypass(tmp_path):
    active = _active(_findings(tmp_path, BAD_EVENT_BUFFER,
                               rule="terminal-outcome"))
    assert {f.symbol for f in active} == \
        {"Engine.sneak_event", "Engine.peek"}
    assert all("FlightRecorder API" in f.message for f in active)


def test_outcome_pass_event_buffer_clean_inside_recorder(tmp_path):
    assert _active(_findings(tmp_path, CLEAN_EVENT_BUFFER,
                             rule="terminal-outcome")) == []


def test_outcome_pass_event_buffer_covers_whole_package(tmp_path):
    """The ring-discipline sub-rule is scoped to the whole package —
    checkpoint/manager.py holds a recorder too, so a bypass there must
    be caught even though the outcome/health checks stay scoped to
    serve/+train/."""
    files = {"incubator_mxnet_tpu/checkpoint/badckpt.py": """
        class Manager:
            def sneak(self, ev):
                self.flight._rings["checkpoint"].append(ev)
    """}
    active = _active(_findings(tmp_path, files,
                               rule="terminal-outcome"))
    assert {f.symbol for f in active} == {"Manager.sneak"}


# --------------------------------------------------------------------- #
# pass 3: page-refcount
# --------------------------------------------------------------------- #

BAD_PAGES = {
    "incubator_mxnet_tpu/serve/badpages.py": """
        class LeakyIndex:
            def retain(self, pages):
                for p in pages:
                    self._alloc.incref(p)

            def grab_one(self):
                return self._alloc.alloc()
    """,
}

CLEAN_PAGES = {
    "incubator_mxnet_tpu/serve/goodpages.py": """
        class PairedIndex:
            def retain(self, pages):
                for p in pages:
                    self._alloc.incref(p)

            def drop(self, pages):
                for p in pages:
                    self._alloc.decref(p)
    """,
}


def test_page_pass_flags_unpaired_acquire(tmp_path):
    active = _active(_findings(tmp_path, BAD_PAGES,
                               rule="page-refcount"))
    assert len(active) == 2
    assert all("silent pool leak" in f.message for f in active)


def test_page_pass_clean_fixture(tmp_path):
    assert _active(_findings(tmp_path, CLEAN_PAGES,
                             rule="page-refcount")) == []


BAD_TIER = {
    "incubator_mxnet_tpu/serve/badtier.py": """
        class Sidecar:
            def peek(self, store):
                return len(store._entries)

            def shrink(self, store):
                store._dram_used -= 4096


        class KVTierStore:
            def promote(self, key, ent):
                # a demoted page has no refcount: the store must not
                # hand out (or free) HBM pages itself
                page = self._alloc.alloc()
                self._alloc.free(page)
                return page
    """,
}

CLEAN_TIER = {
    "incubator_mxnet_tpu/serve/goodtier.py": """
        class KVTierStore:
            def __init__(self):
                self._entries = {}
                self._dram_used = 0

            def entries(self):
                for key, bucket in self._entries.items():
                    for ent in bucket:
                        yield key, ent


        class Sidecar:
            def peek(self, store):
                return sum(1 for _ in store.entries())
    """,
}


def test_page_pass_tier_internals_and_alloc_in_store(tmp_path):
    active = _active(_findings(tmp_path, BAD_TIER,
                               rule="page-refcount"))
    msgs = "\n".join(f.message for f in active)
    assert msgs.count("outside KVTierStore") == 2
    assert msgs.count("inside KVTierStore") == 2
    # the unpaired-alloc check must NOT double-fire here: alloc and
    # free are paired inside the class scope
    assert "silent pool leak" not in msgs


def test_page_pass_tier_clean_fixture(tmp_path):
    assert _active(_findings(tmp_path, CLEAN_TIER,
                             rule="page-refcount")) == []


BAD_TRANSPORT = {
    "incubator_mxnet_tpu/serve/badtransport.py": """
        class ChainForger:
            def splice(self, capsule, payload):
                # forges a record past verify(): the wire chain the
                # destination trusts no longer covers this payload
                capsule._records.append(payload)
                capsule._chain_crc = 0

        class Sidecar:
            def steal_pages(self, engine, rid):
                # moves custody pages around the engine's
                # detach/release seam: audit_pages can no longer
                # prove free XOR live XOR demoted XOR in-capsule
                pages = engine._capsule_pages.pop(rid)
                return pages
    """,
}

CLEAN_TRANSPORT = {
    "incubator_mxnet_tpu/serve/goodtransport.py": """
        class PageCapsule:
            def __init__(self):
                self._records = []
                self._chain_crc = 0

            def payloads(self):
                return list(self._records)

        class PageTransport:
            def nbytes(self, capsule):
                return sum(len(r) for r in capsule._records)

        class Sidecar:
            def ship(self, capsule, engine, rid):
                payloads = capsule.payloads()   # the one read API
                engine.release_capsule(rid)     # the one custody API
                return payloads
    """,
}


def test_page_pass_transport_internals(tmp_path):
    active = _active(_findings(tmp_path, BAD_TRANSPORT,
                               rule="page-refcount"))
    msgs = "\n".join(f.message for f in active)
    assert msgs.count("outside PageCapsule/PageTransport") == 2
    assert msgs.count("outside InferenceEngine") == 1


def test_page_pass_transport_clean_fixture(tmp_path):
    assert _active(_findings(tmp_path, CLEAN_TRANSPORT,
                             rule="page-refcount")) == []


def test_page_pass_null_page_and_rc_internals(tmp_path):
    files = {"incubator_mxnet_tpu/serve/nullpage.py": """
        NULL_PAGE = 0


        class Evil:
            def release(self):
                self._alloc.decref(0)
                self._alloc.free(NULL_PAGE)

            def poke(self):
                self._rc[3] += 1
    """}
    active = _active(_findings(tmp_path, files, rule="page-refcount"))
    msgs = "\n".join(f.message for f in active)
    assert msgs.count("null page") == 2
    assert "outside PageAllocator" in msgs


# --------------------------------------------------------------------- #
# pass 4: host-sync
# --------------------------------------------------------------------- #

BAD_HOTLOOP = {
    "incubator_mxnet_tpu/serve/hotloop.py": """
        import jax
        import numpy as np


        class MiniEngine:
            def __init__(self):
                self._decode = jax.jit(lambda x: x + 1)

            def step(self):
                out = self._decode(self.state)
                tok = int(np.asarray(out))       # designed sync
                extra = out.item()               # hidden second sync
                if out > 0:                      # hidden implicit bool
                    tok += 1
                return tok + extra
    """,
}

_HOT = {"incubator_mxnet_tpu/serve/hotloop.py": {"step"}}


def _hot_passes():
    return [HostSyncPass(hot_seeds=_HOT)]


def test_host_sync_flags_hidden_syncs(tmp_path):
    active = _active(_findings(tmp_path, BAD_HOTLOOP, rule="host-sync",
                               passes=_hot_passes()))
    msgs = "\n".join(f.message for f in active)
    assert "np.asarray" in msgs
    assert ".item()" in msgs
    assert "implicit `bool()`" in msgs


def test_host_sync_untaints_after_cast_and_ignores_is_none(tmp_path):
    files = {"incubator_mxnet_tpu/serve/hotloop.py": """
        import jax
        import numpy as np


        class MiniEngine:
            def __init__(self):
                self._decode = jax.jit(lambda x: x + 1)

            def step(self):
                out = self._decode(self.state)
                if out is None:                # identity: NOT a sync
                    return 0
                # mxlint: allow-host-sync(the one designed readback)
                out = np.asarray(out)
                if out > 0:                    # host np array now: free
                    return 1
                return int(out)                # host int now: free
    """}
    findings = _findings(tmp_path, files, rule="host-sync",
                         passes=_hot_passes())
    assert _active(findings) == []
    assert [f.status for f in findings] == ["waived"]


def test_host_sync_taints_through_jit_dicts_and_returns(tmp_path):
    files = {"incubator_mxnet_tpu/serve/hotloop.py": """
        import jax
        import numpy as np


        class MiniEngine:
            def __init__(self):
                self._jits = {}

            def _get_fn(self, sig):
                fn = self._jits.get(sig)
                if fn is None:
                    fn = jax.jit(lambda x: x)
                    self._jits[sig] = fn
                return fn(sig)

            def step(self):
                flag = self._get_fn(8)
                return bool(np.asarray(flag) > 0)
    """}
    active = _active(_findings(tmp_path, files, rule="host-sync",
                               passes=_hot_passes()))
    assert len(active) == 1
    assert "np.asarray" in active[0].message


# --------------------------------------------------------------------- #
# pass 5: lock-discipline
# --------------------------------------------------------------------- #

BAD_LOCKS = {
    "incubator_mxnet_tpu/checkpoint/badlocks.py": """
        import threading


        class RacyWriter:
            def __init__(self):
                self._lock = threading.Lock()
                self.commits = 0
                self._thread = threading.Thread(target=self._loop)

            def _loop(self):
                while True:
                    self.commits += 1     # writer thread, no lock

            def reset(self):
                self.commits = 0          # main path, no lock
    """,
}

CLEAN_LOCKS = {
    "incubator_mxnet_tpu/checkpoint/goodlocks.py": """
        import threading


        class GuardedWriter:
            def __init__(self):
                self._lock = threading.Lock()
                self.commits = 0
                self._thread = threading.Thread(target=self._loop)

            def _loop(self):
                while True:
                    with self._lock:
                        self.commits += 1

            def reset(self):
                with self._lock:
                    self.commits = 0
    """,
}


def test_lock_pass_flags_unguarded_shared_writes(tmp_path):
    active = _active(_findings(tmp_path, BAD_LOCKS,
                               rule="lock-discipline"))
    assert {f.symbol for f in active} == \
        {"RacyWriter._loop", "RacyWriter.reset"}


def test_lock_pass_clean_fixture(tmp_path):
    assert _active(_findings(tmp_path, CLEAN_LOCKS,
                             rule="lock-discipline")) == []


def test_lock_pass_flags_lockless_thread_class(tmp_path):
    files = {"incubator_mxnet_tpu/io/lockless.py": """
        import threading


        class NoLock:
            def __init__(self):
                self._thread = threading.Thread(target=self._loop)

            def _loop(self):
                self.n = 1
    """}
    active = _active(_findings(tmp_path, files, rule="lock-discipline"))
    assert len(active) == 1
    assert "designates no lock" in active[0].message


# --------------------------------------------------------------------- #
# waivers
# --------------------------------------------------------------------- #

def test_waiver_suppresses_and_records_reason(tmp_path):
    files = {"incubator_mxnet_tpu/serve/waived.py": """
        class Scheduler:
            def evict(self, request, outcome):
                # mxlint: allow-terminal-outcome(distilled fixture, not a real writer)
                request.outcome = outcome
    """}
    findings = _findings(tmp_path, files, rule="terminal-outcome")
    assert len(findings) == 1
    assert findings[0].status == "waived"
    assert "distilled fixture" in findings[0].reason


def test_scope_level_waiver_on_def_line(tmp_path):
    files = {"incubator_mxnet_tpu/serve/scoped.py": """
        class Scheduler:
            # mxlint: allow-terminal-outcome(whole-method waiver: legacy shim)
            def evict(self, request, outcome):
                request.outcome = outcome
    """}
    findings = _findings(tmp_path, files, rule="terminal-outcome")
    assert [f.status for f in findings] == ["waived"]


def test_waiver_without_reason_is_a_finding(tmp_path):
    files = {"incubator_mxnet_tpu/serve/noreason.py": """
        X = 1  # mxlint: allow-terminal-outcome()
    """}
    findings = _findings(tmp_path, files, rule="waiver-syntax")
    assert len(findings) == 1
    assert "no reason" in findings[0].message


def test_waiver_unknown_rule_is_a_finding(tmp_path):
    files = {"incubator_mxnet_tpu/serve/unknown.py": """
        X = 1  # mxlint: allow-made-up-rule(sounds legit)
    """}
    findings = _findings(tmp_path, files, rule="waiver-syntax")
    assert len(findings) == 1
    assert "unknown rule" in findings[0].message


def test_first_body_line_waiver_is_not_scope_wide(tmp_path):
    """Review regression: a LINE waiver on (or above) a function's
    first statement must not silently become a whole-function waiver —
    the later unwaived violation stays active (fail-closed)."""
    files = {"incubator_mxnet_tpu/serve/firstline.py": """
        class Scheduler:
            def evict(self, request, other):
                # mxlint: allow-terminal-outcome(this one write only)
                request.outcome = 1
                other.outcome = 2
    """}
    findings = _findings(tmp_path, files, rule="terminal-outcome")
    assert sorted(f.status for f in findings) == ["active", "waived"]
    active = _active(findings)[0]
    assert "other" in tmp_path.joinpath(
        "incubator_mxnet_tpu/serve/firstline.py").read_text() \
        .splitlines()[active.line - 1]


def test_host_sync_item_on_host_value_not_flagged(tmp_path):
    """Review regression: `.item()` on a pure-host numpy value is not
    a device sync and must not demand a waiver."""
    files = {"incubator_mxnet_tpu/serve/hotloop.py": """
        import numpy as np


        class MiniEngine:
            def step(self):
                host = np.zeros(3)
                return host.max().item()
    """}
    assert _active(_findings(tmp_path, files, rule="host-sync",
                             passes=_hot_passes())) == []


def test_aliased_baseline_group_carries_attribution_note(tmp_path):
    """Review regression: when identical findings split between
    baselined and active, the active one's report admits the line
    attribution is order-based instead of silently pointing at an
    arbitrary line."""
    first = _findings(tmp_path, BAD_OUTCOME, rule="terminal-outcome")
    dup = [f for f in first if f.symbol == "Scheduler.evict_expired"]
    baseline = {dup[0].key: "acknowledged debt"}
    src = textwrap.dedent(
        BAD_OUTCOME["incubator_mxnet_tpu/serve/badoutcome.py"])
    marker = "recorder\n        request.outcome = outcome"
    assert marker in src
    doubled = {
        "incubator_mxnet_tpu/serve/badoutcome.py": src.replace(
            marker, marker + "\n        request.outcome = outcome")}
    findings = [
        f for f in _findings(tmp_path / "d", doubled,
                             rule="terminal-outcome", baseline=baseline)
        if f.symbol == "Scheduler.evict_expired"]
    assert sorted(f.status for f in findings) == ["active", "baselined"]
    active = [f for f in findings if f.status == "active"][0]
    assert "re-triage the whole group" in active.note
    assert "re-triage" in active.render()


def test_docstring_mention_is_not_a_waiver(tmp_path):
    files = {"incubator_mxnet_tpu/serve/docmention.py": '''
        """Docs may say # mxlint: allow-terminal-outcome(reason) freely."""
        X = 1
    '''}
    assert _findings(tmp_path, files, rule="waiver-syntax") == []


# --------------------------------------------------------------------- #
# baseline
# --------------------------------------------------------------------- #

def test_baseline_roundtrip(tmp_path):
    findings = _findings(tmp_path, BAD_OUTCOME, rule="terminal-outcome")
    keys = {f.key: "pre-existing: tracked as debt" for f in findings}
    bl_path = str(tmp_path / "bl.json")
    save_baseline(bl_path, keys)
    loaded = load_baseline(bl_path)
    assert loaded == keys

    again = _findings(tmp_path, BAD_OUTCOME, rule="terminal-outcome",
                      baseline=loaded)
    assert _active(again) == []
    assert all(f.status == "baselined" and "debt" in f.reason
               for f in again)


def test_baseline_key_survives_line_shift(tmp_path):
    first = _findings(tmp_path, BAD_OUTCOME, rule="terminal-outcome")
    shifted = {
        "incubator_mxnet_tpu/serve/badoutcome.py":
            "# a new comment line at the top\n# another\n" +
            textwrap.dedent(
                BAD_OUTCOME["incubator_mxnet_tpu/serve/badoutcome.py"])}
    second = _findings(tmp_path / "b", shifted, rule="terminal-outcome")
    assert {f.key for f in first} == {f.key for f in second}
    assert [f.line for f in first] != [f.line for f in second]


def test_cli_update_baseline_then_clean(tmp_path):
    root = _tree(tmp_path, BAD_OUTCOME)
    bl = "bl.json"
    rc = mxlint_main(["--root", root, "--baseline", bl,
                      "incubator_mxnet_tpu"])
    assert rc == 1
    rc = mxlint_main(["--root", root, "--baseline", bl,
                      "--update-baseline", "incubator_mxnet_tpu"])
    assert rc == 0
    data = json.loads((tmp_path / bl).read_text())
    assert data["entries"] and all(e["reason"] for e in data["entries"])
    rc = mxlint_main(["--root", root, "--baseline", bl,
                      "incubator_mxnet_tpu"])
    assert rc == 0


# --------------------------------------------------------------------- #
# the lintcore CI contract
# --------------------------------------------------------------------- #

def test_lintcore_real_tree_is_clean():
    """`ci/run.sh lintcore` equivalence: the checked-in tree plus the
    checked-in baseline must have zero unbaselined findings."""
    rc = mxlint_main(["--root", REPO_ROOT,
                      "--baseline", "ci/mxlint_baseline.json"])
    assert rc == 0


_INJECTIONS = {
    # one representative bug per pass, injected as a fresh file at a
    # path inside the pass's scope (host-sync: a step() on the real
    # hot-module path so the default HOT_SEEDS pick it up)
    "trace-host-leak": (
        "incubator_mxnet_tpu/ops/injected_trace.py",
        BAD_TRACED["incubator_mxnet_tpu/ops/badtrace.py"]),
    "terminal-outcome": (
        "incubator_mxnet_tpu/serve/injected_outcome.py",
        BAD_OUTCOME["incubator_mxnet_tpu/serve/badoutcome.py"]),
    # second terminal-outcome injection: the round-17 event-buffer
    # rule ("#" suffix = parametrize id only; the rule is the prefix)
    "terminal-outcome#events": (
        "incubator_mxnet_tpu/serve/injected_events.py",
        BAD_EVENT_BUFFER["incubator_mxnet_tpu/serve/badevents.py"]),
    "page-refcount": (
        "incubator_mxnet_tpu/serve/injected_pages.py",
        BAD_PAGES["incubator_mxnet_tpu/serve/badpages.py"]),
    # second page-refcount injection: the round-19 tier rules (a
    # sidecar poking demoted-page bookkeeping + a tier store that
    # allocs/frees HBM pages)
    "page-refcount#tiers": (
        "incubator_mxnet_tpu/serve/injected_tier.py",
        BAD_TIER["incubator_mxnet_tpu/serve/badtier.py"]),
    # third page-refcount injection: the round-20 transport rules (a
    # crc-chain forger + a sidecar moving in-capsule custody pages
    # around detach_slot/release_capsule)
    "page-refcount#transport": (
        "incubator_mxnet_tpu/serve/injected_transport.py",
        BAD_TRANSPORT["incubator_mxnet_tpu/serve/badtransport.py"]),
    "host-sync": (
        "incubator_mxnet_tpu/serve/router.py",
        """
        import jax
        import numpy as np


        class Router:
            def __init__(self):
                self._probe = jax.jit(lambda x: x)

            def _dispatch(self):
                score = self._probe(3)
                return float(np.asarray(score))
        """),
    "lock-discipline": (
        "incubator_mxnet_tpu/checkpoint/injected_locks.py",
        BAD_LOCKS["incubator_mxnet_tpu/checkpoint/badlocks.py"]),
}


@pytest.mark.parametrize("rule", sorted(_INJECTIONS))
def test_lintcore_fails_on_injected_bug(tmp_path, rule):
    """Injecting any SINGLE fixture bug (one per pass) into an
    otherwise-clean tree must flip the lintcore gate non-zero."""
    rel, src = _INJECTIONS[rule]
    rule = rule.split("#")[0]            # "#suffix" = parametrize id
    root = _tree(tmp_path, {rel: src})
    rc = mxlint_main(["--root", root, "incubator_mxnet_tpu"])
    assert rc == 1, f"{rule}: injected bug not caught"
    # and the finding is attributed to the right rule
    findings = _findings(tmp_path / "chk", {rel: src}, rule=rule)
    assert _active(findings), f"{rule}: no active finding for its rule"


def test_parse_error_is_a_finding(tmp_path):
    files = {"incubator_mxnet_tpu/serve/broken.py": "def oops(:\n"}
    findings = _findings(tmp_path, files, rule="parse-error")
    assert len(findings) == 1
