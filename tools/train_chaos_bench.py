"""Training chaos bench: drive the trainers through seeded fault
scenarios and ASSERT the training resilience contract
(docs/RESILIENCE.md "Training resilience"); bank the guard+scaler
overhead and the supervisor recovery timeline (BENCH_TRAIN_RESIL.json).

Scenarios (each asserts exactly-one-outcome-per-step and the jit-once
contract on top of its own expectations):

  nan_grad_skip    a NaN gradient at step k is SKIPPED with params and
                   optimizer state BIT-IDENTICAL to pre-step, and every
                   unfaulted step's loss bit-identical to a fault-free
                   run's
  overflow_storm   scale-dependent Inf gradients: the dynamic loss
                   scale halves its way under the overflow threshold
                   (one skip per halving), regrows after scale_window
                   clean steps, and NEVER retraces the fused step
  poison_halt      persistent NaN: after K consecutive non-finite
                   steps the trainer halts loudly (HALTED_POISONED),
                   never skip-loops forever
  spmd_skip        the same skip contract inside the ONE-compile SPMD
                   step on a dp2 x fsdp4 mesh (the all-finite reduction
                   is global, so every rank skips the same step)
  kill9_resume     a supervised training run kill -9'd twice mid-run:
                   the supervisor restarts it from the latest committed
                   checkpoint and the final per-step loss sequence is
                   BIT-IDENTICAL to an uninterrupted run's; recovery
                   timeline (steps re-run, restart wall) banked
  hang_watchdog    a training child that wedges mid-run is SIGKILLed by
                   the zero-progress watchdog and the restarted run
                   completes
  io_transient     MXTPU_IO_FAIL_READS blips under the retry bound
                   lose no batch; at the bound the error surfaces
                   loudly (never a hung consumer)

Bench workloads (--json / full mode):

  guard_overhead   guarded+scaled fused step vs unguarded step, strict
                   alternation, per-step time quantiles (the round-10
                   methodology — p50 is primary on a noisy host);
                   <2% is the leave-on bar
  recovery         kill9_resume's timeline: steps re-run, wall-clock
                   from kill to resumed progress

Usage:
  python tools/train_chaos_bench.py --smoke        # CI guard (trainchaos)
  python tools/train_chaos_bench.py --json OUT.json
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

FAILURES = []


def check(cond, msg):
    if cond:
        print(f"    ok: {msg}")
    else:
        FAILURES.append(msg)
        print(f"    FAIL: {msg}")


# --------------------------------------------------------------------- #
# shared workload
# --------------------------------------------------------------------- #

def _net(seed=0, width=16):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.gluon import nn
    mx.random.seed(seed)
    net = nn.Sequential()
    net.add(nn.Dense(width, in_units=8, activation="relu"),
            nn.Dense(4, in_units=width))
    net.initialize()
    return net


def _data(seed=1, n=8):
    import numpy as np
    rng = np.random.RandomState(seed)
    return (rng.randn(n, 8).astype(np.float32),
            rng.randn(n, 4).astype(np.float32))


def _mse(out, label):
    return (out - label) ** 2


def _trainer(net, scaler=None, guard=None, max_nf=None):
    from incubator_mxnet_tpu import gluon
    return gluon.Trainer(net.collect_params(), "adam",
                         {"learning_rate": 0.01}, kvstore=None,
                         loss_scaler=scaler, guard=guard,
                         max_consecutive_nonfinite=max_nf)


def _state(tr):
    import numpy as np
    import jax.tree_util as jtu
    snap = [p.data().asnumpy().copy() for p in tr._params]
    for _, st in sorted(tr._updaters[0].states.items()):
        for leaf in jtu.tree_leaves(
                st, is_leaf=lambda x: hasattr(x, "asnumpy")):
            snap.append(np.asarray(leaf.asnumpy()).copy())
    return snap


# --------------------------------------------------------------------- #
# scenarios
# --------------------------------------------------------------------- #

def scenario_nan_grad_skip(steps=10, fault_at=4):
    from incubator_mxnet_tpu.train import (NaNGrad, StepOutcome,
                                           run_train_chaos)
    print("  [nan_grad_skip]")
    X, y = _data()
    ref_net = _net()
    clean_losses, _ = run_train_chaos(ref_net, _trainer(ref_net), _mse,
                                      (X, y), steps)

    net = _net()
    tr = _trainer(net)
    run_train_chaos(net, tr, _mse, (X, y), fault_at)
    losses, outcomes = run_train_chaos(
        net, tr, _mse, (X, y), steps - fault_at,
        [NaNGrad(at_step=0)])
    check(outcomes[0] is StepOutcome.SKIPPED_NONFINITE,
          "faulted step recorded SKIPPED_NONFINITE")
    check(losses[0] == clean_losses[fault_at],
          "loss at the faulted step computed on pre-fault params")
    check(all(o is StepOutcome.APPLIED for o in outcomes[1:]),
          "all later steps APPLIED")
    check(tr._fused.trace_count == 1 and tr._fused.guard_trace_count == 1,
          "fused step + guard compiled exactly once across the fault")
    check(sum(tr.health.values()) == steps,
          "exactly one outcome per step")
    return {"outcomes": [str(o) for o in outcomes]}


def scenario_nan_grad_state_identity(fault_at=3):
    import numpy as np
    from incubator_mxnet_tpu.train import NaNGrad, run_train_chaos
    print("  [nan_grad_state_identity]")
    X, y = _data()
    net = _net()
    tr = _trainer(net)
    run_train_chaos(net, tr, _mse, (X, y), fault_at)
    before = _state(tr)
    run_train_chaos(net, tr, _mse, (X, y), 1, [NaNGrad(at_step=0)])
    after = _state(tr)
    check(all(np.array_equal(b, a) for b, a in zip(before, after)),
          "skipped step left params + optimizer state bit-identical")
    return {}


def scenario_overflow_storm():
    from incubator_mxnet_tpu.amp.loss_scaler import LossScaler
    from incubator_mxnet_tpu.train import (OverflowStorm, StepOutcome,
                                           run_train_chaos)
    print("  [overflow_storm]")
    X, y = _data()
    net = _net()
    scaler = LossScaler(init_scale=64.0, scale_window=3)
    tr = _trainer(net, scaler=scaler)
    _, outcomes = run_train_chaos(
        net, tr, _mse, (X, y), 8,
        [OverflowStorm(at_step=0, overflow_above=16.0)])
    S, A = StepOutcome.SKIPPED_NONFINITE, StepOutcome.APPLIED
    check(outcomes == [S, S, A, A, A, S, A, A],
          "scale halved to the floor, regrew after scale_window, "
          "re-probed the ceiling")
    check(scaler.loss_scale == 16.0, "scale settled at the ceiling")
    check(tr._fused.trace_count == 1,
          "scale growth/decay never retraced the fused step")
    return {"final_scale": scaler.loss_scale,
            "outcomes": [str(o) for o in outcomes]}


def scenario_poison_halt(k=4):
    from incubator_mxnet_tpu.base import MXNetError
    from incubator_mxnet_tpu.train import NaNGrad, run_train_chaos

    class AlwaysNaN(NaNGrad):
        def on_grads(self, step_idx, trainer):
            self.fired = False
            super().on_grads(step_idx, trainer)

    print("  [poison_halt]")
    X, y = _data()
    net = _net()
    tr = _trainer(net, max_nf=k)
    halted = False
    try:
        run_train_chaos(net, tr, _mse, (X, y), k + 5,
                        [AlwaysNaN(at_step=0)])
    except MXNetError as e:
        halted = True
        check("poisoned" in str(e), "halt diagnostic names the poison")
    check(halted, f"halted after {k} consecutive non-finite steps")
    check(tr.health["HALTED_POISONED"] == 1 and
          tr.health["SKIPPED_NONFINITE"] == k - 1,
          "health: k-1 skips then one HALTED_POISONED")
    check(sum(tr.health.values()) == k,
          "exactly one outcome per attempted step")
    return {"health": dict(tr.health)}


def scenario_spmd_skip():
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon, nd, parallel
    from incubator_mxnet_tpu.parallel import mesh as pmesh
    from incubator_mxnet_tpu.train import StepOutcome
    print("  [spmd_skip]")
    os.environ["MXTPU_FSDP_MIN_SIZE"] = "0"
    net = _net(seed=7)
    mesh = pmesh.build_mesh(axis_sizes={"dp": 2, "fsdp": 4})
    tr = parallel.SPMDTrainer(
        net, loss=gluon.loss.SoftmaxCrossEntropyLoss(),
        optimizer="adam", optimizer_params={"learning_rate": 0.01},
        mesh=mesh, sharding="fsdp")
    rng = np.random.RandomState(2)
    X = rng.randn(16, 8).astype(np.float32)
    y = rng.randint(0, 4, size=(16,))
    for _ in range(2):
        tr.step(nd.array(X), nd.array(y))
    w_before = [p.data().asnumpy().copy() for p in tr._params]
    sc = tr.step_count
    Xbad = X.copy()
    Xbad[0, 0] = float("nan")
    tr.step(nd.array(Xbad), nd.array(y))
    check(tr.last_outcome is StepOutcome.SKIPPED_NONFINITE,
          "NaN batch skipped inside the SPMD step")
    check(tr.step_count == sc, "step counter did not advance on skip")
    same = all(np.array_equal(b, p.data().asnumpy())
               for b, p in zip(w_before, tr._params))
    check(same, "params bit-identical across the skipped step "
                "(global skip on an fsdp-sharded mesh)")
    tr.step(nd.array(X), nd.array(y))
    check(tr.last_outcome is StepOutcome.APPLIED and
          tr.step_trace_count == 1,
          "clean step applied through the SAME compiled program")
    check(sum(tr.health.values()) == 4, "exactly one outcome per step")
    os.environ.pop("MXTPU_FSDP_MIN_SIZE", None)
    return {"health": dict(tr.health)}


# --------------------------------------------------------------------- #
# supervisor scenarios (subprocess)
# --------------------------------------------------------------------- #

def _run_target(workdir, tag, steps, kill_at="", hang_at=None,
                max_restarts=0, hang_timeout_s=None, save_every=2):
    from incubator_mxnet_tpu.train import Supervisor
    ckpt = os.path.join(workdir, f"ckpt_{tag}")
    results = os.path.join(workdir, f"results_{tag}.jsonl")
    os.makedirs(ckpt, exist_ok=True)
    env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "MXTPU_TGT_CKPT_DIR": ckpt,
        "MXTPU_TGT_RESULTS": results,
        "MXTPU_TGT_STEPS": str(steps),
        "MXTPU_TGT_SAVE_EVERY": str(save_every),
        "MXTPU_TGT_KILL_AT": kill_at,
    }
    if hang_at is not None:
        env["MXTPU_TGT_HANG_AT"] = str(hang_at)
    sup = Supervisor(
        [sys.executable, "-m",
         "incubator_mxnet_tpu.train.example_target"],
        ckpt_dir=ckpt, progress_file=results,
        max_restarts=max_restarts, backoff_s=0.05,
        hang_timeout_s=hang_timeout_s, env=env)
    t0 = time.perf_counter()
    report = sup.run(raise_on_failure=False)
    wall = time.perf_counter() - t0
    rows = []
    if os.path.exists(results):
        with open(results) as f:
            rows = [json.loads(line) for line in f]
    by_step = {}
    for r in rows:
        by_step[r["step"]] = r["loss"]
    return report, by_step, rows, wall


def scenario_kill9_resume(workdir, steps=16, kills=(6, 11)):
    print("  [kill9_resume]")
    _, clean, _, clean_wall = _run_target(workdir, "clean", steps)
    kill_at = ",".join(str(k) for k in kills)
    report, survived, rows, wall = _run_target(
        workdir, "killed", steps, kill_at=kill_at,
        max_restarts=len(kills) + 2)
    check(report.completed, "supervised run completed")
    check(report.restarts == len(kills),
          f"exactly {len(kills)} restarts for {len(kills)} kills")
    check(set(survived) == set(range(steps)),
          "every step's loss recorded")
    exact = all(survived.get(s) == clean.get(s) for s in range(steps))
    check(exact, "resumed loss sequence BIT-IDENTICAL to uninterrupted "
                 "run")
    steps_rerun = len(rows) - steps
    check(0 <= steps_rerun <= len(kills) * 2 + 2,
          f"steps re-run bounded by save cadence (got {steps_rerun})")
    return {"restarts": report.restarts,
            "steps_rerun": steps_rerun,
            "supervised_wall_s": round(wall, 3),
            "clean_wall_s": round(clean_wall, 3),
            "attempts": [a.reason for a in report.attempts]}


def scenario_hang_watchdog(workdir, steps=8):
    print("  [hang_watchdog]")
    report, by_step, _, _ = _run_target(
        workdir, "hang", steps, hang_at=4, max_restarts=2,
        hang_timeout_s=3.0)
    check(report.completed, "hung run completed after watchdog restart")
    check(report.hang_kills == 1, "exactly one hang kill")
    check(set(by_step) == set(range(steps)), "every step trained")
    return {"hang_kills": report.hang_kills,
            "attempts": [a.reason for a in report.attempts]}


def scenario_io_transient():
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.io import NDArrayIter, PrefetchingIter
    print("  [io_transient]")
    data = np.arange(48, dtype=np.float32).reshape(48, 1)
    os.environ["MXTPU_IO_FAIL_READS"] = "2"
    os.environ["MXTPU_IO_RETRY_ATTEMPTS"] = "3"
    os.environ["MXTPU_IO_RETRY_BACKOFF"] = "0.001"
    try:
        pf = PrefetchingIter(NDArrayIter(data, batch_size=4))
        batches = list(pf)
        check(len(batches) == 12,
              "transient blips under the retry bound lost no batch")
        check(pf.read_retries == 2, "retries counted")
        os.environ["MXTPU_IO_FAIL_READS"] = "99"
        pf2 = PrefetchingIter(NDArrayIter(data, batch_size=4))
        loud = False
        try:
            pf2.next()
        except OSError:
            loud = True
        check(loud, "persistent IO failure surfaced loudly, no hang")
    finally:
        for k in ("MXTPU_IO_FAIL_READS", "MXTPU_IO_RETRY_ATTEMPTS",
                  "MXTPU_IO_RETRY_BACKOFF"):
            os.environ.pop(k, None)
    return {}


# --------------------------------------------------------------------- #
# bench: guard + scaler steady-state overhead (strict alternation)
# --------------------------------------------------------------------- #

def bench_guard_overhead(steps=400, width=64):
    """Per-step wall time, guarded+scaled vs unguarded fused step, in
    STRICT ALTERNATION (round-10 methodology: paired windows disagree
    on the sign at this effect size on a noisy CPU host; per-step
    quantiles of alternating steps are robust — p50 primary)."""
    import numpy as np
    from incubator_mxnet_tpu import autograd, nd
    from incubator_mxnet_tpu.amp.loss_scaler import LossScaler
    print("  [bench guard_overhead]")
    X, y = _data(n=16)
    nets = {}
    trainers = {}
    for arm, (guard, scaler) in {
            "unguarded": (False, None),
            "guarded": (True, LossScaler(init_scale=2.0,
                                         scale_window=10 ** 9))}.items():
        net = _net(seed=3, width=width)
        nets[arm] = net
        trainers[arm] = _trainer(net, scaler=scaler, guard=guard)
    times = {"unguarded": [], "guarded": []}

    def one_step(arm):
        net, tr = nets[arm], trainers[arm]
        t0 = time.perf_counter()
        with autograd.record():
            L = _mse(net(nd.array(X)), nd.array(y)).mean()
        tr.backward(L)       # scale rides the backward seed (free)
        tr.step(X.shape[0])
        return time.perf_counter() - t0

    for arm in ("unguarded", "guarded"):    # warmup: compiles
        for _ in range(5):
            one_step(arm)
    for i in range(steps):                  # strict alternation
        for arm in (("unguarded", "guarded") if i % 2 == 0
                    else ("guarded", "unguarded")):
            times[arm].append(one_step(arm))
    out = {}
    for arm, ts in times.items():
        ts = np.sort(np.asarray(ts))
        out[arm] = {"p50_ms": float(np.percentile(ts, 50) * 1e3),
                    "p90_ms": float(np.percentile(ts, 90) * 1e3),
                    "steps": len(ts)}
    overhead = out["guarded"]["p50_ms"] / out["unguarded"]["p50_ms"] - 1.0
    out["overhead_p50"] = round(overhead, 4)
    tr = trainers["guarded"]
    check(tr._fused.trace_count == 1 and tr._fused.guard_trace_count == 1,
          "guarded arm compiled exactly once")
    print(f"    guarded p50 {out['guarded']['p50_ms']:.3f} ms vs "
          f"unguarded {out['unguarded']['p50_ms']:.3f} ms -> "
          f"overhead {overhead * 100:+.2f}%")
    return out


# --------------------------------------------------------------------- #
def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="CI guard: small sizes, exit non-zero on any "
                         "violated invariant")
    ap.add_argument("--json", default=None,
                    help="write results (and bank-ready bench numbers)")
    ap.add_argument("--overhead-steps", type=int, default=None)
    args = ap.parse_args()

    results = {"mode": "smoke" if args.smoke else "full"}
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as workdir:
        print("== training chaos scenarios ==")
        results["nan_grad_skip"] = scenario_nan_grad_skip()
        results["nan_grad_state_identity"] = \
            scenario_nan_grad_state_identity()
        results["overflow_storm"] = scenario_overflow_storm()
        results["poison_halt"] = scenario_poison_halt()
        results["spmd_skip"] = scenario_spmd_skip()
        results["io_transient"] = scenario_io_transient()
        results["kill9_resume"] = scenario_kill9_resume(workdir)
        results["hang_watchdog"] = scenario_hang_watchdog(workdir)
        print("== bench ==")
        steps = args.overhead_steps or (120 if args.smoke else 400)
        results["guard_overhead"] = bench_guard_overhead(steps=steps)
        if args.smoke:
            check(results["guard_overhead"]["overhead_p50"] < 0.05,
                  "guard+scaler overhead under the smoke bar (5%; the "
                  "banked bar is 2% at full sample size)")
    results["wall_s"] = round(time.perf_counter() - t0, 2)

    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=2)
        print(f"wrote {args.json}")

    print(f"\n{len(FAILURES)} failures; wall {results['wall_s']}s")
    if FAILURES:
        for m in FAILURES:
            print(f"  FAIL: {m}")
        return 1
    print("train chaos: all invariants held")
    return 0


if __name__ == "__main__":
    sys.exit(main())
