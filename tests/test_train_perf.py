"""Round 16 (docs/TRAINING_PERF.md): overlapped bucket-ready allreduce,
in-step gradient accumulation, and MFU accounting.

The training-perf invariants, in the compile-count discipline of
PR 2/6: the overlapped bucket issue order is a DETERMINISTIC pure
function of the trainable set (a reordered collective is a silent
cross-replica deadlock on real hardware); an accumulation-count change
never retraces the microbatch program; the PR-8 guard/scaler compose
with accumulation as ONE combined verdict per accumulated step (a NaN
in microbatch 2 of 8 skips the whole apply bit-identically, the loss
scale halves once); and the int8-allreduce seam (PR 11) reads its
verdict from dequantized gradients unchanged.
"""

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd, parallel
from incubator_mxnet_tpu import kvstore as kv_mod
from incubator_mxnet_tpu.amp.loss_scaler import LossScaler
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.parallel import mesh as pmesh
from incubator_mxnet_tpu.parallel.collectives import (BucketSchedule,
                                                      plan_grad_buckets)
from incubator_mxnet_tpu.train import StepOutcome


def _build_net(seed=0, bn=False):
    mx.random.seed(seed)
    net = nn.Sequential()
    if bn:
        net.add(nn.Dense(16, in_units=8, activation="relu"),
                nn.BatchNorm(in_channels=16),
                nn.Dense(4, in_units=16))
    else:
        net.add(nn.Dense(16, in_units=8, activation="relu"),
                nn.Dense(4, in_units=16))
    net.initialize()
    return net


def _data(seed=1, n=8):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, 8).astype(np.float32),
            rng.randn(n, 4).astype(np.float32))


def _spy_kv(num_workers=2):
    """A 'device' kvstore forced onto the reduction path, with every
    pushpull key recorded (the test_fused_step idiom)."""
    kv = kv_mod.create("device")
    kv._num_workers = num_workers
    calls = []
    orig = kv.pushpull

    def spy(key, value, out=None, priority=0):
        calls.append(key)
        return orig(key, value, out=out, priority=priority)

    kv.pushpull = spy
    return kv, calls


def _params_snapshot(net):
    return [p.data().asnumpy().copy()
            for p in net.collect_params().values()]


# --------------------------------------------------------------------- #
# bucket plan + schedule units (host-only, no compiles)
# --------------------------------------------------------------------- #

def test_plan_grad_buckets_deterministic_pure_function():
    members = [(i, 1000 + i, 4, "float32") for i in range(10)] + \
              [(i, 500, 2, "bfloat16") for i in range(10, 14)]
    a = plan_grad_buckets(members, 8 * 1024)
    b = plan_grad_buckets(list(reversed(members)), 8 * 1024)
    assert [x.key for x in a] == [x.key for x in b]  # input-order free
    assert [x.indices for x in a] == [x.indices for x in b]
    # packing is reverse-param-index within dtype; plan order leads
    # with the bucket holding the deepest parameter
    assert a[0].indices[0] == max(i for b_ in a for i in b_.indices
                                  if b_.dtype == a[0].dtype)
    # byte limit respected (single members may exceed it)
    for bk in a:
        if len(bk.indices) > 1:
            assert bk.nbytes <= 8 * 1024


def test_bucket_schedule_issues_in_plan_order_gated_on_readiness():
    buckets = plan_grad_buckets(
        [(i, 10, 4, "float32") for i in range(6)], 2 * 40)
    sched = BucketSchedule(buckets)
    # bucket 0 holds the HIGHEST indices; readying a later-plan bucket
    # first must not issue it out of order
    later = buckets[1].indices
    issued = []
    for i in later:
        issued += sched.mark_ready(i)
    assert issued == []                    # gated behind plan bucket 0
    for i in buckets[0].indices:
        issued += sched.mark_ready(i)
    # bucket 0 ready -> releases itself AND the already-ready bucket 1
    assert [b.key for b in issued] == [buckets[0].key, buckets[1].key]
    tail = sched.drain()
    assert [b.key for b in tail] == [b.key for b in buckets[2:]]
    assert sched.issued == [b.key for b in buckets]
    sched.reset_round()
    assert sched.issued == []
    assert sched.mark_ready(999) == []     # foreign index: no-op


# --------------------------------------------------------------------- #
# overlapped allreduce on the eager Trainer
# --------------------------------------------------------------------- #

def _overlap_trainer(net, kv, **kw):
    return gluon.Trainer(net.collect_params(), "sgd",
                         {"learning_rate": 0.1}, kvstore=kv,
                         fuse_step=True, overlap_allreduce=True, **kw)


def _one_step(net, tr, x, batch=4):
    with autograd.record():
        loss = (net(x) ** 2).mean()
    loss.backward()
    tr.step(batch)


def test_overlap_issues_during_backward_and_schedule_is_stable(
        monkeypatch):
    monkeypatch.setenv("MXTPU_GRAD_BUCKET_BYTES", "600")  # several buckets
    net = _build_net()
    kv, calls = _spy_kv()
    tr = _overlap_trainer(net, kv)
    x = nd.array(np.random.RandomState(0).randn(4, 8).astype(np.float32))
    _one_step(net, tr, x)                  # plan builds at step 1
    scheds = []
    for _ in range(3):
        with autograd.record():
            loss = (net(x) ** 2).mean()
        before = len(calls)
        loss.backward()
        in_backward = calls[before:len(calls)]
        assert len(in_backward) >= 1       # issued DURING backward
        tr.step(4)
        scheds.append(list(tr.grad_issue_schedule))
        assert in_backward == scheds[-1][:len(in_backward)]
    # stable across runs and equal to the deterministic plan order
    assert scheds[0] == scheds[1] == scheds[2]
    assert scheds[0] == tr._overlap_sched.order
    assert len(scheds[0]) > 1
    snap = tr.health_snapshot()
    assert snap["overlap_allreduce"] is True
    assert snap["grad_issue_schedule"] == scheds[0]


def test_overlap_matches_serial_reduction_bitwise():
    results = []
    for overlap in (False, True):
        net = _build_net()
        kv, _ = _spy_kv()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1}, kvstore=kv,
                           fuse_step=True, overlap_allreduce=overlap)
        x = nd.array(np.random.RandomState(0)
                     .randn(4, 8).astype(np.float32))
        for _ in range(4):
            _one_step(net, tr, x)
        results.append(_params_snapshot(net))
    for a, b in zip(*results):
        np.testing.assert_array_equal(a, b)


def test_overlap_partial_backward_flushes_at_step(monkeypatch):
    """A backward reaching only the DEEP layer readies (and issues) the
    plan's first bucket mid-backward; the shallow layers' buckets never
    ready, and step() drains that tail itself — the gate can stall, the
    step cannot."""
    monkeypatch.setenv("MXTPU_GRAD_BUCKET_BYTES", "300")
    mx.random.seed(0)
    d1 = nn.Dense(16, in_units=8, activation="relu")
    d2 = nn.Dense(4, in_units=16)
    d1.initialize()
    d2.initialize()
    params = list(d1.collect_params().values()) + \
        list(d2.collect_params().values())
    kv, calls = _spy_kv()
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                       kvstore=kv, fuse_step=True,
                       overlap_allreduce=True)
    x = nd.array(np.random.RandomState(0).randn(4, 8).astype(np.float32))
    with autograd.record():
        loss = (d2(d1(x)) ** 2).mean()
    loss.backward()
    tr.step(4)                             # plan builds here
    h = d1(x)                              # outside the tape
    with autograd.record():
        loss = (d2(h) ** 2).mean()
    before = len(calls)
    loss.backward()                        # only d2's grads refresh
    assert len(calls) > before             # deep bucket issued anyway
    tr.step(4, ignore_stale_grad=True)     # drains the unready tail
    assert list(tr.grad_issue_schedule) == tr._overlap_sched.order
    assert sum(tr.health.values()) == 2


def test_overlap_refuses_mid_round_accumulation(monkeypatch):
    monkeypatch.setenv("MXTPU_GRAD_BUCKET_BYTES", "600")
    net = _build_net()
    kv, calls = _spy_kv()
    tr = _overlap_trainer(net, kv)
    x = nd.array(np.random.RandomState(0).randn(4, 8).astype(np.float32))
    _one_step(net, tr, x)                  # plan armed
    with autograd.record():
        loss = (net(x) ** 2).mean()
    loss.backward()                        # hooks issued buckets
    with pytest.raises(MXNetError, match="overlapped allreduce"):
        tr.accumulate_grads()
    tr.step(4)                             # round still closes cleanly
    # declared accumulation defers overlap from the FIRST microbatch
    tr.set_grad_accumulation(True)
    with autograd.record():
        loss = (net(x) ** 2).mean()
    before = len(calls)
    loss.backward()
    assert calls[before:] == []            # nothing issued mid-backward
    tr.accumulate_grads()
    tr.step(1)
    assert tr.last_outcome is StepOutcome.APPLIED


def test_overlap_single_member_never_double_reduces():
    """Review regression: one bucketable dense param, num_workers>1,
    int8 off — the step-time bucketed gate routes it per-param, so the
    overlap plan must DISABLE rather than issue the same gradient into
    both paths (a second reduction inflates it by num_workers)."""
    mx.random.seed(0)
    d = nn.Dense(4, in_units=8, use_bias=False)    # exactly one param
    d.initialize()
    kv, calls = _spy_kv()
    tr = gluon.Trainer(d.collect_params(), "sgd", {"learning_rate": 0.1},
                       kvstore=kv, fuse_step=True, overlap_allreduce=True)
    x = nd.array(np.random.RandomState(0).randn(4, 8).astype(np.float32))
    for _ in range(3):
        with autograd.record():
            loss = (d(x) ** 2).mean()
        loss.backward()
        tr.step(4)
    assert tr._overlap_sched is False              # overlap disabled
    # exactly ONE pushpull per step (the per-param rest path), never two
    assert len(calls) == 3


def test_accum_round_missing_param_grad_is_skipped():
    """Review regression: a parameter that gets no fresh gradient in
    any microbatch of an accumulated round must be SKIPPED (warned),
    never have its stale raw grad applied at the round's rescale."""
    mx.random.seed(0)
    d1 = nn.Dense(16, in_units=8, activation="relu")
    d2 = nn.Dense(4, in_units=16)
    d1.initialize()
    d2.initialize()
    params = list(d1.collect_params().values()) + \
        list(d2.collect_params().values())
    tr = gluon.Trainer(params, "adam", {"learning_rate": 0.05},
                       kvstore=None)
    x = nd.array(np.random.RandomState(0).randn(4, 8).astype(np.float32))
    # round 1 touches BOTH layers (leaves a stale d1 grad behind)
    with autograd.record():
        loss = (d2(d1(x)) ** 2).mean()
    tr.backward(loss)
    tr.accumulate_grads()
    tr.step(1)
    d1_before = [p.data().asnumpy().copy()
                 for p in d1.collect_params().values()]
    d2_before = [p.data().asnumpy().copy()
                 for p in d2.collect_params().values()]
    # round 2's microbatches only reach d2
    h = d1(x)                                      # outside the tape
    for _ in range(2):
        with autograd.record():
            loss = (d2(h) ** 2).mean()
        tr.backward(loss)
        tr.accumulate_grads()
    with pytest.warns(UserWarning, match="no gradient in any microbatch"):
        tr.step(2)
    assert tr.last_outcome is StepOutcome.APPLIED  # d2 still applied
    for p, w in zip(d1.collect_params().values(), d1_before):
        np.testing.assert_array_equal(p.data().asnumpy(), w)
    assert any(np.abs(p.data().asnumpy() - w).max() > 0
               for p, w in zip(d2.collect_params().values(), d2_before))


# --------------------------------------------------------------------- #
# eager microbatch accumulation: equivalence + guard/scaler composition
# --------------------------------------------------------------------- #

def test_eager_accumulation_matches_big_batch():
    X, y = _data(n=8)
    net_a = _build_net()
    tr_a = gluon.Trainer(net_a.collect_params(), "adam",
                         {"learning_rate": 0.01}, kvstore=None)
    for m in range(4):
        xb, yb = X[m * 2:(m + 1) * 2], y[m * 2:(m + 1) * 2]
        with autograd.record():
            loss = ((net_a(nd.array(xb)) - nd.array(yb)) ** 2).mean()
        tr_a.backward(loss)
        tr_a.accumulate_grads()
    tr_a.step(4)          # 4 microbatches, each loss already a mean
    assert tr_a.last_outcome is StepOutcome.APPLIED
    assert tr_a._fused.accum_trace_count == 1

    net_b = _build_net()
    tr_b = gluon.Trainer(net_b.collect_params(), "adam",
                         {"learning_rate": 0.01}, kvstore=None)
    with autograd.record():
        loss = ((net_b(nd.array(X)) - nd.array(y)) ** 2).mean()
    loss.backward()
    tr_b.step(1)
    for a, b in zip(_params_snapshot(net_a), _params_snapshot(net_b)):
        np.testing.assert_allclose(a, b, rtol=2e-6, atol=2e-7)


def test_eager_accum_count_change_never_retraces():
    X, y = _data(n=8)
    net = _build_net()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 0.01}, kvstore=None)
    for k in (1, 4, 2):
        for m in range(k):
            with autograd.record():
                loss = ((net(nd.array(X[:2])) - nd.array(y[:2]))
                        ** 2).mean()
            tr.backward(loss)
            tr.accumulate_grads()
        tr.step(k)
    assert tr._fused.accum_trace_count == 1
    assert tr._fused.trace_count <= len(tr._fused._jits)


def test_eager_nonfinite_microbatch_skips_whole_apply_once():
    """A NaN in microbatch 2 of 4: the whole apply skips bit-identically
    (params AND optimizer state), ONE SKIPPED_NONFINITE outcome, the
    loss scale halves ONCE — not once per microbatch."""
    X, y = _data(n=8)
    net = _build_net()
    sc = LossScaler(init_scale=8.0, scale_window=100)
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 0.01}, kvstore=None,
                       loss_scaler=sc)
    # one clean accumulated round builds optimizer state
    for m in range(2):
        with autograd.record():
            loss = ((net(nd.array(X[:2])) - nd.array(y[:2])) ** 2).mean()
        tr.backward(loss)
        tr.accumulate_grads()
    tr.step(2)
    import jax.tree_util as jtu
    w_before = _params_snapshot(net)
    st_before = [leaf.asnumpy().copy()
                 for _, st in sorted(tr._updaters[0].states.items())
                 for leaf in jtu.tree_leaves(
                     st, is_leaf=lambda x: hasattr(x, "asnumpy"))]
    outcomes_before = sum(tr.health.values())
    for m in range(4):
        xb = X[m * 2:(m + 1) * 2].copy()
        if m == 1:
            xb[0, 0] = np.nan
        with autograd.record():
            loss = ((net(nd.array(xb)) -
                     nd.array(y[m * 2:(m + 1) * 2])) ** 2).mean()
        tr.backward(loss)
        tr.accumulate_grads()
    tr.step(4)
    assert tr.last_outcome is StepOutcome.SKIPPED_NONFINITE
    assert sum(tr.health.values()) == outcomes_before + 1
    assert sc.loss_scale == 4.0            # halved exactly once
    for a, b in zip(_params_snapshot(net), w_before):
        np.testing.assert_array_equal(a, b)
    st_after = [leaf.asnumpy()
                for _, st in sorted(tr._updaters[0].states.items())
                for leaf in jtu.tree_leaves(
                    st, is_leaf=lambda x: hasattr(x, "asnumpy"))]
    for a, b in zip(st_after, st_before):
        np.testing.assert_array_equal(a, b)
    # clean round afterwards applies through the SAME programs
    for m in range(2):
        with autograd.record():
            loss = ((net(nd.array(X[:2])) - nd.array(y[:2])) ** 2).mean()
        tr.backward(loss)
        tr.accumulate_grads()
    tr.step(2)
    assert tr.last_outcome is StepOutcome.APPLIED
    assert tr._fused.accum_trace_count == 1


def test_eager_accum_int8_allreduce_verdict_on_dequantized():
    """Accumulation + the PR-11 int8 seam: the accumulated bucket ships
    quantized at apply time and the guard still reads the DEQUANTIZED
    gradients — a poisoned microbatch poisons the bucket scale, every
    dequantized element, and the verdict."""
    X, y = _data(n=4)
    net = _build_net()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 0.01}, kvstore="device",
                       int8_allreduce=True)
    tr.set_grad_accumulation(True)

    def round_(poison):
        for m in range(2):
            xb = X[m * 2:(m + 1) * 2].copy()
            if poison and m == 1:
                xb[0, 0] = np.nan
            with autograd.record():
                loss = ((net(nd.array(xb)) -
                         nd.array(y[m * 2:(m + 1) * 2])) ** 2).mean()
            tr.backward(loss)
            tr.accumulate_grads()
        tr.step(2)

    round_(False)
    assert tr.int8_buckets > 0             # seam engaged
    w_before = _params_snapshot(net)
    round_(True)
    assert tr.last_outcome is StepOutcome.SKIPPED_NONFINITE
    for a, b in zip(_params_snapshot(net), w_before):
        np.testing.assert_array_equal(a, b)
    round_(False)
    assert tr.last_outcome is StepOutcome.APPLIED


# --------------------------------------------------------------------- #
# SPMD in-step accumulation
# --------------------------------------------------------------------- #

def _flagged_mse(block, x, y, flag):
    """MSE with a per-microbatch poison channel: flag==1 is identity,
    a NaN flag entry poisons the loss (and every gradient) as pure
    traced data — no retrace across clean/poisoned rounds."""
    out = block(x)
    return ((out - y) ** 2).mean() * flag.mean()


def _spmd_setup(sharding="replicated", axes=None, scaler=None, seed=7,
                guard=None, bn=False, two_dev=True):
    import jax
    net = _build_net(seed=seed, bn=bn)
    if two_dev:
        mesh = pmesh.build_mesh(devices=jax.devices()[:2],
                                axis_sizes=axes or {"dp": 2})
    else:
        mesh = pmesh.build_mesh(axis_sizes=axes or {"dp": 8})
    tr = parallel.SPMDTrainer(net, forward_loss=_flagged_mse,
                              optimizer="adam",
                              optimizer_params={"learning_rate": 0.01},
                              mesh=mesh, sharding=sharding,
                              loss_scaler=scaler, guard=guard)
    return net, tr


def _micros(X, y, k, nan_at=None, seed=None):
    n = X.shape[0] // k
    out = []
    for m in range(k):
        flag = np.ones((n,), np.float32)
        if m == nan_at:
            flag[0] = np.nan
        out.append((nd.array(X[m * n:(m + 1) * n]),
                    nd.array(y[m * n:(m + 1) * n]), nd.array(flag)))
    return out


def test_spmd_accum_count_change_never_retraces():
    X, y = _data(n=16)
    net, tr = _spmd_setup()
    for k in (1, 4, 8):
        # fixed MICROBATCH shape (2 rows), varying COUNT k — the count
        # is pure host data, so one compiled program covers every k
        micros = [(nd.array(X[m * 2:(m + 1) * 2]),
                   nd.array(y[m * 2:(m + 1) * 2]),
                   nd.array(np.ones(2, np.float32)))
                  for m in range(k)]
        L = tr.step_microbatches(micros)
        assert np.isfinite(float(L.asnumpy()))
    assert tr.accum_step_trace_count == 1
    assert tr.step_count == 3
    snap = tr.health_snapshot()
    assert snap["accum_step_trace_count"] == 1
    assert snap["last_accum_count"] == 8


def test_spmd_accum_matches_plain_step():
    X, y = _data(n=16)
    net_a, tr_a = _spmd_setup(seed=9)
    for _ in range(3):
        La = tr_a.step_microbatches(_micros(X, y, 4))
    net_b, tr_b = _spmd_setup(seed=9)
    for _ in range(3):
        Lb = tr_b.step(nd.array(X), nd.array(y),
                       nd.array(np.ones(16, np.float32)))
    np.testing.assert_allclose(float(La.asnumpy()), float(Lb.asnumpy()),
                               rtol=1e-5)
    for pa, pb in zip(tr_a._params, tr_b._params):
        np.testing.assert_allclose(pa.data().asnumpy(),
                                   pb.data().asnumpy(),
                                   rtol=3e-6, atol=3e-7)


@pytest.mark.parametrize("sharding,axes", [
    ("replicated", {"dp": 2}),
    ("fsdp", {"dp": 1, "fsdp": 2}),
])
def test_spmd_nonfinite_microbatch_skips_round(monkeypatch, sharding,
                                               axes):
    """One combined verdict per accumulated round on dp AND fsdp: a NaN
    in microbatch 2 of 4 skips the whole apply with params + optimizer
    state bit-identical, exactly one outcome, one scaler halve; the
    clean round after applies through the SAME program."""
    monkeypatch.setenv("MXTPU_FSDP_MIN_SIZE", "0")
    X, y = _data(n=16)
    sc = LossScaler(init_scale=8.0, scale_window=100)
    net, tr = _spmd_setup(sharding=sharding, axes=axes, scaler=sc)
    tr.step_microbatches(_micros(X, y, 4))
    import jax.tree_util as jtu

    def leaves():
        return [np.asarray(leaf._data).copy()
                for st in tr._opt_state
                for leaf in jtu.tree_leaves(
                    st, is_leaf=lambda s: hasattr(s, "asnumpy"))]

    w_before = [p.data().asnumpy().copy() for p in tr._params]
    st_before = leaves()
    sc_steps = tr.step_count
    outcomes_before = sum(tr.health.values())
    tr.step_microbatches(_micros(X, y, 4, nan_at=1))
    assert tr.last_outcome is StepOutcome.SKIPPED_NONFINITE
    assert sum(tr.health.values()) == outcomes_before + 1
    assert tr.step_count == sc_steps       # t did not advance
    assert sc.loss_scale == 4.0            # halved exactly once
    for a, b in zip([p.data().asnumpy() for p in tr._params], w_before):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(leaves(), st_before):
        np.testing.assert_array_equal(a, b)
    tr.step_microbatches(_micros(X, y, 4))
    assert tr.last_outcome is StepOutcome.APPLIED
    assert tr.accum_step_trace_count == 1


def test_spmd_accum_guarded_clean_bitwise_matches_unguarded():
    X, y = _data(n=16)
    finals = []
    for guard in (True, False):
        net, tr = _spmd_setup(seed=11, guard=guard)
        for _ in range(3):
            tr.step_microbatches(_micros(X, y, 4))
        finals.append([p.data().asnumpy() for p in tr._params])
    for a, b in zip(*finals):
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_spmd_vetoed_round_rolls_back_bn_stats():
    """BN running stats advance per microbatch forward; a vetoed round
    must roll them back to the round start (the rolls-NOTHING-forward
    contract of the PR-8 skip)."""
    X, y = _data(n=16)
    net, tr = _spmd_setup(seed=15, bn=True)
    tr.step_microbatches(_micros(X, y, 4))
    frozen_before = [p.data().asnumpy().copy()
                     for i, p in enumerate(tr._params)
                     if i not in set(tr._train_idx)]
    tr.step_microbatches(_micros(X, y, 4, nan_at=2))
    assert tr.last_outcome is StepOutcome.SKIPPED_NONFINITE
    frozen_after = [p.data().asnumpy()
                    for i, p in enumerate(tr._params)
                    if i not in set(tr._train_idx)]
    assert frozen_before  # BatchNorm contributes frozen aux state
    for a, b in zip(frozen_after, frozen_before):
        np.testing.assert_array_equal(a, b)
    # and an APPLIED round does advance them
    tr.step_microbatches(_micros(X, y, 4))
    changed = any(
        np.abs(a - b).max() > 0
        for a, b in zip([p.data().asnumpy()
                         for i, p in enumerate(tr._params)
                         if i not in set(tr._train_idx)],
                        frozen_before))
    assert changed


def test_spmd_halt_escalation_through_accumulated_rounds():
    X, y = _data(n=16)
    net, tr = _spmd_setup(seed=17)
    tr._recorder.max_consecutive_nonfinite = 2
    tr.step_microbatches(_micros(X, y, 2))
    tr.step_microbatches(_micros(X, y, 2, nan_at=0))
    with pytest.raises(MXNetError, match="poisoned"):
        tr.step_microbatches(_micros(X, y, 2, nan_at=1))
    assert tr.health["HALTED_POISONED"] == 1


# --------------------------------------------------------------------- #
# FLOPs / MFU accounting units
# --------------------------------------------------------------------- #

def test_flops_formulas_and_mfu_fields():
    from incubator_mxnet_tpu.models.gpt import GPTModel
    from incubator_mxnet_tpu.utils.flops import (count_params,
                                                 gpt_train_flops,
                                                 mfu, model_train_flops,
                                                 transformer_train_flops)
    mx.random.seed(0)
    model = GPTModel(vocab_size=64, units=32, hidden_size=64,
                     num_layers=2, num_heads=4, max_length=32,
                     dropout=0.0)
    model.initialize()
    n = count_params(model)
    assert n == sum(int(np.prod(p.shape))
                    for p in model.collect_params().values())
    f1 = gpt_train_flops(model, batch=2, seq_len=16)
    f2 = gpt_train_flops(model, batch=4, seq_len=16)
    assert f2 == pytest.approx(2 * f1)     # linear in tokens
    assert model_train_flops(model, 2, 16) == f1
    # 6P lower bound: matmul params exclude embeddings but re-add the
    # tied LM head, attention adds on top
    assert f1 > 6 * (n - 32 * 32) * 2 * 16 * 0.5
    out = mfu(f1, 0.01, 2, peak={"flops": 1e12, "source": "env",
                                 "device_kind": "x"})
    assert out["mfu"] == pytest.approx(f1 / 0.01 / 2 / 1e12)
    for field in ("model_flops_per_step", "achieved_flops_per_device",
                  "peak_flops_per_device", "peak_source", "mfu"):
        assert field in out
    with pytest.raises(ValueError, match="analytic FLOPs"):
        model_train_flops(object(), 1, 1)


def test_bert_flops_counts_mlm_head():
    from incubator_mxnet_tpu.models.bert import BERTModel
    from incubator_mxnet_tpu.utils.flops import bert_train_flops
    mx.random.seed(0)
    m = BERTModel(vocab_size=128, units=32, hidden_size=64,
                  num_layers=2, num_heads=4, max_length=32)
    m.initialize()
    with_head = bert_train_flops(m, 2, 16, mlm_head=True)
    without = bert_train_flops(m, 2, 16, mlm_head=False)
    assert with_head - without == pytest.approx(
        6 * 128 * 32 * 2 * 16)             # 6 · V·d · tokens


def test_peak_flops_env_override(monkeypatch):
    from incubator_mxnet_tpu.utils import flops as flops_mod
    monkeypatch.setenv("MXTPU_PEAK_FLOPS", "123e9")
    peak = flops_mod.peak_flops_per_device()
    assert peak["flops"] == pytest.approx(123e9)
    assert peak["source"] == "env"


@pytest.mark.slow
def test_trace_summary_overlap_stats(tmp_path):
    """overlap_stats parses a real profiler capture of an SPMD step and
    returns the per-lane split fields step_bench banks (slow: the
    profiler capture costs ~9 s; the full bench exercises the same
    path when banking BENCH_MFU.json)."""
    import jax
    import sys, os
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    from trace_summary import overlap_stats
    X, y = _data(n=16)
    net, tr = _spmd_setup(seed=19)
    tr.step_microbatches(_micros(X, y, 2))     # compile outside capture
    with jax.profiler.trace(str(tmp_path)):
        L = tr.step_microbatches(_micros(X, y, 2))
        jax.block_until_ready(L._data)
    st = overlap_stats(str(tmp_path))
    for field in ("compute_us", "collective_us", "overlapped_us",
                  "exposed_us", "overlap_ratio", "n_device_lanes"):
        assert field in st
    assert st["compute_us"] > 0
