"""Engine-internal draft proposers for speculative decoding.

Speculative decoding needs a cheap source of candidate next-tokens; the
engine's first drafter is PROMPT-LOOKUP / N-GRAM drafting (no second
model): real generation is full of spans the sequence has already seen
— templated boilerplate, quoted context, code identifiers, repetition —
so the continuation of the most recent earlier occurrence of the
current suffix n-gram is a strong guess at the next tokens. Proposals
are pure host-side DATA (an int32 vector per slot per step); the jitted
verify step scores them and accepts a variable-length prefix, so a
wrong draft costs nothing but the verify FLOPs and a missing draft
degrades to exactly the non-speculative 1 token/step (serve/engine.py).

A drafter is any callable ``draft_fn(history, k) -> np.ndarray`` with
``history`` the slot's prompt + emitted tokens (1-D int32) and ``k``
the maximum number of drafts wanted; it returns 0..k int32 tokens.
``InferenceEngine(draft_fn=...)`` swaps the proposer (the bench uses an
adversarial random drafter to measure the zero-agreement floor; a
draft-MODEL proposer plugs in the same way later).
"""

from __future__ import annotations

import numpy as np

__all__ = ["ngram_propose", "make_ngram_drafter"]

_EMPTY = np.zeros((0,), np.int32)


def ngram_propose(history, k, max_order=3, min_order=1):
    """Propose up to ``k`` draft tokens by prompt lookup: find the most
    recent earlier occurrence of the history's suffix n-gram (longest
    order first, ``max_order`` down to ``min_order``) and return the
    tokens that followed it. Returns a (0..k,) int32 array — empty when
    no suffix n-gram recurs (the engine then runs a plain decode step).

    The scan is vectorized numpy over a <= max_len history — host-side
    noise next to a decode step's device dispatch."""
    h = np.asarray(history, np.int32).reshape(-1)
    n = h.size
    if k <= 0 or n < min_order + 1:
        return _EMPTY
    for order in range(min(max_order, n - 1), min_order - 1, -1):
        pat = h[-order:]
        # candidate starts i < n - order: every one leaves >= 1
        # continuation token (h[i + order] exists), and the suffix's
        # own trivial zero-continuation match at i = n - order is
        # excluded. i = n - order - 1 IS a legal candidate — its
        # continuation is h[n - 1], the period-1 repetition draft
        starts = n - order
        if starts <= 0:
            continue
        hits = np.ones((starts,), bool)
        for j in range(order):                  # order is tiny (<= 3)
            hits &= h[j:j + starts] == pat[j]
        idx = np.nonzero(hits)[0]
        if idx.size == 0:
            continue
        # most recent occurrence, preferring one far enough from the
        # end to supply all k continuation tokens (on periodic text the
        # nearest occurrence abuts the suffix and would yield only a
        # 1-token draft)
        full = idx[idx + order + k <= n]
        i = int(full[-1]) if full.size else int(idx[-1])
        cont = h[i + order:i + order + k]
        if cont.size:
            return cont.astype(np.int32, copy=True)
    return _EMPTY


def make_ngram_drafter(max_order=3, min_order=1):
    """An ``InferenceEngine``-shaped drafter with fixed n-gram orders."""

    def draft(history, k):
        return ngram_propose(history, k, max_order=max_order,
                             min_order=min_order)

    return draft
