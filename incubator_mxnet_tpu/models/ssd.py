"""SSD single-shot detector (BASELINE.md config: SSD conv + NMS custom
ops; reference: `example/ssd/` + the MultiBox ops in
src/operator/contrib/multibox_*.cc — file-level citations, SURVEY.md
caveat).

Compact TPU-native SSD: a truncated ResNet backbone, extra downsampling
stages, and per-scale class/box conv heads. Anchors come from
``MultiBoxPrior`` per feature scale; training targets from
``MultiBoxTarget``; inference decodes + NMS via ``MultiBoxDetection`` —
all fixed-shape XLA programs (ops/contrib.py)."""

from __future__ import annotations

from ..gluon import nn
from ..gluon.block import HybridBlock

__all__ = ["SSD", "ssd_300"]


def _down_block(channels):
    out = nn.HybridSequential(prefix="")
    out.add(nn.Conv2D(channels // 2, kernel_size=1))
    out.add(nn.BatchNorm())
    out.add(nn.Activation("relu"))
    out.add(nn.Conv2D(channels, kernel_size=3, strides=2, padding=1))
    out.add(nn.BatchNorm())
    out.add(nn.Activation("relu"))
    return out


class SSD(HybridBlock):
    """Multi-scale detector. ``forward(x)`` returns
    (anchors (1, N, 4), cls_preds (B, num_classes+1, N),
    box_preds (B, N*4))."""

    def __init__(self, num_classes=20,
                 sizes=((0.1, 0.14), (0.2, 0.27), (0.37, 0.44),
                        (0.54, 0.62), (0.71, 0.79)),
                 ratios=((1, 2, 0.5),) * 5,
                 base_channels=(32, 64, 128), **kwargs):
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self._sizes = sizes
        self._ratios = ratios
        num_scales = len(sizes)
        with self.name_scope():
            # backbone: three conv stages (compact; swap for a model_zoo
            # features slice at scale)
            self.backbone = nn.HybridSequential(prefix="backbone_")
            with self.backbone.name_scope():
                for c in base_channels:
                    self.backbone.add(nn.Conv2D(c, 3, padding=1,
                                                use_bias=False))
                    self.backbone.add(nn.BatchNorm())
                    self.backbone.add(nn.Activation("relu"))
                    self.backbone.add(nn.MaxPool2D(2, 2))
            self.stages = nn.HybridSequential(prefix="stages_")
            with self.stages.name_scope():
                for _ in range(num_scales - 2):
                    self.stages.add(_down_block(128))
            self.cls_heads = nn.HybridSequential(prefix="cls_")
            self.box_heads = nn.HybridSequential(prefix="box_")
            with self.cls_heads.name_scope():
                for i in range(num_scales):
                    A = len(sizes[i]) + len(ratios[i]) - 1
                    self.cls_heads.add(nn.Conv2D(
                        A * (num_classes + 1), 3, padding=1))
            with self.box_heads.name_scope():
                for i in range(num_scales):
                    A = len(sizes[i]) + len(ratios[i]) - 1
                    self.box_heads.add(nn.Conv2D(A * 4, 3, padding=1))

    def hybrid_forward(self, F, x):
        feats = [self.backbone(x)]
        for stage in self.stages:
            feats.append(stage(feats[-1]))
        # final global scale
        feats.append(F.Pooling(feats[-1], global_pool=True,
                               pool_type="max", kernel=(1, 1)))
        anchors, cls_preds, box_preds = [], [], []
        for i, feat in enumerate(feats):
            anchors.append(F.MultiBoxPrior(feat, sizes=self._sizes[i],
                                           ratios=self._ratios[i]))
            c = self.cls_heads[i](feat)  # (B, A*(C+1), H, W)
            b = self.box_heads[i](feat)  # (B, A*4, H, W)
            cls_preds.append(F.reshape(F.transpose(c, axes=(0, 2, 3, 1)),
                                       shape=(0, -1, self.num_classes + 1)))
            box_preds.append(F.reshape(F.transpose(b, axes=(0, 2, 3, 1)),
                                       shape=(0, -1)))
        anchors = F.concat(*anchors, dim=1)
        cls_preds = F.transpose(F.concat(*cls_preds, dim=1),
                                axes=(0, 2, 1))  # (B, C+1, N)
        box_preds = F.concat(*box_preds, dim=1)  # (B, N*4)
        return anchors, cls_preds, box_preds

    def training_targets(self, anchors, cls_preds, labels):
        """(box_target, box_mask, cls_target) via MultiBoxTarget."""
        from .. import ndarray as nd
        return nd.MultiBoxTarget(anchors, labels, cls_preds,
                                 negative_mining_ratio=3.0)

    def loss(self, cls_preds, box_preds, box_target, box_mask, cls_target):
        """Joint SSD loss: masked softmax-CE over classes (entries with
        cls_target < 0 are hard-negative-mining IGNORES and contribute
        zero gradient) + smooth-L1 on masked box offsets."""
        from .. import ndarray as nd
        keep = cls_target >= 0
        safe_t = nd.where(keep, cls_target,
                          nd.zeros_like(cls_target))
        logp = nd.log_softmax(cls_preds, axis=1)  # (B, C+1, N)
        picked = nd.pick(logp, safe_t, axis=1)
        ce = -(picked * keep).sum() / nd.maximum(keep.sum(), 1.0)
        diff = nd.abs(box_preds * box_mask - box_target * box_mask)
        sl1 = nd.where(diff > 1.0, diff - 0.5, 0.5 * diff * diff)
        box_l = sl1.sum() / nd.maximum(box_mask.sum(), 1.0)
        return ce + box_l

    def detect(self, cls_preds, box_preds, anchors, nms_threshold=0.45,
               threshold=0.01, nms_topk=400):
        """Decoded detections (B, N, 6) via MultiBoxDetection."""
        from .. import ndarray as nd
        probs = nd.softmax(cls_preds, axis=1)
        return nd.MultiBoxDetection(probs, box_preds, anchors,
                                    nms_threshold=nms_threshold,
                                    threshold=threshold, nms_topk=nms_topk)


def ssd_300(num_classes=20, **kwargs):
    """SSD sized for 300x300 inputs (the reference example's headline
    config)."""
    return SSD(num_classes=num_classes, **kwargs)
