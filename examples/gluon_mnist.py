"""LeNet on MNIST with gluon.Trainer — the minimum end-to-end slice
(BASELINE.md config #1; reference: example/gluon/mnist/mnist.py).

Uses the real MNIST files under --data-dir when present, otherwise a
synthetic separable digit problem so the example runs anywhere.

    python examples/gluon_mnist.py --epochs 2
"""

import argparse
import os

import numpy as np

import _common  # noqa: F401  (accelerator-or-CPU bootstrap)

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd, gluon
from incubator_mxnet_tpu.models import LeNet


def load_data(data_dir, n_synth=2048):
    try:
        ds = gluon.data.vision.MNIST(root=data_dir, train=True)
        X = np.stack([np.asarray(x) for x, _ in ds]).astype(np.float32)
        X = X.reshape(-1, 1, 28, 28) / 255.0
        y = np.asarray([int(l) for _, l in ds])
        return X, y
    except Exception:
        rng = np.random.RandomState(0)
        protos = rng.rand(10, 1, 28, 28).astype(np.float32)
        y = rng.randint(0, 10, n_synth)
        X = protos[y] + 0.1 * rng.randn(n_synth, 1, 28, 28) \
            .astype(np.float32)
        print("MNIST not found — using a synthetic stand-in")
        return X, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default=os.path.expanduser("~/.mxtpu/mnist"))
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.005)
    args = ap.parse_args()

    mx.random.seed(0)
    X, y = load_data(args.data_dir)
    net = LeNet(classes=10)
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr}, kvstore="device")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    n = len(X)
    for epoch in range(args.epochs):
        perm = np.random.RandomState(epoch).permutation(n)
        total, correct, lsum, batches = 0, 0, 0.0, 0
        for i in range(0, n - args.batch_size + 1, args.batch_size):
            idx = perm[i:i + args.batch_size]
            data, label = nd.array(X[idx]), nd.array(y[idx])
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label).mean()
            loss.backward()
            trainer.step(1)
            lsum += float(loss.asnumpy())
            batches += 1
            correct += int((np.argmax(out.asnumpy(), 1) ==
                            y[idx]).sum())
            total += len(idx)
        print(f"epoch {epoch}: loss {lsum / batches:.4f} "
              f"acc {correct / total:.3f}")


if __name__ == "__main__":
    main()
