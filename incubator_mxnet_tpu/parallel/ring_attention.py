"""Ring attention: exact attention over sequences sharded across devices.

The reference has NO long-context parallelism (SURVEY.md §5.7 — BERT-era
≤512 windows); this module is the TPU-native capability that subsumes it.
Sequence length is sharded over the mesh ``sp`` axis; each device holds a
Q/K/V block and K/V blocks rotate around the ring via ``lax.ppermute`` on
ICI while a numerically-stable streaming softmax (the flash-attention
recurrence) accumulates partial outputs. Compute on the current block
overlaps with the transfer of the next (XLA schedules the ppermute
asynchronously), so attention of length ``sp × T_blk`` runs with per-device
memory of one block — the Ring Attention construction (see PAPERS.md).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..base import MXNetError

__all__ = ["ring_self_attention", "ring_attention_block"]

_NEG_INF = -1e30


def _stream_block(q, k, v, acc, row_max, row_sum, mask):
    """One flash-attention accumulation step.

    q: (B, Tq, H, D); k/v: (B, Tk, H, D); acc: (B, Tq, H, D);
    row_max/row_sum: (B, Tq, H); mask: (Tq, Tk) additive or None.
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k)
    if mask is not None:
        scores = scores + mask[None, None, :, :]
    blk_max = scores.max(axis=-1)                       # (B,H,Tq)
    blk_max = jnp.moveaxis(blk_max, 1, -1)              # (B,Tq,H)
    new_max = jnp.maximum(row_max, blk_max)
    corr = jnp.exp(row_max - new_max)                   # (B,Tq,H)
    p = jnp.exp(scores - jnp.moveaxis(new_max, -1, 1)[..., None])  # (B,H,Tq,Tk)
    blk_out = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    blk_sum = jnp.moveaxis(p.sum(axis=-1), 1, -1)       # (B,Tq,H)
    acc = acc * corr[..., None] + blk_out
    row_sum = row_sum * corr + blk_sum
    return acc, new_max, row_sum


def ring_attention_block(q, k, v, axis_name: str = "sp",
                         causal: bool = False, scale: Optional[float] = None):
    """Per-shard ring attention body (call inside ``shard_map``).

    q, k, v: local blocks (B, T_blk, H, D); the global sequence is the
    concatenation over the ``axis_name`` mesh axis. Returns the local
    output block (B, T_blk, H, D).
    """
    B, Tq, H, D = q.shape
    n = lax.axis_index(axis_name)
    size = lax.psum(1, axis_name)
    if scale is None:
        scale = D ** -0.5
    q = q * scale

    acc = jnp.zeros(q.shape, jnp.float32)
    row_max = jnp.full((B, Tq, H), _NEG_INF, jnp.float32)
    row_sum = jnp.zeros((B, Tq, H), jnp.float32)
    # constants enter the loop unvarying over the mesh axis while the loop
    # body produces device-varying values; align the carry's varying type
    acc, row_max, row_sum = jax.tree_util.tree_map(
        lambda x: lax.pcast(x, (axis_name,), to="varying"),
        (acc, row_max, row_sum))
    qf = q.astype(jnp.float32)

    pos_q = n * Tq + jnp.arange(Tq)

    def body(step, carry):
        acc, row_max, row_sum, k_cur, v_cur = carry
        # after `step` rotations device n holds the block of device n-step
        src = (n - step) % size
        if causal:
            pos_k = src * Tq + jnp.arange(k_cur.shape[1])
            mask = jnp.where(pos_k[None, :] <= pos_q[:, None], 0.0, _NEG_INF)
        else:
            mask = None
        acc, row_max, row_sum = _stream_block(
            qf, k_cur.astype(jnp.float32), v_cur.astype(jnp.float32),
            acc, row_max, row_sum, mask)
        # rotate k/v one hop around the ring (device i -> i+1)
        perm = [(i, (i + 1) % size) for i in range(size)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return acc, row_max, row_sum, k_nxt, v_nxt

    carry = (acc, row_max, row_sum, k, v)
    carry = lax.fori_loop(0, size, body, carry)
    acc, row_max, row_sum = carry[:3]
    out = acc / jnp.maximum(row_sum[..., None], 1e-30)
    return out.astype(q.dtype)


def ring_self_attention(q, k, v, mesh: Optional[Mesh] = None,
                        axis_name: str = "sp", causal: bool = False,
                        scale: Optional[float] = None,
                        batch_axis: Optional[str] = "dp"):
    """Exact self-attention with the sequence sharded over ``axis_name``.

    q, k, v: global (B, T, H, D) arrays; T must divide by the ``sp`` axis
    size. Returns (B, T, H, D). Differentiable (jax traces through the
    ppermute ring), jit-safe, and composable with data parallelism via
    ``batch_axis``.
    """
    from . import mesh as _mesh_mod

    if mesh is None:
        mesh = _mesh_mod.default_mesh()
    if axis_name not in mesh.shape:
        raise MXNetError(f"mesh has no axis {axis_name!r}")
    sp = mesh.shape[axis_name]
    if q.shape[1] % sp != 0:
        raise MXNetError(
            f"sequence length {q.shape[1]} not divisible by {axis_name} "
            f"axis size {sp}")
    b_ax = batch_axis if batch_axis in mesh.shape else None
    spec = PartitionSpec(b_ax, axis_name, None, None)

    fn = partial(ring_attention_block, axis_name=axis_name, causal=causal,
                 scale=scale)
    mapped = jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                           out_specs=spec)
    return mapped(q, k, v)
