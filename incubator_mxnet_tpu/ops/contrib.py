"""Detection operators (SSD / Faster-RCNN family).

Parity targets (file-level citations — SURVEY.md caveat):
  - ``MultiBoxPrior/Target/Detection``: src/operator/contrib/multibox_*.cc
  - ``box_nms`` / ``box_iou``: src/operator/contrib/bounding_box.cc
  - ``ROIAlign``: src/operator/contrib/roi_align.cc
  - ``ROIPooling``: src/operator/roi_pooling.cc
  - ``Proposal``: src/operator/contrib/proposal.cc

TPU-native design: every op here is FIXED-SHAPE under jit — suppression,
matching and filtering are expressed as masks and ``lax`` loops instead of
the reference's dynamic-length CUDA kernels, so XLA can compile one static
program (scores set to -1 mark suppressed/invalid rows, the reference's own
sentinel convention)."""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

_VARIANCES = (0.1, 0.1, 0.2, 0.2)


def _corner_to_center(boxes):
    xmin, ymin, xmax, ymax = jnp.split(boxes, 4, axis=-1)
    return jnp.concatenate([(xmin + xmax) / 2, (ymin + ymax) / 2,
                            xmax - xmin, ymax - ymin], axis=-1)


def _center_to_corner(boxes):
    cx, cy, w, h = jnp.split(boxes, 4, axis=-1)
    return jnp.concatenate([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                           axis=-1)


def _pairwise_iou(lhs, rhs):
    """IoU between (..., N, 4) and (..., M, 4) corner boxes → (..., N, M)."""
    lt = jnp.maximum(lhs[..., :, None, :2], rhs[..., None, :, :2])
    rb = jnp.minimum(lhs[..., :, None, 2:], rhs[..., None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_l = jnp.maximum(lhs[..., 2] - lhs[..., 0], 0.0) * \
        jnp.maximum(lhs[..., 3] - lhs[..., 1], 0.0)
    area_r = jnp.maximum(rhs[..., 2] - rhs[..., 0], 0.0) * \
        jnp.maximum(rhs[..., 3] - rhs[..., 1], 0.0)
    union = area_l[..., :, None] + area_r[..., None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register("box_iou", aliases=("_contrib_box_iou",))
def box_iou(lhs, rhs, format="corner"):
    """Pairwise IoU (reference: bounding_box.cc box_iou)."""
    if format == "center":
        lhs = _center_to_corner(lhs)
        rhs = _center_to_corner(rhs)
    return _pairwise_iou(lhs, rhs)


@register("MultiBoxPrior", aliases=("multibox_prior",
                                    "_contrib_MultiBoxPrior"))
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Anchor generation (reference: multibox_prior.cc). ``data`` is the
    (B, C, H, W) feature map; returns (1, H*W*(S+R-1), 4) corner anchors
    in [0, 1] coordinates."""
    sizes = tuple(sizes) if not isinstance(sizes, (int, float)) else (sizes,)
    ratios = tuple(ratios) if not isinstance(ratios, (int, float)) \
        else (ratios,)
    H, W = data.shape[2], data.shape[3]
    step_y = steps[0] if steps[0] > 0 else 1.0 / H
    step_x = steps[1] if steps[1] > 0 else 1.0 / W
    cy = (jnp.arange(H, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(W, dtype=jnp.float32) + offsets[1]) * step_x
    cyx = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"), axis=-1)  # (H,W,2)

    # (size_i, ratio_0) for all i, then (size_0, ratio_j) for j >= 1
    wh = [(s * (ratios[0] ** 0.5), s / (ratios[0] ** 0.5)) for s in sizes]
    wh += [(sizes[0] * (r ** 0.5), sizes[0] / (r ** 0.5))
           for r in ratios[1:]]
    wh = jnp.asarray(wh, jnp.float32) / 2.0  # (A, 2) half (w, h)

    cxs = cyx[..., 1][..., None]  # (H,W,1)
    cys = cyx[..., 0][..., None]
    anchors = jnp.stack([
        cxs - wh[:, 0], cys - wh[:, 1], cxs + wh[:, 0], cys + wh[:, 1],
    ], axis=-1)  # (H, W, A, 4)
    anchors = anchors.reshape(1, -1, 4)
    if clip:
        anchors = jnp.clip(anchors, 0.0, 1.0)
    return anchors


def _nms_one(boxes, scores, ids, overlap_thresh, valid_thresh, topk,
             force_suppress):
    """Single-image greedy NMS; returns keep mask + score order."""
    N = scores.shape[0]
    order = jnp.argsort(-scores)
    boxes_s = boxes[order]
    scores_s = scores[order]
    ids_s = ids[order]
    valid = scores_s > valid_thresh
    if topk > 0:
        valid = valid & (jnp.arange(N) < topk)
    iou = _pairwise_iou(boxes_s, boxes_s)
    same_class = jnp.ones((N, N), bool) if force_suppress else \
        (ids_s[:, None] == ids_s[None, :])
    suppress_pair = (iou > overlap_thresh) & same_class

    def body(i, keep):
        # i suppresses later j only if i itself is kept and valid
        cond = keep[i] & valid[i]
        row = suppress_pair[i] & (jnp.arange(N) > i)
        return jnp.where(cond, keep & ~row, keep)

    keep = lax.fori_loop(0, N, body, jnp.ones((N,), bool))
    return keep & valid, order


@register("box_nms", aliases=("box_non_maximum_suppression",
                              "_contrib_box_nms"))
def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1,
            force_suppress=False, in_format="corner",
            out_format="corner", background_id=-1):
    """Greedy non-maximum suppression (reference: bounding_box.cc).
    data: (B, N, K) rows [.., score, .., x1, y1, x2, y2, ..]; suppressed
    rows get score -1 (fixed shape out, the reference's convention)."""
    squeeze = data.ndim == 2
    if squeeze:
        data = data[None]
    B, N, K = data.shape
    boxes = data[..., coord_start:coord_start + 4]
    if in_format == "center":
        boxes = _center_to_corner(boxes)
    scores = data[..., score_index]
    ids = data[..., id_index] if id_index >= 0 else \
        jnp.zeros_like(scores)

    if id_index >= 0 and background_id >= 0:
        # background-class rows never survive NMS (reference contract)
        scores = jnp.where(ids == background_id, -1.0, scores)

    def per_image(b, s, i, row):
        keep, order = _nms_one(b, s, i, overlap_thresh, valid_thresh,
                               topk, force_suppress)
        out = row[order]
        if out_format != in_format:
            bx = b[order] if out_format == "corner" else \
                _corner_to_center(b[order])
            out = out.at[..., coord_start:coord_start + 4].set(bx)
        out = out.at[..., score_index].set(
            jnp.where(keep, out[..., score_index], -1.0))
        if id_index >= 0:
            out = out.at[..., id_index].set(
                jnp.where(keep, out[..., id_index], -1.0))
        return out

    out = jax.vmap(per_image)(boxes, scores, ids, data)
    return out[0] if squeeze else out


@register("MultiBoxTarget", aliases=("multibox_target",
                                     "_contrib_MultiBoxTarget"),
          num_outputs=3)
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=_VARIANCES):
    """Anchor-to-ground-truth matching (reference: multibox_target.cc).

    anchor: (1, N, 4) corner; label: (B, M, 5) [cls x1 y1 x2 y2] with
    cls=-1 padding rows; cls_pred: (B, num_cls+1, N) (only used for hard
    negative mining). Returns (box_target (B, N*4), box_mask (B, N*4),
    cls_target (B, N))."""
    anchors = anchor.reshape(-1, 4)
    N = anchors.shape[0]
    B, M, _ = label.shape
    v = jnp.asarray(variances, jnp.float32)
    a_center = _corner_to_center(anchors)

    def per_image(lab, cp):
        gt_valid = lab[:, 0] >= 0  # (M,)
        gt_boxes = lab[:, 1:5]
        iou = _pairwise_iou(anchors, gt_boxes)  # (N, M)
        iou = jnp.where(gt_valid[None, :], iou, -1.0)

        # stage 1: greedy bipartite — each gt grabs its best anchor
        def bipartite(state, _):
            matched, iou_w = state
            flat = jnp.argmax(iou_w)
            ai, gi = flat // M, flat % M
            ok = iou_w[ai, gi] > 1e-12
            matched = jnp.where(ok, matched.at[ai].set(gi), matched)
            iou_w = jnp.where(ok, iou_w.at[ai, :].set(-1.0)
                              .at[:, gi].set(-1.0), iou_w)
            return (matched, iou_w), None

        matched0 = jnp.full((N,), -1, jnp.int32)
        (matched, _), _ = lax.scan(bipartite, (matched0, iou),
                                   None, length=M)

        # stage 2: anchors whose best IoU clears the threshold
        best_gt = jnp.argmax(iou, axis=1).astype(jnp.int32)
        best_iou = jnp.max(iou, axis=1)
        thresh_match = (best_iou >= overlap_threshold) & (matched < 0)
        matched = jnp.where(thresh_match, best_gt, matched)

        is_pos = matched >= 0
        gi = jnp.maximum(matched, 0)
        g_center = _corner_to_center(gt_boxes[gi])
        # encode offsets (the reference's variance-scaled parameterization)
        tx = (g_center[:, 0] - a_center[:, 0]) / a_center[:, 2] / v[0]
        ty = (g_center[:, 1] - a_center[:, 1]) / a_center[:, 3] / v[1]
        tw = jnp.log(jnp.maximum(g_center[:, 2], 1e-12)
                     / a_center[:, 2]) / v[2]
        th = jnp.log(jnp.maximum(g_center[:, 3], 1e-12)
                     / a_center[:, 3]) / v[3]
        box_t = jnp.stack([tx, ty, tw, th], axis=-1) * is_pos[:, None]
        box_m = jnp.broadcast_to(is_pos[:, None], (N, 4)).astype(jnp.float32)

        cls_t = jnp.where(is_pos, lab[gi, 0] + 1.0, 0.0)
        if negative_mining_ratio > 0:
            # hard negative mining: among ELIGIBLE negatives (best IoU
            # below negative_mining_thresh — near-matches are ignored,
            # the reference contract), keep the ratio-capped hardest
            # (lowest background confidence)
            eligible = (~is_pos) & (best_iou < negative_mining_thresh)
            bg_prob = jax.nn.softmax(cp, axis=0)[0]  # (N,)
            num_pos = jnp.sum(is_pos)
            max_neg = jnp.maximum(
                (negative_mining_ratio * num_pos).astype(jnp.int32),
                minimum_negative_samples)
            neg_scores = jnp.where(eligible, bg_prob, jnp.inf)
            rank = jnp.argsort(jnp.argsort(neg_scores))
            keep_neg = eligible & (rank < max_neg)
            cls_t = jnp.where(is_pos | keep_neg, cls_t, ignore_label)
        return box_t.reshape(-1), box_m.reshape(-1), cls_t

    return jax.vmap(per_image)(label, cls_pred)


@register("MultiBoxDetection", aliases=("multibox_detection",
                                        "_contrib_MultiBoxDetection"))
def multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                       threshold=0.01, background_id=0,
                       nms_threshold=0.5, force_suppress=False,
                       variances=_VARIANCES, nms_topk=-1):
    """Decode + per-class NMS (reference: multibox_detection.cc).

    cls_prob: (B, num_cls+1, N) softmax probs (class 0 background);
    loc_pred: (B, N*4); anchor: (1, N, 4). Returns (B, N, 6) rows
    [class_id, score, x1, y1, x2, y2], invalid rows class_id = -1."""
    B = cls_prob.shape[0]
    N = anchor.shape[1]
    v = jnp.asarray(variances, jnp.float32)
    a_center = _corner_to_center(anchor.reshape(-1, 4))

    def per_image(cp, lp):
        # decode
        off = lp.reshape(N, 4)
        cx = off[:, 0] * v[0] * a_center[:, 2] + a_center[:, 0]
        cy = off[:, 1] * v[1] * a_center[:, 3] + a_center[:, 1]
        w = jnp.exp(off[:, 2] * v[2]) * a_center[:, 2]
        h = jnp.exp(off[:, 3] * v[3]) * a_center[:, 3]
        boxes = _center_to_corner(jnp.stack([cx, cy, w, h], axis=-1))
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # argmax over ALL classes; background winning → invalid row.
        # foreground ids renumber past the background row (bg=0 → id-1,
        # the reference convention)
        best_all = jnp.argmax(cp, axis=0)
        fg = cp.at[background_id].set(-jnp.inf)
        scores = jnp.max(fg, axis=0)
        best_fg = jnp.argmax(fg, axis=0)
        cls_id = jnp.where(best_fg > background_id, best_fg - 1,
                           best_fg).astype(jnp.float32)
        valid = (scores > threshold) & (best_all != background_id)
        rows = jnp.concatenate([
            jnp.where(valid, cls_id, -1.0)[:, None],
            jnp.where(valid, scores, -1.0)[:, None], boxes], axis=-1)
        out = box_nms(rows, overlap_thresh=nms_threshold, valid_thresh=0.0,
                      topk=nms_topk, coord_start=2, score_index=1,
                      id_index=0, force_suppress=force_suppress)
        # box_nms marks suppressed via score/id -1; normalize class col
        return out.at[:, 0].set(jnp.where(out[:, 1] > 0, out[:, 0], -1.0))

    return jax.vmap(per_image)(cls_prob, loc_pred)


def _bilinear(feat, y, x):
    """feat: (C, H, W); y/x: scalar continuous coords → (C,) sample."""
    H, W = feat.shape[1], feat.shape[2]
    y = jnp.clip(y, 0.0, H - 1.0)
    x = jnp.clip(x, 0.0, W - 1.0)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    ly, lx = y - y0, x - x0
    return (feat[:, y0, x0] * (1 - ly) * (1 - lx)
            + feat[:, y0, x1] * (1 - ly) * lx
            + feat[:, y1, x0] * ly * (1 - lx)
            + feat[:, y1, x1] * ly * lx)


@register("ROIAlign", aliases=("roi_align", "_contrib_ROIAlign"))
def roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
              sample_ratio=2, position_sensitive=False, max_samples=8):
    """RoIAlign (reference: roi_align.cc — bilinear-sampled average per
    bin, no quantization). data: (B, C, H, W); rois: (R, 5)
    [batch_idx, x1, y1, x2, y2] in image coords.

    ``sample_ratio <= 0`` means ADAPTIVE (reference semantics:
    ceil(bin_size) samples per bin, per ROI). TPU design: a static grid
    with per-ROI validity weights — same math with static shapes for
    XLA, except the adaptive count is capped at ``max_samples`` per bin
    axis (the reference is uncapped; raise ``max_samples`` for parity on
    very large ROIs at quadratic compute cost)."""
    if isinstance(pooled_size, int):
        pooled_size = (pooled_size, pooled_size)
    PH, PW = pooled_size
    adaptive = int(sample_ratio) <= 0
    S = int(max_samples) if adaptive else max(int(sample_ratio), 1)

    def _ps_select(full):
        """(C*PH*PW, PH, PW) → (C, PH, PW): bin (i, j) reads its own
        channel group (R-FCN position-sensitive pooling)."""
        C = full.shape[0] // (PH * PW)
        grouped = full.reshape(C, PH * PW, PH, PW)
        bin_idx = (jnp.arange(PH)[:, None] * PW
                   + jnp.arange(PW)[None, :])  # (PH, PW)
        idx = jnp.broadcast_to(bin_idx[None, None], (C, 1, PH, PW))
        return jnp.take_along_axis(grouped, idx, axis=1)[:, 0]

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        feat = data[bidx]  # (C, H, W)
        x1, y1, x2, y2 = roi[1] * spatial_scale, roi[2] * spatial_scale, \
            roi[3] * spatial_scale, roi[4] * spatial_scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_h, bin_w = rh / PH, rw / PW
        # S x S bilinear samples per bin, averaged
        iy = jnp.arange(PH, dtype=jnp.float32)
        ix = jnp.arange(PW, dtype=jnp.float32)
        if adaptive:
            s_h = jnp.clip(jnp.ceil(bin_h), 1.0, float(S))
            s_w = jnp.clip(jnp.ceil(bin_w), 1.0, float(S))
        else:
            s_h = s_w = jnp.float32(S)
        j = jnp.arange(S, dtype=jnp.float32)
        sy = (j + 0.5) / s_h          # fractions; only j < s_h are valid
        sx = (j + 0.5) / s_w
        wy = (j < s_h).astype(jnp.float32)  # (S,)
        wx = (j < s_w).astype(jnp.float32)
        ys = y1 + (iy[:, None] + sy[None, :]) * bin_h  # (PH, S)
        xs = x1 + (ix[:, None] + sx[None, :]) * bin_w  # (PW, S)
        samp = jax.vmap(lambda yy: jax.vmap(
            lambda xx: _bilinear(feat, yy, xx))(xs.reshape(-1)))(
                ys.reshape(-1))  # (PH*S, PW*S, C)
        samp = samp.reshape(PH, S, PW, S, -1)
        w = wy[None, :, None, None, None] * wx[None, None, None, :, None]
        out = ((samp * w).sum(axis=(1, 3)) / (s_h * s_w)) \
            .transpose(2, 0, 1)  # (C,PH,PW)
        if position_sensitive:
            out = _ps_select(out)
        return out

    return jax.vmap(one_roi)(rois)


@register("ROIPooling", aliases=("roi_pooling",))
def roi_pooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0):
    """RoI max pooling with the reference's quantized-bin semantics
    (reference: roi_pooling.cc). TPU design: each rectangular bin's max is
    two separable masked maxes (rows then cols) — exact integer-pixel
    pooling with fully static shapes for XLA."""
    if isinstance(pooled_size, int):
        pooled_size = (pooled_size, pooled_size)
    PH, PW = pooled_size
    H, W = data.shape[2], data.shape[3]
    neg = jnp.asarray(-jnp.inf, data.dtype)

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        feat = data[bidx]  # (C, H, W)
        x1 = jnp.round(roi[1] * spatial_scale)
        y1 = jnp.round(roi[2] * spatial_scale)
        x2 = jnp.round(roi[3] * spatial_scale)
        y2 = jnp.round(roi[4] * spatial_scale)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        bin_h, bin_w = rh / PH, rw / PW
        iy = jnp.arange(PH, dtype=jnp.float32)
        ix = jnp.arange(PW, dtype=jnp.float32)
        hs = jnp.floor(y1 + iy * bin_h)
        he = jnp.maximum(jnp.ceil(y1 + (iy + 1) * bin_h), hs + 1)
        ws = jnp.floor(x1 + ix * bin_w)
        we = jnp.maximum(jnp.ceil(x1 + (ix + 1) * bin_w), ws + 1)
        rows = jnp.arange(H, dtype=jnp.float32)
        cols = jnp.arange(W, dtype=jnp.float32)
        mask_y = (rows[None] >= hs[:, None]) & (rows[None] < he[:, None])
        mask_x = (cols[None] >= ws[:, None]) & (cols[None] < we[:, None])
        # separable rectangular max: over rows, then over cols
        rowmax = jnp.max(jnp.where(mask_y[None, :, :, None],
                                   feat[:, None, :, :], neg), axis=2)
        out = jnp.max(jnp.where(mask_x[None, None, :, :],
                                rowmax[:, :, None, :], neg), axis=3)
        return jnp.where(jnp.isfinite(out), out, 0.0)  # empty bin -> 0

    return jax.vmap(one_roi)(rois)


@register("Proposal", aliases=("proposal", "_contrib_Proposal"))
def proposal(cls_prob, bbox_pred, im_info, scales=(4, 8, 16, 32),
             ratios=(0.5, 1.0, 2.0), rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             feature_stride=16):
    """RPN proposal generation (reference: proposal.cc). cls_prob:
    (B, 2*A, H, W); bbox_pred: (B, 4*A, H, W); im_info: (B, 3)
    [height, width, scale]. Returns (B, post_top_n, 5)
    [batch_idx, x1, y1, x2, y2] (fixed shape; invalid rows all-zero)."""
    B, _, H, W = cls_prob.shape
    A = len(scales) * len(ratios)

    # base anchors centered on each stride cell (image coordinates)
    base = []
    cs = feature_stride / 2.0
    for r in ratios:
        for s in scales:
            size = feature_stride * s
            w_half = size * (1.0 / r) ** 0.5 / 2.0
            h_half = size * (r ** 0.5) / 2.0
            base.append([cs - w_half, cs - h_half, cs + w_half, cs + h_half])
    base = jnp.asarray(base, jnp.float32)  # (A, 4)
    shift_x = jnp.arange(W, dtype=jnp.float32) * feature_stride
    shift_y = jnp.arange(H, dtype=jnp.float32) * feature_stride
    sy, sx = jnp.meshgrid(shift_y, shift_x, indexing="ij")
    shifts = jnp.stack([sx, sy, sx, sy], axis=-1).reshape(-1, 1, 4)
    anchors = (base[None] + shifts).reshape(-1, 4)  # (H*W*A, 4)

    def per_image(cp, bp, info):
        scores = cp[A:].transpose(1, 2, 0).reshape(-1)  # fg scores
        deltas = bp.transpose(1, 2, 0).reshape(-1, 4)
        ac = _corner_to_center(anchors)
        cx = deltas[:, 0] * ac[:, 2] + ac[:, 0]
        cy = deltas[:, 1] * ac[:, 3] + ac[:, 1]
        w = jnp.exp(jnp.clip(deltas[:, 2], -10, 10)) * ac[:, 2]
        h = jnp.exp(jnp.clip(deltas[:, 3], -10, 10)) * ac[:, 3]
        boxes = _center_to_corner(jnp.stack([cx, cy, w, h], -1))
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, info[1] - 1),
            jnp.clip(boxes[:, 1], 0, info[0] - 1),
            jnp.clip(boxes[:, 2], 0, info[1] - 1),
            jnp.clip(boxes[:, 3], 0, info[0] - 1)], -1)
        min_size = rpn_min_size * info[2]
        ok = ((boxes[:, 2] - boxes[:, 0] + 1) >= min_size) & \
             ((boxes[:, 3] - boxes[:, 1] + 1) >= min_size)
        scores = jnp.where(ok, scores, -1.0)

        k = min(rpn_pre_nms_top_n, scores.shape[0])
        top_scores, idx = lax.top_k(scores, k)
        top_boxes = boxes[idx]
        keep, order = _nms_one(top_boxes, top_scores,
                               jnp.zeros_like(top_scores), threshold,
                               -1.0, -1, True)
        kept_scores = jnp.where(keep, top_scores[order], -1.0)
        kept_boxes = top_boxes[order]
        k2 = min(rpn_post_nms_top_n, kept_scores.shape[0])
        _, idx2 = lax.top_k(kept_scores, k2)
        final = kept_boxes[idx2] * (kept_scores[idx2] > 0)[:, None]
        pad = rpn_post_nms_top_n - k2
        if pad > 0:
            final = jnp.pad(final, ((0, pad), (0, 0)))
        return final

    out = jax.vmap(per_image)(cls_prob, bbox_pred, im_info)
    bidx = jnp.broadcast_to(
        jnp.arange(B, dtype=out.dtype)[:, None, None],
        (B, out.shape[1], 1))
    return jnp.concatenate([bidx, out], axis=-1)


# --------------------------------------------------------------------- #
# round-3 contrib batch: box codecs, matching, adaptive pooling, misc
# (reference: src/operator/contrib/{bounding_box.cc,adaptive_avg_pooling.cc,
# index_copy.cc,gradient_multiplier_op.cc,optimizer_op.cc} — file-level
# citations, SURVEY.md caveat)
# --------------------------------------------------------------------- #

@register("box_encode", aliases=("_contrib_box_encode",),
          num_outputs=2)
def box_encode(samples, matches, anchors, refs, means=(0., 0., 0., 0.),
               stds=(0.1, 0.1, 0.2, 0.2)):
    """SSD-style corner-box regression-target encoding.

    samples (B,N) in {+1,0,-1}; matches (B,N) ref indices; anchors (B,N,4)
    and refs (B,M,4) corner boxes. Returns (targets (B,N,4), masks (B,N,4)).
    """
    matched = jnp.take_along_axis(
        refs, matches[..., None].astype(jnp.int32), axis=1)  # (B,N,4)

    def _cxywh(b):
        w = b[..., 2] - b[..., 0]
        h = b[..., 3] - b[..., 1]
        return b[..., 0] + 0.5 * w, b[..., 1] + 0.5 * h, w, h

    ax, ay, aw, ah = _cxywh(anchors)
    gx, gy, gw, gh = _cxywh(matched)
    means = jnp.asarray(means, anchors.dtype)
    stds = jnp.asarray(stds, anchors.dtype)
    t = jnp.stack([
        ((gx - ax) / jnp.maximum(aw, 1e-12) - means[0]) / stds[0],
        ((gy - ay) / jnp.maximum(ah, 1e-12) - means[1]) / stds[1],
        (jnp.log(jnp.maximum(gw / jnp.maximum(aw, 1e-12), 1e-12))
         - means[2]) / stds[2],
        (jnp.log(jnp.maximum(gh / jnp.maximum(ah, 1e-12), 1e-12))
         - means[3]) / stds[3]], axis=-1)
    mask = (samples > 0.5)[..., None]
    return jnp.where(mask, t, 0.0), mask.astype(anchors.dtype) * \
        jnp.ones_like(t)


@register("box_decode", aliases=("_contrib_box_decode",))
def box_decode(data, anchors, std0=0.1, std1=0.1, std2=0.2, std3=0.2,
               clip=-1.0, format="corner"):
    """Invert box_encode: deltas (B,N,4) + anchors (1|B,N,4) → corner
    boxes (B,N,4)."""
    a = anchors
    if format == "corner":
        aw = a[..., 2] - a[..., 0]
        ah = a[..., 3] - a[..., 1]
        ax = a[..., 0] + 0.5 * aw
        ay = a[..., 1] + 0.5 * ah
    else:
        ax, ay, aw, ah = (a[..., 0], a[..., 1], a[..., 2], a[..., 3])
    ox = data[..., 0] * std0 * aw + ax
    oy = data[..., 1] * std1 * ah + ay
    dw, dh = data[..., 2] * std2, data[..., 3] * std3
    if clip > 0:
        dw = jnp.minimum(dw, clip)
        dh = jnp.minimum(dh, clip)
    ow = jnp.exp(dw) * aw * 0.5
    oh = jnp.exp(dh) * ah * 0.5
    return jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=-1)


@register("bipartite_matching", aliases=("_contrib_bipartite_matching",),
          num_outputs=2)
def bipartite_matching(data, is_ascend=False, threshold=0.5, topk=-1):
    """Greedy bipartite matching over a (..., N, M) score matrix
    (reference bipartite_matching). Returns (row→col match or -1, col→row
    anchor index). Implemented as a lax.scan over min(N,M) greedy picks —
    fixed trip count, jit-friendly."""
    scores = data
    N, M = scores.shape[-2], scores.shape[-1]
    lead = scores.shape[:-2]
    flat = scores.reshape((-1, N, M))
    big = jnp.asarray(1e30, flat.dtype)
    sgn = 1.0 if not is_ascend else -1.0
    K = min(N, M) if topk < 0 else min(topk, min(N, M))

    def per(mat):
        def body(carry, _):
            m, row_used, col_used = carry
            eff = jnp.where(row_used[:, None] | col_used[None, :],
                            -big, sgn * m)
            idx = jnp.argmax(eff)
            r, c = idx // M, idx % M
            # accept: score >= thresh (descending) / score <= thresh
            # (ascending) — both are `eff >= sgn*thresh` on the sign-
            # flipped matrix (reference bipartite_matching contract)
            ok = eff.reshape(-1)[idx] >= sgn * threshold
            m_match = jnp.where(ok, c, -1)
            row_used = row_used.at[r].set(row_used[r] | ok)
            col_used = col_used.at[c].set(col_used[c] | ok)
            return (m, row_used, col_used), (r, m_match, c)

        (_, _, _), (rows, rmatch, cols) = lax.scan(
            body, (mat, jnp.zeros(N, bool), jnp.zeros(M, bool)),
            None, length=K)
        row_out = jnp.full((N,), -1, jnp.int32)
        row_out = row_out.at[rows].set(
            jnp.where(rmatch >= 0, rmatch, row_out[rows]).astype(jnp.int32))
        col_out = jnp.full((M,), -1, jnp.int32)
        col_out = col_out.at[cols].set(
            jnp.where(rmatch >= 0, rows, col_out[cols]).astype(jnp.int32))
        return row_out, col_out

    row, col = jax.vmap(per)(flat)
    return (row.reshape(lead + (N,)).astype(data.dtype),
            col.reshape(lead + (M,)).astype(data.dtype))


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _grad_mult(x, scalar):
    return x


def _grad_mult_fwd(x, scalar):
    return x, None


def _grad_mult_bwd(scalar, _, g):
    return (g * scalar,)


_grad_mult.defvjp(_grad_mult_fwd, _grad_mult_bwd)


@register("gradientmultiplier", aliases=("_contrib_gradientmultiplier",))
def gradientmultiplier(data, scalar=1.0):
    """Identity forward, gradient scaled by ``scalar`` (reference
    gradient_multiplier_op.cc — the GAN/DANN gradient-reversal trick)."""
    return _grad_mult(data, float(scalar))


@register("group_adagrad_update", aliases=("_contrib_group_adagrad_update",),
          num_outputs=2)
def group_adagrad_update(weight, grad, history, lr=0.01, rescale_grad=1.0,
                         clip_gradient=-1.0, epsilon=1e-5):
    """Row-wise AdaGrad (reference optimizer_op.cc GroupAdagrad — the
    embedding-friendly variant: one accumulator per row).

    Conventions (upstream `python/mxnet/optimizer/contrib.py` GroupAdaGrad
    documents ``div = grad / (sqrt(history) + epsilon)`` — epsilon sits
    OUTSIDE the sqrt, unlike plain AdaGrad's ``sqrt(history + eps)``).
    ``history`` may be (N,) or the reference's keepdims (N, 1, ...) shape;
    the returned accumulator keeps the caller's shape."""
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    hist_shape = history.shape
    history = history.reshape(history.shape[0])
    red_axes = tuple(range(1, g.ndim))
    mean_sq = jnp.mean(jnp.square(g), axis=red_axes) if red_axes else \
        jnp.square(g)
    new_hist = history + mean_sq
    denom = jnp.sqrt(new_hist) + epsilon
    shape = (-1,) + (1,) * (g.ndim - 1)
    return (weight - lr * g / denom.reshape(shape),
            new_hist.reshape(hist_shape))


# --------------------------------------------------------------------- #
# deformable convolution v1/v2 (reference:
# src/operator/contrib/deformable_convolution.cc and
# modulated_deformable_convolution.cc — file-level citations, SURVEY.md
# caveat). TPU-native design: the deformed sampling grid is materialized
# as (K2, Ho, Wo) pixel coordinates, bilinear taps become four clipped
# gathers with validity weights (static shapes, no scatter), and the
# final contraction over (C_in/group, K2) is ONE einsum that XLA maps
# onto the MXU — replacing the reference's im2col+GEMM CUDA pipeline.
# --------------------------------------------------------------------- #

def _deform_conv_core(data, offset, weight, bias, kernel, stride, dilate,
                      pad, num_filter, num_group, num_deformable_group,
                      mask=None):
    from .vision import _grid_sample_zero_pad
    B, C, H, W = data.shape
    kh, kw = kernel
    sh, sw = stride
    dh, dw = dilate
    ph, pw = pad
    Ho = (H + 2 * ph - ((kh - 1) * dh + 1)) // sh + 1
    Wo = (W + 2 * pw - ((kw - 1) * dw + 1)) // sw + 1
    K2 = kh * kw
    G = num_deformable_group

    # base sampling grid: (K2, Ho, Wo) pixel coords
    oy = jnp.arange(Ho) * sh - ph
    ox = jnp.arange(Wo) * sw - pw
    ky = jnp.arange(kh) * dh
    kx = jnp.arange(kw) * dw
    base_y = (ky[:, None, None, None] +
              jnp.zeros((kw,))[None, :, None, None] +
              oy[None, None, :, None] +
              jnp.zeros((Wo,))[None, None, None, :])
    base_x = (jnp.zeros((kh,))[:, None, None, None] +
              kx[None, :, None, None] +
              jnp.zeros((Ho,))[None, None, :, None] +
              ox[None, None, None, :])
    base_y = base_y.reshape(K2, Ho, Wo)
    base_x = base_x.reshape(K2, Ho, Wo)

    off = offset.reshape(B, G, K2, 2, Ho, Wo).astype(jnp.float32)
    dy, dx = off[:, :, :, 0], off[:, :, :, 1]          # (B, G, K2, Ho, Wo)
    if mask is not None:
        mk = mask.reshape(B, G, K2, Ho, Wo).astype(jnp.float32)

    Cg = C // G

    def per_image(feat, dyi, dxi, mki):
        # feat (C,H,W); dyi/dxi (G,K2,Ho,Wo)
        groups = []
        for g in range(G):
            ys = base_y + dyi[g]
            xs = base_x + dxi[g]
            s = _grid_sample_zero_pad(feat[g * Cg:(g + 1) * Cg], ys, xs)
            if mki is not None:
                s = s * mki[g][None]
            groups.append(s)                            # (Cg, K2, Ho, Wo)
        return jnp.concatenate(groups, axis=0)          # (C, K2, Ho, Wo)

    if mask is not None:
        sampled = jax.vmap(per_image)(data.astype(jnp.float32), dy, dx, mk)
    else:
        sampled = jax.vmap(lambda f, a, b: per_image(f, a, b, None))(
            data.astype(jnp.float32), dy, dx)

    # grouped contraction: weight (O, C/num_group, kh, kw)
    Og = num_filter // num_group
    Cng = C // num_group
    w = weight.reshape(num_group, Og, Cng, K2).astype(jnp.float32)
    x = sampled.reshape(B, num_group, Cng, K2, Ho, Wo)
    out = jnp.einsum("bgckhw,gock->bgohw", x, w)
    out = out.reshape(B, num_filter, Ho, Wo)
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out.astype(data.dtype)


@register("DeformableConvolution",
          aliases=("_contrib_DeformableConvolution",))
def deformable_convolution(data, offset, weight, bias=None, kernel=(3, 3),
                           stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                           num_filter=0, num_group=1,
                           num_deformable_group=1, no_bias=False):
    """Deformable convolution v1 (Dai et al. 2017). ``offset``
    (B, 2*K2*deform_groups, Ho, Wo) carries per-tap (dy, dx)."""
    kernel = (kernel, kernel) if isinstance(kernel, int) else tuple(kernel)
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    dilate = (dilate, dilate) if isinstance(dilate, int) else tuple(dilate)
    pad = (pad, pad) if isinstance(pad, int) else tuple(pad)
    return _deform_conv_core(data, offset, weight,
                             None if no_bias else bias, kernel, stride,
                             dilate, pad, num_filter, num_group,
                             num_deformable_group)


@register("ModulatedDeformableConvolution",
          aliases=("_contrib_ModulatedDeformableConvolution",))
def modulated_deformable_convolution(data, offset, mask, weight, bias=None,
                                     kernel=(3, 3), stride=(1, 1),
                                     dilate=(1, 1), pad=(0, 0),
                                     num_filter=0, num_group=1,
                                     num_deformable_group=1, no_bias=False):
    """Deformable convolution v2 (Zhu et al. 2019): adds a sigmoid-gated
    per-tap modulation ``mask`` (B, K2*deform_groups, Ho, Wo)."""
    kernel = (kernel, kernel) if isinstance(kernel, int) else tuple(kernel)
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    dilate = (dilate, dilate) if isinstance(dilate, int) else tuple(dilate)
    pad = (pad, pad) if isinstance(pad, int) else tuple(pad)
    return _deform_conv_core(data, offset, weight,
                             None if no_bias else bias, kernel, stride,
                             dilate, pad, num_filter, num_group,
                             num_deformable_group, mask=mask)


# --------------------------------------------------------------------- #
# round-3 contrib batch 2 (reference: src/operator/contrib/
# {count_sketch.cc,hawkes_ll.cc,mrcnn_mask_target.cu} — file-level
# citations, SURVEY.md caveat)
# --------------------------------------------------------------------- #

@register("count_sketch", aliases=("_contrib_count_sketch",))
def count_sketch(data, h, s, out_dim=0, processing_batch_size=32):
    """Count-sketch projection (compact bilinear pooling building block).

    data (B, D) is scattered into (B, out_dim): out[b, h[d]] += s[d] *
    data[b, d]. ``h``/``s`` are the (D,) bucket indices / ±1 signs. One
    segment-sum scatter-add on TPU (no atomics, unlike the reference's
    CUDA kernel)."""
    out_dim = int(out_dim)
    if out_dim <= 0:
        from ..base import MXNetError
        raise MXNetError("count_sketch requires out_dim > 0")
    hh = h.reshape(-1).astype(jnp.int32)
    ss = s.reshape(-1).astype(data.dtype)
    signed = data * ss[None, :]
    out = jnp.zeros(data.shape[:-1] + (out_dim,), data.dtype)
    return out.at[..., hh].add(signed)


@register("hawkes_ll", aliases=("_contrib_hawkes_ll",), num_outputs=2)
def hawkes_ll(lda, alpha, beta, state, lags, marks, valid_length,
              max_time):
    """Log-likelihood of a marked multivariate Hawkes process with
    exponential kernel (reference hawkes_ll.cc).

    lda (K,)/alpha (K,)/beta (K,): per-mark background rate, excitation
    and decay; state (B, K): kernel state at the interval start;
    lags/marks (B, T): inter-arrival times and mark ids; valid_length
    (B,): events per sequence; max_time: observation horizon. Returns
    (loglik (B,), new_state (B, K)). A lax.scan over the T axis — the
    recurrence is sequential by definition."""
    B, T = lags.shape
    K = lda.shape[0]
    lda_ = lda.reshape(1, K)
    alpha_ = alpha.reshape(1, K)
    beta_ = beta.reshape(1, K)
    marks_i = marks.astype(jnp.int32)
    vl = valid_length.astype(jnp.int32)

    def step(carry, t):
        ll, st, elapsed = carry
        lag_t = lags[:, t].reshape(B, 1)
        mark_t = marks_i[:, t]
        valid = (t < vl).reshape(B)
        decay = jnp.exp(-beta_ * lag_t)
        st_dec = st * decay
        intensity = lda_ + st_dec                     # (B, K)
        lam = jnp.take_along_axis(intensity, mark_t[:, None], axis=1)[:, 0]
        # compensator increment over this interval, all marks
        comp = jnp.sum(lda_ * lag_t + (st / beta_) * (1.0 - decay), axis=1)
        contrib_ll = jnp.log(jnp.maximum(lam, 1e-30)) - comp
        ll = ll + jnp.where(valid, contrib_ll, 0.0)
        add = jnp.zeros((B, K), st.dtype).at[
            jnp.arange(B), mark_t].set(alpha_[0, mark_t] * beta_[0, mark_t])
        st = jnp.where(valid.reshape(B, 1), st_dec + add, st)
        elapsed = elapsed + jnp.where(valid, lag_t[:, 0], 0.0)
        return (ll, st, elapsed), None

    init = (jnp.zeros((B,), jnp.float32),
            state.astype(jnp.float32),
            jnp.zeros((B,), jnp.float32))
    (ll, st, elapsed), _ = lax.scan(step, init, jnp.arange(T))
    # tail compensator from the last event to max_time
    rem = jnp.maximum(max_time - elapsed, 0.0).reshape(B, 1)
    decay = jnp.exp(-beta_ * rem)
    tail = jnp.sum(lda_ * rem + (st / beta_) * (1.0 - decay), axis=1)
    ll = ll - tail
    st = st * decay
    return ll, st


@register("mrcnn_mask_target", aliases=("_contrib_mrcnn_mask_target",),
          num_outputs=2)
def mrcnn_mask_target(rois, gt_masks, matches, cls_targets,
                      num_rois=None, num_classes=None, mask_size=(14, 14)):
    """Mask-RCNN training targets (reference mrcnn_mask_target.cu):
    crop each matched instance mask to its ROI and resize to
    ``mask_size``; returns (mask_targets (B, N, C, H, W), mask_cls
    (B, N, C, H, W) one-hot weights). rois (B, N, 4) corner; gt_masks
    (B, M, IH, IW); matches (B, N); cls_targets (B, N)."""
    from .vision import _grid_sample_zero_pad
    B, N = matches.shape[:2]
    M, IH, IW = gt_masks.shape[1:]
    mh, mw = (mask_size, mask_size) if isinstance(mask_size, int) \
        else tuple(mask_size)
    if not num_classes:
        from ..base import MXNetError
        raise MXNetError("mrcnn_mask_target requires num_classes (the "
                         "class count cannot be derived from a traced "
                         "cls_targets array)")
    C = int(num_classes)

    def per_image(roi, gmask, match, cls_t):
        picked = gmask[match.astype(jnp.int32)]          # (N, IH, IW)

        def crop(m, box):
            x1, y1, x2, y2 = box[0], box[1], box[2], box[3]
            ys = y1 + (y2 - y1) * (jnp.arange(mh) + 0.5) / mh
            xs = x1 + (x2 - x1) * (jnp.arange(mw) + 0.5) / mw
            grid_y = jnp.broadcast_to(ys[:, None], (mh, mw))
            grid_x = jnp.broadcast_to(xs[None, :], (mh, mw))
            return _grid_sample_zero_pad(m[None], grid_y, grid_x)[0]

        cropped = jax.vmap(crop)(picked, roi)            # (N, mh, mw)
        onehot = jax.nn.one_hot(cls_t.astype(jnp.int32), C,
                                dtype=cropped.dtype)     # (N, C)
        targets = cropped[:, None] * onehot[..., None, None]
        weights = jnp.broadcast_to(onehot[..., None, None],
                                   (N, C, mh, mw))
        return targets, weights

    return jax.vmap(per_image)(rois, gt_masks, matches, cls_targets)
