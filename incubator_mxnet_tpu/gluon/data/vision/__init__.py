"""Vision datasets & transforms (re-design of
`python/mxnet/gluon/data/vision/` — SURVEY.md §2.2)."""

from . import datasets
from .datasets import (MNIST, FashionMNIST, CIFAR10, CIFAR100,
                       ImageFolderDataset, ImageRecordDataset)
from . import transforms

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageFolderDataset", "ImageRecordDataset",
           "transforms"]
