"""``mx.rtc`` — runtime kernel compilation (gated).

The reference's ``mx.rtc.CudaModule`` compiles CUDA C at runtime via NVRTC
(`src/common/rtc.cc` — file-level citation, SURVEY.md caveat). On TPU the
runtime-codegen capability is **Pallas**: write the kernel as a Python
function and ``pallas_call`` compiles it for the MXU/VPU — see
ops/pallas_attention.py for a worked example and
/opt/skills/guides/pallas_guide.md. CUDA source strings are not
translatable, so this module is an explicit gate, not a stub."""

from __future__ import annotations

from .base import MXNetError

__all__ = ["CudaModule"]

_MSG = ("mx.rtc.CudaModule compiles CUDA C, which has no TPU analogue. "
        "Write the kernel as a Pallas function instead (jax.experimental."
        "pallas; see incubator_mxnet_tpu/ops/pallas_attention.py for the "
        "pattern) or as a registered op (incubator_mxnet_tpu.ops."
        "registry.register) — both JIT-compile for the TPU at runtime.")


class CudaModule:
    def __init__(self, *args, **kwargs):
        raise MXNetError(_MSG)
