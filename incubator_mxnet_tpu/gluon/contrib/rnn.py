"""gluon.contrib.rnn — experimental recurrent cells.

Parity target: `python/mxnet/gluon/contrib/rnn/` (Conv*LSTM/GRU cells,
LSTMPCell with hidden-state projection, VariationalDropoutCell — file-level
citations, SURVEY.md caveat).

TPU-native design: each cell is a pure step function over (input, states);
the unroll driver (`rnn.rnn_cell` unroll / `lax.scan` in the fused op) is
shared with the core cells, so conv recurrences compile into one scanned
XLA program rather than the reference's per-step imperative launches. The
conv cells reuse the registry ``Convolution`` op, which lowers to a single
MXU-tiled `lax.conv_general_dilated`.
"""

from __future__ import annotations

from ...base import MXNetError
from ..rnn.rnn_cell import RecurrentCell, ModifierCell
from ..block import HybridBlock

__all__ = ["Conv2DLSTMCell", "Conv2DGRUCell", "Conv2DRNNCell",
           "LSTMPCell", "VariationalDropoutCell"]


class _BaseConvCell(RecurrentCell):
    """Shared plumbing for convolutional recurrent cells.

    ``input_shape`` is (C, H, W); spatial dims are preserved (same-pad).
    Gate pre-activations are ``conv(x; Wi) + conv(h; Wh) + b``.
    """

    _gates = 1

    def __init__(self, input_shape, hidden_channels, i2h_kernel=(3, 3),
                 h2h_kernel=(3, 3), i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if isinstance(i2h_kernel, int):
            i2h_kernel = (i2h_kernel, i2h_kernel)
        if isinstance(h2h_kernel, int):
            h2h_kernel = (h2h_kernel, h2h_kernel)
        if any(k % 2 == 0 for k in i2h_kernel) or \
                any(k % 2 == 0 for k in h2h_kernel):
            raise MXNetError("i2h_kernel and h2h_kernel must be odd for "
                             "same-padding (spatial dims are preserved)")
        self._input_shape = tuple(input_shape)
        self._channels = hidden_channels
        self._i2h_kernel = tuple(i2h_kernel)
        self._h2h_kernel = tuple(h2h_kernel)
        in_c = self._input_shape[0]
        G = self._gates
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight",
                shape=(G * hidden_channels, in_c) + self._i2h_kernel,
                init=i2h_weight_initializer)
            self.h2h_weight = self.params.get(
                "h2h_weight",
                shape=(G * hidden_channels, hidden_channels)
                + self._h2h_kernel,
                init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(G * hidden_channels,),
                init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(G * hidden_channels,),
                init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        shape = (batch_size, self._channels) + self._input_shape[1:]
        n = 2 if isinstance(self, Conv2DLSTMCell) else 1
        return [{"shape": shape, "__layout__": "NCHW"}] * n

    def _pre(self, F, x, h, i2h_weight, h2h_weight, i2h_bias, h2h_bias):
        G = self._gates
        ip = tuple(k // 2 for k in self._i2h_kernel)
        hp = tuple(k // 2 for k in self._h2h_kernel)
        gx = F.Convolution(x, i2h_weight, i2h_bias,
                           kernel=self._i2h_kernel, pad=ip,
                           num_filter=G * self._channels)
        gh = F.Convolution(h, h2h_weight, h2h_bias,
                           kernel=self._h2h_kernel, pad=hp,
                           num_filter=G * self._channels)
        return gx + gh


class Conv2DRNNCell(_BaseConvCell):
    """h' = act(conv(x) + conv(h)) (parity: contrib.rnn.Conv2DRNNCell)."""

    _gates = 1

    def __init__(self, input_shape, hidden_channels, activation="tanh",
                 **kwargs):
        super().__init__(input_shape, hidden_channels, **kwargs)
        self._activation = activation

    def _alias(self):
        return "conv_rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight=None,
                       h2h_weight=None, i2h_bias=None, h2h_bias=None):
        pre = self._pre(F, inputs, states[0], i2h_weight, h2h_weight,
                        i2h_bias, h2h_bias)
        out = F.Activation(pre, act_type=self._activation)
        return out, [out]


class Conv2DLSTMCell(_BaseConvCell):
    """ConvLSTM (Shi et al. 2015; parity: contrib.rnn.Conv2DLSTMCell).
    Gate order ``i, f, g, o`` matches the core LSTMCell."""

    _gates = 4

    def _alias(self):
        return "conv_lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight=None,
                       h2h_weight=None, i2h_bias=None, h2h_bias=None):
        h, c = states
        pre = self._pre(F, inputs, h, i2h_weight, h2h_weight, i2h_bias,
                        h2h_bias)
        i, f, g, o = F.split(pre, num_outputs=4, axis=1)
        i = F.sigmoid(i)
        f = F.sigmoid(f)
        g = F.tanh(g)
        o = F.sigmoid(o)
        c2 = f * c + i * g
        h2 = o * F.tanh(c2)
        return h2, [h2, c2]


class Conv2DGRUCell(_BaseConvCell):
    """ConvGRU, gate order ``r, z, n`` (parity: contrib.rnn.Conv2DGRUCell)."""

    _gates = 3

    def _alias(self):
        return "conv_gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight=None,
                       h2h_weight=None, i2h_bias=None, h2h_bias=None):
        h = states[0]
        G = self._gates
        ip = tuple(k // 2 for k in self._i2h_kernel)
        hp = tuple(k // 2 for k in self._h2h_kernel)
        gx = F.Convolution(inputs, i2h_weight, i2h_bias,
                           kernel=self._i2h_kernel, pad=ip,
                           num_filter=G * self._channels)
        gh = F.Convolution(h, h2h_weight, h2h_bias,
                           kernel=self._h2h_kernel, pad=hp,
                           num_filter=G * self._channels)
        xr, xz, xn = F.split(gx, num_outputs=3, axis=1)
        hr, hz, hn = F.split(gh, num_outputs=3, axis=1)
        r = F.sigmoid(xr + hr)
        z = F.sigmoid(xz + hz)
        n = F.tanh(xn + r * hn)
        out = (1.0 - z) * n + z * h
        return out, [out]


class LSTMPCell(RecurrentCell):
    """LSTM with a projection of the hidden state (LSTMP, Sak et al. 2014;
    parity: contrib.rnn.LSTMPCell). The cell state has ``hidden_size``
    units; the output/recurrent state is projected to ``projection_size``.
    """

    def __init__(self, hidden_size, projection_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, projection_size),
                init=h2h_weight_initializer)
            self.h2r_weight = self.params.get(
                "h2r_weight", shape=(projection_size, hidden_size),
                init=h2r_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,),
                init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,),
                init=h2h_bias_initializer)

    def infer_shape(self, inputs, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, inputs.shape[-1])

    def state_info(self, batch_size=0):
        return [
            {"shape": (batch_size, self._projection_size),
             "__layout__": "NC"},
            {"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
        ]

    def _alias(self):
        return "lstmp"

    def hybrid_forward(self, F, inputs, states, i2h_weight=None,
                       h2h_weight=None, h2r_weight=None, i2h_bias=None,
                       h2h_bias=None):
        r, c = states
        G = 4 * self._hidden_size
        gates = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                                 num_hidden=G) + \
            F.FullyConnected(r, h2h_weight, h2h_bias, num_hidden=G)
        i, f, g, o = F.split(gates, num_outputs=4, axis=-1)
        i = F.sigmoid(i)
        f = F.sigmoid(f)
        g = F.tanh(g)
        o = F.sigmoid(o)
        c2 = f * c + i * g
        h2 = o * F.tanh(c2)
        r2 = F.FullyConnected(h2, h2r_weight, None, no_bias=True,
                              num_hidden=self._projection_size)
        return r2, [r2, c2]


class VariationalDropoutCell(ModifierCell):
    """Same dropout mask reused at every time step (Gal & Ghahramani 2016;
    parity: contrib.rnn.VariationalDropoutCell). Masks are drawn once per
    unroll (``reset`` clears them) for inputs, states and outputs."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        super().__init__(base_cell)
        self._drop_inputs = drop_inputs
        self._drop_states = drop_states
        self._drop_outputs = drop_outputs
        self._input_mask = None
        self._state_masks = None
        self._output_mask = None

    def reset(self):
        super().reset()
        self._input_mask = None
        self._state_masks = None
        self._output_mask = None

    @staticmethod
    def _mask(like, p):
        from ... import ndarray as nd
        keep = 1.0 - p
        m = nd.random.uniform(0, 1, like.shape)
        # mask in the activation dtype: an f32 mask would promote a bf16
        # stream to f32 for the rest of the unroll (MXU-rate regression)
        return ((m < keep) / keep).astype(str(like.dtype))

    def forward(self, inputs, states):
        from ... import autograd
        if autograd.is_training():
            if self._drop_inputs > 0:
                if self._input_mask is None or \
                        self._input_mask.shape != inputs.shape:
                    self._input_mask = self._mask(inputs, self._drop_inputs)
                inputs = inputs * self._input_mask
            if self._drop_states > 0:
                if self._state_masks is None or any(
                        m.shape != s.shape
                        for m, s in zip(self._state_masks, states)):
                    self._state_masks = [self._mask(s, self._drop_states)
                                         for s in states]
                states = [s * m for s, m in zip(states, self._state_masks)]
        out, nstates = self.base_cell(inputs, states)
        if autograd.is_training() and self._drop_outputs > 0:
            if self._output_mask is None or \
                    self._output_mask.shape != out.shape:
                self._output_mask = self._mask(out, self._drop_outputs)
            out = out * self._output_mask
        return out, nstates
