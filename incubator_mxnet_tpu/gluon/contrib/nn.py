"""Contrib layers (re-design of
`python/mxnet/gluon/contrib/nn/basic_layers.py` — file-level citation,
SURVEY.md caveat)."""

from __future__ import annotations

from ...base import MXNetError
from .. import nn
from ..block import HybridBlock

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "PixelShuffle1D", "PixelShuffle2D", "PixelShuffle3D",
           "SyncBatchNorm"]


class HybridConcurrent(HybridBlock):
    """Runs children on the same input, concatenates outputs on ``axis``
    (parity: contrib.nn.HybridConcurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def hybrid_call(self, x):
        from ... import ndarray as nd
        outs = [child(x) for child in self._children.values()]
        return nd.concat(*outs, dim=self._axis)

    def forward(self, x):
        return self.hybrid_call(x)


class Concurrent(HybridConcurrent):
    """Eager twin (parity: contrib.nn.Concurrent)."""


class Identity(HybridBlock):
    """Passes input through unchanged (parity: contrib.nn.Identity —
    useful as a no-op branch in Concurrent)."""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(HybridBlock):
    """Embedding with row_sparse gradients (parity:
    contrib.nn.SparseEmbedding). Sugar over
    ``nn.Embedding(sparse_grad=True)`` — the optimizer's lazy path
    touches only looked-up rows (optimizer.py _rows_update)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self._emb = nn.Embedding(input_dim, output_dim, dtype=dtype,
                                     weight_initializer=weight_initializer,
                                     sparse_grad=True, prefix="")
        self.weight = self._emb.weight

    def hybrid_call(self, x):
        return self._emb(x)

    def forward(self, x):
        return self.hybrid_call(x)


class PixelShuffle2D(HybridBlock):
    """Rearranges (B, C*f1*f2, H, W) → (B, C, H*f1, W*f2) (parity:
    contrib.nn.PixelShuffle2D; sub-pixel convolution upsampling)."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        self._factors = (factor, factor) if isinstance(factor, int) \
            else tuple(factor)

    def hybrid_forward(self, F, x):
        f1, f2 = self._factors
        B, C, H, W = x.shape
        if C % (f1 * f2):
            raise MXNetError(
                f"PixelShuffle2D: channels {C} not divisible by "
                f"{f1}*{f2}")
        c = C // (f1 * f2)
        x = F.reshape(x, shape=(B, c, f1, f2, H, W))
        x = F.transpose(x, axes=(0, 1, 4, 2, 5, 3))
        return F.reshape(x, shape=(B, c, H * f1, W * f2))


class PixelShuffle1D(HybridBlock):
    """(B, C*f, W) → (B, C, W*f) (parity: contrib.nn.PixelShuffle1D)."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        self._factor = int(factor)

    def hybrid_forward(self, F, x):
        f = self._factor
        B, C, W = x.shape
        if C % f:
            raise MXNetError(f"PixelShuffle1D: channels {C} % {f} != 0")
        c = C // f
        x = F.reshape(x, shape=(B, c, f, W))
        x = F.transpose(x, axes=(0, 1, 3, 2))
        return F.reshape(x, shape=(B, c, W * f))


class PixelShuffle3D(HybridBlock):
    """(B, C*f1*f2*f3, D, H, W) → (B, C, D*f1, H*f2, W*f3)
    (parity: contrib.nn.PixelShuffle3D)."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        self._factors = (factor,) * 3 if isinstance(factor, int) \
            else tuple(factor)

    def hybrid_forward(self, F, x):
        f1, f2, f3 = self._factors
        B, C, D, H, W = x.shape
        if C % (f1 * f2 * f3):
            raise MXNetError(
                f"PixelShuffle3D: channels {C} not divisible by "
                f"{f1}*{f2}*{f3}")
        c = C // (f1 * f2 * f3)
        x = F.reshape(x, shape=(B, c, f1, f2, f3, D, H, W))
        x = F.transpose(x, axes=(0, 1, 5, 2, 6, 3, 7, 4))
        return F.reshape(x, shape=(B, c, D * f1, H * f2, W * f3))


class SyncBatchNorm(nn.BatchNorm):
    """Cross-device synchronized BatchNorm (parity:
    contrib.nn.SyncBatchNorm — reference src/operator/contrib/
    sync_batch_norm.cc, which all-reduces batch statistics over workers).

    TPU-native design: inside an SPMD train step the batch axis is sharded
    over the mesh's (dp, fsdp) axes, and XLA's partitioner already computes
    GLOBAL batch statistics for a full-axis reduction — `jnp.mean` over a
    sharded batch IS the reference's cross-worker all-reduce, riding ICI.
    The layer therefore reuses the plain BatchNorm op; ``num_devices`` is
    accepted for API parity and ignored (the mesh defines the sync group).
    Outside an SPMD step (single device) it degrades to ordinary BN,
    matching the reference's single-worker behavior.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=(
                             running_variance_initializer),
                         in_channels=in_channels, **kwargs)
        self._num_devices = num_devices
