"""Gluon Parameter / ParameterDict.

Re-design of `python/mxnet/gluon/parameter.py` (file-level citation —
SURVEY.md caveat) with the same deferred-shape-inference contract: a
Parameter may be created with unknown dims (0), initialization is recorded
and finished on the first forward once shapes are inferred.

Single-copy semantics: the reference replicates parameters across a ctx
list; here SPMD replication/sharding is owned by jax.sharding (parallel/),
so a Parameter holds ONE logical array. The list-based API (``list_data``,
``list_ctx``…) is kept for source compatibility and returns singletons.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp

from .. import autograd, initializer as _initializer
from ..base import DeferredInitializationError, MXNetError
from ..context import Context, current_context
from ..ndarray import NDArray
from ..ndarray.ndarray import _to_jnp_dtype

__all__ = ["Parameter", "Constant", "ParameterDict"]


def _norm_shape(shape):
    if shape is None:
        return None
    if isinstance(shape, int):
        shape = (shape,)
    return tuple(0 if s in (None, 0) else int(s) for s in shape)


class Parameter:
    """A trainable (or auxiliary) array with deferred initialization."""

    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None,
                 allow_deferred_init=False, differentiable=True,
                 stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        self._shape = _norm_shape(shape)
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._data: Optional[NDArray] = None
        self._grad: Optional[NDArray] = None
        self._deferred_init = None  # (initializer, ctx)
        self._sharding = None  # optional PartitionSpec hint (parallel/)
        self._stype = stype
        self._grad_stype = grad_stype  # 'row_sparse' → lazy optimizer rows

    # ------------------------------------------------------------------ #
    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        new_shape = _norm_shape(new_shape)
        if self._shape is None:
            self._shape = new_shape
            return
        if len(self._shape) != len(new_shape) or any(
                s not in (0, n) for s, n in zip(self._shape, new_shape)):
            raise MXNetError(
                f"parameter {self.name}: inferred shape {new_shape} "
                f"incompatible with declared {self._shape}")
        self._shape = new_shape

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise MXNetError(f"invalid grad_req {req!r}")
        self._grad_req = req
        if self._data is not None:
            if req == "null":
                self._grad = None
                self._data._ag_grad = None
            else:
                self._init_grad()

    def _shape_known(self) -> bool:
        return self._shape is not None and all(s > 0 for s in self._shape)

    # ------------------------------------------------------------------ #
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """Allocate & initialize; defer if shape not fully known."""
        if self._data is not None and not force_reinit:
            return
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0] if ctx else None  # single-copy semantics
        eff_init = init or self.init or default_init or _initializer.Uniform()
        if not self._shape_known():
            if self.allow_deferred_init:
                self._deferred_init = (eff_init, ctx)
                return
            raise MXNetError(
                f"cannot initialize parameter {self.name}: shape "
                f"{self._shape} unknown; set allow_deferred_init=True or "
                f"provide a full shape")
        self._finish_init(eff_init, ctx)

    def _finish_init(self, init, ctx):
        arr = NDArray(jnp.zeros(self._shape, _to_jnp_dtype(self.dtype)))
        _initializer.create(init)(self.name, arr)
        if ctx is not None:
            arr = arr.as_in_context(ctx)
        self._data = arr
        self._deferred_init = None
        if self._grad_req != "null":
            self._init_grad()

    def _init_grad(self):
        self._grad = NDArray(jnp.zeros(self._data.shape, self._data.dtype))
        # a freshly allocated grad buffer is STALE until backward fills
        # it (reference _fresh_grad contract; Trainer warns/skips)
        self._grad._fresh = False
        autograd.mark_variables([self._data], [self._grad], self._grad_req)

    def _finish_deferred_init(self):
        if self._deferred_init is None:
            return
        if not self._shape_known():
            raise DeferredInitializationError(
                f"parameter {self.name}: shape still unknown")
        init, ctx = self._deferred_init
        self._finish_init(init, ctx)

    # ------------------------------------------------------------------ #
    def data(self, ctx=None) -> NDArray:
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    f"parameter {self.name} pending deferred init; run a "
                    f"forward pass with real inputs first")
            raise MXNetError(
                f"parameter {self.name} not initialized; call .initialize()")
        return self._data

    def list_data(self) -> List[NDArray]:
        return [self.data()]

    def grad(self, ctx=None) -> NDArray:
        if self._grad is None:
            raise MXNetError(
                f"parameter {self.name} has no gradient buffer "
                f"(grad_req={self._grad_req!r})")
        return self._grad

    def list_grad(self) -> List[NDArray]:
        return [self.grad()]

    def list_ctx(self) -> List[Context]:
        return [self.data().context]

    def zero_grad(self):
        if self._grad is not None:
            self._grad._data = jnp.zeros_like(self._grad._data)

    def set_data(self, data):
        if not isinstance(data, NDArray):
            from ..ndarray import array as nd_array
            data = nd_array(data)
        if self._data is None:
            self.shape = data.shape
            self._data = data.astype(self.dtype)
            self._deferred_init = None
            if self._grad_req != "null":
                self._init_grad()
        else:
            self._data._data = data._data.astype(self._data.dtype)

    def reset_ctx(self, ctx):
        if self._data is not None:
            self._data = self._data.as_in_context(ctx)

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is not None:
            self._data = self._data.astype(dtype)
            if self._grad is not None:
                self._grad = self._grad.astype(dtype)
                autograd.mark_variables([self._data], [self._grad],
                                        self._grad_req)

    def var(self):
        from ..symbol import Variable
        attrs = {}
        if self.grad_req == "null":
            # non-differentiable state (running stats) → auxiliary variable
            attrs["__aux__"] = 1
        return Variable(self.name,
                        shape=self._shape if self._shape_known() else None,
                        dtype=str(self.dtype), **attrs)

    def shard(self, partition_spec):
        """TPU extension: attach a ``PartitionSpec`` hint consumed by the
        parallel trainer (SURVEY.md §2.3 — model/tensor parallelism)."""
        self._sharding = partition_spec
        return self

    def __repr__(self):
        return f"Parameter {self.name} (shape={self._shape}, dtype={self.dtype})"


class Constant(Parameter):
    """Non-differentiable constant parameter (parity: gluon.Constant)."""

    def __init__(self, name, value):
        import numpy as np
        if not isinstance(value, np.ndarray):
            value = np.asarray(value, dtype=np.float32)
        self._value = value
        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=str(value.dtype),
                         init=_initializer.Constant(0.0))

    def _finish_init(self, init, ctx):
        arr = NDArray(jnp.asarray(self._value))
        if ctx is not None:
            arr = arr.as_in_context(ctx)
        self._data = arr
        self._deferred_init = None


class ParameterDict:
    """Ordered name→Parameter mapping with prefix (parity: ParameterDict)."""

    def __init__(self, prefix="", shared: Optional["ParameterDict"] = None):
        self._prefix = prefix
        self._params: Dict[str, Parameter] = {}
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __contains__(self, name):
        return name in self._params

    def __getitem__(self, name) -> Parameter:
        return self._params[name]

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def get(self, name, **kwargs) -> Parameter:
        """Get or create (parity: ParameterDict.get). Name is prefixed."""
        full = self._prefix + name
        if self._shared is not None and full in self._shared:
            param = self._shared[full]
        elif full in self._params:
            param = self._params[full]
        else:
            param = Parameter(full, **kwargs)
            self._params[full] = param
            return param
        # merge newly-supplied attrs into existing param
        if "shape" in kwargs and kwargs["shape"] is not None:
            param.shape = _norm_shape(kwargs["shape"])
        self._params.setdefault(full, param)
        return param

    def get_constant(self, name, value=None) -> Constant:
        full = self._prefix + name
        if full not in self._params:
            self._params[full] = Constant(full, value)
        return self._params[full]

    def update(self, other: "ParameterDict"):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError(f"duplicate parameter name {k}")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        for param in self._params.values():
            param.initialize(init=None, ctx=ctx, default_init=init,
                             force_reinit=force_reinit)

    def zero_grad(self):
        for p in self._params.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        for p in self._params.values():
            p.reset_ctx(ctx)

    def setattr(self, name, value):
        for p in self._params.values():
            setattr(p, name, value)

    def save(self, fname, strip_prefix=""):
        from ..ndarray import save as nd_save
        out = {}
        for name, p in self._params.items():
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            out[name] = p.data()
        nd_save(fname, out)

    def load(self, fname, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        from ..ndarray import load as nd_load
        loaded = nd_load(fname)
        if restore_prefix:
            loaded = {restore_prefix + k: v for k, v in loaded.items()}
        for name, p in self._params.items():
            if name in loaded:
                p.set_data(loaded[name])
            elif not allow_missing:
                raise MXNetError(f"parameter {name} missing in {fname}")
        if not ignore_extra:
            extra = set(loaded) - set(self._params)
            if extra:
                raise MXNetError(f"extra parameters in {fname}: {sorted(extra)}")

    def __repr__(self):
        lines = [f"ParameterDict (prefix={self._prefix!r})"]
        lines += [f"  {p!r}" for p in self._params.values()]
        return "\n".join(lines)
