"""linalg op tests vs numpy/scipy oracles (reference strategy:
tests/python/unittest/test_operator.py linalg section)."""

import numpy as np

from incubator_mxnet_tpu import nd


def _rand_spd(n, rng):
    a = rng.randn(n, n).astype(np.float32)
    return a @ a.T + n * np.eye(n, dtype=np.float32)


def test_gemm_and_gemm2():
    rng = np.random.RandomState(0)
    A = rng.randn(3, 4).astype(np.float32)
    B = rng.randn(4, 5).astype(np.float32)
    C = rng.randn(3, 5).astype(np.float32)
    out = nd.linalg.gemm(nd.array(A), nd.array(B), nd.array(C),
                         alpha=2.0, beta=0.5).asnumpy()
    np.testing.assert_allclose(out, 2 * A @ B + 0.5 * C, rtol=1e-5)
    out2 = nd.linalg.gemm2(nd.array(A), nd.array(B.T),
                           transpose_b=True).asnumpy()
    np.testing.assert_allclose(out2, A @ B, rtol=1e-5)


def test_potrf_potri_roundtrip():
    rng = np.random.RandomState(1)
    A = _rand_spd(4, rng)
    L = nd.linalg.potrf(nd.array(A))
    np.testing.assert_allclose(L.asnumpy() @ L.asnumpy().T, A, rtol=1e-3,
                               atol=1e-3)
    Ainv = nd.linalg.potri(L).asnumpy()
    np.testing.assert_allclose(Ainv @ A, np.eye(4), atol=1e-2)


def test_trsm_all_modes():
    rng = np.random.RandomState(2)
    A = np.tril(rng.randn(3, 3).astype(np.float32)) + 3 * np.eye(
        3, dtype=np.float32)
    B = rng.randn(3, 2).astype(np.float32)
    # left: A X = B
    X = nd.linalg.trsm(nd.array(A), nd.array(B)).asnumpy()
    np.testing.assert_allclose(A @ X, B, rtol=1e-4, atol=1e-4)
    # left transposed: A^T X = B
    X = nd.linalg.trsm(nd.array(A), nd.array(B), transpose=True).asnumpy()
    np.testing.assert_allclose(A.T @ X, B, rtol=1e-4, atol=1e-4)
    # right: X A = B
    B2 = rng.randn(2, 3).astype(np.float32)
    X = nd.linalg.trsm(nd.array(A), nd.array(B2), rightside=True).asnumpy()
    np.testing.assert_allclose(X @ A, B2, rtol=1e-4, atol=1e-4)
    # right transposed: X A^T = B
    X = nd.linalg.trsm(nd.array(A), nd.array(B2), rightside=True,
                       transpose=True).asnumpy()
    np.testing.assert_allclose(X @ A.T, B2, rtol=1e-4, atol=1e-4)


def test_trmm_syrk():
    rng = np.random.RandomState(3)
    A = rng.randn(3, 3).astype(np.float32)
    B = rng.randn(3, 4).astype(np.float32)
    out = nd.linalg.trmm(nd.array(A), nd.array(B)).asnumpy()
    np.testing.assert_allclose(out, np.tril(A) @ B, rtol=1e-5)
    s = nd.linalg.syrk(nd.array(B)).asnumpy()
    np.testing.assert_allclose(s, B @ B.T, rtol=1e-5)


def test_gelqf():
    rng = np.random.RandomState(4)
    A = rng.randn(3, 5).astype(np.float32)
    L, Q = nd.linalg.gelqf(nd.array(A))
    L, Q = L.asnumpy(), Q.asnumpy()
    np.testing.assert_allclose(L @ Q, A, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(Q @ Q.T, np.eye(3), atol=1e-4)
    assert np.allclose(np.triu(L, 1), 0, atol=1e-5)


def test_syevd():
    rng = np.random.RandomState(5)
    A = _rand_spd(4, rng)
    U, lam = nd.linalg.syevd(nd.array(A))
    U, lam = U.asnumpy(), lam.asnumpy()
    # rows of U are eigenvectors: A u_i = lam_i u_i
    np.testing.assert_allclose(U @ A, np.diag(lam) @ U, rtol=1e-3,
                               atol=1e-3)


def test_diag_trian_det():
    rng = np.random.RandomState(6)
    A = _rand_spd(3, rng)
    d = nd.linalg.extractdiag(nd.array(A)).asnumpy()
    np.testing.assert_allclose(d, np.diag(A), rtol=1e-6)
    m = nd.linalg.makediag(nd.array(np.array([1., 2., 3.],
                                             np.float32))).asnumpy()
    np.testing.assert_allclose(m, np.diag([1., 2., 3.]), rtol=1e-6)
    sld = nd.linalg.sumlogdiag(nd.array(A)).asnumpy()
    np.testing.assert_allclose(sld, np.log(np.diag(A)).sum(), rtol=1e-5)
    packed = nd.linalg.extracttrian(nd.array(A)).asnumpy()
    back = nd.linalg.maketrian(nd.array(packed)).asnumpy()
    np.testing.assert_allclose(back, np.tril(A), rtol=1e-6)
    det = nd.linalg.det(nd.array(A)).asnumpy()
    np.testing.assert_allclose(det, np.linalg.det(A), rtol=1e-3)
    inv = nd.linalg.inverse(nd.array(A)).asnumpy()
    np.testing.assert_allclose(inv @ A, np.eye(3), atol=1e-3)


def test_trian_offsets():
    A = np.array([[1., 2.], [3., 4.]], np.float32)
    low = nd.linalg.extracttrian(nd.array(A), offset=-1).asnumpy()
    np.testing.assert_array_equal(low, [3.0])
    up = nd.linalg.extracttrian(nd.array(A), offset=1).asnumpy()
    np.testing.assert_array_equal(up, [2.0])
    back = nd.linalg.maketrian(nd.array(np.array([7.0], np.float32)),
                               offset=1).asnumpy()
    np.testing.assert_array_equal(back, [[0., 7.], [0., 0.]])
    back2 = nd.linalg.maketrian(nd.array(np.array([7.0], np.float32)),
                                offset=-1).asnumpy()
    np.testing.assert_array_equal(back2, [[0., 0.], [7., 0.]])
