"""Chaos bench: drive the serving engine through seeded fault
scenarios and ASSERT the resilience invariants (docs/RESILIENCE.md).

Every scenario replays the same mixed workload (shared-prefix + unique
prompts, ragged lengths, chunked prefill, prefix cache on) against a
fresh engine with one deterministic fault injected
(serve/chaos.py), and checks:

  1. QUIESCENCE — 100% of requests reach a structured terminal
     Outcome; the engine never wedges and never raises out of the
     serving loop;
  2. ISOLATION — every request the fault did NOT touch emits tokens
     BIT-IDENTICAL to the fault-free baseline run (no cross-slot
     contamination through the shared page pool, the prefix cache, or
     the batched decode step);
  3. ACCOUNTING — ``audit_pages()`` passes after EVERY scheduler step,
     fault handling included (no page leaked or double-granted on any
     eviction path);
  4. COMPILE DISCIPLINE — the decode step compiled exactly once and
     every prefill/chunk bucket exactly once across the whole faulted
     run (the non-finite guard flag and all fault handling are pure
     data / host bookkeeping — zero steady-state retraces);
  5. scenario-specific outcome expectations (a NaN fault must
     quarantine, overload must shed with retry-after, a deadline storm
     must expire, starvation must not corrupt survivors).

Scenarios: nan_weights, corrupt_page (NaN), dropped_write (zeroed
page — undetectable by the guard, isolation still asserted),
starvation_transient, starvation_full, overload_shed, deadline_storm,
sigterm (subprocess: cooperative SIGTERM drain + final weight
snapshot + every request terminal).

``--fleet`` switches to the FLEET scenarios (serve/router.py,
ci/run.sh ``fleetsmoke`` stage): the same workload against a Router
over N replicas with router-level faults — kill_mid_decode,
kill_mid_prefill (replica death = structured bounded re-queue with
emitted tokens preserved), kill_all (every replica dead → bounded
FAILED_REPLICA give-up, nothing lost), requeue_exhaustion
(max_requeues=0 → immediate FAILED_REPLICA with partial tokens kept),
slow_replica (heartbeat misses must open the circuit breaker and
half-open probes must close it), flapping_replica (the breaker loop
is re-entrant), fleet_shed (router-level backpressure with
retry_after_s). Fleet invariants asserted per scenario: 100% of
requests reach EXACTLY ONE terminal outcome, survivors bit-identical
to the fault-free fleet run, every SURVIVING replica's
``audit_pages()`` clean after every router step, each replica's
decode compiled exactly once, and every retryable outcome carries a
``retry_after_s`` hint.

``--smoke`` is the CI guard (ci/run.sh chaossmoke / fleetsmoke
stages): the same scenarios at a size that runs in minutes on CPU;
exits non-zero on any violated invariant.

Usage:
  python tools/chaos_bench.py --smoke          # CI guard
  python tools/chaos_bench.py --fleet --smoke  # fleet CI guard
  python tools/chaos_bench.py                  # larger sweep
  python tools/chaos_bench.py --json OUT.json
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


# --------------------------------------------------------------------- #
# workload
# --------------------------------------------------------------------- #

def _build_model(seed=0, vocab=64, max_length=128):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.models import gpt as g
    mx.random.seed(seed)
    model = g.gpt_mini(vocab_size=vocab, max_length=max_length)
    model.initialize()
    return model


def _make_requests(n, vocab, seed, deadline_s=None, max_len=128):
    """Mixed greedy workload: ~half share a persona prefix (exercises
    COW page sharing under faults), ragged lengths and budgets. Greedy
    everywhere so token parity is assertable."""
    import numpy as np
    from incubator_mxnet_tpu.serve import Request
    rng = np.random.RandomState(seed)
    persona = rng.randint(0, vocab, size=(18,)).astype(np.int32)
    reqs = []
    for i in range(n):
        if i % 2 == 0:
            tail = rng.randint(0, vocab, size=(3 + i % 7,)).astype(np.int32)
            prompt = np.concatenate([persona, tail])
        else:
            prompt = rng.randint(0, vocab,
                                 size=(4 + 3 * (i % 5),)).astype(np.int32)
        max_new = 4 + 2 * (i % 6)
        assert prompt.size + max_new <= max_len
        reqs.append(Request(prompt, max_new_tokens=max_new,
                            deadline_s=deadline_s))
    return reqs


_SPEC_K = 3     # scenarios run SPECULATIVE engines (greedy speculation
                # is bit-identical to plain decode, so every parity
                # invariant carries over — and every fault now lands on
                # the draft-then-verify path too); --spec-k 0 reverts


def _engine(model, **kw):
    from incubator_mxnet_tpu.serve import InferenceEngine
    cfg = dict(num_slots=4, page_size=8, max_len=128, chunk_pages=1,
               prefix_cache=True, spec_k=_SPEC_K)
    cfg.update(kw)
    return InferenceEngine(model, **cfg)


def _check_compile_once(tag, eng, errors):
    """The decode-family compile contract: the W=1 narrow step and the
    K+1-wide verify each trace AT MOST once (shape-keyed jit cache),
    and at least one ran. A non-speculative engine (--spec-k 0) only
    ever has the narrow program."""
    if eng.decode_trace_count > 1 or eng.verify_trace_count > 1:
        errors.append(f"{tag}: decode retraced (narrow "
                      f"{eng.decode_trace_count}, wide "
                      f"{eng.verify_trace_count}; each must be <= 1)")
    if eng.decode_trace_count + eng.verify_trace_count < 1:
        errors.append(f"{tag}: no decode program ever ran")


# --------------------------------------------------------------------- #
# invariants
# --------------------------------------------------------------------- #

def _check_invariants(tag, eng, reqs, baseline, affected, errors,
                      allow_non_ok=True):
    """The shared post-scenario assertion block; ``affected`` is the
    set of requests (by identity) whose output the fault may change."""
    from incubator_mxnet_tpu.serve.chaos import assert_health_consistent
    from incubator_mxnet_tpu.base import MXNetError
    for i, r in enumerate(reqs):
        if r.outcome is None:
            errors.append(f"{tag}: request {i} non-terminal")
    try:
        assert_health_consistent(eng, reqs)
    except MXNetError as e:
        errors.append(f"{tag}: {e}")
    try:
        eng.audit_pages()
    except MXNetError as e:
        errors.append(f"{tag}: final audit failed: {e}")
    _check_compile_once(tag, eng, errors)
    bad_buckets = {k: v for k, v in eng.prefill_trace_counts.items()
                   if v != 1}
    if bad_buckets:
        errors.append(f"{tag}: prefill buckets retraced: {bad_buckets}")
    aff_ids = {id(r) for r in affected}
    mismatches = unaffected_ok = 0
    for r, base_tokens in zip(reqs, baseline):
        if id(r) in aff_ids:
            continue
        if r.outcome is not None and r.outcome.ok:
            unaffected_ok += 1
            if list(r.token_ids) != base_tokens:
                mismatches += 1
        elif not allow_non_ok:
            errors.append(f"{tag}: unaffected request ended {r.outcome}")
    if mismatches:
        errors.append(f"{tag}: {mismatches} unaffected requests diverged "
                      f"from the fault-free run (cross-contamination)")
    # speculation observability: engine draft/accept counters must
    # equal the per-request sums (these engines serve ONLY ``reqs``),
    # and acceptance can never exceed drafting
    d_sum = sum(r.drafted_tokens for r in reqs)
    a_sum = sum(r.accepted_tokens for r in reqs)
    if (eng.drafted_tokens, eng.accepted_tokens) != (d_sum, a_sum):
        errors.append(
            f"{tag}: engine spec counters "
            f"({eng.drafted_tokens}, {eng.accepted_tokens}) != "
            f"per-request sums ({d_sum}, {a_sum})")
    if eng.accepted_tokens > eng.drafted_tokens:
        errors.append(f"{tag}: accepted {eng.accepted_tokens} > "
                      f"drafted {eng.drafted_tokens}")
    # reporting reads the CONSISTENT snapshot, never the live dict
    snap = eng.health_snapshot()
    return {"outcomes": {o: n for o, n in snap["outcomes"].items()
                         if n},
            "unaffected_ok": unaffected_ok,
            "affected": len(affected),
            "drafted": eng.drafted_tokens,
            "accepted": eng.accepted_tokens,
            "accept_rate": round(eng.accept_rate, 4),
            "decode_trace_count": eng.decode_trace_count,
            "verify_trace_count": eng.verify_trace_count,
            "prefill_buckets": len(eng.prefill_trace_counts)}


def _audit_hook(errors, tag):
    from incubator_mxnet_tpu.base import MXNetError

    def after(eng, i):
        try:
            eng.audit_pages()
        except MXNetError as e:     # record once, with the step index
            errors.append(f"{tag}: audit failed at step {i}: {e}")
            raise

    return after


def run_scenarios(n_requests, errors):
    """All in-process scenarios. Fresh model (same seed → identical
    weights) and fresh engine per scenario so faults cannot leak."""
    from incubator_mxnet_tpu.serve import Outcome
    from incubator_mxnet_tpu.serve.chaos import (CorruptPageWrite,
                                                 DelayedSteps,
                                                 NaNWeights,
                                                 PagePressure, run_chaos)
    results = {}
    vocab = 64

    # ---- fault-free baseline -------------------------------------- #
    model = _build_model()
    eng = _engine(model)
    reqs = _make_requests(n_requests, vocab, seed=42)
    t0 = time.perf_counter()
    run_chaos(eng, reqs, [], audit_every_step=True)
    wall = time.perf_counter() - t0
    baseline = [list(r.token_ids) for r in reqs]
    stats = _check_invariants("baseline", eng, reqs, baseline, set(),
                              errors, allow_non_ok=False)
    if not all(r.outcome is not None and r.outcome.ok for r in reqs):
        errors.append("baseline: not every request succeeded")
    if _SPEC_K > 0 and eng.drafted_tokens == 0:
        errors.append("baseline: speculation enabled but the n-gram "
                      "drafter never proposed — scenarios are not "
                      "exercising the verify path")
    stats["wall_s"] = wall
    results["baseline"] = stats

    # ---- NaN weights at warm_start -------------------------------- #
    model = _build_model()
    eng = _engine(model)
    reqs = _make_requests(n_requests, vocab, seed=42)
    inj = NaNWeights(at_step=6, seed=7)
    run_chaos(eng, reqs, [inj],
              audit_every_step=True)
    stats = _check_invariants("nan_weights", eng, reqs, baseline,
                              inj.affected, errors, allow_non_ok=False)
    if not inj.fired:
        errors.append("nan_weights: injector never fired")
    if eng.quarantined == 0:
        errors.append("nan_weights: nothing quarantined")
    for r in inj.affected:
        if r.outcome != Outcome.FAILED_NONFINITE:
            errors.append(f"nan_weights: poisoned request ended "
                          f"{r.outcome}, not FAILED_NONFINITE")
    # a poisoned VERIFY step must record NOTHING — no base token, no
    # accepted draft: every recorded token predates the fault, so it
    # must be a clean prefix of the fault-free run's tokens
    for r, base_tokens in zip(reqs, baseline):
        if r.outcome == Outcome.FAILED_NONFINITE and \
                list(r.token_ids) != base_tokens[:len(r.token_ids)]:
            errors.append("nan_weights: a quarantined request recorded "
                          "a token from the poisoned step (drafted "
                          "tokens must never be published)")
    stats["log"] = inj.log
    results["nan_weights"] = stats

    # ---- one corrupt (NaN) page write ------------------------------ #
    # prefix_cache off: every mapped page is private, so the fault's
    # blast radius is provably one slot
    model = _build_model()
    eng = _engine(model, prefix_cache=False)
    reqs = _make_requests(n_requests, vocab, seed=42)
    inj = CorruptPageWrite(at_step=5, mode="nan", seed=3)
    run_chaos(eng, reqs, [inj], audit_every_step=True)
    stats = _check_invariants("corrupt_page", eng, reqs, baseline,
                              inj.affected, errors, allow_non_ok=False)
    if not inj.fired:
        errors.append("corrupt_page: injector never fired")
    if len(inj.affected) != 1:
        errors.append(f"corrupt_page: blast radius "
                      f"{len(inj.affected)} != 1 slot")
    for r in inj.affected:
        if r.outcome != Outcome.FAILED_NONFINITE:
            errors.append(f"corrupt_page: poisoned request ended "
                          f"{r.outcome}, not FAILED_NONFINITE")
    for r, base_tokens in zip(reqs, baseline):
        if r.outcome == Outcome.FAILED_NONFINITE and \
                list(r.token_ids) != base_tokens[:len(r.token_ids)]:
            errors.append("corrupt_page: a quarantined request recorded "
                          "a token from the poisoned step")
    stats["log"] = inj.log
    results["corrupt_page"] = stats

    # ---- one dropped (zeroed) page write --------------------------- #
    # finite garbage the guard cannot see: the invariant is pure
    # isolation — the hit request may emit anything, everyone else is
    # bit-identical, accounting exact
    model = _build_model()
    eng = _engine(model, prefix_cache=False)
    reqs = _make_requests(n_requests, vocab, seed=42)
    inj = CorruptPageWrite(at_step=5, mode="zero", seed=3)
    run_chaos(eng, reqs, [inj], audit_every_step=True)
    stats = _check_invariants("dropped_write", eng, reqs, baseline,
                              inj.affected, errors, allow_non_ok=False)
    if not inj.fired:
        errors.append("dropped_write: injector never fired")
    stats["log"] = inj.log
    results["dropped_write"] = stats

    # ---- transient allocator pressure ------------------------------ #
    model = _build_model()
    eng = _engine(model, watchdog_steps=400)
    reqs = _make_requests(n_requests, vocab, seed=42)
    inj = PagePressure(hold_at=4, release_after=25)
    run_chaos(eng, reqs, [inj], audit_every_step=True)
    stats = _check_invariants("starvation_transient", eng, reqs,
                              baseline, inj.affected, errors,
                              allow_non_ok=False)
    if not all(r.outcome is not None and r.outcome.ok for r in reqs):
        errors.append("starvation_transient: a request failed although "
                      "the pressure was released")
    stats["log"] = inj.log
    results["starvation_transient"] = stats

    # ---- full starvation (never released) -------------------------- #
    # watchdog + stall handling must fail the starved requests loudly
    # and keep serving with whatever pages evictions recycle — the held
    # pages stay held, audited, to the end
    model = _build_model()
    eng = _engine(model, watchdog_steps=10, stall_steps=15)
    reqs = _make_requests(n_requests, vocab, seed=42)
    inj = PagePressure(hold_at=4, release_after=None)
    run_chaos(eng, reqs, [inj], audit_every_step=True,
              poll_sleep=1e-4)
    stats = _check_invariants("starvation_full", eng, reqs, baseline,
                              reqs, errors)  # scheduling faults: check
    # accounting/compile only — but completed requests must STILL be
    # bit-identical (pressure is not a data fault)
    for r, base_tokens in zip(reqs, baseline):
        if r.outcome is not None and r.outcome.ok and \
                list(r.token_ids) != base_tokens:
            errors.append("starvation_full: a completed request "
                          "diverged from the fault-free run")
    if eng._alloc.held:
        eng._alloc.release_held()
    try:
        eng.audit_pages()
    except Exception as e:
        errors.append(f"starvation_full: post-release audit failed: {e}")
    stats["log"] = inj.log
    results["starvation_full"] = stats

    # ---- overload shed --------------------------------------------- #
    model = _build_model()
    eng = _engine(model, max_queue=3)
    reqs = _make_requests(n_requests, vocab, seed=42)
    run_chaos(eng, reqs, [], audit_every_step=True)
    stats = _check_invariants("overload_shed", eng, reqs, baseline,
                              [r for r in reqs
                               if r.outcome is not None
                               and not r.outcome.ok], errors)
    if eng.shed == 0:
        errors.append("overload_shed: queue bound never shed")
    from incubator_mxnet_tpu.serve import Outcome as _O
    for r in reqs:
        if r.outcome == _O.SHED and (r.retry_after_s is None
                                     or r.retry_after_s <= 0):
            errors.append("overload_shed: shed without retry_after_s")
    results["overload_shed"] = stats

    # ---- deadline storm (host stalls) ------------------------------ #
    model = _build_model()
    eng = _engine(model)
    # warm the programs so compile time is not the stall under test
    warm = _make_requests(2, vocab, seed=9)
    eng.run(warm)
    reqs = _make_requests(n_requests, vocab, seed=42, deadline_s=0.4)
    inj = DelayedSteps(start=3, end=10 ** 9, sleep_s=0.12)
    run_chaos(eng, reqs, [inj], audit_every_step=True)
    for i, r in enumerate(reqs):
        if r.outcome is None:
            errors.append(f"deadline_storm: request {i} non-terminal")
    if eng.expired == 0:
        errors.append("deadline_storm: stalls expired nothing")
    _check_compile_once("deadline_storm", eng, errors)
    try:
        eng.audit_pages()
    except Exception as e:
        errors.append(f"deadline_storm: audit failed: {e}")
    results["deadline_storm"] = {
        "outcomes": {o: n for o, n in
                     eng.health_snapshot()["outcomes"].items() if n},
        "stalled_steps": inj.stalled_steps}

    return results


# --------------------------------------------------------------------- #
# fleet scenarios (serve/router.py — ci/run.sh fleetsmoke stage)
# --------------------------------------------------------------------- #

def _fleet(model, n=2, spec_k=None, router_kw=None, **eng_kw):
    from incubator_mxnet_tpu.serve import build_fleet
    cfg = dict(num_slots=4, page_size=8, max_len=128, chunk_pages=1,
               prefix_cache=True,
               spec_k=_SPEC_K if spec_k is None else spec_k)
    cfg.update(eng_kw)
    rkw = dict(seed=5)
    rkw.update(router_kw or {})
    return build_fleet(model, n, engine_kw=cfg, **rkw)


def _check_fleet_invariants(tag, router, reqs, baseline, affected,
                            errors):
    """The PR 5 invariants lifted to fleet scope. ``affected`` is the
    set of requests (by identity) whose OUTPUT the fault may change —
    for pure replica kills it is EMPTY: a killed-and-requeued greedy
    request must still end bit-identical to the fault-free run
    (resume-from-suffix replay under position-keyed sampling)."""
    from incubator_mxnet_tpu.base import MXNetError
    from incubator_mxnet_tpu.serve import Outcome
    from incubator_mxnet_tpu.serve.chaos import (
        assert_fleet_health_consistent)
    from incubator_mxnet_tpu.serve.router import ReplicaState
    for i, r in enumerate(reqs):
        if r.outcome is None:
            errors.append(f"{tag}: request {i} non-terminal")
    try:
        assert_fleet_health_consistent(router, reqs)
    except MXNetError as e:
        errors.append(f"{tag}: {e}")
    survivors = [rep for rep in router.replicas
                 if rep.state is not ReplicaState.DEAD
                 and rep.killed is None]
    for rep in survivors:
        try:
            rep.engine.audit_pages()
        except MXNetError as e:
            errors.append(f"{tag}: replica {rep.idx} final audit "
                          f"failed: {e}")
        eng = rep.engine
        if eng.decode_trace_count > 1 or eng.verify_trace_count > 1:
            errors.append(f"{tag}: replica {rep.idx} decode retraced "
                          f"(narrow {eng.decode_trace_count}, wide "
                          f"{eng.verify_trace_count})")
        bad = {k: v for k, v in eng.prefill_trace_counts.items()
               if v != 1}
        if bad:
            errors.append(f"{tag}: replica {rep.idx} prefill buckets "
                          f"retraced: {bad}")
    aff_ids = {id(r) for r in affected}
    mismatches = 0
    for r, base_tokens in zip(reqs, baseline):
        if id(r) in aff_ids:
            continue
        if r.outcome is not None and r.outcome.ok and \
                list(r.token_ids) != base_tokens:
            mismatches += 1
        if r.outcome is not None and not r.outcome.ok and \
                list(r.token_ids) != base_tokens[:len(r.token_ids)]:
            errors.append(f"{tag}: a failed request's partial tokens "
                          f"are not a prefix of its fault-free stream")
    if mismatches:
        errors.append(f"{tag}: {mismatches} completed requests "
                      f"diverged from the fault-free fleet run")
    # one backoff contract: every retryable terminal carries its hint
    for i, r in enumerate(reqs):
        if r.outcome is not None and r.outcome.retryable and \
                (r.retry_after_s is None or r.retry_after_s <= 0):
            errors.append(f"{tag}: request {i} ended {r.outcome} "
                          f"without a retry_after_s hint")
    snap = router.health_snapshot()
    return {"outcomes": {o: n for o, n in snap["outcomes"].items()
                         if n},
            "requeues": snap["requeues"],
            "replica_deaths": snap["replica_deaths"],
            "breaker_opens": snap["breaker_opens"],
            "probes": snap["probes"],
            "recoveries": snap["recoveries"],
            "affinity_routed": snap["affinity_routed"],
            "spill_routed": snap["spill_routed"],
            "replica_states": [e["state"] for e in snap["replicas"]]}


def run_fleet_scenarios(n_requests, errors, n_replicas=2):
    """Router-level chaos: every scenario replays the same workload
    against a fresh fleet with one deterministic fault.

    The kill_mid_decode fleet runs speculation (_SPEC_K) so the death
    also lands on the draft-then-verify path; the other scenarios run
    spec_k=0 to stay inside the fleetsmoke budget (every extra engine
    pays a wide-verify compile). Token PARITY across the mix is sound
    by the PR 6 contract: greedy speculation is bit-identical to plain
    decode, so one fault-free baseline serves both engine configs."""
    from incubator_mxnet_tpu.serve import Outcome
    from incubator_mxnet_tpu.serve.chaos import (FlappingReplica,
                                                 KillReplica,
                                                 SlowReplica,
                                                 run_fleet_chaos)
    from incubator_mxnet_tpu.serve.router import ReplicaState
    results = {}
    vocab = 64

    # ---- fault-free fleet baseline -------------------------------- #
    model = _build_model()
    rt = _fleet(model, n_replicas, spec_k=0)
    reqs = _make_requests(n_requests, vocab, seed=42)
    t0 = time.perf_counter()
    run_fleet_chaos(rt, reqs, [])
    wall = time.perf_counter() - t0
    baseline = [list(r.token_ids) for r in reqs]
    stats = _check_fleet_invariants("fleet_baseline", rt, reqs,
                                    baseline, set(), errors)
    if not all(r.outcome is not None and r.outcome.ok for r in reqs):
        errors.append("fleet_baseline: not every request succeeded")
    stats["wall_s"] = wall
    results["fleet_baseline"] = stats

    # ---- replica killed mid-decode -------------------------------- #
    # the tentpole invariant: a death is a structured re-queue — zero
    # lost requests, zero double-finishes, survivors AND replayed
    # requests bit-identical to the fault-free run
    model = _build_model()
    rt = _fleet(model, n_replicas)          # speculative (_SPEC_K)
    reqs = _make_requests(n_requests, vocab, seed=42)
    inj = KillReplica(replica=0, at_step=6, phase="decode")
    run_fleet_chaos(rt, reqs, [inj])
    stats = _check_fleet_invariants("kill_mid_decode", rt, reqs,
                                    baseline, set(), errors)
    if not inj.fired:
        errors.append("kill_mid_decode: injector never fired")
    if rt.replica_deaths != 1:
        errors.append(f"kill_mid_decode: {rt.replica_deaths} deaths "
                      f"!= 1")
    if not inj.inflight_at_kill:
        errors.append("kill_mid_decode: nothing was in flight at the "
                      "kill — scenario exercised nothing")
    if rt.requeues == 0:
        errors.append("kill_mid_decode: death re-queued nothing")
    if not all(r.outcome is not None and r.outcome.ok for r in reqs):
        errors.append("kill_mid_decode: a request was lost to the "
                      "death (requeue budget was sufficient)")
    for c, pre in inj.inflight_at_kill:
        if list(c.token_ids[:len(pre)]) != pre:
            errors.append("kill_mid_decode: a re-queued request's "
                          "emitted prefix was not preserved")
    stats["log"] = inj.log + rt.log[:6]
    results["kill_mid_decode"] = stats

    # ---- replica killed mid-prefill ------------------------------- #
    # chunked prefill spreads prompts across steps, so the kill lands
    # on a replica holding a half-built prompt: the replay must redo
    # it from scratch on another replica (no tokens yet to preserve)
    model = _build_model()
    rt = _fleet(model, n_replicas, spec_k=0)
    reqs = _make_requests(n_requests, vocab, seed=42)
    inj = KillReplica(replica=0, at_step=2, phase="prefill")
    run_fleet_chaos(rt, reqs, [inj])
    stats = _check_fleet_invariants("kill_mid_prefill", rt, reqs,
                                    baseline, set(), errors)
    if not inj.fired:
        errors.append("kill_mid_prefill: injector never fired")
    if not all(r.outcome is not None and r.outcome.ok for r in reqs):
        errors.append("kill_mid_prefill: a request was lost")
    stats["log"] = inj.log
    results["kill_mid_prefill"] = stats

    # ---- every replica killed ------------------------------------- #
    # bounded give-up: once the last replica dies, in-flight and
    # queued requests terminate FAILED_REPLICA (with retry hints and
    # their partial tokens) — nothing is lost, nothing wedges
    model = _build_model()
    rt = _fleet(model, n_replicas, spec_k=0)
    reqs = _make_requests(n_requests, vocab, seed=42)
    injs = [KillReplica(replica=i, at_step=5 + 3 * i, seed=i)
            for i in range(n_replicas)]
    run_fleet_chaos(rt, reqs, injs)
    stats = _check_fleet_invariants("kill_all", rt, reqs, baseline,
                                    reqs, errors)
    if any(rep.state is not ReplicaState.DEAD for rep in rt.replicas):
        errors.append("kill_all: a replica survived its kill")
    failed = [r for r in reqs if r.outcome == Outcome.FAILED_REPLICA]
    if not failed:
        errors.append("kill_all: nothing ended FAILED_REPLICA — the "
                      "give-up path never ran")
    for r, base_tokens in zip(reqs, baseline):
        if r.outcome is not None and r.outcome.ok and \
                list(r.token_ids) != base_tokens:
            errors.append("kill_all: a request completed before the "
                          "deaths but diverged from fault-free")
    stats["log"] = sum((i.log for i in injs), [])
    results["kill_all"] = stats

    # ---- requeue budget exhausted --------------------------------- #
    # max_requeues=0: the first death immediately fails its in-flight
    # requests FAILED_REPLICA — partial tokens kept, hints attached
    model = _build_model()
    rt = _fleet(model, n_replicas, spec_k=0,
                router_kw=dict(max_requeues=0))
    reqs = _make_requests(n_requests, vocab, seed=42)
    inj = KillReplica(replica=0, at_step=6, phase="decode")
    run_fleet_chaos(rt, reqs, [inj])
    stats = _check_fleet_invariants("requeue_exhaustion", rt, reqs,
                                    baseline,
                                    [c for c, _ in inj.inflight_at_kill],
                                    errors)
    hit = {id(c) for c, _ in inj.inflight_at_kill}
    for r in reqs:
        want = Outcome.FAILED_REPLICA if id(r) in hit else None
        if want is not None and r.outcome != want:
            errors.append(f"requeue_exhaustion: an in-flight request "
                          f"ended {r.outcome}, not FAILED_REPLICA at "
                          f"max_requeues=0")
    for c, pre in inj.inflight_at_kill:
        if list(c.token_ids) != pre:
            errors.append("requeue_exhaustion: partial tokens were "
                          "not preserved on the FAILED_REPLICA path")
    stats["log"] = inj.log
    results["requeue_exhaustion"] = stats

    # ---- slow replica: the circuit breaker ------------------------ #
    # slowness must open the breaker (DEGRADED, no new admissions),
    # half-open probes must close it, and NO request may be lost,
    # re-routed into divergence, or corrupted by pure slowness
    model = _build_model()
    rt = _fleet(model, n_replicas, spec_k=0,
                router_kw=dict(heartbeat_timeout_s=0.05,
                               breaker_failures=2,
                               probe_backoff_s=0.02,
                               probe_recovery=2))
    reqs = _make_requests(n_requests, vocab, seed=42)
    inj = SlowReplica(replica=0, start=4, end=16, sleep_s=0.1)
    run_fleet_chaos(rt, reqs, [inj],
                    arrival_times=[0.01 * i for i in range(len(reqs))])
    stats = _check_fleet_invariants("slow_replica", rt, reqs, baseline,
                                    set(), errors)
    if not inj.fired:
        errors.append("slow_replica: injector never fired")
    if rt.replicas[0].breaker_opens == 0:
        errors.append("slow_replica: heartbeat misses never opened "
                      "the breaker")
    if rt.replica_deaths:
        errors.append("slow_replica: slowness must degrade, never "
                      "kill")
    if not all(r.outcome is not None and r.outcome.ok for r in reqs):
        errors.append("slow_replica: a request was lost to slowness")
    stats["log"] = rt.log[:8]
    results["slow_replica"] = stats

    # ---- flapping replica: the breaker is re-entrant -------------- #
    model = _build_model()
    rt = _fleet(model, n_replicas, spec_k=0,
                router_kw=dict(heartbeat_timeout_s=0.05,
                               breaker_failures=2,
                               probe_backoff_s=0.02,
                               probe_recovery=1))
    reqs = _make_requests(n_requests, vocab, seed=42)
    inj = FlappingReplica(replica=0, start=4, period=12, slow_for=4,
                          sleep_s=0.1, cycles=2)
    run_fleet_chaos(rt, reqs, [inj],
                    arrival_times=[0.015 * i for i in range(len(reqs))])
    stats = _check_fleet_invariants("flapping_replica", rt, reqs,
                                    baseline, set(), errors)
    if not inj.fired:
        errors.append("flapping_replica: injector never fired")
    if rt.replicas[0].breaker_opens < 1 or rt.recoveries < 1:
        errors.append(f"flapping_replica: breaker did not cycle "
                      f"(opens {rt.replicas[0].breaker_opens}, "
                      f"recoveries {rt.recoveries})")
    if not all(r.outcome is not None and r.outcome.ok for r in reqs):
        errors.append("flapping_replica: a request was lost to "
                      "flapping")
    stats["log"] = rt.log[:10]
    results["flapping_replica"] = stats

    # ---- fleet-level shedding ------------------------------------- #
    # the router refuses at ITS admission when its queue bound is hit:
    # bounded, hinted, nothing lost, nothing queued blindly
    model = _build_model()
    rt = _fleet(model, n_replicas, spec_k=0,
                router_kw=dict(max_queue=2, replica_queue_depth=1))
    reqs = _make_requests(n_requests, vocab, seed=42)
    run_fleet_chaos(rt, reqs, [])
    stats = _check_fleet_invariants(
        "fleet_shed", rt, reqs, baseline,
        [r for r in reqs if r.outcome is not None and not r.outcome.ok],
        errors)
    shed = [r for r in reqs if r.outcome == Outcome.SHED]
    if not shed:
        errors.append("fleet_shed: router queue bound never shed")
    for r in shed:
        if r.retry_after_s is None or r.retry_after_s <= 0:
            errors.append("fleet_shed: shed without retry_after_s")
    results["fleet_shed"] = stats

    return results


# --------------------------------------------------------------------- #
# SIGTERM mid-serve (subprocess scenario)
# --------------------------------------------------------------------- #

def _child_main(ckpt_dir):
    """Serve a long workload; on SIGTERM: drain to a final committed
    weight snapshot, shut the engine down (every request terminal),
    audit, report JSON, exit 0. Cooperative stop flag — the signal
    handler only flips it, so no engine invariant can be torn by a
    mid-bookkeeping interrupt."""
    from incubator_mxnet_tpu import checkpoint as ckpt
    from incubator_mxnet_tpu.serve.chaos import assert_health_consistent

    model = _build_model()
    eng = _engine(model)
    reqs = _make_requests(64, 64, seed=42)
    stop = {"flag": False}
    signal.signal(signal.SIGTERM,
                  lambda *_: stop.__setitem__("flag", True))
    for r in reqs:
        eng.submit(r)
    announced = False
    while (eng._queue or eng.active_count) and not stop["flag"]:
        eng.step()
        eng.audit_pages()
        if not announced and eng.decode_steps >= 2:
            print("SERVING", flush=True)
            announced = True
    mgr = ckpt.CheckpointManager(ckpt_dir, keep=1)
    preempted = bool(stop["flag"])
    if preempted:
        eng.save_checkpoint(mgr, block=True)   # final sync snapshot
        eng.shutdown("SIGTERM preemption drain")
    mgr.close()
    eng.audit_pages()
    assert_health_consistent(eng, reqs)
    report = {
        "preempted": preempted,
        "all_terminal": all(r.outcome is not None for r in reqs),
        "outcomes": {o: n for o, n in
                     eng.health_snapshot()["outcomes"].items() if n},
        "decode_trace_count": eng.decode_trace_count,
        "verify_trace_count": eng.verify_trace_count,
        "committed_steps": mgr.all_steps(),
    }
    print("REPORT " + json.dumps(report), flush=True)
    return 0


def run_sigterm_scenario(errors):
    """Parent: spawn the child, SIGTERM it mid-serve, assert the drain
    contract — exit 0, all requests terminal, a committed weight
    snapshot a replacement replica could warm_start from.

    stdout is drained through a reader THREAD: a child that wedges
    inside ``eng.step()`` after announcing SERVING (exactly the
    failure class this stage exists to catch — the cooperative SIGTERM
    handler only flips a flag, so a wedged step never observes it)
    emits nothing further, and a blocking ``readline()`` would hang
    the whole chaossmoke CI stage instead of failing it."""
    import queue as _queue
    import threading
    with tempfile.TemporaryDirectory() as d:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child",
             "--ckpt-dir", d],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        lines: "_queue.Queue" = _queue.Queue()

        def _drain(stream):
            for ln in iter(stream.readline, ""):
                lines.put(ln)
            lines.put(None)                  # EOF sentinel

        threading.Thread(target=_drain, args=(proc.stdout,),
                         daemon=True).start()
        report = None
        rc = None
        try:
            deadline = time.time() + 600
            while time.time() < deadline:
                try:
                    line = lines.get(timeout=min(
                        5.0, max(0.1, deadline - time.time())))
                except _queue.Empty:
                    continue                 # re-check the deadline
                if line is None:
                    break
                if line.startswith("SERVING"):
                    time.sleep(0.2)          # land mid-serve
                    proc.send_signal(signal.SIGTERM)
                elif line.startswith("REPORT "):
                    report = json.loads(line[len("REPORT "):])
            try:
                rc = proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                errors.append("sigterm: child wedged — no exit within "
                              "the scenario deadline")
                return {"rc": None}
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
        if rc != 0:
            errors.append(f"sigterm: child exited {rc}: "
                          f"{proc.stderr.read()[-2000:]}")
            return {"rc": rc}
        if report is None:
            errors.append("sigterm: child never reported")
            return {"rc": rc}
        if not report["preempted"]:
            errors.append("sigterm: child finished before the signal "
                          "landed — scenario did not exercise the drain")
        if not report["all_terminal"]:
            errors.append("sigterm: requests left non-terminal after "
                          "the drain")
        if report["decode_trace_count"] > 1 or \
                report.get("verify_trace_count", 0) > 1:
            errors.append("sigterm: decode retraced in the child")
        if not report["committed_steps"]:
            errors.append("sigterm: no weight snapshot committed")
        else:
            stepdir = os.path.join(
                d, f"step_{report['committed_steps'][-1]:08d}")
            if not os.path.isdir(stepdir):
                errors.append("sigterm: reported step dir missing")
        return report


def main():
    global _SPEC_K
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="CI guard: the same scenarios, small workload")
    ap.add_argument("--json", default=None)
    ap.add_argument("--requests", type=int, default=None)
    ap.add_argument("--skip-sigterm", action="store_true",
                    help="in-process scenarios only")
    ap.add_argument("--fleet", action="store_true",
                    help="fleet (router) scenarios instead of the "
                         "single-engine set (ci/run.sh fleetsmoke)")
    ap.add_argument("--replicas", type=int, default=2,
                    help="fleet size for --fleet scenarios")
    ap.add_argument("--spec-k", type=int, default=_SPEC_K,
                    help="draft depth for every scenario engine "
                         "(0 = non-speculative)")
    ap.add_argument("--child", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--ckpt-dir", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args()

    _SPEC_K = args.spec_k

    if args.child:
        sys.exit(_child_main(args.ckpt_dir))

    n = args.requests or (10 if args.smoke else 24)
    errors = []
    t0 = time.perf_counter()
    if args.fleet:
        results = run_fleet_scenarios(n, errors,
                                      n_replicas=args.replicas)
    else:
        results = run_scenarios(n, errors)
        if not args.skip_sigterm:
            results["sigterm"] = run_sigterm_scenario(errors)
    results["wall_s_total"] = time.perf_counter() - t0
    results["n_requests"] = n

    print(json.dumps(results, indent=2))
    for e in errors:
        print(f"FAIL: {e}", file=sys.stderr)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=2)
            f.write("\n")
        print(f"banked {args.json}")
    if not errors:
        scope = "fleet" if args.fleet else "chaos"
        print(f"{scope}: all scenarios quiescent, isolated, audited, "
              f"compile-clean")
    sys.exit(0 if not errors else 1)


if __name__ == "__main__":
    main()
