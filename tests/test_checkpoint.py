"""Elastic checkpointing subsystem tests (checkpoint/).

Load-bearing claims: (1) commits are ATOMIC — a kill -9 mid-write can
never yield a loadable torn checkpoint, the previous committed step
always survives; (2) corruption fails LOUDLY with the shard named;
(3) resume through the full capsule is BIT-EXACT for gluon.Trainer
(multi-dtype fused groups + stepped lr scheduler — the PR 1 review
fixes end-to-end) and for SPMDTrainer under dp2 and fsdp2; (4) the
SIGTERM hook drains the in-flight snapshot and writes a final one;
(5) serve warm-restart reuses the compiled decode step
(tests/test_serve.py::test_warm_restart_*)."""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, nd
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu import checkpoint as ckpt
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.io import NDArrayIter, PrefetchingIter, ResizeIter
from incubator_mxnet_tpu.optimizer.lr_scheduler import FactorScheduler


# ------------------------------------------------------------------ #
# manifest format
# ------------------------------------------------------------------ #

def test_manifest_roundtrip_and_gc(tmp_path):
    import jax.numpy as jnp
    root = str(tmp_path)
    m = ckpt.CheckpointManager(root, keep=2)
    tree = {"w": jnp.arange(24.0).reshape(4, 6),
            "b": np.arange(3, dtype=np.int32),
            "s": np.float32(2.5)}
    for s in (1, 2, 3):
        m.save(s, tree, meta={"tag": s}, block=True)
    assert m.all_steps() == [2, 3]          # keep-last-2 GC ran
    arrays, meta = m.restore()
    assert meta["tag"] == 3
    np.testing.assert_array_equal(arrays["w"],
                                  np.arange(24.0).reshape(4, 6))
    np.testing.assert_array_equal(arrays["b"], np.arange(3))
    assert arrays["s"].shape == ()
    # explicit step
    arrays2, meta2 = m.restore(step=2)
    assert meta2["tag"] == 2
    m.close()


def test_async_writer_commits_and_one_in_flight(tmp_path):
    import jax.numpy as jnp
    m = ckpt.CheckpointManager(str(tmp_path), keep=0)
    tree = {"x": jnp.ones((64, 64))}
    for s in range(4):
        m.save(s, tree)                     # async; bounded at 1 in flight
    m.wait()
    assert m.all_steps() == [0, 1, 2, 3]
    m.close()


def test_background_write_error_surfaces(tmp_path):
    import jax.numpy as jnp
    m = ckpt.CheckpointManager(str(tmp_path), keep=0)
    tree = {"x": jnp.ones((4,))}
    m.save(7, tree, block=True)
    m.save(7, tree)                         # async duplicate -> fails
    with pytest.raises(MXNetError, match="background checkpoint write"):
        m.wait()
        m.save(8, tree)                     # error also reported here
        m.wait()
    m.close()


def test_transient_write_failures_retried_then_commit(tmp_path,
                                                      monkeypatch):
    """SATELLITE (round 10): n transient IO failures under the attempt
    bound are retried with backoff and the snapshot still COMMITS —
    the writer thread no longer latches a whole run's checkpointing on
    one NFS blip. Fault-injected via MXTPU_CKPT_FAIL_WRITES."""
    import jax.numpy as jnp
    monkeypatch.setenv("MXTPU_CKPT_RETRY_ATTEMPTS", "3")
    monkeypatch.setenv("MXTPU_CKPT_RETRY_BACKOFF", "0.01")
    monkeypatch.setenv("MXTPU_CKPT_FAIL_WRITES", "2")
    m = ckpt.CheckpointManager(str(tmp_path), keep=0)
    m.save(1, {"x": jnp.ones((8,))})        # async
    m.wait()                                # no error surfaced
    assert m.all_steps() == [1]
    assert m.write_retries == 2
    # the injection budget is consumed — later saves are clean
    m.save(2, {"x": jnp.ones((8,))}, block=True)
    assert m.all_steps() == [1, 2]
    assert m.write_retries == 2
    m.close()


def test_persistent_write_failure_latches_after_retries(tmp_path,
                                                        monkeypatch):
    """n+1 failures (>= the attempt bound) exhaust the retries and the
    error latches exactly as a persistent outage must — surfaced on the
    next wait()/save(), naming the injected failure."""
    import jax.numpy as jnp
    monkeypatch.setenv("MXTPU_CKPT_RETRY_ATTEMPTS", "3")
    monkeypatch.setenv("MXTPU_CKPT_RETRY_BACKOFF", "0.01")
    monkeypatch.setenv("MXTPU_CKPT_FAIL_WRITES", "3")
    m = ckpt.CheckpointManager(str(tmp_path), keep=0)
    m.save(1, {"x": jnp.ones((8,))})        # async: all 3 attempts fail
    with pytest.raises(MXNetError,
                       match="background checkpoint write"):
        m.wait()
    assert m.all_steps() == []
    assert m.write_retries == 2             # retried before latching
    m.close()


def test_sync_write_failure_raises_after_retries(tmp_path, monkeypatch):
    """The retry loop also guards the synchronous path (final
    preemption saves): under the bound it commits, over it the OSError
    propagates to the caller."""
    import jax.numpy as jnp
    monkeypatch.setenv("MXTPU_CKPT_RETRY_ATTEMPTS", "2")
    monkeypatch.setenv("MXTPU_CKPT_RETRY_BACKOFF", "0.01")
    monkeypatch.setenv("MXTPU_CKPT_FAIL_WRITES", "1")
    m = ckpt.CheckpointManager(str(tmp_path), keep=0)
    m.save(3, {"x": jnp.ones((4,))}, block=True)    # 1 failure, retried
    assert m.all_steps() == [3]
    monkeypatch.setenv("MXTPU_CKPT_FAIL_WRITES", "3")
    m._injected_failures = 0
    with pytest.raises(OSError, match="injected transient"):
        m.save(4, {"x": jnp.ones((4,))}, block=True)
    assert m.all_steps() == [3]
    m.close()


def test_torn_tmp_and_manifestless_dirs_ignored(tmp_path):
    import jax.numpy as jnp
    root = str(tmp_path)
    m = ckpt.CheckpointManager(root, keep=0)
    m.save(5, {"x": jnp.ones((8,))}, block=True)
    # a kill mid-write leaves a .tmp dir; a stray dir without manifest
    # must also never be offered for restore
    os.makedirs(os.path.join(root, "step_00000006.tmp"))
    with open(os.path.join(root, "step_00000006.tmp", "shards_p0.bin"),
              "wb") as f:
        f.write(b"\x00" * 128)
    os.makedirs(os.path.join(root, "step_00000007"))
    assert m.all_steps() == [5]
    arrays, _ = m.restore()
    assert "x" in arrays
    m.close()


def test_stale_tmp_from_aborted_attempt_is_cleared(tmp_path):
    """Regression: re-saving a step whose earlier attempt died mid-write
    must NOT commit the aborted attempt's leftover rank files — their
    manifests would merge after ours at load and overwrite fresh data."""
    import jax.numpy as jnp
    root = str(tmp_path)
    stale = ckpt.step_dir(root, 4) + ".tmp"
    os.makedirs(stale)
    with open(os.path.join(stale, "shards_p1.bin"), "wb") as f:
        f.write(b"\xde\xad" * 64)
    with open(os.path.join(stale, "manifest.p1.json"), "w") as f:
        f.write('{"arrays": {"w": {"shape": [4], "dtype": "float32", '
                '"shards": [{"file": "shards_p1.bin", "offset": 0, '
                '"nbytes": 16, "crc32": 0, "index": [[0, 4]]}]}}, '
                '"meta": {}}')
    m = ckpt.CheckpointManager(root, keep=0)
    m.save(4, {"w": jnp.arange(4.0)}, block=True)
    committed = ckpt.step_dir(root, 4)
    assert not os.path.exists(os.path.join(committed, "shards_p1.bin"))
    arrays, _ = m.restore(step=4)
    np.testing.assert_array_equal(arrays["w"], np.arange(4.0))
    m.close()


def test_corrupt_shard_fails_loudly_naming_shard(tmp_path):
    import jax.numpy as jnp
    root = str(tmp_path)
    m = ckpt.CheckpointManager(root, keep=0)
    m.save(1, {"w": jnp.arange(256.0), "v": jnp.ones((16,))}, block=True)
    shard = os.path.join(ckpt.step_dir(root, 1), "shards_p0.bin")
    with open(shard, "r+b") as f:           # flip one byte mid-file
        f.seek(100)
        b = f.read(1)
        f.seek(100)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(MXNetError) as ei:
        m.restore(step=1)
    msg = str(ei.value)
    assert "shards_p0.bin" in msg and "crc32" in msg
    m.close()


def test_missing_shard_file_fails_loudly(tmp_path):
    import jax.numpy as jnp
    root = str(tmp_path)
    m = ckpt.CheckpointManager(root, keep=0)
    m.save(1, {"w": jnp.ones((8, 8))}, block=True)
    os.remove(os.path.join(ckpt.step_dir(root, 1), "shards_p0.bin"))
    with pytest.raises(MXNetError, match="shards_p0.bin"):
        m.restore(step=1)
    m.close()


def test_kill9_mid_shard_previous_commit_survives(tmp_path):
    """Fault injection: SIGKILL the process while the background writer
    is mid-shard (deterministically, via the MXTPU_CKPT_WRITE_DELAY
    throttle hook). The previously committed step must load; the torn
    step must be invisible."""
    root = str(tmp_path / "ckpts")
    script = f"""
import os
os.environ['JAX_PLATFORMS'] = 'cpu'
import numpy as np
from incubator_mxnet_tpu import checkpoint as ckpt
m = ckpt.CheckpointManager({root!r}, keep=0)
tree1 = {{f'a{{i}}': np.full((32,), i, np.float32) for i in range(8)}}
m.save(1, tree1, meta={{'ok': True}}, block=True)
print('COMMITTED', flush=True)
os.environ['MXTPU_CKPT_WRITE_DELAY'] = '0.05'
big = {{f'b{{i}}': np.full((64,), i, np.float32) for i in range(200)}}
m.save(2, big)                       # async: ~10s of throttled writing
print('WRITING', flush=True)
import time; time.sleep(60)
"""
    proc = subprocess.Popen([sys.executable, "-c", script],
                            stdout=subprocess.PIPE, cwd=os.path.dirname(
                                os.path.dirname(os.path.abspath(__file__))),
                            text=True)
    try:
        tmp_dir = ckpt.step_dir(root, 2) + ".tmp"
        deadline = time.time() + 120
        saw_writing = False
        while time.time() < deadline:
            line = proc.stdout.readline()
            if "WRITING" in line:
                saw_writing = True
                break
        assert saw_writing, "child never started the async write"
        # wait until at least one shard byte of the torn step is on disk
        shard = os.path.join(tmp_dir, "shards_p0.bin")
        while time.time() < deadline:
            if os.path.exists(shard) and os.path.getsize(shard) > 0:
                break
            time.sleep(0.01)
        proc.kill()                          # SIGKILL mid-shard
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert os.path.exists(tmp_dir), "expected a torn .tmp dir"
    assert ckpt.list_steps(root) == [1], "torn step leaked into commits"
    arrays, meta = ckpt.load_step(root, 1)
    assert meta == {"ok": True}
    for i in range(8):
        np.testing.assert_array_equal(arrays[f"a{i}"],
                                      np.full((32,), i, np.float32))


# ------------------------------------------------------------------ #
# bit-exact resume: gluon.Trainer (multi-dtype fused + scheduler)
# ------------------------------------------------------------------ #

_RNG = np.random.RandomState(0)
_X = _RNG.randn(80, 8).astype(np.float32)
_Y = _RNG.randn(80, 8).astype(np.float32)


def _make_trainer(seed):
    """Two dtype groups (f32 + f16 Dense) + a stepped FactorScheduler:
    resuming through the capsule must reproduce an uninterrupted run
    exactly — this guards BOTH PR 1 review fixes (hoisted multi-group
    scheduler lr read; fused applier rebind on load) end-to-end."""
    mx.random.seed(seed)
    net = nn.Sequential()
    net.add(nn.Dense(16, in_units=8))
    net.add(nn.Dense(8, in_units=16))
    net.initialize()
    for p in net[1].collect_params().values():
        p.cast("float16")
    sched = FactorScheduler(step=3, factor=0.5)
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 1e-2, "lr_scheduler": sched},
                       kvstore=None, fuse_step=True)
    return net, tr


def _train_steps(net, tr, it, n, loss_fn):
    out = []
    for _ in range(n):
        b = it.next()
        x, y = b.data[0], b.label[0]
        with autograd.record():
            # explicit activation casts around the f16 layer: the
            # supported mixed-precision idiom (astype records a Cast on
            # the tape, so f16 params get real gradients)
            h = net[0](x).astype("float16")
            L = loss_fn(net[1](h).astype("float32"), y)
        L.backward()
        tr.step(x.shape[0])
        out.append(float(L.mean().asnumpy()))
    return out


def test_trainer_capsule_resume_bit_exact_multi_dtype_scheduler(tmp_path):
    loss_fn = gluon.loss.L2Loss()
    net, tr = _make_trainer(0)
    it = NDArrayIter(_X, _Y, batch_size=8, shuffle=True)
    ref = _train_steps(net, tr, it, 8, loss_fn)
    assert tr._fused is not None and len(tr._fused._jits) >= 2, \
        "test needs >= 2 fused dtype groups"

    net2, tr2 = _make_trainer(0)
    it2 = NDArrayIter(_X, _Y, batch_size=8, shuffle=True)
    _ = _train_steps(net2, tr2, it2, 4, loss_fn)
    m = ckpt.CheckpointManager(str(tmp_path), keep=3)
    saved = tr2.save_checkpoint(m, iterator=it2)
    m.wait()
    assert m.all_steps() == [saved]

    # "new process": different seed so any missed restore diverges
    net3, tr3 = _make_trainer(99)
    it3 = NDArrayIter(_X, _Y, batch_size=8, shuffle=True)
    got = tr3.restore_checkpoint(m, iterator=it3)
    assert got == saved
    res = _train_steps(net3, tr3, it3, 4, loss_fn)
    assert res == ref[4:], (
        f"resume diverged: {res} vs uninterrupted {ref[4:]}")
    assert tr3._optimizer.num_update == tr._optimizer.num_update
    assert tr3.learning_rate == tr.learning_rate
    m.close()


def test_save_states_routes_through_capsule_and_reads_legacy(tmp_path):
    loss_fn = gluon.loss.L2Loss()
    net, tr = _make_trainer(0)
    it = NDArrayIter(_X, _Y, batch_size=8)
    _train_steps(net, tr, it, 2, loss_fn)
    fname = str(tmp_path / "t.states")
    tr.save_states(fname)
    with open(fname, "rb") as f:
        assert f.read(8) == ckpt.CAPSULE_MAGIC   # new on-disk format
    net2, tr2 = _make_trainer(0)
    _train_steps(net2, tr2, NDArrayIter(_X, _Y, batch_size=8), 1, loss_fn)
    tr2.load_states(fname)
    assert tr2._optimizer.num_update == tr._optimizer.num_update
    for i, st in tr._updaters[0].states.items():
        got = tr2._updaters[0].states[i]
        import jax.tree_util as jtu
        for a, b in zip(jtu.tree_leaves(st, is_leaf=ckpt.capsule._is_nd),
                        jtu.tree_leaves(got,
                                        is_leaf=ckpt.capsule._is_nd)):
            np.testing.assert_array_equal(a.asnumpy(), b.asnumpy())
    # legacy pickle payloads still load (magic-byte dispatch)
    legacy = str(tmp_path / "legacy.states")
    with open(legacy, "wb") as f:
        f.write(tr._updaters[0].get_states(dump_optimizer=False))
    net3, tr3 = _make_trainer(0)
    _train_steps(net3, tr3, NDArrayIter(_X, _Y, batch_size=8), 1, loss_fn)
    tr3.load_states(legacy)
    assert tr3._optimizer.num_update == tr._optimizer.num_update


def test_load_ndarrays_opens_capsule_blob(tmp_path):
    net, tr = _make_trainer(0)
    it = NDArrayIter(_X, _Y, batch_size=8)
    _train_steps(net, tr, it, 1, gluon.loss.L2Loss())
    tree, meta = ckpt.trainer_capsule(tr)
    fname = str(tmp_path / "run.capsule")
    ckpt.save_capsule_file(fname, tree, meta)
    loaded = nd.load(fname)
    for p in tr._params:
        np.testing.assert_array_equal(loaded[p.name].asnumpy(),
                                      p.data().asnumpy())


# ------------------------------------------------------------------ #
# bit-exact resume: SPMDTrainer (dp2 / fsdp2)
# ------------------------------------------------------------------ #

@pytest.mark.parametrize("mode,axes", [("replicated", {"dp": 2}),
                                       ("fsdp", {"fsdp": 2})])
def test_spmd_capsule_resume_bit_exact(tmp_path, mode, axes):
    import jax
    from incubator_mxnet_tpu import parallel
    from incubator_mxnet_tpu.parallel.mesh import build_mesh
    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual multi-device mesh")
    mesh = build_mesh(devices=jax.devices()[:2], axis_sizes=axes)
    xs, ys = nd.array(_X[:16]), nd.array(_Y[:16])

    def make(seed):
        mx.random.seed(seed)
        net = nn.Sequential()
        net.add(nn.Dense(16, in_units=8))
        net.add(nn.Dense(8, in_units=16))
        net.initialize()
        tr = parallel.SPMDTrainer(
            net, loss=lambda o, y: ((o - y) ** 2).mean(),
            optimizer="adam", optimizer_params={"learning_rate": 1e-2},
            mesh=mesh, sharding=mode)
        return net, tr

    _, tr = make(0)
    ref = [float(tr.step(xs, ys).asnumpy()) for _ in range(6)]
    _, tr2 = make(0)
    _ = [float(tr2.step(xs, ys).asnumpy()) for _ in range(3)]
    m = ckpt.CheckpointManager(str(tmp_path), keep=2)
    saved = tr2.save_checkpoint(m)
    m.wait()
    _, tr3 = make(7)
    got = tr3.restore_checkpoint(m)
    assert got == saved == 3
    res = [float(tr3.step(xs, ys).asnumpy()) for _ in range(3)]
    assert res == ref[3:], (
        f"{mode} resume diverged: {res} vs {ref[3:]}")
    m.close()


def test_spmd_fsdp_capsule_saves_unique_shards(tmp_path):
    """fsdp-sharded state must checkpoint each global shard ONCE (the
    addressable replica-0 dedup), and the manifest must record the
    sharding spec."""
    import jax
    from incubator_mxnet_tpu import parallel
    from incubator_mxnet_tpu.parallel.mesh import build_mesh
    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual multi-device mesh")
    mesh = build_mesh(devices=jax.devices()[:2],
                      axis_sizes={"fsdp": 2})
    mx.random.seed(0)
    net = nn.Dense(64, in_units=512)     # big enough for fsdp to shard
    net.initialize()
    tr = parallel.SPMDTrainer(
        net, loss=lambda o, y: ((o - y) ** 2).mean(),
        optimizer="sgd", optimizer_params={"learning_rate": 1e-2},
        mesh=mesh, sharding="fsdp")
    x = nd.array(_RNG.randn(8, 512).astype(np.float32))
    y = nd.array(_RNG.randn(8, 64).astype(np.float32))
    tr.step(x, y)
    m = ckpt.CheckpointManager(str(tmp_path), keep=0)
    tr.save_checkpoint(m, block=True)
    import json
    with open(os.path.join(ckpt.step_dir(str(tmp_path), 1),
                           "manifest.json")) as f:
        man = json.load(f)
    w = man["arrays"]["param/0"]         # the (64, 512) weight
    assert w["spec"] is not None and "fsdp" in w["spec"]
    n_elems = sum(
        int(np.prod([b - a for a, b in sh["index"]]))
        for sh in w["shards"])
    assert n_elems == 64 * 512           # each element saved exactly once
    m.close()


# ------------------------------------------------------------------ #
# preemption
# ------------------------------------------------------------------ #

def test_sigterm_drains_inflight_and_saves_final_capsule(tmp_path):
    loss_fn = gluon.loss.L2Loss()
    net, tr = _make_trainer(0)
    it = NDArrayIter(_X, _Y, batch_size=8)
    _train_steps(net, tr, it, 3, loss_fn)
    m = ckpt.CheckpointManager(str(tmp_path), keep=0)
    # park a slow snapshot in flight, then preempt
    os.environ["MXTPU_CKPT_WRITE_DELAY"] = "0.01"
    try:
        tr.save_checkpoint(m, step=100, iterator=it)
        tr.install_preemption(m, iterator=it, exit_after=False)
        os.kill(os.getpid(), signal.SIGTERM)
    finally:
        os.environ.pop("MXTPU_CKPT_WRITE_DELAY", None)
        m.uninstall_preemption_hook()
    # both the in-flight step AND the final sync capsule are committed
    steps = m.all_steps()
    assert 100 in steps
    assert tr._optimizer.num_update in steps
    arrays, meta = m.restore(step=tr._optimizer.num_update)
    assert meta.get("preempted") is True
    net2, tr2 = _make_trainer(1)
    it2 = NDArrayIter(_X, _Y, batch_size=8)
    tr2.restore_checkpoint(m, step=tr._optimizer.num_update,
                           iterator=it2)
    for a, b in zip(tr._params, tr2._params):
        np.testing.assert_array_equal(a.data().asnumpy(),
                                      b.data().asnumpy())
    m.close()


def test_sigterm_skips_when_step_already_committed(tmp_path):
    loss_fn = gluon.loss.L2Loss()
    net, tr = _make_trainer(0)
    it = NDArrayIter(_X, _Y, batch_size=8)
    _train_steps(net, tr, it, 2, loss_fn)
    m = ckpt.CheckpointManager(str(tmp_path), keep=0)
    tr.save_checkpoint(m, iterator=it, block=True)
    before = m.all_steps()
    tr.install_preemption(m, iterator=it, exit_after=False)
    try:
        os.kill(os.getpid(), signal.SIGTERM)
    finally:
        m.uninstall_preemption_hook()
    assert m.all_steps() == before       # no duplicate-step crash
    m.close()


# ------------------------------------------------------------------ #
# iterator position export
# ------------------------------------------------------------------ #

def test_ndarrayiter_tell_set_position_mid_epoch_shuffled():
    a = NDArrayIter(_X, _Y, batch_size=8, shuffle=True)
    first = [a.next() for _ in range(4)]
    pos = a.tell()
    rest_ref = [b.data[0].asnumpy() for b in list(a)]
    b_it = NDArrayIter(_X, _Y, batch_size=8, shuffle=True)
    b_it.set_position(pos)
    rest = [b.data[0].asnumpy() for b in list(b_it)]
    assert len(rest) == len(rest_ref)
    for r, rr in zip(rest, rest_ref):
        np.testing.assert_array_equal(r, rr)


def test_prefetching_iter_reports_resumable_position():
    inner = NDArrayIter(_X, _Y, batch_size=8, shuffle=True)
    pf = PrefetchingIter(inner)
    seen = [pf.next().data[0].asnumpy() for _ in range(4)]
    pos = pf.tell()
    assert pos["delivered"] == 4
    rest_ref = [b.data[0].asnumpy() for b in list(pf)]
    # fresh wrapper (fresh inner) resumed from the exported position
    pf2 = PrefetchingIter(NDArrayIter(_X, _Y, batch_size=8,
                                      shuffle=True))
    pf2.set_position(pos)
    rest = [b.data[0].asnumpy() for b in list(pf2)]
    assert len(rest) == len(rest_ref)
    for r, rr in zip(rest, rest_ref):
        np.testing.assert_array_equal(r, rr)


def test_resize_iter_position_delegates():
    r = ResizeIter(NDArrayIter(_X, _Y, batch_size=8), size=6)
    r.next(), r.next()
    pos = r.tell()
    assert pos["cur"] == 2 and pos["inner"]["cursor"] >= 0
    r2 = ResizeIter(NDArrayIter(_X, _Y, batch_size=8), size=6)
    r2.set_position(pos)
    np.testing.assert_array_equal(r2.next().data[0].asnumpy(),
                                  r.next().data[0].asnumpy())


def test_non_resumable_iterator_refuses_loudly():
    from incubator_mxnet_tpu.io import DataIter
    with pytest.raises(MXNetError, match="position export"):
        DataIter().tell()


# --------------------------------------------------------------------- #
# corrupt-latest fallback (round 13): keep-last-k earns its keep
# --------------------------------------------------------------------- #

def test_restore_falls_back_to_previous_step_on_corrupt_latest(tmp_path):
    """A truncated shard in the newest step must not fail the run:
    restore() walks back to the previous committed step, warning
    loudly and naming the bad shard."""
    import jax.numpy as jnp
    root = str(tmp_path)
    m = ckpt.CheckpointManager(root, keep=3)
    for s in (1, 2, 3):
        m.save(s, {"w": jnp.full((64,), float(s))}, block=True)
    shard = os.path.join(ckpt.step_dir(root, 3), "shards_p0.bin")
    with open(shard, "r+b") as f:           # truncate the latest shard
        f.truncate(17)
    with pytest.warns(RuntimeWarning, match="step 3 is unreadable"):
        arrays, _ = m.restore()
    np.testing.assert_array_equal(arrays["w"], np.full((64,), 2.0))
    assert m.restore_fallbacks == 1
    # an EXPLICIT step request still fails loudly, naming the shard
    with pytest.raises(MXNetError, match="shards_p0.bin"):
        m.restore(step=3)
    # fallback=False restores the old latest-or-die behavior
    with pytest.raises(MXNetError, match="shards_p0.bin"):
        m.restore(fallback=False)
    m.close()


def test_restore_every_step_corrupt_raises(tmp_path):
    import jax.numpy as jnp
    root = str(tmp_path)
    m = ckpt.CheckpointManager(root, keep=2)
    for s in (1, 2):
        m.save(s, {"w": jnp.ones((32,))}, block=True)
    for s in (1, 2):
        with open(os.path.join(ckpt.step_dir(root, s),
                               "shards_p0.bin"), "r+b") as f:
            f.truncate(3)
    with pytest.warns(RuntimeWarning):
        with pytest.raises(MXNetError, match="every committed"):
            m.restore()
    m.close()


def test_restore_falls_back_on_corrupt_manifest(tmp_path):
    """Manifest corruption (not just shard corruption) must also walk
    back — json/structure errors are 'this step is damaged' too."""
    import jax.numpy as jnp
    root = str(tmp_path)
    m = ckpt.CheckpointManager(root, keep=3)
    for s in (1, 2):
        m.save(s, {"w": jnp.full((16,), float(s))}, block=True)
    mpath = os.path.join(ckpt.step_dir(root, 2), "manifest.json")
    with open(mpath, "w") as f:
        f.write('{"format_version": 1, "arrays": {TRUNCATED')
    with pytest.warns(RuntimeWarning, match="step 2 is unreadable"):
        arrays, _ = m.restore()
    np.testing.assert_array_equal(arrays["w"], np.full((16,), 1.0))
    m.close()
