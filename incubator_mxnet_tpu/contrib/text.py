"""Text utilities (parity: python/mxnet/contrib/text/{utils,vocab,
embedding}.py — file-level citation, SURVEY.md caveat).

Token counting, vocabulary indexing, and token embeddings. The
embedding lookup returns device NDArrays; file-backed pretrained
formats load the whitespace ``token v1 v2 ...`` layout the reference's
TokenEmbedding readers consume (GloVe-style)."""

from __future__ import annotations

import collections
import os
import re
from typing import Dict, List, Optional, Sequence, Union

import numpy as _np

from ..base import MXNetError
from ..ndarray import NDArray, array as nd_array

__all__ = ["count_tokens_from_str", "Vocabulary", "CustomEmbedding",
           "TokenEmbedding"]


def count_tokens_from_str(source_str: str, token_delim: str = " ",
                          seq_delim: str = "\n", to_lower: bool = False,
                          counter_to_update: Optional[
                              collections.Counter] = None):
    """Tokenize a string and count tokens (reference:
    contrib/text/utils.py count_tokens_from_str)."""
    source_str = re.sub(rf"{re.escape(token_delim)}+|"
                        rf"{re.escape(seq_delim)}+",
                        " ", source_str)
    if to_lower:
        source_str = source_str.lower()
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    counter.update(t for t in source_str.split(" ") if t)
    return counter


class Vocabulary:
    """Indexed vocabulary (reference: contrib/text/vocab.py Vocabulary).

    Index 0 is the unknown token; ``reserved_tokens`` follow; the rest
    are counter keys sorted by frequency (ties broken alphabetically),
    capped by ``most_freq_count`` and filtered by ``min_freq``."""

    def __init__(self, counter: Optional[collections.Counter] = None,
                 most_freq_count: Optional[int] = None, min_freq: int = 1,
                 unknown_token: str = "<unk>",
                 reserved_tokens: Optional[List[str]] = None):
        if min_freq < 1:
            raise MXNetError("min_freq must be >= 1")
        reserved_tokens = list(reserved_tokens or [])
        if unknown_token in reserved_tokens:
            raise MXNetError("unknown_token must not be reserved")
        if len(set(reserved_tokens)) != len(reserved_tokens):
            raise MXNetError("reserved_tokens must be unique")
        self._unknown_token = unknown_token
        self._reserved_tokens = reserved_tokens
        self._idx_to_token = [unknown_token] + reserved_tokens
        if counter is not None:
            pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
            if most_freq_count is not None:
                pairs = pairs[:most_freq_count]
            for tok, freq in pairs:
                if freq < min_freq or tok == unknown_token \
                        or tok in reserved_tokens:
                    continue
                self._idx_to_token.append(tok)
        self._token_to_idx = {t: i for i, t in
                              enumerate(self._idx_to_token)}

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def idx_to_token(self) -> List[str]:
        return self._idx_to_token

    @property
    def token_to_idx(self) -> Dict[str, int]:
        return self._token_to_idx

    @property
    def unknown_token(self) -> str:
        return self._unknown_token

    @property
    def reserved_tokens(self) -> List[str]:
        return self._reserved_tokens

    def to_indices(self, tokens: Union[str, Sequence[str]]):
        single = isinstance(tokens, str)
        toks = [tokens] if single else list(tokens)
        idx = [self._token_to_idx.get(t, 0) for t in toks]
        return idx[0] if single else idx

    def to_tokens(self, indices: Union[int, Sequence[int]]):
        single = isinstance(indices, int)
        idxs = [indices] if single else list(indices)
        for i in idxs:
            if not 0 <= i < len(self._idx_to_token):
                raise MXNetError(f"index {i} out of vocabulary range")
        toks = [self._idx_to_token[i] for i in idxs]
        return toks[0] if single else toks


class TokenEmbedding(Vocabulary):
    """Token → vector mapping (reference: contrib/text/embedding.py
    _TokenEmbedding). Unknown tokens get ``init_unknown_vec`` (zeros)."""

    def __init__(self, vec_len: int, **kwargs):
        super().__init__(**kwargs)
        self._vec_len = int(vec_len)
        self._idx_to_vec = _np.zeros(
            (len(self._idx_to_token), self._vec_len), _np.float32)

    @property
    def vec_len(self) -> int:
        return self._vec_len

    @property
    def idx_to_vec(self) -> NDArray:
        return nd_array(self._idx_to_vec)

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        single = isinstance(tokens, str)
        toks = [tokens] if single else list(tokens)
        if lower_case_backup:
            toks = [t if t in self._token_to_idx else t.lower()
                    for t in toks]
        idx = [self._token_to_idx.get(t, 0) for t in toks]
        vecs = self._idx_to_vec[_np.asarray(idx, _np.int64)]
        return nd_array(vecs[0] if single else vecs)

    def update_token_vectors(self, tokens, new_vectors):
        toks = [tokens] if isinstance(tokens, str) else list(tokens)
        vecs = new_vectors.asnumpy() if isinstance(new_vectors, NDArray) \
            else _np.asarray(new_vectors)
        vecs = vecs.reshape(len(toks), self._vec_len)
        for t, v in zip(toks, vecs):
            if t not in self._token_to_idx:
                raise MXNetError(f"token {t!r} not in the embedding")
            self._idx_to_vec[self._token_to_idx[t]] = v

    @classmethod
    def from_file(cls, file_path: str, elem_delim: str = " ",
                  **kwargs) -> "TokenEmbedding":
        """Load a GloVe-style text file: ``token v1 v2 ...`` per line."""
        tokens, rows = [], []
        with open(file_path, encoding="utf-8") as f:
            for line in f:
                parts = line.rstrip("\n").split(elem_delim)
                if len(parts) < 2:
                    continue
                tokens.append(parts[0])
                rows.append([float(x) for x in parts[1:]])
        if not rows:
            raise MXNetError(f"no embedding vectors in {file_path!r}")
        vec_len = len(rows[0])
        counter = collections.Counter(tokens)
        emb = cls(vec_len, counter=counter, **kwargs)
        for t, r in zip(tokens, rows):
            if len(r) != vec_len:
                raise MXNetError(
                    f"inconsistent vector length for token {t!r}")
            emb._idx_to_vec[emb._token_to_idx[t]] = _np.asarray(
                r, _np.float32)
        return emb


class CustomEmbedding(TokenEmbedding):
    """Embedding built from an in-memory token → vector mapping
    (reference: contrib/text/embedding.py CustomEmbedding)."""

    def __init__(self, token_to_vec: Dict[str, Sequence[float]], **kwargs):
        if not token_to_vec:
            raise MXNetError("empty token_to_vec")
        lens = {len(v) for v in token_to_vec.values()}
        if len(lens) != 1:
            raise MXNetError("all vectors must share one length")
        counter = collections.Counter(token_to_vec.keys())
        super().__init__(lens.pop(), counter=counter, **kwargs)
        for t, v in token_to_vec.items():
            self._idx_to_vec[self._token_to_idx[t]] = _np.asarray(
                v, _np.float32)


# ------------------------------------------------------------------ #
# reference submodule layout (python/mxnet/contrib/text/{embedding,
# vocab,utils}.py): real module objects registered in sys.modules so
# every import form works (`from ...text import embedding`,
# `import ...text.embedding`, `from ...text.embedding import ...`)
# ------------------------------------------------------------------ #
import sys as _sys
import types as _types


def _submodule(name, **names):
    mod = _types.ModuleType(__name__ + "." + name)
    for k, v in names.items():
        setattr(mod, k, v)
    _sys.modules[mod.__name__] = mod
    return mod


embedding = _submodule("embedding", TokenEmbedding=TokenEmbedding,
                       CustomEmbedding=CustomEmbedding)
vocab = _submodule("vocab", Vocabulary=Vocabulary)
utils = _submodule("utils",
                   count_tokens_from_str=count_tokens_from_str)
