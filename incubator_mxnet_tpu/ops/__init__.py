"""Operator library (TPU-native re-design of `src/operator/**` — SURVEY.md §2.1).

Importing this package registers all operators into the registry; both the
``mx.nd`` and ``mx.sym`` front ends are generated from it (one registration
serving both front ends, mirroring the reference's single NNVM registry).
"""

from . import registry
from . import tensor  # mxlint: allow-import-effect(registers ops)
from . import nn  # mxlint: allow-import-effect(registers ops)
from . import random_ops  # mxlint: allow-import-effect(registers ops)
from . import optimizer_ops  # mxlint: allow-import-effect(registers ops)
from . import attention  # mxlint: allow-import-effect(registers ops)
from . import rnn  # mxlint: allow-import-effect(registers ops)
from . import contrib  # mxlint: allow-import-effect(registers ops)
from . import vision  # mxlint: allow-import-effect(registers ops)
from . import misc  # mxlint: allow-import-effect(registers ops)
from . import linalg  # mxlint: allow-import-effect(registers ops)
from . import quantization  # mxlint: allow-import-effect(registers ops)
from .registry import get, list_all_ops, describe_op, register
