"""The full per-slot sampling menu — pure data through the jit-once
decode contract (docs/SERVING.md "Sampling").

Until round 18 the engine sampled greedy/temperature only. Every
production serving API exposes more: top-k / top-p (nucleus)
truncation, repetition/presence penalties, per-token logit bias,
multi-token stop sequences, and grammar/JSON-constrained decoding.
This module is that menu, designed around the engine's one invariant:
EVERYTHING is per-slot DATA into the already-compiled programs — a
(S,) knob vector, a (S, V) bias/count table, a (S, W, V) vocabulary
mask — never a new shape, never a retrace (``decode_trace_count`` /
``verify_trace_count`` stay 1 under every parameter combination;
asserted in tests/test_sampling.py and serve_bench ``--frontend
--smoke``).

Three layers:

  - ``SamplingParams``: the per-request knob bundle a ``Request``
    carries (``Request.sampling``). Neutral values are exact
    identities by construction — every filter is applied through a
    ``jnp.where(enabled, filtered, logits)`` select, so a request
    with top_k=0 / top_p=1.0 / penalties off emits tokens
    BIT-IDENTICAL to the pre-round-18 engine (asserted).
  - ``constrain_logits``: the ONE traced transform every sampling
    site shares — dense prefill, chunked prefill, the W=1 decode
    step, and every column of the K+1-wide speculative verify. Order:
    logit bias → repetition/presence penalties (over the token-count
    table) → vocabulary mask → top-k → top-p (nucleus over the
    temperature-scaled distribution). The mask comes BEFORE the
    truncations so they operate WITHIN the legal set: neither can
    resurrect a masked token (they only lower logits), and neither
    can empty the legal set — grammar + top_k=1 emits the best
    LEGAL token instead of collapsing the whole vocab to the floor.
  - ``TokenGrammar`` / ``TokenFsm`` / ``choice_grammar``:
    grammar-constrained decoding as a per-slot vocabulary mask. The
    grammar is a host-side DFA over TOKEN IDS (this repo has no
    tokenizer — a real BNF/JSON-schema compiler targets the same
    ``mask(state, eos_id)`` surface); the engine advances the state
    per recorded token and, under speculation, along the draft chain,
    shipping a (W, V) mask block per slot so every verify column is
    constrained at ITS OWN grammar state. A drafted token the grammar
    forbids has probability 0 under the masked target distribution,
    so the PR-6 rejection-sampling acceptance rejects it and resamples
    from the masked residual — speculation stays distribution-correct
    under truncated AND masked proposals (the degenerate case where
    the mask leaves a single allowed token is force-accepted: the
    residual has no mass, and the target distribution is that point
    mass).

Speculative correctness under truncation (the round-18 extension of
the PR-6 argument): the draft proposal is a point mass q = δ_d, and
acceptance tests ``log u < log p̃(d)`` where p̃ is the FULLY
constrained target (bias, penalties with in-window count updates,
top-k/top-p truncation, grammar mask). On rejection the emission
resamples from p̃ with d's mass removed — exactly max(p̃ - q, 0)
renormalized. The emitted distribution is therefore p̃ itself,
whatever the proposal — the same theorem as PR 6, now over the
constrained distribution.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError

__all__ = ["SamplingParams", "TokenGrammar", "TokenFsm",
           "choice_grammar", "constrain_logits", "grammar_mask",
           "match_stop", "NEUTRAL"]

_NEG_BIG = -1e30                       # matches serve/engine.py


# --------------------------------------------------------------------- #
# grammars: host-side DFAs over token ids -> per-state vocabulary masks
# --------------------------------------------------------------------- #

class TokenGrammar:
    """Interface a constrained-decoding grammar implements. States are
    small immutable handles (ints): the engine stores one per slot,
    re-derives it from the generated history on preemption/failover
    resume (determinism is part of the contract), and advances COPIES
    along speculative draft chains.

    ``vocab_size`` must equal the serving model's — validated at
    engine admission (mismatch is FAILED_UNSERVABLE, fail-fast)."""

    vocab_size: int

    def start(self):
        raise NotImplementedError

    def advance(self, state, token: int):
        """The state after consuming ``token``, or None when the
        grammar forbids it (callers treat None as 'keep state' for
        robustness — the mask should have made it unreachable)."""
        raise NotImplementedError

    def allowed(self, state) -> np.ndarray:
        """Bool (V,) of tokens with an outgoing transition. Callers
        must NOT mutate the returned array (it may be cached)."""
        raise NotImplementedError

    def accepting(self, state) -> bool:
        """True when the generated text so far is a complete sentence
        of the grammar — EOS becomes legal."""
        raise NotImplementedError


class TokenFsm(TokenGrammar):
    """Explicit DFA over token ids: ``transitions[state][token] ->
    state``; ``accept`` is the set of accepting states. The generic
    carrier every higher-level grammar compiles down to."""

    def __init__(self, vocab_size: int, transitions: Dict[int, Dict[int, int]],
                 start_state: int = 0, accept=()):
        self.vocab_size = int(vocab_size)
        self.transitions = {int(s): {int(t): int(n) for t, n in d.items()}
                            for s, d in transitions.items()}
        self.start_state = int(start_state)
        self.accept = frozenset(int(s) for s in accept)
        for s, d in self.transitions.items():
            for t in d:
                if not (0 <= t < self.vocab_size):
                    raise MXNetError(f"grammar transition on token {t} "
                                     f"outside vocab [0, {vocab_size})")
        self._allowed_cache: Dict[int, np.ndarray] = {}

    def start(self):
        return self.start_state

    def advance(self, state, token: int):
        return self.transitions.get(state, {}).get(int(token))

    def allowed(self, state) -> np.ndarray:
        m = self._allowed_cache.get(state)
        if m is None:
            m = np.zeros((self.vocab_size,), bool)
            for t in self.transitions.get(state, {}):
                m[t] = True
            self._allowed_cache[state] = m
        return m

    def accepting(self, state) -> bool:
        return state in self.accept


def choice_grammar(sequences: Sequence[Sequence[int]],
                   vocab_size: int) -> TokenFsm:
    """A grammar accepting EXACTLY ONE of ``sequences`` (a trie DFA) —
    the constrained agent/tool-call shape: the model must emit one of
    a fixed menu of token templates, then stop. Shared prefixes share
    trie states, so the mask mid-prefix is the union of the surviving
    continuations."""
    if not sequences:
        raise MXNetError("choice_grammar needs at least one sequence")
    transitions: Dict[int, Dict[int, int]] = {0: {}}
    accept = set()
    next_state = 1
    for seq in sequences:
        seq = [int(t) for t in seq]
        if not seq:
            raise MXNetError("choice_grammar sequences must be "
                             "non-empty")
        state = 0
        for tok in seq:
            nxt = transitions.setdefault(state, {}).get(tok)
            if nxt is None:
                nxt = next_state
                next_state += 1
                transitions[state][tok] = nxt
                transitions.setdefault(nxt, {})
            state = nxt
        accept.add(state)
    return TokenFsm(vocab_size, transitions, 0, accept)


def grammar_mask(grammar: TokenGrammar, state, eos_id: int) -> np.ndarray:
    """The (V,) bool mask for the NEXT token at ``state``: every token
    with an outgoing transition, plus EOS when the state accepts. A
    dead end (no outgoing) forces EOS — the only honest move left;
    ``SamplingParams`` validation requires ``eos_id >= 0`` whenever a
    grammar is set, so the forced finish always has a token."""
    m = grammar.allowed(state)
    if eos_id < 0:
        return m
    out = m.copy()
    out[eos_id] = grammar.accepting(state) or not m.any()
    return out


# --------------------------------------------------------------------- #
# the per-request knob bundle
# --------------------------------------------------------------------- #

@dataclasses.dataclass
class SamplingParams:
    """Per-request sampling configuration (``Request.sampling``).

    ``top_k`` 0 disables (full vocab); ``top_p`` 1.0 disables;
    ``repetition_penalty`` (HF convention: seen-token logits divided
    by it when positive, multiplied when negative) 1.0 disables;
    ``presence_penalty`` (flat subtraction from seen tokens) 0.0
    disables. BOTH penalties act on tokens present in the FULL history
    — prompt plus generated. (The OpenAI convention penalizes
    generated tokens only; the full-history definition is what keeps a
    preemption/failover resume — where emitted tokens re-enter as the
    replay attempt's prompt — bit-identical to the unbroken run, which
    this engine guarantees for every knob.)

    ``logit_bias`` maps token id -> additive bias (ban a token with a
    large negative value). ``stop_sequences`` are token-id sequences:
    generation stops with ``Outcome.STOP`` when the generated stream
    ends with one, and the matched sequence is NOT included in the
    output (the common API semantic). ``grammar`` constrains decoding
    to a ``TokenGrammar``'s language via a per-step vocabulary mask;
    it requires the request to have ``eos_id >= 0`` (grammar
    completion is expressed by making EOS legal)."""

    top_k: int = 0
    top_p: float = 1.0
    repetition_penalty: float = 1.0
    presence_penalty: float = 0.0
    logit_bias: Optional[Dict[int, float]] = None
    stop_sequences: Tuple[Tuple[int, ...], ...] = ()
    grammar: Optional[TokenGrammar] = None

    def __post_init__(self):
        self.top_k = int(self.top_k)
        if self.top_k < 0:
            raise MXNetError(f"top_k must be >= 0, got {self.top_k}")
        self.top_p = float(self.top_p)
        if not (0.0 < self.top_p <= 1.0):
            raise MXNetError(f"top_p must be in (0, 1], got "
                             f"{self.top_p}")
        self.repetition_penalty = float(self.repetition_penalty)
        if self.repetition_penalty <= 0.0:
            raise MXNetError(f"repetition_penalty must be > 0, got "
                             f"{self.repetition_penalty}")
        self.presence_penalty = float(self.presence_penalty)
        if self.logit_bias is not None:
            self.logit_bias = {int(t): float(b)
                               for t, b in self.logit_bias.items()}
        seqs = []
        for seq in self.stop_sequences:
            seq = tuple(int(t) for t in seq)
            if not seq:
                raise MXNetError("stop sequences must be non-empty")
            seqs.append(seq)
        self.stop_sequences = tuple(seqs)
        if self.grammar is not None and \
                not isinstance(self.grammar, TokenGrammar):
            raise MXNetError(f"grammar must be a TokenGrammar, got "
                             f"{type(self.grammar).__name__}")

    @property
    def max_stop_len(self) -> int:
        return max((len(s) for s in self.stop_sequences), default=0)

    @property
    def logits_neutral(self) -> bool:
        """True when every LOGIT-touching knob is at its exact-identity
        value — the request samples bit-identically to the plain
        temperature path. Stop sequences are deliberately excluded:
        stop matching is pure host-side bookkeeping after a token
        lands, so a stop-only request stays on the engine's
        zero-copy neutral-operand fast path."""
        return (self.top_k == 0 and self.top_p == 1.0 and
                self.repetition_penalty == 1.0 and
                self.presence_penalty == 0.0 and
                not self.logit_bias and self.grammar is None)

    @property
    def neutral(self) -> bool:
        """True when the request behaves exactly like a plain
        temperature request end to end — ``logits_neutral`` AND no
        stop sequences (stops change the output, just not the
        logits)."""
        return self.logits_neutral and not self.stop_sequences

    def validate_for(self, vocab_size: int,
                     eos_id: int) -> Optional[str]:
        """Fail-fast admission check against a concrete engine: the
        error string (→ FAILED_UNSERVABLE) or None."""
        if self.grammar is not None:
            if eos_id < 0:
                return ("grammar-constrained decoding requires "
                        "eos_id >= 0 (grammar completion is expressed "
                        "through EOS)")
            if self.grammar.vocab_size != vocab_size:
                return (f"grammar vocab_size "
                        f"{self.grammar.vocab_size} != model vocab "
                        f"{vocab_size}")
        if self.logit_bias:
            bad = [t for t in self.logit_bias
                   if not (0 <= t < vocab_size)]
            if bad:
                return f"logit_bias tokens {bad} outside vocab " \
                       f"[0, {vocab_size})"
        return None


NEUTRAL = SamplingParams()


def match_stop(tail: Sequence[int],
               stop_sequences: Sequence[Sequence[int]]) -> int:
    """Length of the longest stop sequence the token ``tail`` ends
    with, or 0. The engine calls this after every recorded token with
    the trailing window of the GENERATED stream (which spans
    preemption resume boundaries — the tail is seeded from the replay
    prompt's generated suffix at admission)."""
    best = 0
    n = len(tail)
    for seq in stop_sequences:
        m = len(seq)
        if m <= n and m > best and tuple(tail[n - m:]) == tuple(seq):
            best = m
    return best


# --------------------------------------------------------------------- #
# the traced transform (pure jnp — called from inside the engine's
# compiled programs; no host ops, no shapes from values)
# --------------------------------------------------------------------- #

def constrain_logits(logits, temps, counts, bias, mask, top_k, top_p,
                     rep_pen, pres_pen):
    """Apply the full sampling menu to raw LM-head logits.

    ``logits`` is (..., V); every knob broadcasts against the leading
    dims: ``temps/top_k/top_p/rep_pen/pres_pen`` are (...,)-shaped (or
    (..., 1) for the verify block), ``counts``/``bias``/``mask`` are
    (..., V). Every stage is gated by an explicit ``jnp.where(enabled,
    filtered, logits)`` on the DISABLED sentinel (top_k == 0 or >= V,
    top_p == 1.0, penalties at 1.0/0.0), so a neutral configuration
    returns the input logits VALUE-IDENTICAL — the engine's
    bit-identity guarantee costs a select, not a numeric round-trip.

    Stage order: bias → penalties → mask → top-k → top-p. Top-p
    computes its nucleus over the temperature-scaled distribution
    (greedy slots use T=1 for the nucleus — top-p cannot change an
    argmax). The grammar mask comes BEFORE the truncations: top-k's
    k-th threshold and top-p's nucleus are then computed over LEGAL
    tokens only, so the constraint outranks every heuristic (a
    truncation only lowers logits — it can never resurrect a masked
    token) and the combination can never leave zero tokens above the
    floor (masked-then-truncated-to-nothing would sample uniform
    garbage). Masked tokens sit at -1e30 where the rejection sampler
    sees probability 0."""
    import jax
    import jax.numpy as jnp

    V = logits.shape[-1]
    l = logits.astype(jnp.float32) + bias
    # repetition (divide/multiply by sign) + presence (flat subtract)
    # penalties over tokens PRESENT in the history (counts > 0)
    pen_on = (rep_pen != 1.0) | (pres_pen != 0.0)
    penalized = jnp.where(l > 0, l / rep_pen[..., None],
                          l * rep_pen[..., None]) - pres_pen[..., None]
    l = jnp.where(pen_on[..., None] & (counts > 0), penalized, l)
    # the vocabulary mask (grammar / constrained decoding) — applied
    # BEFORE top-k/top-p so both truncate within the legal set
    l = jnp.where(mask, l, _NEG_BIG)
    # top-k: keep the k largest logits (ties at the k-th value kept)
    k_on = (top_k > 0) & (top_k < V)
    srt = jnp.sort(l, axis=-1)              # ascending
    kidx = jnp.clip(V - top_k, 0, V - 1)[..., None]
    kidx = jnp.broadcast_to(kidx, l.shape[:-1] + (1,))
    kth = jnp.take_along_axis(srt, kidx, axis=-1)
    l = jnp.where(k_on[..., None] & (l < kth), _NEG_BIG, l)
    # top-p: smallest prefix of the descending-prob order with
    # cumulative mass >= p (ties at the threshold prob kept)
    p_on = top_p < 1.0
    safe_t = jnp.where(temps > 0, jnp.maximum(temps, 1e-6),
                       1.0)[..., None]
    # the sorted probs come from the top-k sort already in hand:
    # flooring below the k-th value commutes with sorting, and exp is
    # monotone + elementwise — no second O(V log V) sort on the
    # constrained hot path. One shared max/normalizer keeps sp
    # BIT-IDENTICAL to a sort of probs (softmax'ing the sorted copy
    # separately would round its denominator differently, and the
    # ties-at-the-threshold-kept contract compares probs < thr with
    # exact equality at the boundary).
    srt2 = jnp.where(k_on[..., None] & (srt < kth), _NEG_BIG, srt)
    m = jnp.max(l, axis=-1, keepdims=True)
    e = jnp.exp(l / safe_t - m / safe_t)
    z = jnp.sum(e, axis=-1, keepdims=True)
    probs = e / z
    sp = (jnp.exp(srt2 / safe_t - m / safe_t) / z)[..., ::-1]
    csum = jnp.cumsum(sp, axis=-1)
    keep_sorted = (csum - sp) < top_p[..., None]
    thr = jnp.min(jnp.where(keep_sorted, sp, jnp.inf), axis=-1,
                  keepdims=True)
    l = jnp.where(p_on[..., None] & (probs < thr), _NEG_BIG, l)
    return l
