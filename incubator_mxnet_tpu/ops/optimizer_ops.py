"""Fused optimizer-update operators.

TPU-native re-design of `src/operator/optimizer_op.cc` (sgd_update,
sgd_mom_update, adam_update, lamb_update_phase1/2, multi-precision and
multi-tensor variants; file-level citations — SURVEY.md caveat).

Each update is a single pure function — XLA fuses the elementwise chain into
one kernel, which is what the reference's hand-fused CUDA updaters achieve.
Multi-tensor variants take pytrees and are intended to be called inside one
jit so the whole optimizer step compiles to one fused launch per dtype.
State is returned, not mutated (functional contract); the imperative
`Optimizer` layer writes results back into NDArrays.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


@register("sgd_update", num_outputs=1)
def sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    return weight - lr * g


@register("sgd_mom_update", num_outputs=2)
def sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    """Returns (new_weight, new_mom)."""
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    new_mom = momentum * mom - lr * g
    return weight + new_mom, new_mom


@register("nag_mom_update", num_outputs=2)
def nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register("adam_update", num_outputs=3)
def adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    """Returns (new_weight, new_mean, new_var). Bias correction is folded
    into lr by the Optimizer layer, matching the reference."""
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * jnp.square(g)
    new_weight = weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    return new_weight, new_mean, new_var


@register("adamw_update", num_outputs=3)
def adamw_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, eta=1.0, rescale_grad=1.0,
                 clip_gradient=-1.0):
    """Decoupled weight decay (reference: src/operator/contrib/adamw.cc)."""
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * jnp.square(g)
    new_weight = weight - eta * (lr * new_mean / (jnp.sqrt(new_var) + epsilon)
                                 + wd * weight)
    return new_weight, new_mean, new_var


@register("rmsprop_update", num_outputs=2)
def rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.9, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                   clip_weights=-1.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    new_n = gamma1 * n + (1.0 - gamma1) * jnp.square(g)
    new_weight = weight - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        new_weight = jnp.clip(new_weight, -clip_weights, clip_weights)
    return new_weight, new_n


@register("rmspropalex_update", num_outputs=4)
def rmspropalex_update(weight, grad, n, g_avg, delta, lr=0.001, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0):
    """Centered RMSProp (Graves 2013): gamma1 decays both running moments,
    gamma2 is momentum on the update ``delta``
    (reference: rmspropalex_update in src/operator/optimizer_op.cc)."""
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    new_n = gamma1 * n + (1.0 - gamma1) * jnp.square(g)
    new_g = gamma1 * g_avg + (1.0 - gamma1) * g
    new_delta = gamma2 * delta - \
        lr * g / jnp.sqrt(new_n - jnp.square(new_g) + epsilon)
    return weight + new_delta, new_n, new_g, new_delta


@register("ftrl_update", num_outputs=3)
def ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    new_weight = jnp.where(
        jnp.abs(new_z) > lamda1,
        -(new_z - jnp.sign(new_z) * lamda1) / ((beta + jnp.sqrt(new_n)) / lr + wd),
        0.0)
    return new_weight, new_z, new_n


@register("signsgd_update", num_outputs=1)
def signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register("signum_update", num_outputs=2)
def signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    new_mom = momentum * mom - (1.0 - momentum) * g
    new_weight = (1.0 - lr * wd_lh) * weight + lr * jnp.sign(new_mom)
    return new_weight, new_mom


@register("lamb_update_phase1", num_outputs=3)
def lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0):
    """LAMB phase 1: raw update direction
    (reference: src/operator/optimizer_op.cc lamb_update_phase1)."""
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * jnp.square(g)
    if bias_correction:
        mean_hat = new_mean / (1.0 - beta1 ** t)
        var_hat = new_var / (1.0 - beta2 ** t)
    else:
        mean_hat, var_hat = new_mean, new_var
    update = mean_hat / (jnp.sqrt(var_hat) + epsilon) + wd * weight
    return update, new_mean, new_var


@register("lamb_update_phase2", num_outputs=1)
def lamb_update_phase2(weight, g_update, r1=None, r2=None, lr=0.001,
                       lower_bound=-1.0, upper_bound=-1.0):
    """LAMB phase 2: trust-ratio scaling. r1/r2 may be passed precomputed
    (multi-tensor path) or are computed here."""
    if r1 is None or r2 is None:
        # Keep the norm reductions in their OWN kernels: without this
        # barrier XLA fuses them into the phase-1 elementwise chain as a
        # (scalar, scalar, matrix, matrix) multi-output fusion whose
        # serialized tiling ran at ~35 GB/s on v5e (trace_r4,
        # multiply_reduce_fusion ~2 ms per FFN weight ~= 48 ms/step at
        # BERT-base B=48). The barrier is semantically the identity.
        weight, g_update = jax.lax.optimization_barrier((weight, g_update))
    if r1 is None:
        r1 = jnp.sqrt(jnp.sum(jnp.square(weight)))
    if r2 is None:
        r2 = jnp.sqrt(jnp.sum(jnp.square(g_update)))
    if lower_bound is not None and lower_bound > 0:
        r1 = jnp.maximum(r1, lower_bound)
    if upper_bound is not None and upper_bound > 0:
        r1 = jnp.minimum(r1, upper_bound)
    ratio = jnp.where(jnp.logical_and(r1 > 0, r2 > 0), r1 / r2, 1.0)
    return weight - lr * ratio * g_update


# ------------------------------------------------------------------ #
# multi-tensor fused updates (reference: multi_sgd_update etc.). These take
# lists and are meant to run inside one jit — XLA fuses across params.
# ------------------------------------------------------------------ #
@register("multi_sgd_mom_update", num_outputs=None, wrap_list=True)
def multi_sgd_mom_update(weights, grads, moms, lrs=None, wds=None,
                         momentum=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    outs = []
    for i, (w, g, m) in enumerate(zip(weights, grads, moms)):
        lr = lrs[i] if lrs else 0.01
        wd = wds[i] if wds else 0.0
        outs.append(sgd_mom_update(w, g, m, lr=lr, momentum=momentum, wd=wd,
                                   rescale_grad=rescale_grad,
                                   clip_gradient=clip_gradient))
    return tuple(x for pair in outs for x in pair)


@register("mp_sgd_mom_update", num_outputs=3)
def mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """Multi-precision update: bf16/fp16 weight with fp32 master copy
    (reference: optimizer_op.cc MP_SGD kernels)."""
    new_w32, new_mom = sgd_mom_update(weight32, grad.astype(jnp.float32), mom,
                                      lr=lr, momentum=momentum, wd=wd,
                                      rescale_grad=rescale_grad,
                                      clip_gradient=clip_gradient)
    return new_w32.astype(weight.dtype), new_mom, new_w32


@register("mp_adam_update", num_outputs=4)
def mp_adam_update(weight, grad, mean, var, weight32, lr=0.001, beta1=0.9,
                   beta2=0.999, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    new_w32, new_mean, new_var = adam_update(
        weight32, grad.astype(jnp.float32), mean, var, lr=lr, beta1=beta1,
        beta2=beta2, epsilon=epsilon, wd=wd, rescale_grad=rescale_grad,
        clip_gradient=clip_gradient)
    return new_w32.astype(weight.dtype), new_mean, new_var, new_w32


@register("multi_sgd_update", num_outputs=None, wrap_list=True)
def multi_sgd_update(weights, grads, lrs=None, wds=None, rescale_grad=1.0,
                     clip_gradient=-1.0):
    outs = []
    for i, (w, g) in enumerate(zip(weights, grads)):
        outs.append(sgd_update(
            w, g, lr=lrs[i] if lrs else 0.01, wd=wds[i] if wds else 0.0,
            rescale_grad=rescale_grad, clip_gradient=clip_gradient))
    return tuple(outs)


@register("mp_sgd_update", num_outputs=2)
def mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0):
    new_w32 = sgd_update(weight32, grad.astype(jnp.float32), lr=lr, wd=wd,
                         rescale_grad=rescale_grad,
                         clip_gradient=clip_gradient)
    return new_w32.astype(weight.dtype), new_w32


@register("multi_mp_sgd_update", num_outputs=None, wrap_list=True)
def multi_mp_sgd_update(weights, grads, weights32, lrs=None, wds=None,
                        rescale_grad=1.0, clip_gradient=-1.0):
    outs = []
    for i, (w, g, w32) in enumerate(zip(weights, grads, weights32)):
        outs.append(mp_sgd_update(
            w, g, w32, lr=lrs[i] if lrs else 0.01,
            wd=wds[i] if wds else 0.0, rescale_grad=rescale_grad,
            clip_gradient=clip_gradient))
    return tuple(x for pair in outs for x in pair)


@register("multi_mp_sgd_mom_update", num_outputs=None, wrap_list=True)
def multi_mp_sgd_mom_update(weights, grads, moms, weights32, lrs=None,
                            wds=None, momentum=0.0, rescale_grad=1.0,
                            clip_gradient=-1.0):
    outs = []
    for i, (w, g, m, w32) in enumerate(zip(weights, grads, moms,
                                           weights32)):
        outs.append(mp_sgd_mom_update(
            w, g, m, w32, lr=lrs[i] if lrs else 0.01,
            wd=wds[i] if wds else 0.0, momentum=momentum,
            rescale_grad=rescale_grad, clip_gradient=clip_gradient))
    return tuple(x for trio in outs for x in trio)


@register("mp_nag_mom_update", num_outputs=3)
def mp_nag_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    new_w32, new_mom = nag_mom_update(
        weight32, grad.astype(jnp.float32), mom, lr=lr, momentum=momentum,
        wd=wd, rescale_grad=rescale_grad, clip_gradient=clip_gradient)
    return new_w32.astype(weight.dtype), new_mom, new_w32


@register("mp_adamw_update", num_outputs=4)
def mp_adamw_update(weight, grad, mean, var, weight32, lr=0.001, beta1=0.9,
                    beta2=0.999, epsilon=1e-8, wd=0.0, eta=1.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    new_w32, new_mean, new_var = adamw_update(
        weight32, grad.astype(jnp.float32), mean, var, lr=lr, beta1=beta1,
        beta2=beta2, epsilon=epsilon, wd=wd, eta=eta,
        rescale_grad=rescale_grad, clip_gradient=clip_gradient)
    return new_w32.astype(weight.dtype), new_mean, new_var, new_w32


@register("ftml_update", num_outputs=4)
def ftml_update(weight, grad, d, v, z, lr=0.0025, beta1=0.6, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_grad=-1.0,
                t=1):
    """FTML (reference: optimizer_op.cc FTMLKernel). Returns
    (new_weight, new_d, new_v, new_z). The reference clips the FULL
    quantity rescale*grad + wd*weight, and the update preserves input
    dtypes (low-precision storage stays low-precision)."""
    g = grad * rescale_grad + wd * weight
    if clip_grad is not None and clip_grad > 0:
        g = jnp.clip(g, -clip_grad, clip_grad)
    new_v = beta2 * v + (1 - beta2) * jnp.square(g)
    bias2 = 1 - jnp.power(beta2, t)
    d_t = (1 - jnp.power(beta1, t)) / lr * (
        jnp.sqrt(new_v / bias2) + epsilon)
    sigma = d_t - beta1 * d
    new_z = beta1 * z + (1 - beta1) * g - sigma * weight
    new_w = -new_z / d_t
    return (new_w.astype(weight.dtype), d_t.astype(d.dtype), new_v,
            new_z.astype(z.dtype))


@register("adagrad_update", num_outputs=2)
def adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    new_hist = history + jnp.square(g)
    # epsilon INSIDE the sqrt (reference AdagradUpdate / the AdaGrad
    # optimizer class — keep the two surfaces numerically identical)
    return weight - lr * g / jnp.sqrt(new_hist + epsilon), new_hist


@register("multi_sum_sq", num_outputs=1, wrap_list=True)
def multi_sum_sq(*arrays, num_arrays=None):
    """Per-array sum of squares, returned as one (N,) vector (reference:
    src/operator/contrib/multi_sum_sq.cc). Feeds multi_lars / global-norm
    gradient clipping; one fused reduction launch per call under jit."""
    if len(arrays) == 1 and isinstance(arrays[0], (list, tuple)):
        arrays = tuple(arrays[0])
    return jnp.stack([jnp.sum(jnp.square(a.astype(jnp.float32)))
                      for a in arrays])


@register("multi_lars", num_outputs=1)
def multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, eta=0.001,
               eps=1e-8, rescale_grad=1.0):
    """LARS layer-wise lr scaling over stacked per-layer norms (reference:
    src/operator/contrib/multi_lars.cc). All inputs are (N,) vectors."""
    w_norm = jnp.sqrt(weights_sum_sq)
    g_norm = jnp.sqrt(grads_sum_sq) * rescale_grad
    trust = jnp.where(
        (w_norm > 0) & (g_norm > 0),
        eta * w_norm / (g_norm + wds * w_norm + eps),
        jnp.ones_like(w_norm))
    return lrs * trust


@register("adadelta_update", num_outputs=3)
def adadelta_update(weight, grad, acc_g, acc_delta, rho=0.9, epsilon=1e-5,
                    wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    new_acc_g = rho * acc_g + (1 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_delta + epsilon) / \
        jnp.sqrt(new_acc_g + epsilon) * g
    new_acc_delta = rho * acc_delta + (1 - rho) * jnp.square(delta)
    return weight - delta, new_acc_g, new_acc_delta


# ------------------------------------------------------------------ #
# preloaded_* variants: learning rates / weight decays ride as DEVICE
# arrays instead of host attrs, so an LR schedule updates without
# re-setting op attrs (reference: preloaded_multi_sgd_update family in
# src/operator/contrib/preloaded_multi_sgd-inl.h — file-level citation,
# SURVEY.md caveat). Indexing a jnp vector yields 0-d arrays that flow
# straight into the scalar arithmetic of the per-tensor kernels.
# ------------------------------------------------------------------ #

@register("preloaded_multi_sgd_update", num_outputs=None, wrap_list=True)
def preloaded_multi_sgd_update(weights, grads, lrs, wds, rescale_grad=1.0,
                               clip_gradient=-1.0):
    return tuple(
        sgd_update(w, g, lr=lrs[i], wd=wds[i], rescale_grad=rescale_grad,
                   clip_gradient=clip_gradient)
        for i, (w, g) in enumerate(zip(weights, grads)))


@register("preloaded_multi_sgd_mom_update", num_outputs=None,
          wrap_list=True)
def preloaded_multi_sgd_mom_update(weights, grads, moms, lrs, wds,
                                   momentum=0.0, rescale_grad=1.0,
                                   clip_gradient=-1.0):
    outs = []
    for i, (w, g, m) in enumerate(zip(weights, grads, moms)):
        outs.append(sgd_mom_update(
            w, g, m, lr=lrs[i], wd=wds[i], momentum=momentum,
            rescale_grad=rescale_grad, clip_gradient=clip_gradient))
    return tuple(x for pair in outs for x in pair)


@register("preloaded_multi_mp_sgd_update", num_outputs=None,
          wrap_list=True)
def preloaded_multi_mp_sgd_update(weights, grads, weights32, lrs, wds,
                                  rescale_grad=1.0, clip_gradient=-1.0):
    outs = []
    for i, (w, g, w32) in enumerate(zip(weights, grads, weights32)):
        outs.append(mp_sgd_update(
            w, g, w32, lr=lrs[i], wd=wds[i], rescale_grad=rescale_grad,
            clip_gradient=clip_gradient))
    return tuple(x for pair in outs for x in pair)


@register("preloaded_multi_mp_sgd_mom_update", num_outputs=None,
          wrap_list=True)
def preloaded_multi_mp_sgd_mom_update(weights, grads, moms, weights32,
                                      lrs, wds, momentum=0.0,
                                      rescale_grad=1.0,
                                      clip_gradient=-1.0):
    outs = []
    for i, (w, g, m, w32) in enumerate(zip(weights, grads, moms,
                                           weights32)):
        outs.append(mp_sgd_mom_update(
            w, g, m, w32, lr=lrs[i], wd=wds[i], momentum=momentum,
            rescale_grad=rescale_grad, clip_gradient=clip_gradient))
    return tuple(x for trio in outs for x in trio)
