"""Speculative-decoding engine tests (serve/ + ops ragged verify).

The load-bearing claims: (1) GREEDY TOKEN PARITY — a speculative
engine emits BIT-IDENTICAL tokens to the dense-cache
``cached_generate`` oracle and to the non-speculative engine, at mixed
occupancy, through chunked prefill, and across prefix-cache hits —
acceptance only ever admits the exact argmax chain; (2) the decode
family compiles EXACTLY TWO programs — the W=1 narrow step (bitwise
the non-speculative decode, run when no slot drafted) and the
K+1-wide verify — each traced at most once: drafts, acceptance
lengths, and per-request RNG keys are pure data; (3) ``audit_pages()`` stays clean
every step while the draft window lazily maps tail pages; (4) zero
draft agreement degrades to exactly 1 token/step — speculation can
slow nothing down semantically; (5) per-request seeds make temperature
sampling reproducible across engines, occupancy, and speculation
depth; (6) a non-finite verify step quarantines the slot WITHOUT
recording any token of that step — drafted tokens included."""

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.models import gpt as g
from incubator_mxnet_tpu.serve import (InferenceEngine, Outcome, Request,
                                       ngram_propose)


@pytest.fixture(scope="module")
def model():
    mx.random.seed(0)
    m = g.gpt_mini(vocab_size=64, max_length=64)
    m.initialize()
    return m


def _solo_reference(model, prompt, max_new):
    out = g.cached_generate(model, nd.array(prompt[None, :],
                                            dtype="int32"),
                            max_new_tokens=max_new).asnumpy()
    return out[0, prompt.size:]


def _assert_compile_once(eng):
    """The speculative engine's compile contract: TWO decode-family
    programs exist — the W=1 narrow step (bitwise the non-speculative
    decode; runs whenever no slot drafted) and the K+1-wide verify —
    and EACH traces at most once, with at least one having run.
    Occupancy, drafts, acceptance, keys and weights are data."""
    assert eng.decode_trace_count <= 1, \
        f"narrow decode retraced ({eng.decode_trace_count})"
    assert eng.verify_trace_count <= 1, \
        f"wide verify retraced ({eng.verify_trace_count})"
    assert eng.decode_trace_count + eng.verify_trace_count >= 1


def _repetitive_prompts(rng, vocab=64):
    """Prompts with recurring n-grams so prompt-lookup drafting fires,
    mixed with plain random ones (zero-recurrence)."""
    base = rng.randint(0, vocab, size=(6,)).astype(np.int32)
    return [np.concatenate([base, base, base[:3]]),
            rng.randint(0, vocab, size=(9,)).astype(np.int32),
            np.concatenate([base, base]),
            rng.randint(0, vocab, size=(17,)).astype(np.int32)]


def _oracle_drafter(model, prompts, max_new, wrong=False, vocab=64):
    """A drafter that knows each request's true greedy continuation
    (precomputed): proposes exactly the right tokens — or, with
    ``wrong=True``, tokens guaranteed to all be rejected (each draft is
    the true token + 1 mod vocab). Requests are identified by their
    prompt+emitted history matching a known (prompt, reference) pair."""
    table = [(p, _solo_reference(model, p, mn))
             for p, mn in zip(prompts, max_new)]

    def draft(history, k):
        h = np.asarray(history, np.int32)
        for prompt, ref in table:
            t0 = prompt.size
            if h.size < t0 or not np.array_equal(h[:t0], prompt):
                continue
            e = h.size - t0
            if not np.array_equal(h[t0:], ref[:e]):
                continue
            d = ref[e:e + k].astype(np.int32)
            return (d + 1) % vocab if wrong else d
        return np.zeros((0,), np.int32)

    return draft


# --------------------------------------------------------------------- #
# the tentpole: greedy parity, compile-once, page audit, cache hits
# --------------------------------------------------------------------- #

def test_spec_greedy_parity_mixed_occupancy_cache_and_chunking(model):
    """One speculative engine (K=3, chunked prefill, prefix cache, a
    reclaim-forcing pool) serves ragged mixed-occupancy requests COLD
    then WARM (cache-hit admissions): every request must emit exactly
    its solo dense-cache tokens, the decode step compiles once across
    both passes, ``audit_pages()`` passes after every scheduler step,
    and speculation demonstrably compresses decode steps below 1
    token/step/slot accounting."""
    rng = np.random.RandomState(7)
    prompts = _repetitive_prompts(rng)
    news = (14, 10, 12, 8)
    refs = [_solo_reference(model, p, k) for p, k in zip(prompts, news)]
    eng = InferenceEngine(model, num_slots=3, page_size=8, max_len=64,
                          num_pages=20, spec_k=3, chunk_pages=1,
                          token_budget=16)
    audit = lambda e, i: e.audit_pages()
    for tag in ("cold", "warm"):
        reqs = [Request(p, max_new_tokens=k)
                for p, k in zip(prompts, news)]
        eng.run(reqs, arrival_times=[0.0, 0.0, 0.01, 0.02],
                after_step=audit)
        for i, (req, ref) in enumerate(zip(reqs, refs)):
            np.testing.assert_array_equal(
                np.asarray(req.token_ids, np.int32), ref,
                err_msg=f"{tag} request {i} diverged from the "
                        f"non-speculative oracle")
            assert req.outcome is not None and req.outcome.ok
        _assert_compile_once(eng)
        assert eng.verify_trace_count == 1, \
            f"no wide verify step ran ({tag}) — drafting never fired"
        eng.audit_pages()
    assert eng.prefix_hits > 0               # warm pass hit the cache
    # the accounting: drafting happened, some drafts were accepted, and
    # engine counters equal the per-request sums
    assert eng.drafted_tokens > 0
    assert 0 < eng.accepted_tokens <= eng.drafted_tokens
    assert 0.0 < eng.accept_rate <= 1.0
    # every decode token is a step's base emission or an accepted
    # draft: accepted > 0 means some step advanced a slot by more than
    # one token — the compression speculation exists for
    total_tokens = 2 * sum(len(r) for r in refs)
    total_decode = total_tokens - 2 * len(refs)   # first tok: prefill
    assert 0 < eng.accepted_tokens <= total_decode


def test_spec_counters_match_per_request_sums(model):
    """Engine-level drafted/accepted counters must equal the sums of
    the per-request twins, and a non-speculative engine reports zeros
    (the observability satellite's contract)."""
    rng = np.random.RandomState(8)
    prompts = _repetitive_prompts(rng)[:2]
    news = (12, 10)
    eng = InferenceEngine(model, num_slots=2, page_size=8, max_len=64,
                          num_pages=16, spec_k=2)
    reqs = [Request(p, max_new_tokens=k) for p, k in zip(prompts, news)]
    eng.run(reqs)
    assert eng.drafted_tokens == sum(r.drafted_tokens for r in reqs)
    assert eng.accepted_tokens == sum(r.accepted_tokens for r in reqs)
    for r in reqs:
        assert 0 <= r.accepted_tokens <= r.drafted_tokens


# --------------------------------------------------------------------- #
# agreement extremes: oracle-right and oracle-wrong drafting
# --------------------------------------------------------------------- #

@pytest.mark.slow   # 11 s (oracle refs + 2 runs); ci stage_unit
def test_full_agreement_compresses_steps_and_eos_truncates(model):
    """With a drafter that proposes the TRUE continuation: every draft
    is accepted (accept_rate 1.0) and N decode tokens take
    ceil(N / (K+1)) steps. A second request whose reference contains
    its EOS mid-window must stop exactly AT the EOS — accepted tokens
    past it are discarded, as sequential decode would never have
    emitted them."""
    rng = np.random.RandomState(9)
    prompts = [rng.randint(0, 64, size=(8,)).astype(np.int32),
               rng.randint(0, 64, size=(11,)).astype(np.int32)]
    news = (12, 12)
    refs = [_solo_reference(model, p, k) for p, k in zip(prompts, news)]
    drafter = _oracle_drafter(model, prompts, news)
    K = 3
    eng = InferenceEngine(model, num_slots=1, page_size=8, max_len=64,
                          num_pages=16, spec_k=K, draft_fn=drafter,
                          prefix_cache=False)
    r0 = Request(prompts[0], max_new_tokens=news[0])
    eng.run([r0])
    np.testing.assert_array_equal(np.asarray(r0.token_ids, np.int32),
                                  refs[0])
    assert eng.accept_rate == 1.0
    # 11 decode tokens (first came from prefill): capped drafting gives
    # 4 + 4 + 3 = 11 in exactly 3 steps
    n_decode = news[0] - 1
    assert eng.decode_steps == -(-n_decode // (K + 1))
    assert r0.accepted_tokens == r0.drafted_tokens > 0

    # EOS inside the accepted window: pick the first reference token
    # that did not occur earlier (so generation stops exactly there)
    eos_pos = next(j for j in range(1, len(refs[1]))
                   if refs[1][j] not in refs[1][:j])
    eos_id = int(refs[1][eos_pos])
    r1 = Request(prompts[1], max_new_tokens=news[1], eos_id=eos_id)
    eng.run([r1])
    np.testing.assert_array_equal(np.asarray(r1.token_ids, np.int32),
                                  refs[1][:eos_pos + 1])
    assert r1.outcome == Outcome.EOS
    _assert_compile_once(eng)
    assert eng.verify_trace_count == 1   # full agreement: all steps wide


def test_zero_agreement_degrades_to_one_token_per_step(model):
    """With a drafter whose every proposal is WRONG (true token + 1):
    parity must hold bit-for-bit, zero drafts are accepted, and every
    decode step advances exactly one token — the non-speculative
    floor. After ``spec_patience`` fully-rejected windows, adaptive
    gating stops drafting for the slot and the engine runs the W=1
    narrow program (bitwise the non-speculative step) — the
    zero-agreement floor pays no verify width."""
    rng = np.random.RandomState(10)
    prompts = [rng.randint(0, 64, size=(8,)).astype(np.int32)]
    news = (12,)
    refs = [_solo_reference(model, prompts[0], news[0])]
    drafter = _oracle_drafter(model, prompts, news, wrong=True)
    eng = InferenceEngine(model, num_slots=1, page_size=8, max_len=64,
                          num_pages=16, spec_k=3, draft_fn=drafter,
                          prefix_cache=False)
    req = Request(prompts[0], max_new_tokens=news[0])
    eng.run([req])
    np.testing.assert_array_equal(np.asarray(req.token_ids, np.int32),
                                  refs[0])
    assert eng.accepted_tokens == 0
    assert eng.drafted_tokens > 0
    assert eng.decode_steps == news[0] - 1   # 1 token/step, no worse
    # gating engaged: exactly spec_patience (default 2) fully-rejected
    # windows ran wide, then every drafting-eligible step was gated
    # narrow (the final step is narrow too — its token budget leaves
    # no draft room), so BOTH programs traced exactly once
    assert eng.spec_steps == eng.spec_patience
    assert eng.spec_gated_steps == eng.decode_steps - eng.spec_steps - 1
    assert eng.decode_trace_count == 1 and eng.verify_trace_count == 1


# --------------------------------------------------------------------- #
# per-request seeds (satellite): reproducible temperature sampling
# --------------------------------------------------------------------- #

@pytest.mark.slow   # 26 s: 3 engines × temperature runs; ci stage_unit
def test_equal_seed_engines_emit_identical_temperature_tokens(model):
    """Two speculative engines given requests with equal seeds must
    emit identical temperature-path tokens; a different seed diverges.
    The same request served SOLO must also match its batched tokens —
    the per-request key is independent of occupancy."""
    rng = np.random.RandomState(11)
    base = rng.randint(0, 64, size=(5,)).astype(np.int32)
    prompts = [np.concatenate([base, base]),
               rng.randint(0, 64, size=(11,)).astype(np.int32)]

    def serve(eng, seeds):
        reqs = [Request(p, max_new_tokens=10, temperature=t, seed=sd)
                for p, t, sd in zip(prompts, (0.8, 1.1), seeds)]
        eng.run(reqs)
        return [list(r.token_ids) for r in reqs]

    eng_a = InferenceEngine(model, num_slots=2, page_size=8, max_len=64,
                            num_pages=16, spec_k=2)
    eng_b = InferenceEngine(model, num_slots=2, page_size=8, max_len=64,
                            num_pages=16, spec_k=2)
    toks_a = serve(eng_a, (123, 456))
    toks_b = serve(eng_b, (123, 456))
    assert toks_a == toks_b
    assert serve(eng_a, (124, 456))[0] != toks_a[0]   # seed matters
    solo = Request(prompts[0], max_new_tokens=10, temperature=0.8,
                   seed=123)
    eng_b.run([solo])                         # occupancy-independent
    assert list(solo.token_ids) == toks_a[0]
    _assert_compile_once(eng_a)
    _assert_compile_once(eng_b)


# --------------------------------------------------------------------- #
# quarantine during a verify step (the PR 5 guard must see speculation)
# --------------------------------------------------------------------- #

@pytest.mark.slow   # 12 s: private model build + oracle refs; stage_unit
def test_nonfinite_verify_step_records_no_drafted_token():
    """Poison the weights mid-generation (warm_start, the chaos
    NaNWeights fault): the very next verify step must quarantine the
    slot with NOTHING recorded from that step — no base token, no
    accepted draft — and the draft/accept counters must not move for
    the poisoned step. Tokens recorded before the fault stay a clean
    prefix of the fault-free reference.

    Uses a PRIVATE model: warm_start swaps weights into the model's
    Parameters in place (by design), so the poison must not leak into
    the shared fixture."""
    mx.random.seed(0)
    model = g.gpt_mini(vocab_size=64, max_length=64)
    model.initialize()
    rng = np.random.RandomState(12)
    prompt = rng.randint(0, 64, size=(8,)).astype(np.int32)
    max_new = 16
    ref = _solo_reference(model, prompt, max_new)
    drafter = _oracle_drafter(model, [prompt], [max_new])
    eng = InferenceEngine(model, num_slots=1, page_size=8, max_len=64,
                          num_pages=16, spec_k=3, draft_fn=drafter,
                          prefix_cache=False)
    req = Request(prompt, max_new_tokens=max_new)
    eng.submit(req)
    while len(req.token_ids) < 4:            # prefill + >=1 verify step
        eng.step()
    tokens_before = list(req.token_ids)
    drafted_before = eng.drafted_tokens
    accepted_before = eng.accepted_tokens
    # NaN a few embedding entries via warm_start (pure data, no retrace)
    params = {str(i): np.asarray(p.data().asnumpy())
              for i, p in enumerate(eng._eng_params)}
    tab = params["0"].copy()
    tab.reshape(-1)[:4] = np.nan
    params["0"] = tab
    eng.warm_start(params=params)
    eng.step()                               # the poisoned verify step
    assert req.outcome == Outcome.FAILED_NONFINITE
    assert list(req.token_ids) == tokens_before
    assert eng.drafted_tokens == drafted_before
    assert eng.accepted_tokens == accepted_before
    assert tokens_before == list(ref[:len(tokens_before)])
    _assert_compile_once(eng)
    assert eng.verify_trace_count == 1   # the poisoned step WAS a verify
    eng.audit_pages()


# --------------------------------------------------------------------- #
# draft window vs page machinery
# --------------------------------------------------------------------- #

@pytest.mark.slow   # builds two engines; ci stage_unit runs it
def test_draft_window_spans_page_boundary_and_survives_tiny_pool(model):
    """page_size 4 with K=6: a verify window can span two freshly
    allocated tail pages in one step — parity, audit, and compile-once
    must hold. Then a pool sized to the bare admission minimum forces
    the window allocation to fail sometimes: drafts are truncated (best
    effort), never a stall — parity still exact."""
    rng = np.random.RandomState(13)
    prompts = _repetitive_prompts(rng)[:3]
    news = (14, 10, 12)
    refs = [_solo_reference(model, p, k) for p, k in zip(prompts, news)]
    audit = lambda e, i: e.audit_pages()
    eng = InferenceEngine(model, num_slots=2, page_size=4, max_len=64,
                          num_pages=24, spec_k=6, prefix_cache=False)
    reqs = [Request(p, max_new_tokens=k) for p, k in zip(prompts, news)]
    eng.run(reqs, after_step=audit)
    for req, ref in zip(reqs, refs):
        np.testing.assert_array_equal(np.asarray(req.token_ids,
                                                 np.int32), ref)
    _assert_compile_once(eng)
    assert eng.accepted_tokens > 0

    # bare-minimum pool: slots fight for window pages
    worst = max(-(-(p.size + k) // 4) for p, k in zip(prompts, news))
    eng2 = InferenceEngine(model, num_slots=2, page_size=4, max_len=64,
                           num_pages=2 * worst + 1, spec_k=6,
                           prefix_cache=False)
    reqs = [Request(p, max_new_tokens=k) for p, k in zip(prompts, news)]
    eng2.run(reqs, after_step=audit)
    for req, ref in zip(reqs, refs):
        np.testing.assert_array_equal(np.asarray(req.token_ids,
                                                 np.int32), ref)
        assert req.outcome is not None and req.outcome.ok
    _assert_compile_once(eng2)


def test_spec_k_validation(model):
    with pytest.raises(MXNetError):
        InferenceEngine(model, num_slots=1, max_len=64, spec_k=-1)
    with pytest.raises(MXNetError):
        InferenceEngine(model, num_slots=1, max_len=64, spec_k=64)


# --------------------------------------------------------------------- #
# the n-gram drafter itself (pure host-side unit tests)
# --------------------------------------------------------------------- #

def test_ngram_propose_basics():
    h = np.asarray([1, 2, 3, 9, 1, 2, 3], np.int32)
    # suffix [1,2,3] recurs at 0; continuation is 9
    np.testing.assert_array_equal(ngram_propose(h, 1), [9])
    np.testing.assert_array_equal(ngram_propose(h, 3), [9, 1, 2])
    # no recurrence anywhere: empty
    assert ngram_propose(np.arange(8, dtype=np.int32), 4).size == 0
    # k=0 or tiny history: empty
    assert ngram_propose(h, 0).size == 0
    assert ngram_propose(np.asarray([5], np.int32), 2).size == 0


def test_ngram_propose_prefers_full_k_continuation():
    """On periodic text the NEAREST occurrence abuts the suffix and
    yields a short draft; the drafter must prefer the latest occurrence
    with a full-k continuation."""
    h = np.asarray([4, 5, 6, 7, 4, 5, 6, 7, 4, 5, 6], np.int32)
    # suffix [4,5,6]: occurrences at 0 and 4; from 4, continuation
    # [7, 4, 5] is full-k
    np.testing.assert_array_equal(ngram_propose(h, 3), [7, 4, 5])
    # falls back to shorter orders before giving up
    h2 = np.asarray([9, 1, 9, 2, 9, 3, 9], np.int32)
    d = ngram_propose(h2, 2, max_order=3)
    assert d.size > 0                       # order-1 match on 9


def test_ngram_drafter_cap_and_dtype():
    h = np.tile(np.asarray([3, 1, 4], np.int32), 5)
    d = ngram_propose(h, 2)
    assert d.dtype == np.int32 and d.size <= 2
