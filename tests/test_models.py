"""Model zoo tests (SURVEY.md §4: tiny fixtures, numpy oracles)."""

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd, gluon, parallel
from incubator_mxnet_tpu.models import LeNet, bert_tiny, BERTForPretraining
from incubator_mxnet_tpu.models import bert as bert_mod


def test_lenet_forward_and_train_step():
    mx.random.seed(0)
    net = LeNet()
    net.initialize()
    x = nd.array(np.random.RandomState(0).randn(4, 1, 28, 28),
                 dtype="float32")
    out = net(x)
    assert out.shape == (4, 10)

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore=None)
    y = nd.array([1, 2, 3, 4], dtype="int32")
    with autograd.record():
        L = loss_fn(net(x), y).mean()
    L.backward()
    tr.step(1)
    assert np.isfinite(float(L.asnumpy()))


def test_bert_shapes_and_masking():
    mx.random.seed(0)
    model = bert_tiny()
    model.initialize()
    rng = np.random.RandomState(0)
    B, T = 2, 16
    ids = nd.array(rng.randint(0, 1024, (B, T)), dtype="int32")
    vl = nd.array([T, 5], dtype="int32")
    seq, pooled = model(ids, None, vl)
    assert seq.shape == (B, T, 128) and pooled.shape == (B, 128)

    # padding tokens beyond valid_length must not affect earlier outputs
    ids2_np = np.array(ids.asnumpy())
    ids2_np[1, 5:] = 0  # change padding content
    seq2, _ = model(nd.array(ids2_np, dtype="int32"), None, vl)
    np.testing.assert_allclose(seq.asnumpy()[1, :5],
                               seq2.asnumpy()[1, :5], rtol=1e-4, atol=1e-4)


@pytest.mark.slow   # 12s (round-11 tier-1 budget repair); BERT tier-1
                    # coverage stays via test_bert_classifier_finetunes;
                    # ci stage_unit runs it
def test_bert_pretraining_loss_decreases():
    mx.random.seed(1)
    model = bert_tiny(vocab_size=256, max_length=32)
    model.initialize()
    pre = BERTForPretraining(model)
    pre.initialize()
    rng = np.random.RandomState(0)
    B, T, M = 8, 16, 3
    batch = (
        nd.array(rng.randint(0, 256, (B, T)), dtype="int32"),
        nd.array(rng.randint(0, 2, (B, T)), dtype="int32"),
        nd.array(np.full((B,), T), dtype="int32"),
        nd.array(rng.randint(0, T, (B, M)), dtype="int32"),
        nd.array(rng.randint(0, 256, (B, M)), dtype="int32"),
        nd.ones((B, M)),
        nd.array(rng.randint(0, 2, (B,)), dtype="int32"),
    )
    tr = parallel.SPMDTrainer(
        pre, forward_loss=bert_mod.pretraining_loss, optimizer="adam",
        optimizer_params={"learning_rate": 1e-3})
    l0 = float(tr.step(*batch).asnumpy())
    for _ in range(12):
        l_last = float(tr.step(*batch).asnumpy())
    assert l_last < l0, (l0, l_last)


def test_bert_flash_matches_dense():
    """flash (blockwise) attention path must match the dense path."""
    mx.random.seed(3)
    dense_model = bert_tiny()
    dense_model.initialize()
    flash_model = bert_tiny(flash=True)
    flash_model.initialize()
    # copy params dense -> flash
    src = dense_model._collect_params_with_prefix()
    dst = flash_model._collect_params_with_prefix()
    assert set(src) == set(dst)
    for k, p in src.items():
        dst[k].set_data(p.data())
    rng = np.random.RandomState(0)
    ids = nd.array(rng.randint(0, 1024, (2, 24)), dtype="int32")
    vl = nd.array([24, 17], dtype="int32")
    s1, p1 = dense_model(ids, None, vl)
    s2, p2 = flash_model(ids, None, vl)
    np.testing.assert_allclose(s1.asnumpy(), s2.asnumpy(),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_bert_remat_matches_no_remat():
    """jax.checkpoint on encoder layers must not change the training
    trajectory (memory-only transform)."""
    import numpy as np
    from incubator_mxnet_tpu import nd, parallel
    from incubator_mxnet_tpu.models import bert as bm

    rng = np.random.RandomState(0)
    B, T, M = 8, 16, 3
    batch = (nd.array(rng.randint(0, 128, (B, T)), dtype="int32"),
             nd.array(rng.randint(0, 2, (B, T)), dtype="int32"),
             nd.array(np.full((B,), T), dtype="int32"),
             nd.array(rng.randint(0, T, (B, M)), dtype="int32"),
             nd.array(rng.randint(0, 128, (B, M)), dtype="int32"),
             nd.ones((B, M)),
             nd.array(rng.randint(0, 2, (B,)), dtype="int32"))
    losses = {}
    for remat in (False, True, "dots"):
        mx.random.seed(9)
        model = bm.bert_tiny(vocab_size=128, max_length=T, remat=remat,
                             dropout=0.0)
        model.initialize()
        pre = bm.BERTForPretraining(model)
        pre.initialize()
        tr = parallel.SPMDTrainer(
            pre, forward_loss=bm.pretraining_loss, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1})
        for _ in range(3):
            L = tr.step(*batch)
        losses[remat] = float(L.asnumpy())
    assert abs(losses[True] - losses[False]) < 1e-5, losses
    # selective remat ("dots": save matmul outputs, recompute elementwise)
    # must also be a memory-only transform
    assert abs(losses["dots"] - losses[False]) < 1e-5, losses


def test_gpt_train_and_generate():
    """Decoder-only LM: causal training loss drops under SPMDTrainer on
    the dp/fsdp/tp mesh; greedy_generate continues a memorized
    sequence (fixed-shape fori_loop decode)."""
    import numpy as np
    from incubator_mxnet_tpu import nd, parallel
    from incubator_mxnet_tpu.parallel import mesh as pmesh
    from incubator_mxnet_tpu.models import gpt as gm

    mx.random.seed(0)
    model = gm.gpt_mini(vocab_size=32, max_length=24, dropout=0.0)
    model.initialize()
    # a repeating pattern the tiny model can memorize quickly
    seq = np.tile(np.arange(8, dtype=np.int32), 3)[:16]
    X = np.stack([seq] * 8)
    inp = nd.array(X[:, :-1], dtype="int32")
    lab = nd.array(X[:, 1:], dtype="int32")

    mesh = pmesh.build_mesh(axis_sizes={"dp": 2, "fsdp": 2, "tp": 2})
    tr = parallel.SPMDTrainer(model, forward_loss=gm.lm_loss,
                              optimizer="adam",
                              optimizer_params={"learning_rate": 3e-3},
                              mesh=mesh, sharding="fsdp")
    l0 = float(tr.step(inp, lab).asnumpy())
    for _ in range(25):
        ln = float(tr.step(inp, lab).asnumpy())
    assert ln < 0.5 * l0, (l0, ln)

    out = gm.greedy_generate(model, nd.array(X[:1, :8], dtype="int32"),
                             max_new_tokens=4)
    got = out.asnumpy()[0]
    np.testing.assert_array_equal(got[:8], X[0, :8])
    # memorized pattern continues
    np.testing.assert_array_equal(got[8:12], X[0, 8:12])


@pytest.mark.slow   # 14s (round-11 tier-1 budget repair); GPT tier-1
                    # coverage stays via test_gpt_train_and_generate;
                    # ci stage_unit runs it
def test_gpt_remat_parity():
    import numpy as np
    from incubator_mxnet_tpu import nd, parallel
    from incubator_mxnet_tpu.models import gpt as gm

    rng = np.random.RandomState(1)
    X = rng.randint(0, 64, (8, 12)).astype(np.int32)
    losses = {}
    for remat in (False, True, "dots"):
        mx.random.seed(4)
        m = gm.gpt_mini(vocab_size=64, max_length=16, dropout=0.0,
                        remat=remat)
        m.initialize()
        tr = parallel.SPMDTrainer(m, forward_loss=gm.lm_loss,
                                  optimizer="sgd",
                                  optimizer_params={"learning_rate": 0.1})
        for _ in range(3):
            L = tr.step(nd.array(X[:, :-1], dtype="int32"),
                        nd.array(X[:, 1:], dtype="int32"))
        losses[remat] = float(L.asnumpy())
    assert abs(losses[True] - losses[False]) < 1e-5, losses
    # selective remat ("dots": save matmul outputs, recompute elementwise)
    # must also be a memory-only transform
    assert abs(losses["dots"] - losses[False]) < 1e-5, losses


@pytest.mark.slow
def test_gpt_kv_cache_decode_matches_full_recompute():
    """cached_generate (prefill + per-token KV-cache steps) must emit
    exactly the tokens of greedy_generate's full-prefix recompute —
    greedy, seeded-sampled, and bfloat16 variants."""
    from incubator_mxnet_tpu.models import gpt as g

    mx.random.seed(0)
    m = g.gpt_mini(vocab_size=64, max_length=64)
    m.initialize()
    rng = np.random.RandomState(0)
    prompt = nd.array(rng.randint(0, 64, (2, 8)), dtype="int32")
    slow = g.greedy_generate(m, prompt, max_new_tokens=12).asnumpy()
    fast = g.cached_generate(m, prompt, max_new_tokens=12).asnumpy()
    np.testing.assert_array_equal(slow, fast)
    # prompt is preserved verbatim
    np.testing.assert_array_equal(fast[:, :8], prompt.asnumpy())

    # seeded sampling: same global key stream -> same tokens
    mx.random.seed(9)
    s1 = g.greedy_generate(m, prompt, max_new_tokens=8,
                           temperature=0.8).asnumpy()
    mx.random.seed(9)
    s2 = g.cached_generate(m, prompt, max_new_tokens=8,
                           temperature=0.8).asnumpy()
    np.testing.assert_array_equal(s1, s2)

    # bf16 model: ln_f cast ordering must match the training path
    mx.random.seed(1)
    mb = g.gpt_mini(vocab_size=64, max_length=64, dtype="bfloat16")
    mb.initialize()
    b1 = g.greedy_generate(mb, prompt, max_new_tokens=10).asnumpy()
    b2 = g.cached_generate(mb, prompt, max_new_tokens=10).asnumpy()
    np.testing.assert_array_equal(b1, b2)


def test_gpt_decode_forward_logits_match_full_forward():
    """Prefill logits from the KV-cache path must match the training
    forward position-for-position (not just argmax parity)."""
    from incubator_mxnet_tpu.models import gpt as g
    from incubator_mxnet_tpu.gluon.block import _hybrid_trace_scope

    mx.random.seed(2)
    m = g.gpt_mini(vocab_size=64, max_length=32)
    m.initialize()
    rng = np.random.RandomState(0)
    ids = nd.array(rng.randint(0, 64, (2, 16)), dtype="int32")
    with autograd.predict_mode():
        full = m(ids).asnumpy()                       # (2, 16, 64)
        caches = g.init_kv_cache(m, 2, max_len=16)
        with _hybrid_trace_scope():
            logits, _ = g.decode_forward(m, ids, caches, 0)
    np.testing.assert_allclose(logits.asnumpy(), full, rtol=2e-4,
                               atol=2e-5)


def test_bert_mlm_onehot_gather_is_exact_gather():
    """The MLM head's one-hot-matmul position gather must equal an index
    gather EXACTLY (each one-hot row has a single 1.0, so the contraction
    copies one value untouched) — in f32 AND bf16, forward and backward."""
    rng = np.random.RandomState(3)
    B, T, M, U = 2, 16, 5, 8
    pos_np = rng.randint(0, T, (B, M))
    for dtype in ("float32", "bfloat16"):
        seq = nd.array(rng.randn(B, T, U).astype("float32")).astype(dtype)
        pos = nd.array(pos_np, dtype="int32")
        seq.attach_grad()
        with autograd.record():
            onehot = nd.one_hot(pos, depth=T, dtype=dtype)
            out = nd.batch_dot(onehot, seq)
            loss = (out * out).sum()
        loss.backward()
        g_matmul = seq.grad.asnumpy().astype(np.float32)

        ref = nd.batch_take(seq, pos)
        assert (out.asnumpy() == ref.asnumpy()).all()

        seq.attach_grad()
        with autograd.record():
            out2 = nd.batch_take(seq, pos)
            loss2 = (out2 * out2).sum()
        loss2.backward()
        g_gather = seq.grad.asnumpy().astype(np.float32)
        np.testing.assert_allclose(g_matmul, g_gather, rtol=1e-6, atol=1e-6)


def test_bert_seq_output_keeps_compute_dtype():
    """bf16 models return the sequence output in bf16 (the f32 cast that
    used to sit here poisoned every downstream matmul); pooled stays f32."""
    mx.random.seed(4)
    model = bert_tiny(dtype="bfloat16")
    model.initialize()
    ids = nd.array(np.zeros((2, 8)), dtype="int32")
    seq, pooled = model(ids, None, None)
    assert seq.dtype == "bfloat16", seq.dtype
    assert pooled.dtype == "float32", pooled.dtype


@pytest.mark.slow   # 18s (round-21 tier-1 budget repair); ci
def test_bert_classifier_finetunes():
    # stage_unit still runs it every time
    """BERTClassifier (GluonNLP finetune_classifier surface): logits
    shape and a few SPMD fine-tuning steps reduce the loss."""
    from incubator_mxnet_tpu.models import BERTClassifier
    from incubator_mxnet_tpu.gluon import loss as gloss

    mx.random.seed(5)
    clf = BERTClassifier(bert_tiny(vocab_size=64, max_length=16),
                         num_classes=3, dropout=0.0)
    clf.initialize()
    rng = np.random.RandomState(0)
    B, T = 8, 12
    ids = nd.array(rng.randint(0, 64, (B, T)), dtype="int32")
    tt = nd.array(rng.randint(0, 2, (B, T)), dtype="int32")
    vl = nd.array(np.full((B,), T), dtype="int32")
    y = nd.array(rng.randint(0, 3, (B,)), dtype="int32")
    out = clf(ids, tt, vl)
    assert out.shape == (B, 3)

    sce = gloss.SoftmaxCrossEntropyLoss()

    def clf_loss(model, i, t, v, labels):
        return sce(model(i, t, v), labels).mean()

    tr = parallel.SPMDTrainer(
        clf, forward_loss=clf_loss, optimizer="adam",
        optimizer_params={"learning_rate": 5e-4})
    l0 = float(tr.step(ids, tt, vl, y).asnumpy())
    for _ in range(10):
        ll = float(tr.step(ids, tt, vl, y).asnumpy())
    assert ll < l0, (l0, ll)


def test_packed_fast_path_matches_unpacked():
    """The packed (3,B,H,T,D) attention wiring (models/_attention.py)
    must be numerically identical to the per-tensor path: forced on via
    MXTPU_FORCE_PACKED on the CPU mesh, where both route to the same
    blockwise math."""
    import os
    import numpy as np
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.models.bert import bert_tiny
    from incubator_mxnet_tpu.models.gpt import gpt_mini

    rng = np.random.RandomState(0)
    ids = nd.array(rng.randint(0, 100, (2, 24)), dtype="int32")
    vl = nd.array(np.array([24, 11]), dtype="int32")

    def run_bert():
        m = bert_tiny(flash=True)
        m.initialize()
        s, p = m(ids, None, vl)
        return m, s.asnumpy()

    os.environ.pop("MXTPU_FORCE_PACKED", None)
    m1, base = run_bert()
    os.environ["MXTPU_FORCE_PACKED"] = "1"
    try:
        m2 = bert_tiny(flash=True)
        m2.initialize()
        src = m1._collect_params_with_prefix()
        dst = m2._collect_params_with_prefix()
        for k_, v_ in src.items():
            dst[k_].set_data(v_.data())
        s2, _ = m2(ids, None, vl)
        np.testing.assert_allclose(s2.asnumpy(), base, rtol=2e-4,
                                   atol=2e-4)

        g = gpt_mini(vocab_size=100, max_length=24, dropout=0.0, flash=True)
        g.initialize()
        out_packed = g(ids).asnumpy()
        os.environ.pop("MXTPU_FORCE_PACKED", None)
        g2 = gpt_mini(vocab_size=100, max_length=24, dropout=0.0, flash=True)
        g2.initialize()
        srcg = g._collect_params_with_prefix()
        dstg = g2._collect_params_with_prefix()
        for k_, v_ in srcg.items():
            dstg[k_].set_data(v_.data())
        np.testing.assert_allclose(g2(ids).asnumpy(), out_packed,
                                   rtol=2e-4, atol=2e-4)
    finally:
        os.environ.pop("MXTPU_FORCE_PACKED", None)


def test_packed_fast_path_matches_kernels_interpret(monkeypatch):
    """ADVICE r4: the packed bhtd handoff must be parity-checked against
    the PALLAS KERNELS, not just the blockwise fallback — interpret mode
    runs the same kernel code on CPU. Baseline: plain per-tensor path on
    the fallback; packed run: MXTPU_FLASH_INTERPRET routes the
    dispatcher to the dense kernels with the packed layout."""
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.models.bert import bert_tiny

    rng = np.random.RandomState(0)
    ids = nd.array(rng.randint(0, 100, (2, 16)), dtype="int32")
    vl = nd.array(np.array([16, 7]), dtype="int32")

    monkeypatch.delenv("MXTPU_FORCE_PACKED", raising=False)
    monkeypatch.delenv("MXTPU_FLASH_INTERPRET", raising=False)
    m1 = bert_tiny(flash=True)
    m1.initialize()
    base, _ = m1(ids, None, vl)
    base = base.asnumpy()

    monkeypatch.setenv("MXTPU_FORCE_PACKED", "1")
    monkeypatch.setenv("MXTPU_FLASH_INTERPRET", "1")
    m2 = bert_tiny(flash=True)
    m2.initialize()
    src = m1._collect_params_with_prefix()
    dst = m2._collect_params_with_prefix()
    for k_, v_ in src.items():
        dst[k_].set_data(v_.data())
    s2, _ = m2(ids, None, vl)
    np.testing.assert_allclose(s2.asnumpy(), base, rtol=2e-3, atol=2e-3)
