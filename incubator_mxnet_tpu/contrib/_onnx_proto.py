"""Minimal vendored ONNX protobuf (de)serializer — no onnx wheel needed.

Implements the protobuf wire format by hand for exactly the schema subset
the converter (`contrib/onnx.py`) emits and consumes: ModelProto /
GraphProto / NodeProto / AttributeProto / TensorProto / ValueInfoProto
with raw_data tensors. Field numbers follow the public onnx.proto3 schema
(ONNX IR; reference counterpart: python/mxnet/contrib/onnx's dependency on
the onnx package — this build is environment-independent instead).

Wire format recap: each field is a varint key ``(field_number << 3) |
wire_type`` followed by a varint (type 0), 8-byte scalar (1), length-
delimited bytes (2), or 4-byte scalar (5). Unknown fields are skipped on
read, so files produced by the real onnx library parse fine.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence

import numpy as np

# TensorProto.DataType (onnx.proto3 enum)
FLOAT = 1
UINT8 = 2
INT8 = 3
INT32 = 6
INT64 = 7
BOOL = 9
FLOAT16 = 10
DOUBLE = 11
BFLOAT16 = 16

_NP_OF = {FLOAT: np.float32, UINT8: np.uint8, INT8: np.int8,
          INT32: np.int32, INT64: np.int64, BOOL: np.bool_,
          FLOAT16: np.float16, DOUBLE: np.float64}
_DT_OF = {np.dtype(v): k for k, v in _NP_OF.items()}

# AttributeProto.AttributeType
ATTR_FLOAT, ATTR_INT, ATTR_STRING = 1, 2, 3
ATTR_FLOATS, ATTR_INTS, ATTR_STRINGS = 6, 7, 8


# --------------------------------------------------------------------- #
# wire primitives
# --------------------------------------------------------------------- #

def _varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64  # two's-complement for negative int64
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: bytes, pos: int):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _signed(n: int) -> int:
    return n - (1 << 64) if n >= 1 << 63 else n


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_field(field: int, payload: bytes) -> bytes:
    return _key(field, 2) + _varint(len(payload)) + payload


def _varint_field(field: int, value: int) -> bytes:
    return _key(field, 0) + _varint(value)


def _str_field(field: int, s) -> bytes:
    return _len_field(field, s if isinstance(s, bytes) else s.encode())


def _parse(buf: bytes) -> Dict[int, List]:
    """One message level → {field_number: [raw values in order]}."""
    fields: Dict[int, List] = {}
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        fnum, wire = key >> 3, key & 7
        if wire == 0:
            v, pos = _read_varint(buf, pos)
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            v = buf[pos:pos + 4]
            pos += 4
        elif wire == 1:
            v = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        fields.setdefault(fnum, []).append(v)
    return fields


def _first(fields, n, default=None):
    return fields[n][0] if n in fields else default


# --------------------------------------------------------------------- #
# writers (dict-shaped messages → bytes)
# --------------------------------------------------------------------- #

def tensor_bytes(name: str, arr: np.ndarray) -> bytes:
    """TensorProto: dims=1, data_type=2, name=8, raw_data=9."""
    dt = _DT_OF.get(arr.dtype)
    if dt is None:
        raise ValueError(f"unsupported tensor dtype {arr.dtype}")
    out = b"".join(_varint_field(1, int(d)) for d in arr.shape)
    out += _varint_field(2, dt)
    out += _str_field(8, name)
    out += _len_field(9, np.ascontiguousarray(arr).tobytes())
    return out


def _attr_bytes(name: str, value) -> bytes:
    """AttributeProto: name=1 f=2 i=3 s=4 floats=7 ints=8 type=20.
    Python-typed values map the way onnx.helper.make_node does."""
    out = _str_field(1, name)
    if isinstance(value, bool):
        out += _varint_field(3, int(value)) + _varint_field(20, ATTR_INT)
    elif isinstance(value, int):
        out += _varint_field(3, value) + _varint_field(20, ATTR_INT)
    elif isinstance(value, float):
        out += _key(2, 5) + struct.pack("<f", value)
        out += _varint_field(20, ATTR_FLOAT)
    elif isinstance(value, (str, bytes)):
        out += _str_field(4, value) + _varint_field(20, ATTR_STRING)
    elif isinstance(value, (list, tuple)) and value and \
            all(isinstance(v, float) for v in value):
        for v in value:
            out += _key(7, 5) + struct.pack("<f", v)
        out += _varint_field(20, ATTR_FLOATS)
    elif isinstance(value, (list, tuple)):
        for v in value:
            out += _varint_field(8, int(v))
        out += _varint_field(20, ATTR_INTS)
    else:
        raise ValueError(f"unsupported attribute {name}={value!r}")
    return out


def node_bytes(op_type: str, inputs: Sequence[str], outputs: Sequence[str],
               name: str = "", attrs: Optional[Dict] = None) -> bytes:
    """NodeProto: input=1 output=2 name=3 op_type=4 attribute=5."""
    out = b"".join(_str_field(1, i) for i in inputs)
    out += b"".join(_str_field(2, o) for o in outputs)
    if name:
        out += _str_field(3, name)
    out += _str_field(4, op_type)
    for k in sorted(attrs or {}):
        out += _len_field(5, _attr_bytes(k, (attrs or {})[k]))
    return out


def value_info_bytes(name: str, elem_type: int,
                     shape: Optional[Sequence[int]]) -> bytes:
    """ValueInfoProto: name=1 type=2{tensor_type=1{elem_type=1
    shape=2{dim=1{dim_value=1}}}}."""
    tensor = _varint_field(1, elem_type)
    if shape is not None:
        dims = b"".join(
            _len_field(1, _varint_field(1, int(d))) for d in shape)
        tensor += _len_field(2, dims)
    return _str_field(1, name) + _len_field(2, _len_field(1, tensor))


def graph_bytes(nodes: Sequence[bytes], name: str,
                inputs: Sequence[bytes], outputs: Sequence[bytes],
                initializers: Sequence[bytes]) -> bytes:
    """GraphProto: node=1 name=2 initializer=5 input=11 output=12."""
    out = b"".join(_len_field(1, n) for n in nodes)
    out += _str_field(2, name)
    out += b"".join(_len_field(5, t) for t in initializers)
    out += b"".join(_len_field(11, i) for i in inputs)
    out += b"".join(_len_field(12, o) for o in outputs)
    return out


def model_bytes(graph: bytes, opset: int = 13, ir_version: int = 8,
                producer: str = "incubator_mxnet_tpu") -> bytes:
    """ModelProto: ir_version=1 producer_name=2 graph=7 opset_import=8;
    OperatorSetIdProto: domain=1 version=2."""
    opset_id = _str_field(1, "") + _varint_field(2, opset)
    return (_varint_field(1, ir_version) + _str_field(2, producer)
            + _len_field(7, graph) + _len_field(8, opset_id))


# --------------------------------------------------------------------- #
# readers (bytes → dict-shaped messages)
# --------------------------------------------------------------------- #

def parse_tensor(buf: bytes):
    f = _parse(buf)
    dims = [v for v in f.get(1, [])]
    dt_enum = _first(f, 2, FLOAT)
    dtype = _NP_OF.get(dt_enum)
    name = _first(f, 8, b"").decode()
    if dtype is None:
        raise ValueError(
            f"ONNX tensor {name!r}: unsupported data_type enum {dt_enum} "
            f"(supported: {sorted(_NP_OF)}; bfloat16/float16 initializers "
            f"are not handled by the vendored parser)")
    if 9 in f:                                   # raw_data
        arr = np.frombuffer(f[9][0], dtype=dtype).reshape(dims).copy()
    elif 4 in f:                                 # packed float_data
        arr = np.frombuffer(f[4][0], np.float32).reshape(dims).copy()
    elif 7 in f:                                 # packed int64_data
        vals, pos = [], 0
        buf7 = f[7][0]
        while pos < len(buf7):
            v, pos = _read_varint(buf7, pos)
            vals.append(_signed(v))
        arr = np.array(vals, np.int64).reshape(dims)
    else:
        arr = np.zeros(dims, dtype)
    return name, arr


def _parse_attr(buf: bytes):
    f = _parse(buf)
    name = _first(f, 1, b"").decode()
    atype = _first(f, 20)
    if atype == ATTR_INT or (atype is None and 3 in f):
        return name, _signed(_first(f, 3, 0))
    if atype == ATTR_FLOAT or (atype is None and 2 in f):
        return name, struct.unpack("<f", _first(f, 2))[0]
    if atype == ATTR_STRING or (atype is None and 4 in f):
        return name, _first(f, 4)          # bytes, like onnx.helper
    if atype == ATTR_INTS or (atype is None and 8 in f):
        vals = []
        for raw in f.get(8, []):
            if isinstance(raw, int):        # unpacked
                vals.append(_signed(raw))
            else:                           # packed
                pos = 0
                while pos < len(raw):
                    v, pos = _read_varint(raw, pos)
                    vals.append(_signed(v))
        return name, vals
    if atype == ATTR_FLOATS or (atype is None and 7 in f):
        vals = []
        for raw in f.get(7, []):
            if isinstance(raw, bytes) and len(raw) > 4:  # packed
                vals.extend(struct.unpack(f"<{len(raw) // 4}f", raw))
            else:
                vals.append(struct.unpack("<f", raw)[0])
        return name, vals
    return name, None


def parse_node(buf: bytes):
    f = _parse(buf)
    return {
        "op_type": _first(f, 4, b"").decode(),
        "name": _first(f, 3, b"").decode(),
        "inputs": [v.decode() for v in f.get(1, [])],
        "outputs": [v.decode() for v in f.get(2, [])],
        "attrs": dict(_parse_attr(a) for a in f.get(5, [])),
    }


def parse_value_info(buf: bytes):
    f = _parse(buf)
    name = _first(f, 1, b"").decode()
    shape: List[int] = []
    tt = _first(_parse(_first(f, 2, b"")), 1)
    if tt:
        shape_f = _parse(tt)
        if 2 in shape_f:
            for dim_buf in _parse(shape_f[2][0]).get(1, []):
                shape.append(_first(_parse(dim_buf), 1, 0))
    return {"name": name, "shape": shape}


def parse_model(buf: bytes):
    """bytes → {"graph": {nodes, inputs, outputs, initializers}, "opset"}."""
    f = _parse(buf)
    g = _parse(_first(f, 7, b""))
    opset = 0
    for op_buf in f.get(8, []):
        opset = max(opset, _first(_parse(op_buf), 2, 0))
    initializers = dict(parse_tensor(t) for t in g.get(5, []))
    return {
        "opset": opset,
        "graph": {
            "name": _first(g, 2, b"").decode(),
            "nodes": [parse_node(n) for n in g.get(1, [])],
            "inputs": [parse_value_info(i) for i in g.get(11, [])],
            "outputs": [parse_value_info(o) for o in g.get(12, [])],
            "initializers": initializers,
        },
    }
