"""Shared per-layer rematerialization helper.

``jax.checkpoint`` around one block call (the reference's
mirroring/memonger memory plan, SURVEY.md §2.1 PlanMemory row). The
block's dropout keys are drawn OUTSIDE the checkpoint and passed as an
explicit input: provider state mutated inside the checkpoint trace would
leak inner tracers, and an input key replays identically in the remat
pass. Params enter via closure → saved as residuals, not recomputed."""

from __future__ import annotations

import math

import jax

from .. import random as _rand
from ..ndarray import NDArray

__all__ = ["remat_call", "resolve_policy", "plan_remat_from_profile"]


def resolve_policy(remat):
    """Map a model-level ``remat`` flag to a jax.checkpoint policy.

    False → no remat; True → whole-layer remat (recompute everything);
    "dots" → selective: matmul outputs are SAVED, only elementwise/norm
    intermediates are recomputed — a fraction of full remat's recompute
    FLOPs for most of its memory win (the B=64 OOM in TPU_STATUS.md was
    bound by gelu/norm intermediates, not dot outputs)."""
    if remat == "dots":
        return jax.checkpoint_policies.checkpoint_dots
    if remat not in (False, True):
        raise ValueError(
            f"remat must be False, True, or 'dots'; got {remat!r}")
    return None


def plan_remat_from_profile(stats, num_blocks):
    """Derive a per-block remat plan from a measured overlap profile.

    ``stats`` is ``tools.trace_summary.overlap_stats(trace_dir)`` — the
    per-lane compute/collective split of a real profile. Returns a list
    of ``num_blocks`` entries (``False`` | ``"dots"`` | ``True``)
    suitable for ``SPMDTrainer(remat_plan=...)``, which wraps each
    pipeline block in ``jax.checkpoint`` with the matching policy
    (parallel/pipelined.py).

    Heuristic, keyed on the EXPOSED fraction (collective time the
    backward failed to hide, relative to compute):

      exposed/compute < 0.05  → no remat: collectives already overlap,
                                extra recompute only slows the step.
      exposed/compute < 0.25  → ``"dots"`` everywhere: cheap recompute
                                (elementwise/norm only) lengthens each
                                block's backward a little, giving the
                                in-flight bucket reductions more compute
                                to hide behind, and frees activation HBM.
      otherwise               → full remat on the EARLIEST
                                ``ceil(frac * num_blocks)`` blocks (they
                                backward LAST, exactly when the deep
                                buckets drain and exposure concentrates)
                                and ``"dots"`` on the rest.

    A profile with no compute attribution (e.g. cpu_mode traces) maps to
    no remat — never guess from an empty window."""
    num_blocks = int(num_blocks)
    if num_blocks <= 0:
        return []
    compute = float(stats.get("compute_us") or 0.0)
    exposed = float(stats.get("exposed_us") or 0.0)
    if compute <= 0.0:
        return [False] * num_blocks
    frac = exposed / compute
    if frac < 0.05:
        return [False] * num_blocks
    if frac < 0.25:
        return ["dots"] * num_blocks
    n_full = min(num_blocks, max(1, math.ceil(min(frac, 1.0) * num_blocks)))
    return [True] * n_full + ["dots"] * (num_blocks - n_full)


def remat_call(block, *args, policy=None):
    """Apply ``block(*args)`` under jax.checkpoint. ``args`` are NDArrays
    or None; returns an NDArray."""
    base = _rand.new_key()
    vals = [a._data if a is not None else None for a in args]

    def _ckpt(key, *vs):
        with _rand.key_provider(key):
            nds = [NDArray(v) if v is not None else None for v in vs]
            return block(*nds)._data

    return NDArray(jax.checkpoint(_ckpt, policy=policy)(base, *vals))
