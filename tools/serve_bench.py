"""Serving benchmark: continuous batching vs looped per-request decode.

Measures what the serve/ subsystem buys over the repo's previous only
inference path (per-request ``cached_generate`` over dense (B, Tmax)
KV buffers): requests arrive by a Poisson process, the engine packs
them into fixed decode slots with a paged KV cache, and the comparison
baseline serves the SAME request set one at a time. Reported:

  - tokens/s (generated tokens / wall-clock from first arrival to last
    completion) for both paths, and the speedup;
  - p50/p99 time-per-output-token (TPOT) across all generated tokens
    (each token is stamped with the decode-step wall time that emitted
    it; the first token carries its prefill time — so p99 captures the
    prefill-insert stalls continuous batching is supposed to hide);
  - steady-state compile discipline: the decode step must have compiled
    EXACTLY ONCE across the whole run despite occupancy churn.

``--smoke`` is the CI guard (ci/run.sh servebench stage): a fast run
that exits non-zero on any steady-state decode retrace. CPU-measurable
by design — the scheduler/cache win (batch 8 decode streams into one
program instead of 8 programs of batch 1) does not need a TPU to show.

Fairness notes for the baseline: every request uses the same
(prompt_pad, total) shape so ``cached_generate`` compiles ONCE (warmed
outside the timed window) — the 3x bar is against its best case, not
its retrace pathology. Arrivals gate the baseline too: it may not start
a request before that request arrived.

Usage:
  python tools/serve_bench.py                # full bench, banks
                                             # BENCH_SERVE.json
  python tools/serve_bench.py --smoke        # CI guard (fast, asserts)
  python tools/serve_bench.py --json OUT.json
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _build(seed=0, vocab=64, max_length=256):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.models import gpt as g
    mx.random.seed(seed)
    model = g.gpt_mini(vocab_size=vocab, max_length=max_length)
    model.initialize()
    return model


def _make_requests(n, prompt_len, max_new, rate_hz, vocab, seed=0):
    """n requests, fixed shape (fair single-compile baseline), Poisson
    arrival times at ``rate_hz``."""
    import numpy as np
    from incubator_mxnet_tpu.serve import Request
    rng = np.random.RandomState(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate_hz, size=n))
    arrivals[0] = 0.0                      # the clock starts at work
    reqs = [Request(rng.randint(0, vocab, size=(prompt_len,)),
                    max_new_tokens=max_new) for _ in range(n)]
    return reqs, arrivals.tolist()


def _percentile(xs, q):
    xs = sorted(xs)
    if not xs:
        return 0.0
    idx = min(len(xs) - 1, int(round(q / 100.0 * (len(xs) - 1))))
    return xs[idx]


def bench_engine(model, reqs, arrivals, num_slots, page_size):
    from incubator_mxnet_tpu.serve import InferenceEngine
    eng = InferenceEngine(model, num_slots=num_slots,
                          page_size=page_size)
    t0 = time.perf_counter()
    eng.run(reqs, arrival_times=arrivals)
    wall = time.perf_counter() - t0
    tokens = sum(len(r.token_ids) for r in reqs)
    # every request's FIRST token is emitted by its prefill program, not
    # a decode step — exclude them so mean_occupancy is per-decode-step
    decode_tokens = tokens - len(reqs)
    tpot = [dt for r in reqs for dt in r.token_times]
    return {
        "tokens": tokens,
        "wall_s": wall,
        "tokens_per_s": tokens / wall,
        "tpot_p50_ms": _percentile(tpot, 50) * 1e3,
        "tpot_p99_ms": _percentile(tpot, 99) * 1e3,
        "decode_steps": eng.decode_steps,
        "decode_trace_count": eng.decode_trace_count,
        "prefill_trace_count": eng.prefill_trace_count,
        "mean_occupancy": decode_tokens / max(eng.decode_steps, 1),
    }


def bench_baseline(model, reqs, arrivals, max_new):
    """Looped per-request cached_generate over the same arrival trace.
    One warmup call outside the timed window so the (single) shape is
    pre-compiled — the baseline pays no retraces, only its serial,
    dense-cache design."""
    import numpy as np
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.models.gpt import cached_generate
    prompt0 = np.asarray(reqs[0].prompt_ids, np.int32)[None, :]
    cached_generate(model, nd.array(prompt0, dtype="int32"),
                    max_new_tokens=max_new).asnumpy()    # warm compile
    t0 = time.perf_counter()
    tokens = 0
    tpot = []
    for req, arr in zip(reqs, arrivals):
        now = time.perf_counter() - t0
        if now < arr:                       # cannot start early
            time.sleep(arr - now)
        ids = np.asarray(req.prompt_ids, np.int32)[None, :]
        t1 = time.perf_counter()
        out = cached_generate(model, nd.array(ids, dtype="int32"),
                              max_new_tokens=max_new).asnumpy()
        dt = time.perf_counter() - t1
        n = out.shape[1] - ids.shape[1]
        tokens += n
        tpot.extend([dt / n] * n)
    wall = time.perf_counter() - t0
    return {
        "tokens": tokens,
        "wall_s": wall,
        "tokens_per_s": tokens / wall,
        "tpot_p50_ms": _percentile(tpot, 50) * 1e3,
        "tpot_p99_ms": _percentile(tpot, 99) * 1e3,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="fast CI guard: assert exactly one decode-step "
                         "compile in steady state")
    ap.add_argument("--json", default=None,
                    help="bank results here (default BENCH_SERVE.json "
                         "at the repo root for a full run)")
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--prompt-len", type=int, default=24)
    ap.add_argument("--max-new", type=int, default=32)
    ap.add_argument("--rate", type=float, default=30.0,
                    help="Poisson arrival rate (req/s) — default keeps "
                         "~all 8 slots busy on a CPU host")
    args = ap.parse_args()

    if args.smoke:
        args.requests, args.max_new = 12, 12

    model = _build(max_length=args.prompt_len + args.max_new + 8)
    vocab = model.vocab_size
    reqs, arrivals = _make_requests(args.requests, args.prompt_len,
                                    args.max_new, args.rate, vocab)
    engine = bench_engine(model, reqs, arrivals, args.slots,
                          args.page_size)

    result = {
        "config": {"requests": args.requests, "slots": args.slots,
                   "page_size": args.page_size,
                   "prompt_len": args.prompt_len,
                   "max_new": args.max_new, "rate_hz": args.rate,
                   "backend": os.environ.get("JAX_PLATFORMS", "cpu")},
        "engine": engine,
    }
    if not args.smoke:
        reqs_b, arrivals_b = _make_requests(
            args.requests, args.prompt_len, args.max_new, args.rate,
            vocab)
        baseline = bench_baseline(model, reqs_b, arrivals_b,
                                  args.max_new)
        result["baseline_cached_generate"] = baseline
        result["throughput_speedup"] = (
            engine["tokens_per_s"] / baseline["tokens_per_s"])

    print(json.dumps(result, indent=2))

    ok = True
    if engine["decode_trace_count"] != 1:
        print(f"FAIL: decode step compiled "
              f"{engine['decode_trace_count']} times across occupancy "
              f"churn (must be exactly 1)", file=sys.stderr)
        ok = False
    if not args.smoke and result["throughput_speedup"] < 3.0:
        print(f"WARN: serving speedup "
              f"{result['throughput_speedup']:.1f}x below the 3x bar",
              file=sys.stderr)

    out = args.json
    if out is None and not args.smoke:
        out = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "BENCH_SERVE.json")
    if out:
        with open(out, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
        print(f"banked {out}")

    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
