"""SVRG optimization (parity:
python/mxnet/contrib/svrg_optimization/{svrg_module,svrg_optimizer}.py —
file-level citation, SURVEY.md caveat).

Stochastic Variance-Reduced Gradient: every ``update_freq`` epochs a full
pass stores snapshot weights w~ and the full-data gradient mu; minibatch
updates then use the variance-reduced direction
    g_vr = g_i(w) - g_i(w~) + mu.

TPU-first design: instead of the reference's pair of mutated Modules and
a special KVStore-intercepting optimizer (_SVRGOptimizer rewriting key
names), the snapshot is an immutable pytree and the variance-reduced
gradient is computed functionally — one extra forward/backward at the
snapshot weights per batch, all inside the normal autograd machinery.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from .. import autograd
from ..base import MXNetError
from ..module.module import Module

__all__ = ["SVRGModule"]


class SVRGModule(Module):
    """Module with SVRG variance reduction (reference: SVRGModule).

    Extra arg ``update_freq``: take a new full-gradient snapshot every
    ``update_freq`` epochs. Use exactly like Module; call
    ``update_full_grads(train_data)`` at the epochs ``is_update_epoch``
    flags (``fit`` does both automatically).
    """

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), update_freq=2, **kwargs):
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, **kwargs)
        if update_freq < 1:
            raise MXNetError("update_freq must be >= 1")
        self.update_freq = int(update_freq)
        self._snapshot: Optional[Dict[str, "object"]] = None
        self._mu: Optional[Dict[str, "object"]] = None

    # -- snapshot ------------------------------------------------------ #
    def is_update_epoch(self, epoch: int) -> bool:
        return epoch % self.update_freq == 0

    def update_full_grads(self, train_data):
        """One full pass at the current weights: store snapshot weights
        w~ and the averaged full gradient mu (reference:
        SVRGModule.update_full_grads)."""
        import numpy as np

        arg_params, _ = self.get_params()
        self._snapshot = {k: v.copy() for k, v in arg_params.items()}

        sums: Dict[str, np.ndarray] = {}
        n_batches = 0
        train_data.reset()
        for batch in train_data:
            self.forward(batch, is_train=True)
            self.backward()
            for name, grad in self._exec.grad_dict.items():
                if grad is None:
                    continue
                g = grad.asnumpy()
                sums[name] = sums.get(name, 0.0) + g
            n_batches += 1
        train_data.reset()
        if n_batches == 0:
            raise MXNetError("update_full_grads: empty data iterator")
        from ..ndarray import array as nd_array
        self._mu = {k: nd_array(v / n_batches) for k, v in sums.items()}

    # -- variance-reduced step ---------------------------------------- #
    def forward_backward(self, data_batch):
        """fwd+bwd at the snapshot weights first, then at the current
        weights; grad := g(w) - g(w~) + mu. Order matters: the LAST
        forward is at the current weights, so executor outputs (and
        therefore update_metric) reflect w, not w~; aux state (e.g. BN
        running stats) is saved/restored around the snapshot pass so it
        only ever advances with current-weight activations."""
        if self._snapshot is None:
            super().forward_backward(data_batch)
            return
        current = {k: v.copy() for k, v in self.get_params()[0].items()}
        aux_saved = {k: v.copy()
                     for k, v in self._exec.aux_dict.items()}
        self.set_params(self._snapshot, allow_missing=True,
                        force_init=True)
        super().forward_backward(data_batch)
        grad_snap = {k: (g.copy() if g is not None else None)
                     for k, g in self._exec.grad_dict.items()}
        self.set_params(current, aux_params=aux_saved,
                        allow_missing=True, force_init=True)
        super().forward_backward(data_batch)
        # write the variance-reduced gradient back into the executor
        for name, g in self._exec.grad_dict.items():
            if g is None or name not in self._mu:
                continue
            gs = grad_snap.get(name)
            vr = g - gs + self._mu[name] if gs is not None \
                else g + self._mu[name]
            self._exec.grad_dict[name]._data = vr._data

    # -- fit with automatic snapshotting ------------------------------- #
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            optimizer="sgd", optimizer_params=None, num_epoch=1,
            batch_end_callback=None, epoch_end_callback=None,
            initializer=None, kvstore="local"):
        from ..module.module import _BatchEndParam, _as_list

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True)
        self.init_params(initializer=initializer)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params or {})
        from .. import metric as metric_mod
        em = metric_mod.create(eval_metric) \
            if not hasattr(eval_metric, "update") else eval_metric
        for epoch in range(num_epoch):
            if self.is_update_epoch(epoch):
                self.update_full_grads(train_data)
            em.reset()
            train_data.reset()
            for nbatch, batch in enumerate(train_data):
                self.forward_backward(batch)
                self.update()
                self.update_metric(em, batch.label)
                for cb in _as_list(batch_end_callback or []):
                    cb(_BatchEndParam(epoch, nbatch, em))
            if epoch_end_callback:
                epoch_end_callback(epoch, self.symbol,
                                   *self.get_params())
            if eval_data is not None:
                self.score(eval_data, em)
        return em
