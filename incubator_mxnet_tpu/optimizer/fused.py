"""Fused whole-tree optimizer step (multi-tensor apply).

The reference kills per-parameter launch overhead with engine op bulking
plus hand-fused multi-tensor kernels (`src/operator/contrib/
preloaded_multi_sgd-inl.h`, the multi_* family in optimizer_op.cc —
file-level citations, SURVEY.md caveat). The TPU-native translation is to
put the WHOLE update into one XLA program: group trainable parameters by
(dtype, storage type, hyperparameter signature) and apply each group's
update as ONE jitted, donated call over the stacked pytree of
(weights, grads, optimizer states).

Two consumers share the same functional core (``apply_updates``):

  - ``gluon.Trainer`` jits it per group via ``FusedApplier`` — the eager
    per-parameter Python loop (one un-jitted dispatch per param per step)
    collapses to one compiled call per group per step.
  - ``parallel.SPMDTrainer`` calls it INSIDE its single jitted train step,
    so fwd+bwd+reduce+update stay one XLA program.

The imperative ``Optimizer`` subclasses are reused unchanged: inside the
trace each parameter's update runs through ``update_multi_precision`` on
NDArray views of the traced arrays, and XLA fuses the resulting
elementwise chains across parameters. Step count, learning rate, and
gradient rescale ride as traced scalars (``_traced_t`` / ``_traced_lr`` /
a temporarily swapped ``rescale_grad``) so schedules and Adam/LAMB bias
correction advance without recompiling.

What does NOT fuse (falls back to the eager per-param path):

  - optimizers with per-step host-side state (``fusable = False``:
    Nadam's ``m_schedule``, SGLD's fresh host RNG key per update) —
    baking those into a trace would freeze them at their step-1 values;
  - ``row_sparse``-gradient parameters — their active-row index sets
    change shape every step, which would retrace per step.

Round 13 adds the IN-STEP NON-FINITE GUARD (docs/RESILIENCE.md): one
jitted all-finite reduction over every fused gradient produces a device
scalar ``ok`` that rides into each group's update program as PURE
TRACED DATA, where a ``where``-select returns the OLD weights and
optimizer state when the step must be skipped. The skip is therefore
decided on device with zero extra host syncs on the dispatch path (the
flag is read AFTER the updates are enqueued, only to keep host step
counters and the loss scaler honest), and the group programs still
compile exactly once — overflow/clean transitions and loss-scale
growth/decay never retrace (``guard_trace_count`` /
``trace_count`` asserted in tests and tools/train_chaos_bench.py).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np

from ..base import getenv_bool
from ..ndarray import NDArray

__all__ = ["apply_updates", "FusedApplier", "hyperparam_signature",
           "all_finite", "norm_based"]


def _is_nd(x):
    return isinstance(x, NDArray)


def norm_based(optimizer) -> bool:
    """True for optimizers whose update rule reads a GLOBAL weight/grad
    norm (LAMB/LARS trust ratios). Those updates are only correct over
    full parameter values: under fsdp the pipelined step applies updates
    on shard-local slices, where a per-shard norm would silently change
    the trust ratio — parallel/pipelined.py rejects the combination via
    this one shared predicate so the two trainers cannot drift."""
    name = type(optimizer).__name__.lower()
    return any(t in name for t in ("lamb", "lars"))


def all_finite(grad_vals):
    """Traceable all-finite reduction over a sequence of jax arrays →
    an f32 scalar (1.0 = every float entry finite). THE guard
    reduction — shared by the fused group programs, the external
    multi-group guard, and the SPMD step (parallel/spmd.py), so the
    guard semantics cannot drift between trainers."""
    ok = jnp.asarray(True)
    for g in grad_vals:
        if jnp.issubdtype(g.dtype, jnp.floating):
            ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(g)))
    return ok.astype(jnp.float32)


def apply_updates(optimizer, indices, weight_vals, grad_vals, states,
                  t, lr, rescale_grad=None):
    """Functional whole-tree optimizer application (call under a trace).

    Parameters
    ----------
    optimizer : Optimizer — imperative optimizer, reused as the update rule.
    indices : sequence of parameter indices (the optimizer's state keys).
    weight_vals / grad_vals : sequences of jax arrays, aligned to indices.
    states : sequence of optimizer-state pytrees with jax-array leaves.
    t : traced step count — scalar, or a (len(indices),) vector for
        per-parameter counts (Adam/LAMB bias correction).
    lr : traced base learning rate (per-param multipliers apply inside).
    rescale_grad : optional traced gradient rescale; when given it
        temporarily replaces ``optimizer.rescale_grad`` so batch-size
        changes do not force a retrace.

    Returns ``(new_weights, new_states)`` — tuples aligned to indices,
    with jax-array leaves. The optimizer's host-side counters are touched
    at trace time only; callers own their true values.
    """
    new_weights: List = []
    new_states: List = []
    saved_rescale = optimizer.rescale_grad
    optimizer._traced_lr = lr
    if rescale_grad is not None:
        optimizer.rescale_grad = rescale_grad
    t_is_vec = getattr(t, "ndim", 0) >= 1
    try:
        for slot, (pi, w, g) in enumerate(
                zip(indices, weight_vals, grad_vals)):
            w_nd = NDArray(w)
            g_nd = NDArray(g)
            st = jtu.tree_map(NDArray, states[slot])
            optimizer._traced_t = t[slot] if t_is_vec else t
            optimizer.update_multi_precision(pi, w_nd, g_nd, st)
            # pin output dtypes to the input dtypes: the traced t/lr
            # scalars are f32 arrays, and jnp promotion would otherwise
            # widen low-precision weights/state (breaking donation buffer
            # reuse and the group's dtype key). Low-precision groups thus
            # compute scalar-touched arithmetic in f32 and round back to
            # the storage dtype — documented in docs/PERF_NOTES.md.
            new_w = w_nd._data
            if new_w.dtype != w.dtype:
                new_w = new_w.astype(w.dtype)
            new_weights.append(new_w)
            new_states.append(jtu.tree_map(
                lambda old, new: (
                    new._data.astype(old.dtype)
                    if _is_nd(new) and new._data.dtype != old.dtype
                    else (new._data if _is_nd(new) else new)),
                states[slot], st))
    finally:
        optimizer._traced_t = optimizer._traced_lr = None
        optimizer.rescale_grad = saved_rescale
    return tuple(new_weights), tuple(new_states)


def hyperparam_signature(optimizer) -> Tuple:
    """Hashable signature of every host scalar an update trace bakes in.

    A fused trace captures the optimizer's scalar attributes (momentum,
    betas, wd, clip_gradient, ...) as constants; if any of them changes the
    jitted group function must be rebuilt. Step count, learning rate and
    rescale_grad are excluded — they ride as traced inputs.
    """
    skip = {"num_update", "lr", "rescale_grad", "_traced_t", "_traced_lr"}
    items = []
    for k, v in sorted(vars(optimizer).items()):
        if k in skip:
            continue
        if isinstance(v, (int, float, bool, str)) or v is None:
            items.append((k, v))
    return (type(optimizer).__name__, tuple(items))


class FusedApplier:
    """Whole-tree fused apply for ``Trainer``'s eager step.

    Groups (index, param, grad) triples by (dtype, grad storage type),
    and runs each group through ONE jitted call of ``apply_updates`` with
    the weights and optimizer-state leaves donated. The jit cache is keyed
    by (group key, member indices, hyperparameter signature, per-param
    lr/wd multipliers, state treedef) — any change retraces exactly once,
    steady state re-dispatches the cached executable.
    """

    def __init__(self, optimizer, donate: Optional[bool] = None,
                 guard: Optional[bool] = None):
        self.optimizer = optimizer
        if donate is None:
            # donation is a no-op (plus a warning) on the CPU backend
            donate = jax.default_backend() != "cpu" or \
                getenv_bool("MXTPU_FUSED_DONATE", False)
        self.donate = donate
        if guard is None:
            guard = getenv_bool("MXTPU_STEP_GUARD", True)
        self.guard = bool(guard)
        self._jits: Dict = {}
        self._guard_jits: Dict = {}
        self._accum_jits: Dict = {}
        self.trace_count = 0      # executions of a traced body (compiles)
        self.call_count = 0       # fused group dispatches
        self.guard_trace_count = 0  # all-finite reduction compiles
        self.accum_trace_count = 0  # f32 accumulate-program compiles
        self.skipped_steps = 0    # guard-vetoed apply() calls

    # ------------------------------------------------------------------ #
    def supported(self) -> bool:
        return getattr(self.optimizer, "fusable", True)

    def grad_all_finite(self, grad_vals):
        """One jitted all-finite reduction over every fused gradient →
        an f32 device scalar (1.0 = apply, 0.0 = skip). Compiled once
        per (shape, dtype) signature; non-float grads are vacuously
        finite and excluded."""
        vals = tuple(g for g in grad_vals
                     if jnp.issubdtype(g.dtype, jnp.floating))
        if not vals:
            return None
        sig = tuple((v.shape, str(v.dtype)) for v in vals)
        fn = self._guard_jits.get(sig)
        if fn is None:
            applier = self

            def allfinite(grads):
                applier.guard_trace_count += 1   # trace-time only
                return all_finite(grads)

            fn = jax.jit(allfinite)
            self._guard_jits[sig] = fn
        return fn(vals)

    def accumulate(self, acc_vals, grad_vals):
        """One jitted f32 microbatch-gradient accumulation:
        ``acc + grad.astype(f32)`` over the whole fused set (round 16,
        docs/TRAINING_PERF.md). f32 accumulators keep low-precision
        microbatch gradients from losing mass to rounding, and
        non-finite values PROPAGATE through the sum — so the apply-time
        all-finite verdict over the accumulators is the COMBINED
        verdict for the accumulated step (a NaN in any microbatch skips
        the whole apply). Compiled once per (shape, dtype) signature,
        accumulators donated; the program's shape never depends on the
        accumulation count, so changing counts never retraces
        (``accum_trace_count`` asserted in tests and
        tools/step_bench.py --mfu --smoke)."""
        sig = tuple((v.shape, str(v.dtype)) for v in grad_vals)
        fn = self._accum_jits.get(sig)
        if fn is None:
            applier = self

            def accum(accs, grads):
                applier.accum_trace_count += 1   # trace-time only
                return tuple(a + g.astype(jnp.float32)
                             for a, g in zip(accs, grads))

            fn = jax.jit(accum,
                         donate_argnums=(0,) if self.donate else ())
            self._accum_jits[sig] = fn
        return fn(tuple(acc_vals), tuple(grad_vals))

    def apply(self, items: Sequence, updater,
              extra_grads: Sequence = ()) -> bool:
        """Apply one fused update to ``items`` = [(index, param, grad)].

        ``updater`` is the Trainer's ``Updater`` — optimizer state is
        created into / read from ``updater.states`` so eager and fused
        paths share one serializable state store (save_states parity).

        With the guard on, the skip decision is computed on device and
        ``where``-selected inside each group's program; the flag is
        read back only AFTER every group is dispatched, and a vetoed
        step rolls the host update counters back so schedules and
        Adam/LAMB bias correction do not advance on skipped steps.
        ``extra_grads`` are gradients applied OUTSIDE the fused call
        (the Trainer's row_sparse path) that must still join the
        all-or-nothing verdict — any non-finite entry there vetoes the
        fused groups too. Returns True when the update was applied,
        False when the guard skipped it (params/state bit-identical to
        before the call).
        """
        opt = self.optimizer
        groups: Dict = {}
        for i, p, g in items:
            if i not in updater.states:
                updater.states[i] = opt.create_state_multi_precision(
                    i, p.data())
            gkey = (str(p.data().dtype),
                    getattr(p, "_grad_stype", "default"))
            groups.setdefault(gkey, []).append((i, p, g))
        # guard plumbing: with ONE group (the common case) the
        # all-finite reduction folds INTO the group's own program and
        # the flag comes back as an extra output — zero added
        # dispatches (the separate-program design measured ~12% on the
        # CPU dispatch floor; inline is <2%, PERF_NOTES round 13).
        # Multi-group sets — and steps carrying extra (row_sparse)
        # grads — need the COMBINED flag before any group selects, so
        # they pay one small external reduction program.
        extra_vals = tuple(getattr(g, "_data", g) for g in extra_grads)
        inline_guard = self.guard and len(groups) == 1 and not extra_vals
        ok = None
        if self.guard and not inline_guard:
            ok = self.grad_all_finite(
                tuple(g._data for _, _, g in items) + extra_vals)
        # commit the step's counters BEFORE dispatching: the eager path
        # bumps _update_count before reading the lr, so the scheduler must
        # see the post-bump num_update here too (scheduler(t), not t-1).
        # Trace-time bumps inside update() land on already-bumped counts
        # and are overwritten below, keeping the host counters exact.
        counts = opt._index_update_count
        prev_counts = dict(counts)
        prev_num_update = opt.num_update
        new_counts = {i: counts.get(i, 0) + 1 for i, _, _ in items}
        counts.update(new_counts)
        opt.num_update = max(counts.values(), default=opt.num_update)
        # read the schedule ONCE, before any group dispatch: a group's
        # trace-time _update_count() calls inside apply_updates bump
        # num_update mid-loop, so a per-group read would hand LATER
        # groups scheduler(t+1) instead of scheduler(t) whenever an
        # earlier group (re)traces (multi-dtype/stype sets only)
        lr = np.float32(float(opt.learning_rate))
        rescale = np.float32(float(opt.rescale_grad))
        for gkey, group in groups.items():
            group_ok = self._apply_group(gkey, group, updater, lr,
                                         rescale, ok,
                                         inline_guard=inline_guard)
            if inline_guard:
                ok = group_ok
        counts.update(new_counts)
        opt.num_update = max(counts.values(), default=opt.num_update)
        # mxlint: allow-host-sync(flag read AFTER every group dispatched; off the dispatch critical path by design)
        if ok is None or bool(np.asarray(ok) > 0):
            return True
        # guard veto: the programs already selected the old params and
        # state; un-advance the host counters so the next applied step
        # reuses this step's t (a skipped step never happened, contract
        # of the reference's multi_all_finite skip)
        counts.clear()
        counts.update(prev_counts)
        opt.num_update = prev_num_update
        self.skipped_steps += 1
        return False

    # ------------------------------------------------------------------ #
    def _apply_group(self, gkey, group, updater, lr, rescale,
                     ok=None, inline_guard=False):
        opt = self.optimizer
        indices = tuple(i for i, _, _ in group)
        states = [updater.states[i] for i in indices]
        state_leaves, state_tree = jtu.tree_flatten(
            jtu.tree_map(lambda s: s._data if _is_nd(s) else s,
                         tuple(states), is_leaf=_is_nd))
        mults = tuple((float(getattr(p, "lr_mult", 1.0)),
                       float(getattr(p, "wd_mult", 1.0)))
                      for _, p, _ in group)
        mode = ("inline" if inline_guard
                else "external" if ok is not None else "off")
        sig = (gkey, indices, state_tree,
               hyperparam_signature(opt), mults, mode)
        fn = self._jits.get(sig)
        if fn is None:
            fn = self._build(indices, state_tree, mode)
            self._jits[sig] = fn

        weight_vals = tuple(p.data()._data for _, p, _ in group)
        grad_vals = tuple(g._data for _, _, g in group)
        # apply() already committed this step's counts: use them directly
        t_vec = np.asarray(
            [opt._index_update_count.get(i, 1) for i in indices],
            np.float32)

        group_ok = None
        if mode == "external":
            new_ws, new_state_leaves = fn(
                weight_vals, grad_vals, tuple(state_leaves), t_vec, lr,
                rescale, ok)
        elif mode == "inline":
            new_ws, new_state_leaves, group_ok = fn(
                weight_vals, grad_vals, tuple(state_leaves), t_vec, lr,
                rescale)
        else:
            new_ws, new_state_leaves = fn(
                weight_vals, grad_vals, tuple(state_leaves), t_vec, lr,
                rescale)
        self.call_count += 1

        for (_, p, _), new_w in zip(group, new_ws):
            p.data()._data = new_w
        new_states = jtu.tree_unflatten(state_tree, list(new_state_leaves))
        jtu.tree_map(
            lambda old, new: setattr(old, "_data", new) if _is_nd(old)
            else None,
            tuple(states), new_states, is_leaf=_is_nd)
        return group_ok

    def _build(self, indices, state_tree, mode="off"):
        opt = self.optimizer
        applier = self

        def core(weight_vals, grad_vals, state_leaves, t_vec, lr, rescale,
                 ok):
            applier.trace_count += 1  # python body runs at trace time only
            states = jtu.tree_unflatten(state_tree, list(state_leaves))
            new_ws, new_states = apply_updates(
                opt, indices, weight_vals, grad_vals, states, t_vec, lr,
                rescale_grad=rescale)
            new_leaves = tuple(jtu.tree_leaves(new_states))
            if ok is not None:
                # skip-step as pure data: the guard flag selects the OLD
                # params/state, so a vetoed step is bit-identical to not
                # stepping — and the program is the same either way (no
                # retrace across overflow/clean transitions)
                apply_p = ok > 0
                new_ws = tuple(jnp.where(apply_p, nw, w)
                               for nw, w in zip(new_ws, weight_vals))
                new_leaves = tuple(
                    jnp.where(apply_p, nl, ol)
                    for nl, ol in zip(new_leaves, state_leaves))
            return new_ws, new_leaves

        donate = (0, 2) if self.donate else ()
        if mode == "external":
            def fused_ext(weight_vals, grad_vals, state_leaves, t_vec, lr,
                          rescale, ok):
                return core(weight_vals, grad_vals, state_leaves, t_vec,
                            lr, rescale, ok)
            return jax.jit(fused_ext, donate_argnums=donate)
        if mode == "inline":
            # single-group fast path: the all-finite reduction runs
            # inside the SAME program and the flag rides out as a third
            # output — no extra dispatch, no extra host sync point
            def fused_inline(weight_vals, grad_vals, state_leaves, t_vec,
                             lr, rescale):
                applier.guard_trace_count += 1   # trace-time only
                ok = all_finite(grad_vals)
                new_ws, new_leaves = core(
                    weight_vals, grad_vals, state_leaves, t_vec, lr,
                    rescale, ok)
                return new_ws, new_leaves, ok
            return jax.jit(fused_inline, donate_argnums=donate)

        def fused_off(weight_vals, grad_vals, state_leaves, t_vec, lr,
                      rescale):
            return core(weight_vals, grad_vals, state_leaves, t_vec, lr,
                        rescale, None)
        return jax.jit(fused_off, donate_argnums=donate)
