"""Benchmark: BERT pretraining throughput on the available device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The metric is tokens/sec/chip on a fused BERT pretraining step (BASELINE.md
config #3); vs_baseline is achieved MFU divided by the 0.45 north-star MFU.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np


def _backend_alive(timeout=180) -> bool:
    """Probe accelerator init in a child process — a dead TPU tunnel hangs
    inside the PJRT client, so the probe must be killable."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return False
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, timeout=timeout, text=True)
        return r.returncode == 0 and "cpu" not in r.stdout
    except subprocess.TimeoutExpired:
        return False


def _peak_flops_per_chip() -> float:
    """bf16 peak FLOP/s for the local chip generation (used for MFU)."""
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "")
    table = {
        "v4": 275e12,
        "v5e": 197e12,
        "v5p": 459e12,
        "v6e": 918e12,
    }
    for k, v in table.items():
        if gen.startswith(k):
            return v
    return 197e12  # default: v5e


def main():
    if not _backend_alive():
        # accelerator unreachable: run the CPU smoke configuration so the
        # bench always produces its JSON line
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd, parallel
    from incubator_mxnet_tpu.models import bert as bert_mod

    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    if on_tpu:
        B, T, M = int(os.environ.get("MXTPU_BENCH_BATCH", "16")), 512, 76
        dtype = "bfloat16"
        steps, warmup = 10, 3
    else:  # CPU smoke mode so the bench is runnable anywhere
        B, T, M = 4, 128, 20
        dtype = "float32"
        steps, warmup = 3, 1

    mx.random.seed(0)
    model = bert_mod.bert_base(dtype=dtype, max_length=T)
    model.initialize()
    pre = bert_mod.BERTForPretraining(model)
    pre.initialize()

    rng = np.random.RandomState(0)
    batch = (
        nd.array(rng.randint(0, 30522, (B, T)), dtype="int32"),
        nd.array(rng.randint(0, 2, (B, T)), dtype="int32"),
        nd.array(np.full((B,), T), dtype="int32"),
        nd.array(rng.randint(0, T, (B, M)), dtype="int32"),
        nd.array(rng.randint(0, 30522, (B, M)), dtype="int32"),
        nd.ones((B, M)),
        nd.array(rng.randint(0, 2, (B,)), dtype="int32"),
    )

    trainer = parallel.SPMDTrainer(
        pre, forward_loss=bert_mod.pretraining_loss, optimizer="lamb",
        optimizer_params={"learning_rate": 1e-4}, sharding="replicated")

    for _ in range(warmup):
        loss = trainer.step(*batch)
    float(loss.asnumpy())  # real fence: block_until_ready is a no-op on
    # the axon tunnel backend (verified empirically), so the fetch IS the
    # synchronization point — the reference's asnumpy contract

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = trainer.step(*batch)
    float(loss.asnumpy())
    dt = time.perf_counter() - t0

    n_chips = len(jax.devices())
    tokens_per_sec_chip = B * T * steps / dt / n_chips

    # 6 * params * tokens for fwd+bwd (transformer rule of thumb)
    n_params = sum(
        int(np.prod(p.shape)) for p in pre.collect_params().values())
    flops_per_step = 6.0 * n_params * B * T
    mfu = (flops_per_step * steps / dt) / (_peak_flops_per_chip() * n_chips)

    print(json.dumps({
        "metric": "bert_base_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_chip, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.45, 4),
    }))


if __name__ == "__main__":
    main()
