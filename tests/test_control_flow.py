"""Control-flow operator tests (reference:
tests/python/unittest/test_contrib_control_flow.py strategy)."""

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, autograd


def test_foreach_cumulative_sum():
    data = nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    init = nd.zeros((3,))

    def body(x, state):
        new = state + x
        return new, new

    outs, final = nd.contrib.foreach(body, data, init)
    want = np.cumsum(np.arange(12).reshape(4, 3), axis=0)
    np.testing.assert_allclose(outs.asnumpy(), want)
    np.testing.assert_allclose(final.asnumpy(), want[-1])


def test_foreach_multiple_states_and_grad():
    data = nd.array(np.ones((5, 2), np.float32))
    w = nd.array(np.full((2,), 2.0, np.float32))
    w.attach_grad()

    def body(x, states):
        s1, s2 = states
        new1 = s1 + x * w
        new2 = s2 * 1.0
        return new1, [new1, new2]

    with autograd.record():
        outs, (f1, f2) = nd.contrib.foreach(
            body, data, [nd.zeros((2,)), nd.ones((2,))])
        loss = f1.sum()
    loss.backward()
    np.testing.assert_allclose(f1.asnumpy(), [10.0, 10.0])
    np.testing.assert_allclose(w.grad.asnumpy(), [5.0, 5.0])


def test_while_loop_collatz_style():
    """Iterate x -> x + 2 while x < 10, max 8 iterations."""
    def cond_fn(x, i):
        return x.sum() < 10.0

    def func(x, i):
        new_x = x + 2.0
        return new_x, [new_x, i + 1]

    outs, (final_x, n) = nd.contrib.while_loop(
        cond_fn, func, [nd.zeros((1,)), nd.zeros((1,))],
        max_iterations=8)
    # 0 -> 2 -> 4 -> ... stops when sum >= 10 → final 10 after 5 steps
    np.testing.assert_allclose(final_x.asnumpy(), [10.0])
    np.testing.assert_allclose(n.asnumpy(), [5.0])
    got = outs.asnumpy()
    np.testing.assert_allclose(got[:5, 0], [2, 4, 6, 8, 10])
    np.testing.assert_allclose(got[5:], 0.0)  # zero-padded tail


def test_while_loop_hits_max_iterations():
    _, (x, ) = nd.contrib.while_loop(
        lambda x: nd.array([1.0]).sum() > 0,  # always true
        lambda x: (x, [x + 1.0]),
        [nd.zeros((1,))], max_iterations=3)
    np.testing.assert_allclose(x.asnumpy(), [3.0])


def test_cond_branches():
    a = nd.array([1.0, 2.0])
    b = nd.array([3.0, 4.0])
    hi = nd.contrib.cond(nd.array([1.0]), lambda: a + b, lambda: a - b)
    lo = nd.contrib.cond(nd.array([0.0]), lambda: a + b, lambda: a - b)
    np.testing.assert_allclose(hi.asnumpy(), [4.0, 6.0])
    np.testing.assert_allclose(lo.asnumpy(), [-2.0, -2.0])


def test_contrib_misc_ops():
    rng = np.random.RandomState(0)
    x = rng.rand(1, 2, 4, 4).astype(np.float32)
    out = nd.contrib.BilinearResize2D(nd.array(x), height=8, width=8)
    assert out.shape == (1, 2, 8, 8)
    # corners preserved under align_corners semantics
    np.testing.assert_allclose(out.asnumpy()[..., 0, 0], x[..., 0, 0],
                               rtol=1e-5)
    np.testing.assert_allclose(out.asnumpy()[..., -1, -1], x[..., -1, -1],
                               rtol=1e-5)

    d = nd.array(np.arange(6, dtype=np.float32).reshape(3, 2))
    kept = nd.contrib.boolean_mask(d, nd.array([1.0, 0.0, 1.0]))
    np.testing.assert_allclose(kept.asnumpy(), [[0, 1], [4, 5]])

    ia = nd.contrib.index_array(nd.zeros((2, 3)))
    assert ia.shape == (2, 3, 2)
    assert ia.asnumpy()[1, 2].tolist() == [1, 2]

    q = nd.contrib.quadratic(nd.array([2.0]), a=1.0, b=2.0, c=3.0)
    np.testing.assert_allclose(q.asnumpy(), [11.0])

    assert float(nd.contrib.allclose(nd.ones((2,)),
                                     nd.ones((2,))).asnumpy()) == 1.0
    al = nd.contrib.arange_like(nd.zeros((2, 3)))
    np.testing.assert_allclose(al.asnumpy(),
                               np.arange(6).reshape(2, 3))


def test_foreach_matches_under_jit_trace():
    """Outside recording, foreach lowers to lax.scan — under jit the
    traced result must equal the eager one."""
    import jax
    from incubator_mxnet_tpu.gluon.block import _hybrid_trace_scope

    data = np.arange(8, dtype=np.float32).reshape(4, 2)

    def body(x, s):
        new = s + x * 2.0
        return new, new

    eager_outs, eager_final = nd.contrib.foreach(
        body, nd.array(data), nd.zeros((2,)))

    def fn(d, s0):
        with _hybrid_trace_scope():
            outs, final = nd.contrib.foreach(
                body, nd.NDArray(d), nd.NDArray(s0))
        return outs._data, final._data

    outs_j, final_j = jax.jit(fn)(data, np.zeros(2, np.float32))
    np.testing.assert_allclose(np.asarray(outs_j),
                               eager_outs.asnumpy(), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(final_j),
                               eager_final.asnumpy(), rtol=1e-6)


def test_review_regressions():
    """Pin the review findings: tuple states under trace, cond-guarded
    while_loop with false initial condition, BilinearResize2D like mode,
    arange_like repeat with axis."""
    import jax
    from incubator_mxnet_tpu.gluon.block import _hybrid_trace_scope

    # tuple-returning body under the traced (lax.scan) path
    def body(x, s):
        s1, s2 = s
        return x + s1, (s1 + 1.0, s2)

    def fn(d):
        with _hybrid_trace_scope():
            outs, fin = nd.contrib.foreach(
                body, nd.NDArray(d),
                [nd.zeros(()), nd.ones(())])
        return outs._data
    got = jax.jit(fn)(np.arange(3, dtype=np.float32))
    np.testing.assert_allclose(np.asarray(got), [0.0, 2.0, 4.0])

    # while_loop with initially-false cond never executes func
    calls = []

    def func(i):
        calls.append(1)
        return i, [i + 1.0]

    outs, (fin,) = nd.contrib.while_loop(
        lambda i: i.sum() < 0.0, func, [nd.zeros((1,))],
        max_iterations=4)
    # structure discovery + the lax.while_loop body trace each run the
    # python body once ABSTRACTLY (no numeric compute on real data); the
    # zero-iteration results below prove no concrete execution happened
    assert len(calls) <= 2
    np.testing.assert_allclose(fin.asnumpy(), [0.0])
    np.testing.assert_allclose(outs.asnumpy(), 0.0)

    # BilinearResize2D like-mode + scale validation
    x = nd.array(np.random.RandomState(0).rand(1, 1, 4, 4)
                 .astype(np.float32))
    ref = nd.zeros((1, 1, 6, 8))
    out = nd.contrib.BilinearResize2D(x, like=ref, mode="like")
    assert out.shape == (1, 1, 6, 8)
    with pytest.raises(mx.MXNetError):
        nd.contrib.BilinearResize2D(x, scale_height=2.0)

    # arange_like repeat semantics on an axis
    al = nd.contrib.arange_like(nd.zeros((2, 4)), repeat=2, axis=1)
    np.testing.assert_allclose(al.asnumpy(), [0, 0, 1, 1])


def test_eager_paths_match_traced_edge_cases():
    """Zero-length foreach and int dtype while_loop behave identically
    under autograd.record() and on the traced path (review pins)."""
    # zero-length data under recording
    with autograd.record():
        outs, fin = nd.contrib.foreach(
            lambda x, s: (x + s, s), nd.zeros((0, 3)), nd.ones((3,)))
    assert outs.shape == (0, 3)
    np.testing.assert_allclose(fin.asnumpy(), 1.0)

    # int32 loop vars keep their dtype in both modes
    def run():
        return nd.contrib.while_loop(
            lambda i: i.sum() < 3, lambda i: (i, [i + 1]),
            [nd.array(np.zeros(1, np.int32), dtype="int32")],
            max_iterations=5)

    outs_t, _ = run()
    with autograd.record():
        outs_e, _ = run()
    assert str(outs_t.dtype) == str(outs_e.dtype) == "int32"
