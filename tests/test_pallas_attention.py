"""Pallas flash-attention kernel tests.

Reference test idiom §4.2 (cross-backend consistency): the kernel runs in
INTERPRET mode on CPU and must match the dense softmax oracle; gradients
flow through the custom-vjp rematerializing backward.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from incubator_mxnet_tpu.ops.attention import _sdpa_dense
from incubator_mxnet_tpu.ops.pallas_attention import (
    _flash_forward, flash_attention_bhtd, use_flash_attention)


@pytest.fixture(params=["streaming", "dense"])
def kernel_path(request, monkeypatch):
    """Run kernel parity tests against BOTH Pallas paths: the streaming
    FlashAttention-2 kernels (dense dispatch disabled via threshold 0)
    and the dense single-tile kernels (threshold above every test
    shape). The threshold is re-read per call in the non-jitted wrappers
    and passed as a static jit arg, so flipping the env between tests
    retraces instead of reusing the cached path."""
    monkeypatch.setenv("MXTPU_FLASH_DENSE_T",
                       "0" if request.param == "streaming" else "4096")
    return request.param


def _dense_ref(q, k, v, valid, causal):
    """(B,H,T,D) dense oracle."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    mask = np.arange(Tk)[None, :] < valid[:, None]          # (B, Tk)
    m = jnp.asarray(mask)[:, None, None, :]
    if causal:
        cm = np.tril(np.ones((Tq, Tk), bool))
        m = jnp.logical_and(m, jnp.asarray(cm)[None, None])
    out = _sdpa_dense(jnp.asarray(q.transpose(0, 2, 1, 3)),
                      jnp.asarray(k.transpose(0, 2, 1, 3)),
                      jnp.asarray(v.transpose(0, 2, 1, 3)),
                      m, D ** -0.5)
    return np.asarray(out).transpose(0, 2, 1, 3)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("Tq,Tk,vl", [(16, 16, (16, 9)),
                                      (32, 16, (16, 16)),
                                      (8, 24, (24, 5))])
def test_kernel_interpret_matches_dense(causal, Tq, Tk, vl, kernel_path):
    if causal and Tq != Tk:
        pytest.skip("causal assumes square")
    rng = np.random.RandomState(0)
    B, H, D = 2, 3, 8
    q = rng.randn(B, H, Tq, D).astype(np.float32)
    k = rng.randn(B, H, Tk, D).astype(np.float32)
    v = rng.randn(B, H, Tk, D).astype(np.float32)
    valid = np.asarray(vl, np.int32)
    got = np.asarray(_flash_forward(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(valid), causal=causal, block_q=8, block_k=8,
        interpret=True))
    ref = _dense_ref(q, k, v, valid, causal)
    # rows past valid length have all-masked scores in BOTH impls only
    # when causal+query masking applies; compare valid region per batch
    for b in range(B):
        np.testing.assert_allclose(got[b], ref[b], rtol=2e-4, atol=2e-4)


def test_kernel_blocking_invariance(monkeypatch):
    """Different block sizes must give identical results (streaming path
    only — the dense kernel has no blocks, so it is pinned off here to
    keep the comparison meaningful)."""
    monkeypatch.setenv("MXTPU_FLASH_DENSE_T", "0")
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 2, 32, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 2, 32, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 2, 32, 8).astype(np.float32))
    vl = jnp.asarray([32], jnp.int32)
    a = _flash_forward(q, k, v, vl, block_q=8, block_k=8, interpret=True)
    b = _flash_forward(q, k, v, vl, block_q=32, block_k=16, interpret=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                               atol=1e-5)


def test_gradients_match_dense(kernel_path):
    rng = np.random.RandomState(2)
    B, H, T, D = 1, 2, 16, 8
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    vl = jnp.asarray([T], jnp.int32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention_bhtd(q, k, v, vl, False, None,
                                            True) ** 2)

    def loss_dense(q, k, v):
        out = _dense_ref(np.asarray(q), np.asarray(k), np.asarray(v),
                         np.asarray(vl), False)
        return (out ** 2).sum()

    gq, gk, gv = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)

    # numeric check on a few coordinates of dq
    eps = 1e-3
    base = float(loss_dense(q, k, v))
    for idx in [(0, 0, 0, 0), (0, 1, 7, 3), (0, 0, 15, 7)]:
        qp = np.asarray(q).copy()
        qp[idx] += eps
        num = (float(loss_dense(jnp.asarray(qp), k, v)) - base) / eps
        assert abs(num - float(gq[idx])) < 0.05 * max(1.0, abs(num)), idx


def test_dispatch_fallback_on_cpu():
    """On the CPU test backend the dispatcher must take the jnp path and
    agree with the dense oracle (B,T,H,D layout)."""
    rng = np.random.RandomState(3)
    B, T, H, D = 2, 12, 2, 4
    q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    out = use_flash_attention(q, k, v, causal=True)
    ref = _dense_ref(np.asarray(q).transpose(0, 2, 1, 3),
                     np.asarray(k).transpose(0, 2, 1, 3),
                     np.asarray(v).transpose(0, 2, 1, 3),
                     np.full((B,), T, np.int32), True)
    np.testing.assert_allclose(np.asarray(out),
                               ref.transpose(0, 2, 1, 3), rtol=1e-4,
                               atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_backward_matches_dense_grads(causal, kernel_path):
    """The Pallas dq/dk/dv kernels (interpret mode) must match analytic
    gradients through the dense softmax oracle, including key-padding
    and causal masks."""
    from incubator_mxnet_tpu.ops.attention import _sdpa_dense
    rng = np.random.RandomState(4)
    B, H, T, D = 2, 2, 24, 8
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    vl = jnp.asarray([T, 13], jnp.int32)
    g = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))

    def flash_loss(q, k, v):
        out = flash_attention_bhtd(q, k, v, vl, causal, None, True)
        return jnp.sum(out * g)

    def dense_loss(q, k, v):
        mask = jnp.arange(T)[None, :] < vl[:, None]
        m = mask[:, None, None, :]
        if causal:
            m = jnp.logical_and(
                m, jnp.tril(jnp.ones((T, T), bool))[None, None])
        out = _sdpa_dense(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                          v.transpose(0, 2, 1, 3), m, D ** -0.5)
        return jnp.sum(out.transpose(0, 2, 1, 3) * g)

    gq, gk, gv = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    rq, rk, rv = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(gq), np.asarray(rq),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(rk),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(rv),
                               rtol=2e-4, atol=2e-4)


def test_pallas_backward_block_invariance(monkeypatch):
    monkeypatch.setenv("MXTPU_FLASH_DENSE_T", "0")
    from incubator_mxnet_tpu.ops.pallas_attention import (
        _flash_backward, _flash_fwd_lse)
    rng = np.random.RandomState(5)
    B, H, T, D = 1, 2, 32, 8
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    vl = jnp.asarray([T], jnp.int32)
    g = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    out, lse = _flash_fwd_lse(q, k, v, vl, interpret=True)
    a = _flash_backward(q, k, v, vl, out, lse, g, block_q=8, block_k=8,
                        interpret=True)
    b = _flash_backward(q, k, v, vl, out, lse, g, block_q=32, block_k=16,
                        interpret=True)
    for x, y in zip(a, b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-5, atol=1e-5)


def test_block_attn_lse_interpret_matches_dense(kernel_path):
    """(out, lse) primitive through the Pallas kernels in interpret mode
    (the ring-attention building block)."""
    from incubator_mxnet_tpu.ops.pallas_attention import (
        block_attn_lse, _dense_attn_lse)
    rng = np.random.RandomState(11)
    B, H, T, D = 2, 2, 16, 8
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    vl = jnp.asarray([T, 9], jnp.int32)
    for causal in (False, True):
        o_p, lse_p = block_attn_lse(q, k, v, vl, causal, None, True)
        o_d, lse_d = _dense_attn_lse(q, k, v, vl, causal, None)
        np.testing.assert_allclose(np.asarray(o_p), np.asarray(o_d),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(lse_p), np.asarray(lse_d),
                                   rtol=2e-4, atol=2e-4)
    # gradient through the custom vjp (Pallas backward kernels)
    g = jax.grad(lambda q: block_attn_lse(
        q, k, v, vl, True, None, True)[0].sum())(q)
    g_ref = jax.grad(lambda q: _dense_attn_lse(
        q, k, v, vl, True, None)[0].sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=3e-4, atol=3e-4)


def test_kernel_bf16_operands_match_f32_reference(kernel_path):
    """bf16 inputs keep bf16 DOT OPERANDS (full-rate MXU) with f32
    accumulation — outputs must track the f32 dense reference within
    bf16 tolerance, fwd and bwd."""
    rng = np.random.RandomState(7)
    B, H, T, D = 2, 2, 32, 8
    q = rng.randn(B, H, T, D).astype(np.float32)
    k = rng.randn(B, H, T, D).astype(np.float32)
    v = rng.randn(B, H, T, D).astype(np.float32)
    valid = np.array([T, T - 5], np.int32)

    got = np.asarray(_flash_forward(
        jnp.asarray(q, jnp.bfloat16), jnp.asarray(k, jnp.bfloat16),
        jnp.asarray(v, jnp.bfloat16), jnp.asarray(valid),
        causal=False, block_q=8, block_k=8,
        interpret=True)).astype(np.float32)
    ref = _dense_ref(q, k, v, valid, False)
    np.testing.assert_allclose(got, ref, rtol=0.05, atol=0.05)

    # backward: bf16 flash grads track the f32 dense grads
    from incubator_mxnet_tpu.ops.pallas_attention import flash_attention_bhtd

    def loss_flash(q_, k_, v_):
        o = flash_attention_bhtd(q_, k_, v_, jnp.asarray(valid),
                                 False, None, interpret=True)
        return (o.astype(jnp.float32) ** 2).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(
        jnp.asarray(q, jnp.bfloat16), jnp.asarray(k, jnp.bfloat16),
        jnp.asarray(v, jnp.bfloat16))
    key_mask = jnp.asarray(np.arange(T)[None, None, None, :] <
                           valid[:, None, None, None])

    def dense_f32(q_, k_, v_):
        s = jnp.einsum("bhqd,bhkd->bhqk", q_, k_) * D ** -0.5
        s = jnp.where(key_mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, v_)
        return (o ** 2).sum()

    g_f32 = jax.grad(dense_f32, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    # ALL THREE grads (dq via the dq kernel, dk/dv via the dkv kernel —
    # both kernels' dtype handling changed) against the f32 reference
    for gf, gr in zip(g_flash, g_f32):
        np.testing.assert_allclose(np.asarray(gf, np.float32),
                                   np.asarray(gr), rtol=0.1, atol=0.1)


def test_sdpa_valid_length_equals_boolean_mask():
    """sdpa(flash=True, valid_length=vl) must equal the (B,Tk) boolean
    mask form — valid_length is the form that engages the TPU Pallas
    kernel (a boolean mask alone falls back to the jnp path), so the
    two spellings must be interchangeable."""
    from incubator_mxnet_tpu import nd

    rng = np.random.RandomState(0)
    B, T, H, D = 2, 24, 2, 8
    q = nd.array(rng.randn(B, T, H, D).astype(np.float32))
    k = nd.array(rng.randn(B, T, H, D).astype(np.float32))
    v = nd.array(rng.randn(B, T, H, D).astype(np.float32))
    vl = np.array([T, 13], np.int32)
    mask = nd.array((np.arange(T)[None, :] < vl[:, None])
                    .astype(np.float32))
    out_mask = nd.scaled_dot_product_attention(q, k, v, mask=mask,
                                               flash=True)
    out_vl = nd.scaled_dot_product_attention(
        q, k, v, flash=True, valid_length=nd.array(vl, dtype="int32"))
    # rows beyond a batch's valid length attend nothing in the vl form;
    # compare the valid region
    a, b = out_mask.asnumpy(), out_vl.asnumpy()
    np.testing.assert_allclose(a[0], b[0], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(a[1, :13], b[1, :13], rtol=1e-5, atol=1e-5)


def test_sdpa_dense_path_honors_valid_length():
    """The non-flash dense path must mask padding keys when only
    valid_length (no boolean mask) is given."""
    from incubator_mxnet_tpu import nd

    rng = np.random.RandomState(1)
    B, T, H, D = 2, 10, 1, 4
    q = rng.randn(B, T, H, D).astype(np.float32)
    k = rng.randn(B, T, H, D).astype(np.float32)
    v = rng.randn(B, T, H, D).astype(np.float32)
    vl = np.array([T, 6], np.int32)
    mask = nd.array((np.arange(T)[None, :] < vl[:, None])
                    .astype(np.float32))
    out_vl = nd.scaled_dot_product_attention(
        nd.array(q), nd.array(k), nd.array(v),
        valid_length=nd.array(vl, dtype="int32"))           # flash=False
    out_mask = nd.scaled_dot_product_attention(
        nd.array(q), nd.array(k), nd.array(v), mask=mask)
    np.testing.assert_allclose(out_vl.asnumpy(), out_mask.asnumpy(),
                               rtol=1e-5, atol=1e-5)


def test_fully_masked_rows_zero_output_and_safe_grads(kernel_path):
    """ADVICE r4: a fully-masked query row (vl==0, or q rows past the
    valid prefix) must produce ZERO output — not the uniform mean of V —
    with lse pinned to a finite -inf surrogate, and zero (not NaN)
    gradients. Checked on both kernel families and the jnp fallback."""
    rng = np.random.RandomState(0)
    B, H, T, D = 2, 2, 16, 8
    q = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    vl = jnp.asarray([0, 5], jnp.int32)        # row 0 fully masked

    def loss(q, k, v):
        return flash_attention_bhtd(q, k, v, vl, False, None, True).sum()

    out = flash_attention_bhtd(q, k, v, vl, False, None, True)
    out_np = np.asarray(out)
    # batch 0: every row fully masked -> all zeros
    np.testing.assert_array_equal(out_np[0], 0.0)
    # batch 1: rows attend the 5-key prefix regardless of q position
    # (prefix mask, non-causal) -> finite and nonzero
    assert np.isfinite(out_np[1]).all() and np.abs(out_np[1]).sum() > 0

    dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g in (dq, dk, dv):
        assert np.isfinite(np.asarray(g)).all()
    np.testing.assert_array_equal(np.asarray(dq)[0], 0.0)
    # masked-out keys (beyond the prefix) contribute nothing
    np.testing.assert_array_equal(np.asarray(dk)[1, :, 5:], 0.0)

    # jnp fallback path agrees (dispatcher with a boolean mask routes
    # to _sdpa_blockwise)
    from incubator_mxnet_tpu.ops.attention import _sdpa_blockwise
    km = np.arange(T)[None, :] < np.asarray([0, 5])[:, None]
    fb = _sdpa_blockwise(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                         v.transpose(0, 2, 1, 3), jnp.asarray(km), False,
                         D ** -0.5)
    np.testing.assert_array_equal(np.asarray(fb)[0], 0.0)
    np.testing.assert_allclose(np.asarray(fb).transpose(0, 2, 1, 3),
                               out_np, rtol=2e-5, atol=2e-5)
