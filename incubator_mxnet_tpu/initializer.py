"""Weight initializers.

Re-design of `python/mxnet/initializer.py` (file-level citation — SURVEY.md
caveat). Initializers are registered by alias so string specs like
``init='xavier'`` work, and draw from the global counter-based RNG stream
(SURVEY.md §7.2 RNG parity).
"""

from __future__ import annotations

import math

import jax
import re as _re
import jax.numpy as jnp

from . import random as _random
from .base import MXNetError, Registry
from .ndarray.ndarray import NDArray, _to_jnp_dtype

__all__ = ["Initializer", "Uniform", "Normal", "Zero", "One", "Constant",
           "Xavier", "MSRAPrelu", "Orthogonal", "Bilinear", "LSTMBias",
           "Mixed", "InitDesc", "Load", "register", "create"]

_REGISTRY = Registry("initializer")
register = _REGISTRY.register


class Initializer:
    """Base initializer: call pattern ``init(name, arr)`` mirrors the
    reference (name-based dispatch for bias/gamma/beta conventions)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, name, arr: NDArray):
        if not isinstance(name, str):
            name, arr = arr, name  # tolerate swapped order
        # InitDesc: a per-parameter attrs["__init__"] overrides this
        # initializer (reference: initializer.py InitDesc dispatch)
        override = getattr(name, "attrs", {}).get("__init__")
        if override:
            create(override)(str(name), arr)
            return
        self.init_weight(name, arr)

    def init_weight(self, name: str, arr: NDArray):
        name = name.lower()
        if name.endswith("bias") or name.endswith("beta") or "moving_mean" in name \
                or "running_mean" in name:
            arr._data = jnp.zeros(arr.shape, arr.dtype)
        elif name.endswith("gamma") or "moving_var" in name or "running_var" in name:
            arr._data = jnp.ones(arr.shape, arr.dtype)
        else:
            self._init_weight(name, arr)

    def _init_weight(self, name: str, arr: NDArray):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"


@register("uniform")
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        arr._data = jax.random.uniform(_random.new_key(), arr.shape,
                                       minval=-self.scale, maxval=self.scale,
                                       dtype=jnp.float32).astype(arr.dtype)


@register("normal")
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        arr._data = (self.sigma * jax.random.normal(
            _random.new_key(), arr.shape, dtype=jnp.float32)).astype(arr.dtype)


@register("zeros", aliases=("zero",))
class Zero(Initializer):
    def _init_weight(self, name, arr):
        arr._data = jnp.zeros(arr.shape, arr.dtype)


@register("ones", aliases=("one",))
class One(Initializer):
    def _init_weight(self, name, arr):
        arr._data = jnp.ones(arr.shape, arr.dtype)


@register("constant")
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        arr._data = jnp.full(arr.shape, self.value, arr.dtype)


def _fan(shape, factor_type):
    hw = 1
    for d in shape[2:]:
        hw *= d
    fan_in = (shape[1] if len(shape) > 1 else shape[0]) * hw
    fan_out = shape[0] * hw
    if factor_type == "avg":
        return (fan_in + fan_out) / 2.0
    if factor_type == "in":
        return float(fan_in)
    if factor_type == "out":
        return float(fan_out)
    raise MXNetError(f"unknown factor_type {factor_type}")


@register("xavier")
class Xavier(Initializer):
    """Glorot initialization (reference: initializer.py Xavier)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        factor = _fan(arr.shape, self.factor_type)
        scale = math.sqrt(self.magnitude / max(factor, 1.0))
        if self.rnd_type == "uniform":
            arr._data = jax.random.uniform(
                _random.new_key(), arr.shape, minval=-scale, maxval=scale,
                dtype=jnp.float32).astype(arr.dtype)
        elif self.rnd_type == "gaussian":
            arr._data = (scale * jax.random.normal(
                _random.new_key(), arr.shape, dtype=jnp.float32)).astype(arr.dtype)
        else:
            raise MXNetError(f"unknown rnd_type {self.rnd_type}")


@register("msraprelu")
class MSRAPrelu(Xavier):
    """He initialization (reference: initializer.py MSRAPrelu)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register("orthogonal")
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        nout = arr.shape[0]
        nin = 1
        for d in arr.shape[1:]:
            nin *= d
        if self.rand_type == "uniform":
            tmp = jax.random.uniform(_random.new_key(), (nout, nin),
                                     minval=-1.0, maxval=1.0)
        else:
            tmp = jax.random.normal(_random.new_key(), (nout, nin))
        u, _, v = jnp.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        arr._data = (self.scale * q.reshape(arr.shape)).astype(arr.dtype)


@register("bilinear")
class Bilinear(Initializer):
    """Bilinear upsampling kernels for deconvolution
    (reference: initializer.py Bilinear)."""

    def _init_weight(self, name, arr):
        import numpy as np
        weight = np.zeros(arr.shape, dtype=np.float32)
        f = math.ceil(arr.shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        flat = weight.reshape(-1)
        for i in range(flat.size):
            x = i % arr.shape[3]
            y = (i // arr.shape[3]) % arr.shape[2]
            flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr._data = jnp.asarray(weight.reshape(arr.shape)).astype(arr.dtype)


@register("lstmbias")
class LSTMBias(Initializer):
    """Forget-gate bias init (reference: initializer.py LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = jnp.zeros(arr.shape, arr.dtype)
        n = arr.shape[0] // 4
        b = b.at[n:2 * n].set(self.forget_bias)
        arr._data = b


def create(init) -> Initializer:
    if isinstance(init, Initializer):
        return init
    if init is None:
        return Uniform()
    if isinstance(init, str):
        cls = _REGISTRY.get(init)
        return cls()
    raise MXNetError(f"cannot create initializer from {init!r}")


@register("truncnorm")
class TruncNorm(Initializer):
    """Truncated normal within 2 stdev (reference: initializer.py used by
    BERT; GluonNLP TruncNorm)."""

    def __init__(self, mean=0.0, stdev=0.01, **kwargs):
        super().__init__(**kwargs)
        self.mean = mean
        self.stdev = stdev

    def _init_weight(self, name, arr):
        import jax
        from . import random as _random
        key = _random.new_key()
        arr._data = (self.mean + self.stdev * jax.random.truncated_normal(
            key, -2.0, 2.0, arr.shape)).astype(arr.dtype)


class InitDesc(str):
    """Parameter-description string with attrs (parity: InitDesc) —
    carries the attribute dict and global_init alongside the name."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Mixed(Initializer):
    """Per-name-pattern initializer dispatch (parity: Mixed): patterns
    are regexes tried in order; the first match initializes."""

    def __init__(self, patterns, initializers):
        super().__init__(patterns=patterns, initializers=initializers)
        if len(patterns) != len(initializers):
            raise MXNetError("Mixed: len(patterns) != len(initializers)")
        self._map = [(_re.compile(p), init)
                     for p, init in zip(patterns, initializers)]

    def init_weight(self, name, arr):
        for prog, init in self._map:
            if prog.match(name):
                init(name, arr)
                return
        raise MXNetError(
            f"Mixed: parameter {name!r} did not match any pattern — "
            f"add a '.*' catch-all as the last pattern")


@register("load")
class Load:
    """Initialize from a dict of saved arrays (parity: Load): exact
    name match first, then with arg:/aux: prefixes stripped;
    ``default_init`` covers the rest."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            from .ndarray import load as nd_load
            param = nd_load(param)
        self.param = {}
        for k, v in param.items():
            self.param[k.split(":", 1)[1] if k.startswith(("arg:", "aux:"))
                       else k] = v
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if not isinstance(name, str):
            name, arr = arr, name
        if name in self.param:
            src = self.param[name]
            if tuple(src.shape) != tuple(arr.shape):
                raise MXNetError(
                    f"Load: shape mismatch for {name}: saved "
                    f"{tuple(src.shape)} vs expected {tuple(arr.shape)}")
            arr._data = src._data.astype(arr.dtype)
            if self.verbose:
                print(f"Initialized {name} from the loaded arrays")
        elif self.default_init is not None:
            self.default_init(name, arr)
        else:
            raise MXNetError(
                f"Load: no saved value for {name!r} and no default_init")
