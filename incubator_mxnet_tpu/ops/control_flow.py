"""Control-flow operators (parity: python/mxnet/ndarray/contrib.py
``foreach`` / ``while_loop`` / ``cond`` backed by
src/operator/control_flow.cc — file-level citations, SURVEY.md caveat).

The reference builds explicit subgraphs and runs them through the
executor; here each construct IS the corresponding XLA structured-
control-flow primitive (``lax.scan`` / ``lax.while_loop`` /
``lax.cond``), so the user-facing Python-callable API is identical but
the loop compiles into one fused program — including under hybridize /
SPMDTrainer tracing, where the body is traced exactly once.

Contracts (matching the reference):
  - ``foreach(body, data, init_states)``: body(data_slice, states) ->
    (step_output, new_states); iterates over axis 0; returns
    (stacked outputs, final states). data/states may be NDArrays or
    lists of NDArrays.
  - ``while_loop(cond, func, loop_vars, max_iterations)``:
    cond(*loop_vars) -> boolean scalar; func(*loop_vars) ->
    (step_output, new_loop_vars). Runs at most ``max_iterations``;
    outputs are stacked into a fixed (max_iterations, ...) buffer
    (rows beyond the actual trip count are zeros — the reference's
    fixed-shape contract) and returned with the final loop_vars.
  - ``cond(pred, then_func, else_func)``: funcs take no args (close
    over NDArrays); both branches trace and must return matching
    structures.
"""

from __future__ import annotations

from typing import Callable, List, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from ..ndarray import NDArray

__all__ = ["foreach", "while_loop", "cond"]


def _unwrap(x):
    if isinstance(x, (list, tuple)):
        return [_unwrap(v) for v in x]
    return x._data if isinstance(x, NDArray) else jnp.asarray(x)


def _tree_unwrap(x):
    """Unwrap NDArrays AND normalize tuples to lists so user-returned
    structures always match the scan/while carry pytree (tuple vs list
    is a structure mismatch to jax)."""
    if isinstance(x, (list, tuple)):
        return [_tree_unwrap(v) for v in x]
    return x._data if isinstance(x, NDArray) else x


def _discover_outputs(func, lv):
    """Abstract-evaluate one func step (no compute, no tape) to learn
    the step-output structure."""
    lv_j = [_unwrap(v) for v in lv]

    def probe(vals):
        out, _ = func(*[NDArray(v) for v in vals])
        outs = [out] if not isinstance(out, (list, tuple)) else list(out)
        return [_tree_unwrap(o) for o in outs]

    return jax.eval_shape(probe, lv_j)


def _wrap(x):
    if isinstance(x, (list, tuple)):
        return [_wrap(v) for v in x]
    return NDArray(x)


def _recording() -> bool:
    from .. import autograd
    return autograd.is_recording()


def foreach(body: Callable, data, init_states):
    """Scan ``body`` over axis 0 of ``data`` (reference: contrib.foreach).

    Under ``autograd.record()`` the loop runs eagerly per-iteration so
    every op lands on the tape — gradients flow to loop-carried state
    AND closure-captured parameters, exactly like the reference's
    imperative foreach. Outside recording (inference, or inside a
    hybridize/SPMDTrainer trace) it lowers to ONE ``lax.scan``."""
    multi = isinstance(data, (list, tuple))
    n = (data[0] if multi else data).shape[0]
    if _recording() and n > 0:  # n == 0: the scan path handles it
        states = init_states
        outs = []
        for i in range(n):
            sl = [d[i] for d in data] if multi else data[i]
            out, states = body(sl, states)
            outs.append(out)
        if isinstance(outs[0], (list, tuple)):
            from ..ndarray import stack as nd_stack
            stacked = [nd_stack(*[o[k] for o in outs], axis=0)
                       for k in range(len(outs[0]))]
        else:
            from ..ndarray import stack as nd_stack
            stacked = nd_stack(*outs, axis=0)
        return stacked, states

    xs = _unwrap(data)
    init = _unwrap(init_states)

    def scan_body(carry, x):
        out, new_states = body(_wrap(x), _wrap(carry))
        return _tree_unwrap(new_states), _tree_unwrap(out)

    final, outs = lax.scan(scan_body, init, xs)
    return _wrap(outs), _wrap(final)


def while_loop(cond_fn: Callable, func: Callable, loop_vars,
               max_iterations: int):
    """Bounded while loop (reference: contrib.while_loop). Returns
    (outputs (max_iterations, ...) zero-padded, final loop_vars)."""
    if max_iterations is None or int(max_iterations) <= 0:
        raise MXNetError("while_loop needs a positive max_iterations")
    M = int(max_iterations)
    single = not isinstance(loop_vars, (list, tuple))
    lv = [loop_vars] if single else list(loop_vars)

    if _recording():
        # eager tape path (see foreach): host-evaluated condition,
        # per-iteration ops recorded; outputs zero-padded to M rows
        import numpy as _host_np
        from ..ndarray import stack as nd_stack, zeros as nd_zeros
        outs = []
        vars_ = list(lv)
        while len(outs) < M:
            c = cond_fn(*vars_)
            if not bool(_host_np.asarray(
                    c.asnumpy() if isinstance(c, NDArray) else c).all()):
                break
            out, new_vars = func(*vars_)
            vars_ = [new_vars] if not isinstance(new_vars, (list, tuple)) \
                else list(new_vars)
            outs.append([out] if not isinstance(out, (list, tuple))
                        else list(out))
        if not outs:
            shapes = _discover_outputs(func, lv)  # abstract, no compute
            bufs = [nd_zeros((M,) + tuple(s.shape), dtype=str(s.dtype))
                    for s in shapes]
        else:
            k = len(outs[0])
            bufs = []
            for j in range(k):
                rows = [o[j] for o in outs]
                pad = [nd_zeros(tuple(rows[0].shape),
                                dtype=str(rows[0].dtype))
                       for _ in range(M - len(rows))]
                bufs.append(nd_stack(*(rows + pad), axis=0))
        out_single0 = len(bufs) == 1
        return (bufs[0] if out_single0 else bufs), \
            (vars_[0] if single else vars_)

    lv_j = [_unwrap(v) for v in lv]

    # abstract-evaluate one step to discover the output structure (the
    # reference likewise traces func once; eval_shape runs NO compute,
    # so a cond-guarded func is never executed on invalid inputs)
    shapes = _discover_outputs(func, lv)
    out_single = len(shapes) == 1
    bufs0 = [jnp.zeros((M,) + tuple(s.shape), s.dtype) for s in shapes]

    def _cond(carry):
        i, vars_, bufs = carry
        c = cond_fn(*[NDArray(v) for v in vars_])
        c = c._data if isinstance(c, NDArray) else jnp.asarray(c)
        return jnp.logical_and(i < M, c.reshape(()).astype(bool))

    def _body(carry):
        i, vars_, bufs = carry
        out, new_vars = func(*[NDArray(v) for v in vars_])
        outs = [out] if not isinstance(out, (list, tuple)) else list(out)
        new_vars = [new_vars] if not isinstance(new_vars, (list, tuple)) \
            else list(new_vars)
        bufs = [lax.dynamic_update_slice(
            b, _unwrap(o)[None].astype(b.dtype),
            (i,) + (0,) * (b.ndim - 1)) for b, o in zip(bufs, outs)]
        return i + 1, [_unwrap(v) for v in new_vars], bufs

    n, final_vars, bufs = lax.while_loop(
        _cond, _body, (jnp.asarray(0, jnp.int32), lv_j, bufs0))
    outs = [NDArray(b) for b in bufs]
    finals = [NDArray(v) for v in final_vars]
    return (outs[0] if out_single else outs), \
        (finals[0] if single else finals)


def cond(pred, then_func: Callable, else_func: Callable):
    """Conditional execution (reference: contrib.cond)."""
    p = pred._data if isinstance(pred, NDArray) else jnp.asarray(pred)
    p = p.reshape(()).astype(bool)
    if _recording():
        # eager tape path: pick the branch on the host so its ops record
        import numpy as _host_np
        return then_func() if bool(_host_np.asarray(p)) else else_func()

    out = lax.cond(p, lambda _: _tree_unwrap(then_func()),
                   lambda _: _tree_unwrap(else_func()), operand=None)
    return _wrap(out)
