"""Detection image pipeline (parity: python/mxnet/image/detection.py —
``DetAugmenter``s, ``CreateDetAugmenter``, ``ImageDetIter``; file-level
citation, SURVEY.md caveat).

Labels ride with the images as (num_obj, 5) float arrays
``[class_id, x1, y1, x2, y2]`` with coordinates NORMALIZED to [0, 1]
(the reference's det-label convention). Augmenters transform image AND
boxes together; the iterator pads every batch's object dim to a fixed
``max_objects`` with -1 rows (the reference pads with the header's
label_width) so batches are shape-static for jit consumers (SSD's
MultiBoxTarget masks the -1 rows out)."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray
from ..ndarray.ndarray import _as_jax
from ..io import DataBatch, DataIter, DataDesc
from . import Augmenter, imresize, resize_short


def _np_img(src):
    return np.asarray(src.asnumpy() if isinstance(src, NDArray) else src)


class DetAugmenter(Augmenter):
    """Base: __call__(img, label) -> (img, label)."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetForceResizeAug(DetAugmenter):
    """Resize to exactly (w, h); normalized boxes are unchanged."""

    def __init__(self, size, interp=1):
        super().__init__(size=size, interp=interp)
        self.size = tuple(size)
        self.interp = interp

    def __call__(self, src, label):
        return imresize(src, self.size[0], self.size[1],
                        self.interp), label


class DetHorizontalFlipAug(DetAugmenter):
    """Flip image and mirror box x-coordinates with probability p."""

    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        from .. import random as _random
        if _random.np_rng().rand() < self.p:
            img = _np_img(src)[:, ::-1].copy()
            lab = np.array(label, np.float32, copy=True)
            valid = lab[:, 0] >= 0
            x1 = lab[valid, 1].copy()
            lab[valid, 1] = 1.0 - lab[valid, 3]
            lab[valid, 3] = 1.0 - x1
            return NDArray(_as_jax(img)), lab
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Random crop keeping enough object coverage (simplified reference
    semantics: sample a sub-window, keep boxes whose center survives,
    clip them to the window; retry up to max_attempts, else identity)."""

    def __init__(self, min_object_covered=0.3, area_range=(0.5, 1.0),
                 max_attempts=10):
        super().__init__(min_object_covered=min_object_covered,
                         area_range=area_range, max_attempts=max_attempts)
        self.min_cov = float(min_object_covered)
        self.area_range = tuple(area_range)
        self.max_attempts = int(max_attempts)

    def __call__(self, src, label):
        from .. import random as _random
        rng = _random.np_rng()
        img = _np_img(src)
        H, W = img.shape[:2]
        lab = np.array(label, np.float32, copy=True)
        valid = lab[:, 0] >= 0
        for _ in range(self.max_attempts):
            area = rng.uniform(*self.area_range)
            side = np.sqrt(area)
            cw, ch = max(int(W * side), 1), max(int(H * side), 1)
            x0 = rng.randint(0, W - cw + 1)
            y0 = rng.randint(0, H - ch + 1)
            wx0, wy0 = x0 / W, y0 / H
            wx1, wy1 = (x0 + cw) / W, (y0 + ch) / H
            cx = (lab[:, 1] + lab[:, 3]) / 2
            cy = (lab[:, 2] + lab[:, 4]) / 2
            keep = valid & (cx >= wx0) & (cx <= wx1) & \
                (cy >= wy0) & (cy <= wy1)
            if valid.any() and keep.sum() < max(
                    1, int(np.ceil(self.min_cov * valid.sum()))):
                continue
            out = np.full_like(lab, -1.0)
            k = 0
            sw, sh = wx1 - wx0, wy1 - wy0
            for row in lab[keep]:
                nx1 = (max(row[1], wx0) - wx0) / sw
                ny1 = (max(row[2], wy0) - wy0) / sh
                nx2 = (min(row[3], wx1) - wx0) / sw
                ny2 = (min(row[4], wy1) - wy0) / sh
                out[k] = [row[0], nx1, ny1, nx2, ny2]
                k += 1
            return NDArray(_as_jax(img[y0:y0 + ch,
                                       x0:x0 + cw].copy())), out
        return src, lab


class DetRandomSelectAug(DetAugmenter):
    """Apply the wrapped augmenter with probability p (parity:
    DetRandomSelectAug's select-or-skip behavior)."""

    def __init__(self, aug: DetAugmenter, p: float):
        super().__init__(p=p)
        self.aug, self.p = aug, float(p)

    def __call__(self, src, label):
        from .. import random as _random
        if _random.np_rng().rand() < self.p:
            return self.aug(src, label)
        return src, label


class DetResizeShortAug(DetAugmenter):
    """Resize the shorter image side to ``size``; normalized boxes are
    unchanged."""

    def __init__(self, size, interp=1):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = int(size), interp

    def __call__(self, src, label):
        return resize_short(src, self.size, self.interp), label


class DetNormalizeAug(DetAugmenter):
    """Subtract mean / divide std on the image (HWC float)."""

    def __init__(self, mean, std=None):
        super().__init__()
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32) if std is not None else None

    def __call__(self, src, label):
        arr = _np_img(src).astype(np.float32) - self.mean
        if self.std is not None:
            arr = arr / self.std
        return NDArray(_as_jax(arr)), label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0.0,
                       rand_mirror=False, mean=None, std=None,
                       min_object_covered=0.3,
                       area_range=(0.5, 1.0)) -> List[DetAugmenter]:
    """Build the standard detection augmenter list (parity:
    mx.image.CreateDetAugmenter). ``rand_crop`` is the APPLICATION
    PROBABILITY of the random crop (reference DetRandomSelectAug
    semantics); ``mean``/``std`` append a normalization stage; color
    jitter composes via the classifier augmenters on the image alone."""
    augs: List[DetAugmenter] = []
    if resize > 0:
        augs.append(DetResizeShortAug(resize))
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered=min_object_covered,
                                area_range=area_range)
        augs.append(crop if rand_crop >= 1.0
                    else DetRandomSelectAug(crop, rand_crop))
    if rand_mirror:
        augs.append(DetHorizontalFlipAug(0.5))
    augs.append(DetForceResizeAug((data_shape[2], data_shape[1])))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53], np.float32)
    if std is True:
        std = np.array([58.395, 57.12, 57.375], np.float32)
    if mean is not None:
        augs.append(DetNormalizeAug(mean, std))
    return augs


class ImageDetIter(DataIter):
    """Detection data iterator (parity: mx.image.ImageDetIter).

    Sources: ``path_imgrec`` (RecordIO written by tools/im2rec.py with
    det labels in the header) or in-memory ``(imgs, labels)`` lists.
    Emits DataBatch(data (B, C, H, W), label (B, max_objects, 5))."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 imgs: Optional[Sequence] = None,
                 labels: Optional[Sequence] = None, shuffle=False,
                 max_objects=None, mean=None, std=None,
                 aug_list: Optional[List[DetAugmenter]] = None, **kwargs):
        super().__init__(batch_size)
        self._shape = tuple(data_shape)
        if path_imgrec is not None:
            from ..io import MXRecordIO
            from ..io.recordio import unpack_img
            rec = MXRecordIO(path_imgrec, "r")
            imgs, labels = [], []
            while True:
                payload = rec.read()
                if payload is None:
                    break
                header, img = unpack_img(payload)
                flat = np.asarray(header.label, np.float32).ravel()
                # reference det header: [header_width, obj_width, ...objs]
                hw, ow = int(flat[0]), int(flat[1])
                objs = flat[hw:].reshape(-1, ow)[:, :5]
                imgs.append(img)
                labels.append(objs)
        if imgs is None or labels is None:
            raise MXNetError("ImageDetIter needs path_imgrec or "
                             "imgs+labels")
        if len(imgs) != len(labels):
            raise MXNetError("imgs and labels length mismatch")
        self._imgs = list(imgs)
        self._labels = [np.asarray(l, np.float32).reshape(-1, 5)
                        for l in labels]
        self._max_obj = max_objects or max(
            (l.shape[0] for l in self._labels), default=1)
        self._shuffle = shuffle
        self._mean = np.asarray(mean, np.float32) if mean is not None \
            else None
        self._std = np.asarray(std, np.float32) if std is not None else None
        self._augs = aug_list if aug_list is not None else \
            CreateDetAugmenter(self._shape, **kwargs)
        self._order = np.arange(len(self._imgs))
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self._shape)]

    @property
    def provide_label(self):
        return [DataDesc("label",
                         (self.batch_size, self._max_obj, 5))]

    def reset(self):
        if self._shuffle:
            from .. import random as _random
            _random.np_rng().shuffle(self._order)
        self._cursor = 0

    def iter_next(self):
        return self._cursor < len(self._order)

    def _prep(self, i):
        img = self._imgs[i]
        lab = np.array(self._labels[i], np.float32, copy=True)
        pad = np.full((self._max_obj, 5), -1.0, np.float32)
        pad[:min(len(lab), self._max_obj)] = lab[:self._max_obj]
        lab = pad
        for aug in self._augs:
            img, lab = aug(img, lab)
        arr = _np_img(img).astype(np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self._mean is not None:
            arr = arr - self._mean
        if self._std is not None:
            arr = arr / self._std
        return arr.transpose(2, 0, 1), lab

    def next(self):
        if not self.iter_next():
            raise StopIteration
        end = self._cursor + self.batch_size
        ids = self._order[self._cursor:end].tolist()
        pad = 0
        if len(ids) < self.batch_size:
            pad = self.batch_size - len(ids)
            fill = np.resize(self._order, pad).tolist()  # wraps if tiny
            ids = ids + fill
        self._cursor = end
        import jax.numpy as jnp
        data, labs = zip(*(self._prep(i) for i in ids))
        return DataBatch([NDArray(jnp.asarray(np.stack(data)))],
                         [NDArray(jnp.asarray(np.stack(labs)))], pad=pad)
