"""Graph passes over the Symbol DAG.

Parity surface for NNVM's pass machinery (`nnvm::ApplyPass`,
`src/nnvm/graph_editor.cc` and the reference's custom-pass plugin API
`MXOptimizeForBackend` / `SubgraphProperty` — file-level citations,
SURVEY.md caveat §2.1 "NNVM IR + passes" row).

The reference runs C++ passes (Gradient, PlanMemory, PlaceDevice) over
the node DAG; here those jobs belong to XLA, but the USER-facing pass
surface — inspect, edit, and rewrite graphs programmatically — is kept:

  - ``register_pass`` / ``apply_pass``: named graph → graph transforms.
  - ``rewrite(sym, fn)``: node-level rewriter; ``fn(node_view)`` returns
    None (keep) or a replacement op application — the building block
    custom passes are written with.
  - built-ins: ``eliminate_identity``, ``fold_transpose_pairs``,
    ``count_ops`` (analysis), ``replace_op``.

Passes are pure: they rebuild fresh ``_Node`` DAGs and never mutate the
input symbol (functional graphs, the jax idiom — unlike the reference's
in-place graph editor).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError
from .symbol import Symbol, _Node, _topo

__all__ = ["register_pass", "apply_pass", "list_passes", "rewrite",
           "eliminate_identity", "fold_transpose_pairs", "count_ops",
           "replace_op", "NodeView"]

_PASSES: Dict[str, Callable] = {}


def register_pass(name: str):
    """Register a named graph pass: ``fn(sym, **kwargs) -> Symbol``."""
    def deco(fn):
        if name in _PASSES:
            raise MXNetError(f"pass {name!r} already registered")
        _PASSES[name] = fn
        return fn
    return deco


def apply_pass(sym: Symbol, name: str, **kwargs) -> Symbol:
    """Apply a registered pass by name (parity: nnvm.ApplyPass)."""
    if name not in _PASSES:
        raise MXNetError(
            f"unknown pass {name!r}; registered: {sorted(_PASSES)}")
    return _PASSES[name](sym, **kwargs)


def list_passes() -> List[str]:
    return sorted(_PASSES)


class NodeView:
    """Read-only view of one node handed to rewriter callbacks."""

    __slots__ = ("op", "name", "attrs", "inputs")

    def __init__(self, node: _Node, inputs):
        self.op = node.op
        self.name = node.name
        self.attrs = dict(node.attrs)
        self.inputs = inputs        # list of (NodeView | None for vars)


def rewrite(sym: Symbol, fn: Callable[["_Node", List[Tuple[_Node, int]]],
                                      Optional[Tuple]]) -> Symbol:
    """Rebuild the DAG bottom-up, letting ``fn`` replace nodes.

    ``fn(node, new_inputs)`` receives the ORIGINAL node and its already-
    rewritten inputs ``[(node, out_idx), ...]``; it returns None to keep
    the node as-is, or ``(op, name, attrs, inputs)`` to substitute, or a
    single ``(node, out_idx)`` tuple to splice an existing output in
    place of this node (e.g. identity elimination)."""
    mapping: Dict[int, _Node] = {}
    redirect: Dict[int, Tuple[_Node, int]] = {}
    multi_out: Dict[int, bool] = {}

    def lookup(src: _Node, idx: int) -> Tuple[_Node, int]:
        if id(src) in redirect:
            if multi_out.get(id(src)):
                raise MXNetError(
                    f"rewrite: cannot splice multi-output node "
                    f"{src.name!r} to a single output — consumers "
                    f"reference distinct output slots")
            return redirect[id(src)]
        return mapping[id(src)], idx

    for node in _topo(sym._heads):
        new_inputs = [lookup(src, idx) for src, idx in node.inputs]
        out = fn(node, new_inputs)
        if out is None:
            mapping[id(node)] = _Node(node.op, node.name, new_inputs,
                                      node.attrs, node.annotations)
        elif isinstance(out, tuple) and len(out) == 2 \
                and isinstance(out[0], _Node):
            redirect[id(node)] = out
            multi_out[id(node)] = node.num_outputs() > 1
        elif isinstance(out, tuple) and len(out) == 4:
            op, name, attrs, inputs = out
            mapping[id(node)] = _Node(op, name, list(inputs), attrs,
                                      node.annotations)
        else:
            raise MXNetError(
                "rewriter must return None, (node, idx), or "
                "(op, name, attrs, inputs)")
    heads = [lookup(n, i) for n, i in sym._heads]
    return Symbol(heads)


# --------------------------------------------------------------------- #
# built-in passes
# --------------------------------------------------------------------- #

_IDENTITY_OPS = ("identity", "_copy")


@register_pass("EliminateIdentity")
def eliminate_identity(sym: Symbol, ops: Sequence[str] = _IDENTITY_OPS
                       ) -> Symbol:
    """Splice out identity-like single-input ops (reference:
    graph_editor / CSE-style cleanups). BlockGrad/stop_gradient are NOT
    in the default set: they are identity only in the forward pass, and
    removing them changes gradient semantics — pass them via ``ops``
    explicitly for inference-only graphs."""
    ops = set(ops)

    def fn(node, new_inputs):
        if node.op in ops and len(new_inputs) == 1:
            return new_inputs[0]
        return None

    return rewrite(sym, fn)


@register_pass("FoldTransposePairs")
def fold_transpose_pairs(sym: Symbol) -> Symbol:
    """Cancel transpose(transpose(x, p), q) when q∘p is the identity."""
    def fn(node, new_inputs):
        if node.op != "transpose" or len(new_inputs) != 1:
            return None
        src, idx = new_inputs[0]
        if src.op != "transpose":
            return None
        p = src.attrs.get("axes")
        q = node.attrs.get("axes")
        if p is None and q is None:
            # both default = full reversal: reversal∘reversal = identity
            return src.inputs[0]
        if p is None or q is None:
            # one explicit, one default reversal: the composite depends
            # on the (unknown at graph level) rank — keep the pair
            return None
        perm = [p[qi] for qi in q]
        if perm == list(range(len(perm))):
            return src.inputs[0]
        return None

    return rewrite(sym, fn)


@register_pass("CountOps")
def count_ops(sym: Symbol) -> Dict[str, int]:
    """Analysis pass: op histogram (reference: graph attr passes)."""
    counts: Dict[str, int] = {}
    for node in _topo(sym._heads):
        counts[node.op] = counts.get(node.op, 0) + 1
    return counts


@register_pass("ReplaceOp")
def replace_op(sym: Symbol, from_op: str = "", to_op: str = "",
               attr_map: Optional[Callable[[dict], dict]] = None
               ) -> Symbol:
    """Substitute every ``from_op`` node with ``to_op`` (the minimal
    custom-backend rewrite, e.g. swapping an op for a quantized twin)."""
    if not from_op or not to_op:
        raise MXNetError("ReplaceOp needs from_op and to_op")

    def fn(node, new_inputs):
        if node.op != from_op:
            return None
        attrs = attr_map(dict(node.attrs)) if attr_map else node.attrs
        return (to_op, node.name, attrs, new_inputs)

    return rewrite(sym, fn)
