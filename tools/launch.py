#!/usr/bin/env python
"""Distributed launcher (parity: the reference's ``tools/launch.py`` +
dmlc-core tracker — SURVEY.md §3.4).

The reference starts scheduler/server/worker processes over ssh/yarn/...
for the ps-lite parameter server. The TPU-native substitute is SPMD:
every process is a WORKER running the same program; coordination is
``jax.distributed.initialize`` (one coordinator, N processes) and
parameter sync is XLA collectives over ICI/DCN — no scheduler or server
roles exist (SURVEY.md §3.4 "TPU translation").

Supported launchers:
  local  — fork N worker processes on this host (the reference's CI idiom
           for testing dist kvstore without a cluster; SURVEY.md §4
           idiom 4). Sets JAX_COORDINATOR_ADDRESS / JAX_PROCESS_ID /
           JAX_NUM_PROCESSES plus the DMLC_* names scripts may read.
  ssh    — print the per-host commands (zero-egress build: execution via
           ssh is left to the operator / real cluster tooling).

Example:
  python tools/launch.py -n 4 --launcher local python train.py \
      --kvstore dist_sync
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker_env(rank, n, coord, extra=None):
    env = dict(os.environ)
    env.update({
        # JAX multi-process bootstrap (jax.distributed.initialize reads
        # these when called with no args)
        "JAX_COORDINATOR_ADDRESS": coord,
        "JAX_PROCESS_ID": str(rank),
        "JAX_NUM_PROCESSES": str(n),
        # reference-compatible names (scripts written against the
        # reference's tracker keep working)
        "DMLC_ROLE": "worker",
        "DMLC_NUM_WORKER": str(n),
        "DMLC_NUM_SERVER": "0",
        "DMLC_WORKER_ID": str(rank),
        "MXTPU_COORDINATOR": coord,
        "MXTPU_NUM_PROCS": str(n),
        "MXTPU_PROC_ID": str(rank),
    })
    if extra:
        env.update(extra)
    return env


def launch_local(n: int, command, port=None) -> int:
    """Fork n workers on this host; returns the first nonzero exit code
    (0 when all succeed)."""
    coord = f"127.0.0.1:{port or _free_port()}"
    procs = []
    for rank in range(n):
        procs.append(subprocess.Popen(
            command, env=_worker_env(rank, n, coord)))
    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Launch a distributed SPMD job "
                    "(reference tools/launch.py parity)")
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-s", "--num-servers", type=int, default=0,
                    help="accepted for parity; SPMD has no server role")
    ap.add_argument("--launcher", choices=("local", "ssh"),
                    default="local")
    ap.add_argument("-H", "--hostfile", default=None,
                    help="one host per line (ssh launcher)")
    ap.add_argument("--port", type=int, default=None)
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)

    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    if not args.command:
        ap.error("no command given")
    if args.num_servers:
        print("note: SPMD has no parameter-server processes; "
              "-s is ignored (optimizer runs data-parallel in-step)",
              file=sys.stderr)

    if args.launcher == "local":
        return launch_local(args.num_workers, args.command, args.port)

    # ssh: emit the exact command per host (zero-egress environment)
    hosts = []
    if args.hostfile:
        with open(args.hostfile) as f:
            hosts = [h.strip() for h in f if h.strip()]
    if len(hosts) < args.num_workers:
        hosts += ["<host%d>" % i for i in range(len(hosts),
                                                args.num_workers)]
    coord = f"{hosts[0]}:{args.port or 9876}"
    cmd = " ".join(args.command)
    for rank in range(args.num_workers):
        env = (f"JAX_COORDINATOR_ADDRESS={coord} JAX_PROCESS_ID={rank} "
               f"JAX_NUM_PROCESSES={args.num_workers} DMLC_ROLE=worker")
        print(f"ssh {hosts[rank]} '{env} {cmd}'")
    return 0


if __name__ == "__main__":
    sys.exit(main())
