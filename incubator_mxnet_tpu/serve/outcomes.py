"""Structured terminal outcomes for serving requests.

Every request handed to the engine ends in EXACTLY ONE terminal
outcome — success-or-exception is not a contract a serving tier can
offer under overload and faults (docs/RESILIENCE.md). The taxonomy:

  EOS                 stopped at the request's eos_id (success)
  MAX_TOKENS          generated max_new_tokens (success)
  STOP                a client stop sequence matched the generated
                      stream (success; the matched sequence is NOT
                      part of the output — serve/sampling.py)
  DEADLINE_EXPIRED    the request's deadline (or the engine's per-slot
                      wall cap) passed — queued requests are dropped,
                      decoding slots are evicted with their pages
                      reclaimed; partial tokens are kept
  SHED                refused at admission (bounded queue depth /
                      estimated queue delay over the limit) or failed
                      by an engine shutdown; ``retry_after_s`` carries
                      the backpressure hint
  FAILED_NONFINITE    the slot's logits went non-finite (poisoned
                      weights / corrupt KV) — quarantined and failed
                      rather than sampling garbage forever
  FAILED_UNSERVABLE   the request can never (or did not, within the
                      watchdog/stall budget) get the pages it needs —
                      too large for the pool, or page-starved
  FAILED_REPLICA      the fleet router re-queued the request across
                      replica deaths ``max_requeues`` times (or had no
                      serving replica left) and gave up — bounded
                      recovery, never a silent loss (serve/router.py)
  PREEMPTED           a higher-tier admission reclaimed the request's
                      slot ``max_preemptions`` times and the engine
                      gave up re-queuing it — bounded, retryable,
                      partial tokens kept (an in-budget preemption is
                      NOT terminal: the request re-queues through
                      normal admission as a resume-from-suffix replay,
                      continuation bit-identical — serve/slo.py)
  CANCELLED           the client withdrew the request
                      (``engine.cancel`` / ``router.cancel``) — a
                      first-class transition from ANY live state
                      (queued, prefilling, mid-decode,
                      mid-spec-verify) with pages reclaimed and
                      partial tokens kept; not retryable (the client
                      asked for it)

``EOS`` and ``MAX_TOKENS`` are the success outcomes (``.ok``); the
rest are the failure surface the chaos harness (serve/chaos.py,
tools/chaos_bench.py) drives and asserts. ``.retryable`` marks the
outcomes a client (or the fleet router) may legitimately retry —
every terminal with a retryable outcome carries a machine-readable
``retry_after_s`` backoff hint (one contract, engine- and
router-level; asserted in tests/test_router.py).
"""

from __future__ import annotations

import enum

__all__ = ["Outcome"]


class Outcome(enum.Enum):
    EOS = "EOS"
    MAX_TOKENS = "MAX_TOKENS"
    STOP = "STOP"
    DEADLINE_EXPIRED = "DEADLINE_EXPIRED"
    SHED = "SHED"
    FAILED_NONFINITE = "FAILED_NONFINITE"
    FAILED_UNSERVABLE = "FAILED_UNSERVABLE"
    FAILED_REPLICA = "FAILED_REPLICA"
    PREEMPTED = "PREEMPTED"
    CANCELLED = "CANCELLED"

    @property
    def ok(self) -> bool:
        """True for the success outcomes (the request's own stopping
        condition, not an engine intervention)."""
        return self in (Outcome.EOS, Outcome.MAX_TOKENS, Outcome.STOP)

    @property
    def retryable(self) -> bool:
        """True for the shed/deadline-class outcomes a client may retry
        (elsewhere, or later): the request itself was fine, the system
        lacked capacity/time/replicas for it. These are exactly the
        outcomes that must carry a ``retry_after_s`` hint. CANCELLED
        is deliberately absent: the client withdrew the request, so
        'retry later' is not advice it asked for."""
        return self in (Outcome.SHED, Outcome.DEADLINE_EXPIRED,
                        Outcome.FAILED_REPLICA, Outcome.PREEMPTED)

    def __str__(self) -> str:  # readable in logs / JSON dumps
        return self.value
