"""Utility subsystems: serialization, FLOPs/MFU accounting, misc."""

from . import serialization  # noqa: F401
from . import flops  # noqa: F401
