"""Parallelism & distribution: device meshes, collectives, fused SPMD
training, and sequence/context parallelism.

TPU-native replacement for the reference's distribution stack
(KVStore comm `src/kvstore/comm.h`, NCCL `kvstore_nccl.h`, ps-lite
`3rdparty/ps-lite/` — SURVEY.md §2.3/§5.8): instead of parameter-server
processes and explicit NCCL calls, a `jax.sharding.Mesh` + `NamedSharding`
annotations let XLA place `psum`/`all_gather`/`reduce_scatter` on ICI
(intra-slice) and DCN (cross-slice) automatically.
"""

from . import mesh
from .mesh import (MeshConfig, build_mesh, current_mesh, default_mesh,
                   set_default_mesh, initialize)
from . import collectives
from .collectives import host_allreduce
from . import spmd
from .spmd import (SPMDTrainer, shard_params, replicate, constrain,
                   activation_sharding_scope)
from . import pipeline
from .pipeline import pipeline_apply, stack_stage_params
from . import moe
from .moe import switch_moe, stack_expert_params
from . import ring_attention
from .ring_attention import ring_self_attention, ring_flash_attention

__all__ = [
    "MeshConfig", "build_mesh", "current_mesh", "default_mesh",
    "set_default_mesh", "initialize", "collectives", "host_allreduce",
    "SPMDTrainer", "shard_params", "replicate", "ring_self_attention",
    "ring_flash_attention",
]
