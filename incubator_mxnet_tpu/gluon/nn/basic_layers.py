"""Basic layers: Dense, Dropout, Embedding, normalizations, containers.

Re-design of `python/mxnet/gluon/nn/basic_layers.py` (file-level citation —
SURVEY.md caveat). Layers are HybridBlocks: eager by default, one XLA
program when hybridized.
"""

from __future__ import annotations

from ... import autograd
from ...base import MXNetError
from ..block import Block, HybridBlock

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "Embedding",
           "BatchNorm", "LayerNorm", "InstanceNorm", "GroupNorm", "Flatten",
           "Lambda", "HybridLambda", "Identity"]


class Sequential(Block):
    """Sequential container (eager)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(HybridBlock):
    """Sequential container, hybridizable into one XLA program."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_call(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer (parity: gluon.nn.Dense; op:
    FullyConnected — reference src/operator/nn/fully_connected.cc)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None
            self.act = Activation(activation, prefix=activation + "_") \
                if activation is not None else None

    def infer_shape(self, x, *args):
        if self._flatten:
            in_units = 1
            for d in x.shape[1:]:
                in_units *= d
        else:
            in_units = x.shape[-1]
        self.weight.shape = (self._units, in_units)
        if self.bias is not None:
            self.bias.shape = (self._units,)

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, no_bias=bias is None,
                               num_hidden=self._units, flatten=self._flatten)
        if self.act is not None:
            out = self.act(out)
        return out


class Dropout(HybridBlock):
    """(parity: gluon.nn.Dropout; op: Dropout)."""

    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate > 0:
            return F.Dropout(x, p=self._rate, axes=self._axes)
        return x


class Embedding(HybridBlock):
    """(parity: gluon.nn.Embedding; op: Embedding).

    ``sharded=True`` attaches a vocab-dim PartitionSpec hint
    (``P(('tp','fsdp'), None)``) so SPMDTrainer/pjit splits the table's
    ROWS across the mesh — the TPU-native analogue of the reference's
    PS-sharded ``row_sparse`` embedding weights (SURVEY.md §2.3 last
    row): each device stores a vocab shard, the gather and its backward
    scatter become collective ops XLA schedules on ICI, and the lookup
    output stays batch-sharded (keeping units replicated avoids
    activation resharding against batch-sharded encoder layouts)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False,
                 sharded=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer,
                grad_stype="row_sparse" if sparse_grad else "default")
        if sharded:
            from jax.sharding import PartitionSpec as _P
            self.weight._sharding = _P(("tp", "fsdp"), None)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)


class BatchNorm(HybridBlock):
    """(parity: gluon.nn.BatchNorm; op: BatchNorm — reference
    src/operator/nn/batch_norm.cc). Running stats are aux state: updated
    eagerly in imperative mode, captured as aux outputs under hybridize."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                differentiable=scale)
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        out, mean, var = F.BatchNorm(
            x, gamma, beta, running_mean, running_var, eps=self._epsilon,
            momentum=self._momentum, fix_gamma=not self._scale,
            use_global_stats=self._use_global_stats, axis=self._axis)
        if autograd.is_training() and not self._use_global_stats:
            m = self._momentum
            from ...ndarray import NDArray
            self.running_mean._data = NDArray(
                m * running_mean._data + (1 - m) * mean._data)
            self.running_var._data = NDArray(
                m * running_var._data + (1 - m) * var._data)
        return out


class LayerNorm(HybridBlock):
    """(parity: gluon.nn.LayerNorm; op: LayerNorm)."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)

    def infer_shape(self, x, *args):
        c = x.shape[1]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._num_groups = num_groups
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)

    def infer_shape(self, x, *args):
        c = x.shape[1]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._epsilon)


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.flatten(x)

    def __repr__(self):
        return "Flatten"


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return x


class Lambda(Block):
    """Wrap a function as a Block (parity: gluon.nn.Lambda)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        from ... import ndarray as nd_mod
        if isinstance(function, str):
            if not hasattr(nd_mod, function):
                raise MXNetError(f"unknown nd function {function!r}")
            self._func = getattr(nd_mod, function)
            self._name = function
        else:
            self._func = function
            self._name = getattr(function, "__name__", "lambda")

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            self._fname = function
            self._func = None
        else:
            self._func = function
            self._fname = None

    def hybrid_forward(self, F, *args):
        if self._fname is not None:
            return getattr(F, self._fname)(*args)
        return self._func(F, *args)


# imported at bottom to avoid a cycle (activations imports HybridBlock)
from .activations import Activation  # noqa: E402
