"""Int8 PTQ tests (reference strategy:
tests/python/quantization/test_quantization.py — quantize/dequantize
numerics, calibrated net accuracy preservation)."""

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, nd
from incubator_mxnet_tpu.contrib.quantization import (
    calib_thresholds_entropy, quantize_net)


def test_quantize_dequantize_roundtrip():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 8).astype(np.float32) * 3
    q, mn, mxr = nd.quantize_v2(nd.array(x))
    assert str(q.dtype) == "int8"
    back = nd.dequantize(q, mn, mxr).asnumpy()
    # max quantization error is scale/2 = amax/127/2
    np.testing.assert_allclose(back, x, atol=float(np.abs(x).max()) / 127)


def test_quantize_with_calib_range_clips():
    x = nd.array(np.array([[-10.0, 0.5, 10.0]], np.float32))
    q, _, _ = nd.quantize_v2(x, min_calib_range=-1.0, max_calib_range=1.0)
    qn = q.asnumpy()
    assert qn[0, 0] == -127 and qn[0, 2] == 127


def test_quantized_fully_connected_matches_float():
    rng = np.random.RandomState(1)
    x = rng.randn(5, 16).astype(np.float32)
    w = rng.randn(8, 16).astype(np.float32) * 0.2
    b = rng.randn(8).astype(np.float32) * 0.1
    xq, mn, mxr = nd.quantize_v2(nd.array(x))
    amax_w = np.abs(w).max()
    wq = nd.array(np.clip(np.round(w / (amax_w / 127)), -127,
                          127).astype(np.int8))
    out, _, _ = nd.quantized_fully_connected(
        xq, wq, nd.array(b), mn, mxr, -float(amax_w), float(amax_w))
    ref = x @ w.T + b
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=0.1, atol=0.1)


def test_entropy_threshold_reasonable():
    rng = np.random.RandomState(2)
    # gaussian bulk with rare huge outlier: entropy threshold should be
    # far below the outlier
    a = np.abs(np.concatenate([rng.randn(100000), [50.0]]))
    hist, edges = np.histogram(a, bins=2048, range=(0, 50.0))
    t = calib_thresholds_entropy(hist, edges[1:])
    assert t < 25.0


@pytest.mark.parametrize("mode", ["naive", "entropy"])
def test_quantize_net_mlp_accuracy(mode):
    rng = np.random.RandomState(0)
    X = rng.randn(256, 10).astype(np.float32)
    W = rng.randn(10, 3).astype(np.float32)
    y = np.argmax(X @ W, 1).astype(np.float32)

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(3))
    net.initialize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 1.0})
    from incubator_mxnet_tpu import autograd
    for _ in range(60):
        with autograd.record():
            l = loss_fn(net(nd.array(X)), nd.array(y))
        l.backward()
        tr.step(256)
    float_acc = (np.argmax(net(nd.array(X)).asnumpy(), 1) == y).mean()

    qnet = quantize_net(net, calib_data=[nd.array(X[i:i + 64])
                                         for i in range(0, 256, 64)],
                        calib_mode=mode)
    q_out = qnet(nd.array(X)).asnumpy()
    q_acc = (np.argmax(q_out, 1) == y).mean()
    assert float_acc > 0.9
    assert q_acc >= float_acc - 0.05, (float_acc, q_acc)


def test_quantize_net_conv():
    rng = np.random.RandomState(1)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1, activation="relu"),
            gluon.nn.GlobalAvgPool2D(), gluon.nn.Dense(4))
    net.initialize()
    X = nd.array(rng.randn(2, 3, 8, 8).astype(np.float32))
    ref = net(X).asnumpy()
    qnet = quantize_net(net, calib_data=[X])
    got = qnet(X).asnumpy()
    np.testing.assert_allclose(got, ref, rtol=0.25, atol=0.25)


def test_quantize_net_errors():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(4))
    net.initialize()
    with pytest.raises(mx.base.MXNetError):
        quantize_net(net, calib_data=None)
    with pytest.raises(mx.base.MXNetError):
        quantize_net(net, calib_data=[nd.ones((1, 4))], calib_mode="bogus")
    with pytest.raises(mx.base.MXNetError):
        quantize_net(net, calib_data=[nd.ones((1, 4))],
                     quantized_dtype="uint4")


def test_quantize_net_hybridized():
    """Regression: calibrating a hybridized net must not trace the hooks."""
    rng = np.random.RandomState(3)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(3))
    net.initialize()
    net.hybridize()
    X = nd.array(rng.randn(4, 6).astype(np.float32))
    net(X)  # warm the cached op
    ref = net(X).asnumpy()
    qnet = quantize_net(net, calib_data=[X])
    got = qnet(X).asnumpy()
    np.testing.assert_allclose(got, ref, rtol=0.3, atol=0.3)


def test_entropy_range_growth():
    """Regression: a later batch with larger range must widen the
    histogram instead of being clipped into the first batch's range."""
    from incubator_mxnet_tpu.contrib.quantization import _Collector

    c = _Collector(mode="entropy", num_bins=256)
    hook = c.hook("L")
    hook(None, (nd.array(np.linspace(-1, 1, 1000,
                                     dtype=np.float32)),), None)
    hook(None, (nd.array(np.linspace(-10, 10, 100000,
                                     dtype=np.float32)),), None)
    t = c.threshold("L")
    assert t > 2.0, t  # not capped at the first batch's max of 1.0


def test_quantized_export_gated():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(4))
    net.initialize()
    X = nd.ones((2, 6))
    net(X)
    qnet = quantize_net(net, calib_data=[X])
    import incubator_mxnet_tpu as mx2
    with pytest.raises(mx2.base.MXNetError):
        qnet(mx2.sym.Variable("data"))
