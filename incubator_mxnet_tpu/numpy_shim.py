"""``mx.np`` — NumPy-compatible array API (re-design of
`python/mxnet/numpy/` ≥1.6; file-level citation — SURVEY.md caveat).

The reference re-implements the NumPy surface op-by-op on its own runtime.
The TPU-native build sits on jnp, which *is* a NumPy-compatible tracer —
so ``mx.np`` is a forwarding namespace: any ``numpy``-named function is
resolved on ``jax.numpy``, executed through the imperative dispatcher (so
``autograd.record()`` sees it as a tape node, exactly like a registry op),
and returns :class:`~incubator_mxnet_tpu.ndarray.NDArray`.

This gives the full jnp surface (hundreds of functions) with MXNet
autograd/async semantics instead of a hand-ported subset.
"""

from __future__ import annotations

import numpy as _onp

import jax.numpy as jnp

from .base import MXNetError
from .ndarray import NDArray
from .ndarray.register import imperative_invoke
from .ops.registry import OpSpec

# numpy-API constants / dtypes re-exported verbatim
pi = _onp.pi
e = _onp.e
inf = _onp.inf
nan = _onp.nan
newaxis = None
float32 = "float32"
float64 = "float64"
float16 = "float16"
bfloat16 = "bfloat16"
int8 = "int8"
int32 = "int32"
int64 = "int64"
uint8 = "uint8"
bool_ = "bool"

ndarray = NDArray  # parity: mx.np.ndarray is the array type

_spec_cache = {}

# jnp callables that are not array-valued ops (predicates/introspection):
# call directly and return python/numpy values, no tape node
_PASSTHROUGH = {"shape", "ndim", "size", "result_type", "promote_types",
                "can_cast", "issubdtype", "isscalar", "iterable",
                "broadcast_shapes"}


def _unwrap(x):
    if isinstance(x, NDArray):
        return x._data
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(v) for v in x)
    return x


def _make_spec(name: str, fn) -> OpSpec:
    spec = _spec_cache.get(name)
    if spec is None:
        import jax

        def op(*arrays, **params):
            return fn(*arrays, **params)

        op.__doc__ = fn.__doc__
        spec = OpSpec("np." + name, op)
        # variadic/multi-output jnp fns (split, meshgrid…) return sequences;
        # detect at call time inside imperative_invoke via tuple normalize
        spec.num_outputs = None
        _spec_cache[name] = spec
    return spec


def array(obj, dtype=None, ctx=None):
    """Parity: ``mx.np.array``."""
    from .ndarray import array as _nd_array

    return _nd_array(obj, dtype=dtype, ctx=ctx)


def __getattr__(name: str):
    fn = getattr(jnp, name, None)
    if fn is None:
        raise AttributeError(f"mx.np has no attribute {name!r} "
                             "(not in jax.numpy)")
    if not callable(fn):
        return fn
    if name in _PASSTHROUGH:
        def passthrough(*args, **kwargs):
            return fn(*_unwrap(args), **kwargs)

        passthrough.__name__ = name
        return passthrough

    spec = _make_spec(name, fn)

    def np_function(*args, **kwargs):
        try:
            return imperative_invoke(spec, *args, **kwargs)
        except MXNetError:
            # fns with non-array leading args (e.g. np.arange(5)) fail the
            # array path; fall back to a direct call, still wrapping outputs
            res = fn(*_unwrap(args), **{k: _unwrap(v)
                                        for k, v in kwargs.items()})
            if isinstance(res, (tuple, list)):
                return type(res)(NDArray(r) for r in res)
            return NDArray(res)

    np_function.__name__ = name
    np_function.__doc__ = fn.__doc__
    return np_function
