"""Operator registry.

TPU-native analogue of the reference's NNVM op registry
(`NNVM_REGISTER_OP` in `3rdparty/tvm/nnvm/include/nnvm/op.h`, MXNet-side
registration in `src/operator/**`; file-level citations — SURVEY.md caveat).

Key differences from the reference, by design:
  - An op here is ONE pure, jit-traceable function over ``jax.Array``s. There
    is no separate FCompute/FGradient pair: gradients come from ``jax.vjp``
    of the same function, so every registered op is differentiable for free
    (custom VJPs may still be attached via ``jax.custom_vjp`` inside the fn).
  - Shape/type inference (`FInferShape`/`FInferType`) is XLA's abstract
    evaluation — ``jax.eval_shape`` over the same function — instead of
    per-op C++ inference functions.
  - ``dmlc::Parameter`` typed attribute structs become keyword arguments with
    defaults; ``describe_op`` regenerates registry-driven docs the way the
    reference generates Python signatures from the C registry at import
    (`python/mxnet/ndarray/register.py`).

Ops registered here are surfaced on BOTH front ends (``mx.nd`` imperatively,
``mx.sym`` symbolically), mirroring how a single NNVM registration served the
reference's imperative and symbolic paths (SURVEY.md §1 pillar b).
"""

from __future__ import annotations

import inspect
from typing import Callable, Dict, List, Optional, Tuple

from ..base import MXNetError

__all__ = ["register", "get", "list_all_ops", "OpSpec", "describe_op"]

_OP_REGISTRY: Dict[str, "OpSpec"] = {}


class OpSpec:
    """Metadata for a registered operator.

    Attributes
    ----------
    name : canonical snake_case op name (reference op names kept verbatim,
        e.g. ``broadcast_add``, ``FullyConnected`` is an alias).
    fn : pure function ``fn(*arrays, **params) -> array | tuple``.
    num_outputs : static output arity (None if variadic, e.g. ``split``),
        or a callable ``attrs -> int`` for attr-dependent arity (RNN).
    needs_key : op consumes a PRNG key as its LAST array argument (stochastic
        ops: dropout, samplers). The imperative front end feeds the global
        stream; traced front ends must thread keys explicitly.
    training_aware : fn takes a ``training`` kwarg resolved from autograd
        mode at call time (dropout, batchnorm).
    """

    __slots__ = ("name", "fn", "aliases", "num_outputs", "needs_key",
                 "training_aware", "wrap_list", "doc")

    def __init__(self, name, fn, aliases=(), num_outputs=1, needs_key=False,
                 training_aware=False, wrap_list=False):
        self.name = name
        self.fn = fn
        self.aliases = tuple(aliases)
        self.num_outputs = num_outputs
        self.needs_key = needs_key
        self.training_aware = training_aware
        self.wrap_list = wrap_list
        self.doc = fn.__doc__

    def __repr__(self):
        return f"OpSpec({self.name})"


def register(name: str, aliases: Tuple[str, ...] = (), num_outputs: Optional[int] = 1,
             needs_key: bool = False, training_aware: bool = False,
             wrap_list: bool = False) -> Callable:
    """Register a pure operator function under ``name`` (+ aliases)."""

    def _deco(fn):
        spec = OpSpec(name, fn, aliases, num_outputs, needs_key,
                      training_aware, wrap_list)
        if name in _OP_REGISTRY:
            raise MXNetError(f"operator {name!r} registered twice")
        _OP_REGISTRY[name] = spec
        for a in aliases:
            if a in _OP_REGISTRY:
                raise MXNetError(f"operator alias {a!r} registered twice")
            _OP_REGISTRY[a] = spec
        return fn

    return _deco


def get(name: str) -> OpSpec:
    try:
        return _OP_REGISTRY[name]
    except KeyError:
        raise MXNetError(f"operator {name!r} not registered") from None


def exists(name: str) -> bool:
    return name in _OP_REGISTRY


def list_all_ops() -> List[str]:
    """Canonical names only (parity: ``MXListAllOpNames``)."""
    return sorted({s.name for s in _OP_REGISTRY.values()})


def list_all_names() -> List[str]:
    """All registered names including aliases."""
    return sorted(_OP_REGISTRY)


def describe_op(name: str) -> str:
    """Registry-driven documentation, the analogue of the reference's
    ``MXSymbolGetAtomicSymbolInfo`` docstring generation."""
    spec = get(name)
    sig = inspect.signature(spec.fn)
    lines = [f"Operator `{spec.name}`"]
    if spec.aliases:
        lines.append(f"aliases: {', '.join(spec.aliases)}")
    lines.append(f"signature: {spec.name}{sig}")
    if spec.doc:
        lines.append("")
        lines.append(inspect.cleandoc(spec.doc))
    return "\n".join(lines)
