"""Multi-host (2-process) execution test (VERDICT r2 next-round #6).

Launches tests/dist_worker.py through tools/launch.py --launcher local —
the TPU-native mirror of the reference's
tests/nightly/dist_sync_kvstore.py CI idiom: prove the distributed
kvstore and the fused SPMD step on one box with real separate processes
(jax.distributed over a 2x4-virtual-device CPU mesh)."""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_two_process_dist_sync_and_spmd_step():
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    # the launcher must not inherit the single-process test mesh flags
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    for attempt in range(2):  # coordinator port/races under load: 1 retry
        r = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "launch.py"),
             "-n", "2", "--launcher", "local", "--",
             sys.executable, os.path.join(_REPO, "tests",
                                          "dist_worker.py")],
            capture_output=True, text=True, timeout=540, env=env,
            cwd=_REPO)
        if r.returncode == 0:
            break
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    # both workers share the launcher's stdout pipe: concurrent writes can
    # interleave on one line, so count occurrences, not lines
    oks = r.stdout.count("DIST_WORKER_OK")
    assert oks == 2, f"expected 2 worker OK markers, got: {r.stdout}"
