#!/bin/bash
# CI pipeline (reference parity: ci/build.py + Jenkins stage set,
# SURVEY.md §2.4 — sanity/lint, native build, unit tests, driver entry
# checks). Self-contained: run from anywhere inside the repo.
#
#   ci/run.sh            # all stages
#   ci/run.sh sanity     # just the named stage
#   ci/run.sh native unit
set -e
cd "$(dirname "$0")/.."

stage_sanity() {
  echo "== sanity: byte-compile every python file"
  python -m compileall -q incubator_mxnet_tpu tests tools bench.py \
      __graft_entry__.py
  echo "== sanity: import the package on the CPU backend"
  JAX_PLATFORMS=cpu python -c "
import os; os.environ['JAX_PLATFORMS']='cpu'
import jax; jax.config.update('jax_platforms','cpu')
import incubator_mxnet_tpu as mx
print('import ok:', mx.__version__)"
}

stage_lintcore() {
  echo "== lintcore: mxlint AST invariant analyzer (trace purity,"
  echo "             terminal outcomes, page refcounts, hot-loop host"
  echo "             syncs, lock discipline — docs/STATIC_ANALYSIS.md.)"
  echo "             Fails on any unbaselined, unwaived finding; the"
  echo "             summary line reports the baseline size so debt"
  echo "             growth is visible per PR. To acknowledge NEW debt:"
  echo "             python -m tools.mxlint --baseline ci/mxlint_baseline.json --update-baseline"
  echo "             then replace every UNREVIEWED reason with a real one."
  python -m tools.mxlint --baseline ci/mxlint_baseline.json
}

stage_native() {
  echo "== native: build the C++ runtime components (make)"
  make -C incubator_mxnet_tpu/src
  echo "== native: CMake configure parity check"
  cmake -S incubator_mxnet_tpu/src -B /tmp/mxtpu_cmake_build \
      >/dev/null && cmake --build /tmp/mxtpu_cmake_build >/dev/null
  echo "cmake build ok"
}

stage_unit() {
  echo "== unit: full pytest suite (virtual 8-device CPU mesh)"
  python -m pytest tests/ -q
}

stage_stepbench() {
  echo "== stepbench: fused-step regression guard (steady-state compile"
  echo "              count must stay at 1 per (shape, dtype) signature)"
  JAX_PLATFORMS=cpu python tools/step_bench.py --smoke
}

stage_mfubench() {
  echo "== mfubench: training-throughput regression guard (round 16"
  echo "             gates: the microbatch-accumulation program must"
  echo "             compile exactly once across accumulation counts,"
  echo "             a non-finite microbatch must veto the WHOLE"
  echo "             accumulated apply as one outcome with params"
  echo "             bit-identical, the guarded accumulated trajectory"
  echo "             must match the unguarded one bitwise on clean"
  echo "             streams, the overlapped bucket issue order must be"
  echo "             deterministic and equal to the plan order, and"
  echo "             every banked arm must carry tokens/s AND an MFU"
  echo "             field computed from the same run."
  echo "             Round-19 pipelined gates: the in-program overlapped"
  echo "             step on dp2 AND fsdp2 must (a) compile its"
  echo "             microbatch program exactly once across accumulation"
  echo "             counts {1,4,8}, (b) hold loss+param parity with the"
  echo "             paired GSPMD baseline over 3 steps — BITWISE on dp2,"
  echo "             allclose under fsdp (GSPMD's per-dot contraction"
  echo "             choice for sharded params is shape-regime noise),"
  echo "             (c) show structural overlap in StableHLO: grad"
  echo "             collectives in plan_grad_buckets order with backward"
  echo "             dots strictly between them (CPU-checkable); the int8"
  echo "             grad all-reduce must stay within 5% convergence"
  echo "             divergence of f32, and any arm tagged arm_kind="
  echo "             overlap that issues 0 buckets fails the stage)"
  JAX_PLATFORMS=cpu python tools/step_bench.py --mfu --smoke
}

stage_servebench() {
  echo "== servebench: continuous-batching regression guard (the decode"
  echo "               family must compile exactly once per program — W=1"
  echo "               narrow + K+1-wide verify — across occupancy churn and"
  echo "               mixed-agreement speculation; cache-hit admission must"
  echo "               compile ZERO new programs; chunked prefill must respect"
  echo "               its per-step token budget; zero-agreement speculation"
  echo "               must stay bit-identical to plain decode at the same"
  echo "               step count and within noise of its tokens/s)"
  JAX_PLATFORMS=cpu python tools/serve_bench.py --smoke
}

stage_quantbench() {
  echo "== quantbench: quantized-KV regression guard (int8 pages vs the"
  echo "               f32 jnp oracle: greedy top-1 token match >= 99%,"
  echo "               p99 logit error under the accuracy gate, decode/"
  echo "               verify/prefill each compiled exactly once in the"
  echo "               quantized arm, slots-at-fixed-pool-bytes >= 1.8x"
  echo "               the f32 layout; plus the int8-allreduce seam:"
  echo "               loss-curve divergence vs f32 bounded at 5%)"
  JAX_PLATFORMS=cpu python tools/serve_bench.py --quant --smoke
}

stage_chaossmoke() {
  echo "== chaossmoke: resilience guard (seeded faults — NaN weights,"
  echo "               corrupt/dropped page writes, allocator starvation,"
  echo "               host stalls, SIGTERM mid-serve; fails on any"
  echo "               non-terminal request, cross-slot contamination,"
  echo "               page-audit violation, or steady-state retrace)"
  JAX_PLATFORMS=cpu python tools/chaos_bench.py --smoke
}

stage_fleetsmoke() {
  echo "== fleetsmoke: fleet resilience guard (router over N replicas —"
  echo "               replica kills mid-decode/mid-prefill become bounded"
  echo "               structured re-queues with emitted tokens preserved,"
  echo "               breaker opens/half-open-probes/closes under slow and"
  echo "               flapping replicas, fleet-level shedding carries"
  echo "               retry_after_s; fails on any lost/double-finished"
  echo "               request, survivor divergence, page-audit violation"
  echo "               on a surviving replica, or per-replica retrace)"
  JAX_PLATFORMS=cpu python tools/chaos_bench.py --fleet --smoke
}

stage_tiersmoke() {
  echo "== tiersmoke: SLO-tier resilience guard (priority scheduling under"
  echo "              a mixed-tier overload storm — LATENCY preempts BATCH"
  echo "              slots and resumes them bit-identically, shedding"
  echo "              drains BATCH first; client cancel storms land as"
  echo "              exactly-one CANCELLED terminal from any live state;"
  echo "              preemption composes with NaN quarantine; brownout"
  echo "              hysteresis steps degrade levels up and back down;"
  echo "              fails on any non-terminal request, tier-ordering"
  echo "              violation, parity break, page-audit violation, or"
  echo "              steady-state retrace)"
  JAX_PLATFORMS=cpu python tools/chaos_bench.py --tiers --smoke
}

stage_hiersmoke() {
  echo "== hiersmoke: hierarchical KV-cache guard (demote evicted prefix"
  echo "              pages to host DRAM/disk, re-admit by COPY — tiered"
  echo "              serving must be bit-identical to flat and recompute"
  echo "              arms, every page free XOR live XOR demoted at every"
  echo "              step, one promotion program ever; a corrupted demoted"
  echo "              payload must be convicted by crc and recomputed"
  echo "              loudly, a full disk must degrade the tier to a loud"
  echo "              no-op, and a kill mid-promotion must leave a"
  echo "              replacement engine that wipes stale tier dirs and"
  echo "              serves clean)"
  JAX_PLATFORMS=cpu python tools/serve_bench.py --hier --smoke
  JAX_PLATFORMS=cpu python tools/chaos_bench.py --hier --smoke
}

stage_migratesmoke() {
  echo "== migratesmoke: page-transport guard (drain a replica under"
  echo "              load — decode-ready slots migrate with ZERO redone"
  echo "              prefill and zero lost requests, vs the replay arm's"
  echo "              full recompute; prefill/decode role split hands"
  echo "              every slot off at publication, bit-identical to"
  echo "              mixed; quantized capsules ship ~4x fewer wire"
  echo "              bytes; chaos: kill source mid-capture leaves the"
  echo "              slot decoding in place, kill destination"
  echo "              mid-install and capsule bit rot fall back to replay"
  echo "              LOUDLY, a migrate-vs-cancel race keeps exactly one"
  echo "              CANCELLED terminal; fails on any parity break,"
  echo "              page-audit violation, or steady-state retrace)"
  JAX_PLATFORMS=cpu python tools/serve_bench.py --migrate --smoke
  JAX_PLATFORMS=cpu python tools/chaos_bench.py --migrate --smoke
}

stage_elasticsmoke() {
  echo "== elasticsmoke: elastic-membership guard (wave load against the"
  echo "              autoscaling supervisor — grow on sustained brownout,"
  echo "              shrink in the gaps, zero lost requests either arm;"
  echo "              rolling same-weights upgrade under load stays"
  echo "              bit-identical to the un-upgraded control; chaos:"
  echo "              scale-down racing scale-up in one fleet pass,"
  echo "              supervisor killed mid-roll leaves no replica"
  echo "              stranded DRAINING, replica death mid-drain replays"
  echo "              everything the drain had not moved — each ending"
  echo "              100% exactly-one-terminal with clean page audits"
  echo "              on every survivor and zero retraces)"
  JAX_PLATFORMS=cpu python tools/serve_bench.py --elastic --smoke
  JAX_PLATFORMS=cpu python tools/chaos_bench.py --elastic --smoke
}

stage_frontsmoke() {
  echo "== frontsmoke: client-protocol guard (HTTP/SSE front end over"
  echo "               localhost — an end-to-end SSE stream must deliver"
  echo "               tokens incrementally, a mid-stream disconnect must"
  echo "               land as exactly-one CANCELLED terminal with pages"
  echo "               reclaimed, stop-sequence truncation must be correct"
  echo "               over the wire, decode must compile exactly once"
  echo "               through the HTTP path, and the constrained"
  echo "               tool-call arm must stay 100% in-language with the"
  echo "               decode family untraced by grammar masks)"
  JAX_PLATFORMS=cpu python tools/serve_bench.py --frontend --smoke
}

stage_frontchaos() {
  echo "== frontchaos: client-edge resilience guard (real-socket chaos —"
  echo "               disconnect storms and slow-reader backpressure must"
  echo "               each end in exactly one terminal per request with"
  echo "               clean page audits, survivor parity, and no retrace)"
  JAX_PLATFORMS=cpu python tools/chaos_bench.py --frontend --smoke
}

stage_obssmoke() {
  echo "== obssmoke: observability guard (flight recorder + tracing —"
  echo "             a seeded replica kill with the recorder on must dump"
  echo "             a postmortem JSON that validates against the event"
  echo "             schema and names the injected fault, the dead"
  echo "             replica, and every re-queued request; the Perfetto"
  echo "             export of a mixed prefill/decode/preemption run must"
  echo "             validate and show per-slot lanes; recorder overhead"
  echo "             is gated by the servebench stage's smoke run)"
  JAX_PLATFORMS=cpu python tools/trace_export.py --smoke
}

stage_trainchaos() {
  echo "== trainchaos: training resilience guard (seeded faults — NaN"
  echo "               gradients, overflow storms, persistent poison, NaN"
  echo "               batches on an fsdp mesh, kill -9 + supervisor resume,"
  echo "               hung-step watchdog, transient data-iterator IO errors;"
  echo "               fails on any step without exactly one recorded"
  echo "               outcome, a skip that mutated params/optimizer state,"
  echo "               a loss sequence that diverges across kill -9 resume,"
  echo "               a steady-state retrace, or guard+scaler overhead"
  echo "               over the smoke bar)"
  JAX_PLATFORMS=cpu python tools/train_chaos_bench.py --smoke
}

stage_ckptbench() {
  echo "== ckptbench: elastic-checkpoint regression guard (async commit +"
  echo "              keep-last-k GC + bit-exact capsule resume)"
  JAX_PLATFORMS=cpu python tools/ckpt_bench.py --smoke
}

stage_report() {
  echo "== report: bench trajectory (aggregates every banked BENCH_*.json"
  echo "           into BENCH_TRAJECTORY.md — informational, never fails)"
  python tools/bench_report.py || true
}

stage_entry() {
  echo "== entry: driver entry points (single-chip compile is driver-side;"
  echo "          here the 8-device multichip dryrun must pass)"
  JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python -c "
import os
os.environ['JAX_PLATFORMS']='cpu'
import jax; jax.config.update('jax_platforms','cpu')
import __graft_entry__ as ge
ge.dryrun_multichip(8)"
}

stages=("$@")
[ ${#stages[@]} -eq 0 ] && stages=(sanity lintcore native unit stepbench mfubench servebench quantbench chaossmoke fleetsmoke tiersmoke hiersmoke migratesmoke elasticsmoke frontsmoke frontchaos obssmoke trainchaos ckptbench entry report)
for s in "${stages[@]}"; do
  "stage_$s"
done
echo "CI: all stages green (${stages[*]})"
